"""Fault-injection layer tests (ISSUE 4).

- failpoint registry semantics: action grammar, NxM one-in-N firing,
  delay, env / SET / HTTP activation, information_schema.failpoints;
- RetryingObjectStore: backoff, give-up, transient classification,
  greptime_objstore_retry_* counters;
- S3 error taxonomy: 5xx/429 and socket errors are S3TransientError,
  4xx stays terminal S3Error;
- graceful degradation: read-cache corruption and scan-cache corruption
  both fall back to a cold read with identical answers;
- WAL torn-tail repair: truncate + WARN instead of raising, CRC catches
  corrupt-but-complete records;
- the crash-recovery torture matrix (tests/torture.py) as parametrized
  tier-1 cases plus a slow-marked extended sweep;
- the acceptance shape: ingest+flush+scan completes through 1-in-3
  injected transient object-store faults with retries visible in
  runtime_metrics.
"""

import json
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from greptimedb_tpu.common import failpoint as fp

from torture import CRASH_POINTS, TortureRig, make_batch, run_crash_case


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.clear_all()
    yield
    fp.clear_all()


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_parse_actions(self):
        assert fp.parse_action("err") == ("err", None, 1, 1)
        assert fp.parse_action("err(transient)") == ("err", "transient", 1, 1)
        assert fp.parse_action("crash") == ("crash", None, 1, 1)
        assert fp.parse_action("delay(25)") == ("delay", "25", 1, 1)
        assert fp.parse_action("1x3*err") == ("err", None, 1, 3)
        assert fp.parse_action("2x5*crash") == ("crash", None, 2, 5)
        for bad in ("nope", "err(", "0x3*err", "4x3*err", "delay",
                    "delay(ms)", "1x0*err"):
            with pytest.raises(ValueError):
                fp.parse_action(bad)

    def test_inactive_is_noop_and_zero_cost_guard(self):
        fp.register("fi_test_point")
        assert not fp._ACTIVE
        fp.fail_point("fi_test_point")    # must not raise or count
        assert not fp.fires("fi_test_point")
        rec = [p for p in fp.list_points() if p["name"] == "fi_test_point"]
        assert rec and rec[0]["hits"] == 0 and rec[0]["action"] is None

    def test_err_and_off(self):
        fp.configure("fi_test_err", "err")
        with pytest.raises(fp.FailpointError):
            fp.fail_point("fi_test_err")
        fp.configure("fi_test_err", "off")
        fp.fail_point("fi_test_err")      # disarmed: no-op

    def test_transient_flag(self):
        with fp.cfg("fi_test_tr", "err(transient)"):
            with pytest.raises(fp.FailpointError) as ei:
                fp.fail_point("fi_test_tr")
            assert ei.value.transient
        with fp.cfg("fi_test_tr", "err"):
            with pytest.raises(fp.FailpointError) as ei:
                fp.fail_point("fi_test_tr")
            assert not ei.value.transient

    def test_crash_is_base_exception(self):
        with fp.cfg("fi_test_crash", "crash"):
            with pytest.raises(fp.SimulatedCrash):
                try:
                    fp.fail_point("fi_test_crash")
                except Exception:  # noqa: BLE001
                    pytest.fail("SimulatedCrash caught by except Exception")

    def test_one_in_n_firing(self):
        with fp.cfg("fi_test_nxm", "1x3*err"):
            fired = 0
            for _ in range(9):
                try:
                    fp.fail_point("fi_test_nxm")
                except fp.FailpointError:
                    fired += 1
            assert fired == 3             # exactly one per window of 3
        rec = [p for p in fp.list_points() if p["name"] == "fi_test_nxm"][0]
        assert rec["hits"] == 9 and rec["fires"] == 3

    def test_delay(self):
        with fp.cfg("fi_test_delay", "delay(40)"):
            t0 = time.perf_counter()
            fp.fail_point("fi_test_delay")
            assert time.perf_counter() - t0 >= 0.03

    def test_env_activation(self, monkeypatch):
        monkeypatch.setenv("GREPTIME_FAILPOINTS",
                           "fi_env_a=err;fi_env_b=1x2*delay(1)")
        fp.refresh_from_env()
        points = {p["name"]: p for p in fp.list_points()}
        assert points["fi_env_a"]["action"] == "err"
        assert points["fi_env_b"]["action"] == "1x2*delay(1)"

    def test_bad_names_rejected(self):
        with pytest.raises(ValueError):
            fp.configure("Bad Name!", "err")
        with pytest.raises(ValueError):
            fp.configure("x", "nonsense-action")


# ---------------------------------------------------------------------------
# RetryingObjectStore
# ---------------------------------------------------------------------------

class _FlakyStore:
    """Object-store stub failing the first `fail_n` calls per op."""

    def __init__(self, fail_n, exc_factory):
        self.fail_n = fail_n
        self.exc_factory = exc_factory
        self.calls = 0
        self.data = {}

    def _maybe_fail(self):
        self.calls += 1
        if self.calls <= self.fail_n:
            raise self.exc_factory()

    def read(self, key):
        self._maybe_fail()
        return self.data[key]

    def write(self, key, data):
        self._maybe_fail()
        self.data[key] = data

    def delete(self, key):
        self._maybe_fail()
        self.data.pop(key, None)

    def exists(self, key):
        self._maybe_fail()
        return key in self.data

    def list(self, prefix):
        self._maybe_fail()
        return sorted(k for k in self.data if k.startswith(prefix))


class TestRetryingObjectStore:
    def _counter_value(self, name):
        from prometheus_client import REGISTRY
        v = REGISTRY.get_sample_value(name)
        return v or 0.0

    def test_retries_transient_then_succeeds(self):
        from greptimedb_tpu.storage.retry import (RetryingObjectStore,
                                                  configure_retry)
        configure_retry(max_retries=3, base_ms=1)
        inner = _FlakyStore(2, ConnectionResetError)
        store = RetryingObjectStore(inner)
        before = self._counter_value("greptime_objstore_retry_total")
        store.write("k", b"v")
        assert inner.data["k"] == b"v"
        assert inner.calls == 3
        assert self._counter_value(
            "greptime_objstore_retry_total") == before + 2

    def test_gives_up_after_budget(self):
        from greptimedb_tpu.storage.retry import (RetryingObjectStore,
                                                  configure_retry)
        configure_retry(max_retries=2, base_ms=1)
        inner = _FlakyStore(10, ConnectionResetError)
        store = RetryingObjectStore(inner)
        before = self._counter_value("greptime_objstore_retry_giveup_total")
        with pytest.raises(ConnectionResetError):
            store.read("k")
        assert inner.calls == 3           # 1 try + 2 retries
        assert self._counter_value(
            "greptime_objstore_retry_giveup_total") == before + 1

    def test_terminal_errors_surface_immediately(self):
        from greptimedb_tpu.storage.retry import (RetryingObjectStore,
                                                  configure_retry)
        configure_retry(max_retries=3, base_ms=1)
        inner = _FlakyStore(10, lambda: FileNotFoundError("k"))
        store = RetryingObjectStore(inner)
        with pytest.raises(FileNotFoundError):
            store.read("k")
        assert inner.calls == 1           # no retry on a logical 404

    def test_backoff_grows(self, monkeypatch):
        from greptimedb_tpu.storage import retry as retry_mod
        retry_mod.configure_retry(max_retries=3, base_ms=8)
        sleeps = []
        monkeypatch.setattr(retry_mod.time, "sleep", sleeps.append)
        inner = _FlakyStore(3, ConnectionResetError)
        store = retry_mod.RetryingObjectStore(inner)
        store.read.__func__  # noqa: B018 — touch to keep linters quiet
        inner.data["k"] = b"v"
        assert store.read("k") == b"v"
        assert len(sleeps) == 3
        # exponential with ±50% jitter: each window is [0.5, 1.5]×base·2ⁱ
        for i, s in enumerate(sleeps):
            base = 0.008 * (2 ** i)
            assert 0.5 * base <= s <= 1.5 * base

    def test_transient_classification(self):
        from greptimedb_tpu.storage.retry import is_transient
        from greptimedb_tpu.storage.s3 import S3Error, S3TransientError
        assert is_transient(S3TransientError("x"))
        assert not is_transient(S3Error("x"))
        assert is_transient(ConnectionResetError())
        assert is_transient(TimeoutError())
        assert not is_transient(FileNotFoundError("k"))
        assert not is_transient(ValueError("x"))
        assert is_transient(fp.FailpointError("x", transient=True))
        assert not is_transient(fp.FailpointError("x"))

    def test_set_knobs_apply_live(self, tmp_path):
        from greptimedb_tpu.storage import retry as retry_mod
        old = retry_mod.retry_settings()
        try:
            retry_mod.configure_retry(max_retries=7, base_ms=13)
            assert retry_mod.retry_settings() == {"max_retries": 7,
                                                 "base_ms": 13}
        finally:
            retry_mod.configure_retry(**old)


# ---------------------------------------------------------------------------
# S3 error taxonomy (satellite 1)
# ---------------------------------------------------------------------------

class TestS3Taxonomy:
    def test_status_classification(self):
        from greptimedb_tpu.storage.s3 import (S3Error, S3TransientError,
                                               _status_error)
        for st in (429, 500, 502, 503, 504):
            assert isinstance(_status_error("GET", "k", st),
                              S3TransientError)
        for st in (400, 403, 409, 412):
            e = _status_error("GET", "k", st)
            assert isinstance(e, S3Error)
            assert not isinstance(e, S3TransientError)

    def test_socket_error_is_transient(self):
        from greptimedb_tpu.storage.s3 import (S3Config, S3ObjectStore,
                                               S3TransientError)
        # nothing listens on this port: connection refused before any
        # status line → must classify transient, not raise raw OSError
        store = S3ObjectStore(S3Config(
            bucket="b", endpoint="http://127.0.0.1:1"))
        with pytest.raises(S3TransientError):
            store.read("k")


# ---------------------------------------------------------------------------
# graceful degradation (cache corruption → cold read)
# ---------------------------------------------------------------------------

class TestCacheDegradation:
    def test_read_cache_corruption_falls_back_cold(self, tmp_path):
        from greptimedb_tpu.storage.cache import LruCacheLayer
        from greptimedb_tpu.storage.object_store import FsObjectStore
        inner = FsObjectStore(str(tmp_path / "data"))
        cache = LruCacheLayer(inner, str(tmp_path / "cache"))
        inner.write("a/k", b"payload-bytes")
        assert cache.read("a/k") == b"payload-bytes"   # admit
        # corrupt the cached blob on disk (truncate)
        blob = cache._cache_path("a/k")
        with open(blob, "wb") as f:
            f.write(b"junk")
        # differential: the corrupted cache entry must not surface
        hits_before = cache.hits
        assert cache.read("a/k") == inner.read("a/k")
        # the corrupt read counts as a miss, NOT a hit-plus-miss
        assert cache.hits == hits_before
        # and the cache re-admitted a good copy
        assert cache.read("a/k") == b"payload-bytes"
        assert cache.hits == hits_before + 1

    def test_read_cache_io_error_falls_back_cold(self, tmp_path):
        from greptimedb_tpu.storage.cache import LruCacheLayer
        from greptimedb_tpu.storage.object_store import FsObjectStore
        inner = FsObjectStore(str(tmp_path / "data"))
        cache = LruCacheLayer(inner, str(tmp_path / "cache"))
        inner.write("a/k", b"v1")
        cache.read("a/k")
        with fp.cfg("cache_read", "err"):
            assert cache.read("a/k") == b"v1"          # injected IO error

    def test_scan_cache_corruption_falls_back_cold(self, tmp_path):
        """Differential: a poisoned incremental scan-cache refresh must
        rebuild cold and produce the same answer."""
        from greptimedb_tpu.query.tpu_exec import SCAN_CACHE
        rig = TortureRig(str(tmp_path))
        rig.create()
        rows = make_batch(0)
        rig.write(rows)
        SCAN_CACHE.get(rig.region)                    # prime the entry
        rows2 = make_batch(1)
        rig.write(rows2)                              # forces incremental
        with fp.cfg("scan_cache_incremental", "err"):
            scan = SCAN_CACHE.get(rig.region)
        assert SCAN_CACHE.last_outcome() == "full"
        got = {(rig.region.series_dict.decode_tag_column(
                    scan.series_ids, 0)[i], int(scan.ts[i]))
               for i in range(len(scan.ts))}
        assert got == set(rows) | set(rows2)
        rig.region.close()


# ---------------------------------------------------------------------------
# WAL torn tail (satellite 2)
# ---------------------------------------------------------------------------

class TestWalTornTail:
    def _wal(self, tmp_path, **kw):
        from greptimedb_tpu.storage.wal import Wal
        return Wal(str(tmp_path / "wal"), **kw)

    def test_torn_tail_truncates_and_warns(self, tmp_path, caplog):
        import logging as _logging
        w = self._wal(tmp_path)
        for seq in range(1, 4):
            w.append(seq, f"payload-{seq}".encode() * 10)
        w.close()
        seg = next(iter(sorted((tmp_path / "wal").glob("*.wal"))))
        good_size = seg.stat().st_size
        with open(seg, "ab") as f:        # simulate a half-written record
            f.write(b"\x50\x00\x00\x00torngarbage")
        w2 = self._wal(tmp_path)
        with caplog.at_level(_logging.WARNING):
            recs = list(w2.read_from(1))
        assert [r[0] for r in recs] == [1, 2, 3]
        assert any("truncating" in r.message for r in caplog.records)
        assert seg.stat().st_size == good_size         # physically repaired
        # appends after repair land cleanly and replay end-to-end
        w2.append(4, b"after-recovery")
        w2.close()
        w3 = self._wal(tmp_path)
        assert [r[0] for r in w3.read_from(1)] == [1, 2, 3, 4]
        w3.close()

    def test_torn_injection_on_live_wal_self_heals(self, tmp_path):
        """If the process SURVIVES an injected torn write (live server,
        not the torture rig), the next append must cut the garbage off —
        otherwise later acked records sit behind bytes replay cannot
        cross and are silently lost at the next restart."""
        from greptimedb_tpu.storage.wal import Wal
        w = Wal(str(tmp_path / "wal"))
        w.append(1, b"first-record")
        with fp.cfg("wal_append_torn", "crash"):
            with pytest.raises(fp.SimulatedCrash):
                w.append(2, b"torn-record")
        w.append(3, b"acked-after-tear")   # same live Wal object
        w.close()
        recs = list(Wal(str(tmp_path / "wal")).read_from(1))
        assert [r[0] for r in recs] == [1, 3]

    def test_crc_catches_corrupt_complete_record(self, tmp_path):
        w = self._wal(tmp_path)
        w.append(1, b"aaaa-bbbb-cccc")
        w.append(2, b"dddd-eeee-ffff")
        w.close()
        seg = next(iter(sorted((tmp_path / "wal").glob("*.wal"))))
        data = bytearray(seg.read_bytes())
        data[-3] ^= 0xFF                  # flip a payload byte of record 2
        seg.write_bytes(bytes(data))
        w2 = self._wal(tmp_path)
        recs = list(w2.read_from(1))
        assert [r[0] for r in recs] == [1]             # not silently replayed
        w2.close()

    def test_mid_log_corruption_still_raises(self, tmp_path):
        from greptimedb_tpu.errors import StorageError
        w = self._wal(tmp_path, segment_bytes=64)      # force tiny segments
        for seq in range(1, 5):
            w.append(seq, f"record-{seq}".encode() * 8)
        w.close()
        segs = sorted((tmp_path / "wal").glob("*.wal"))
        assert len(segs) >= 2
        first = segs[0]
        data = bytearray(first.read_bytes())
        data[-1] ^= 0xFF                  # corrupt an EARLIER segment
        first.write_bytes(bytes(data))
        w2 = self._wal(tmp_path, segment_bytes=64)
        with pytest.raises(StorageError):
            list(w2.read_from(1))
        w2.close()


# ---------------------------------------------------------------------------
# crash-recovery torture matrix (the tentpole invariant)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("point", sorted(CRASH_POINTS))
def test_torture_matrix(tmp_path, point):
    run_crash_case(str(tmp_path), point)


@pytest.mark.slow
@pytest.mark.parametrize("sync_wal", [False, True])
@pytest.mark.parametrize("point", sorted(CRASH_POINTS))
def test_torture_matrix_extended(tmp_path, point, sync_wal):
    """The extended sweep: both WAL fsync modes, deeper baselines."""
    run_crash_case(str(tmp_path), point, sync_wal=sync_wal,
                   baseline_batches=6)


def test_failed_wal_append_burns_its_sequence(tmp_path):
    """A WAL append that fails AFTER the record may be durable (fsync
    fault) must consume the sequence: reusing it would put two different
    batches at one seq and make the replay winner undefined."""
    from greptimedb_tpu.storage.write_batch import WriteBatch
    rig = TortureRig(str(tmp_path), sync_wal=True)
    rig.create()
    region = rig.region
    vc = region.version_control
    rig.write(make_batch(0))
    seq_before = vc.committed_sequence
    with fp.cfg("wal_fsync", "err"):
        wb = WriteBatch(region.schema)
        wb.put({"host": ["x"], "ts": [999_000], "v": [9.0]})
        with pytest.raises(fp.FailpointError):
            region.write(wb)
    # the failed write's sequence is consumed, not handed to the next one
    assert vc.committed_sequence == seq_before + 1
    rig.write(make_batch(1))
    assert vc.committed_sequence == seq_before + 2
    # reopen: the failed batch is durable in the WAL at its own seq and
    # replays exactly once alongside the acked batches — no collision
    rig2 = TortureRig(str(tmp_path), sync_wal=True)
    rig2.open()
    got = rig2.region.snapshot().read_merged()
    keys = list(zip(got.series_ids.tolist(), got.ts.tolist()))
    assert len(keys) == len(set(keys))
    assert 999_000 in got.ts
    rig2.region.close()


def test_sync_flush_reports_coalesced_background_failure(tmp_path):
    """flush() coalescing onto an already-queued background flush whose
    failure is swallowed for retry must still raise — /v1/admin/flush
    and bulk_ingest rely on success meaning 'the memtables are on disk'."""
    import threading
    from greptimedb_tpu.errors import StorageError
    from greptimedb_tpu.storage.engine import EngineConfig, StorageEngine
    from greptimedb_tpu.storage.write_batch import WriteBatch
    from torture import make_schema
    eng = StorageEngine(EngineConfig(data_home=str(tmp_path),
                                     bg_workers=1))
    region = eng.create_region("r", make_schema())
    release = threading.Event()
    eng.scheduler.submit("blocker", release.wait)   # pin the only worker
    region.flush_size_bytes = 1
    wb = WriteBatch(region.schema)
    wb.put({"host": ["a"], "ts": [1000], "v": [1.0]})
    region.write(wb)               # queues the background flush (held)
    result = {}

    def do_flush():
        try:
            result["files"] = region.flush()
        except StorageError as e:
            result["err"] = e

    with fp.cfg("flush_commit", "err"):
        th = threading.Thread(target=do_flush)
        th.start()
        time.sleep(0.2)            # let flush() coalesce onto the bg job
        release.set()
        th.join(timeout=30)
        assert not th.is_alive()
        assert "err" in result, \
            "sync flush reported success while its memtables stayed dirty"
    # fault cleared: the background retry ladder finishes the flush
    deadline = time.time() + 20
    while time.time() < deadline and \
            not region.version_control.current.ssts.all_files():
        time.sleep(0.05)
    assert region.version_control.current.ssts.all_files()
    eng.close()


def test_background_flush_failure_retries_with_backoff(tmp_path):
    """A failing background flush must not wedge the region: it records
    the failure (surfaced via /status), backs off, retries, and the
    retry succeeds once the fault clears."""
    from greptimedb_tpu.storage.engine import EngineConfig, StorageEngine
    from torture import make_schema
    from greptimedb_tpu.storage.write_batch import WriteBatch
    eng = StorageEngine(EngineConfig(data_home=str(tmp_path),
                                     flush_size_bytes=1))
    region = eng.create_region("r", make_schema())
    # first flush-commit attempt fails, the backoff retry succeeds
    with fp.cfg("flush_commit", "1x2*err"):
        wb = WriteBatch(region.schema)
        wb.put({"host": ["a"], "ts": [1000], "v": [1.0]})
        region.write(wb)                  # triggers the background flush
        deadline = time.time() + 20
        while time.time() < deadline:
            if region.version_control.current.ssts.all_files():
                break
            time.sleep(0.02)
    files = region.version_control.current.ssts.all_files()
    assert files, "background flush never recovered from the fault"
    assert region.bg_errors["flush"]["count"] == 1
    assert "FailpointError" in region.bg_errors["flush"]["last_error"]
    eng.close()


def test_flush_retry_after_drop_writes_nothing(tmp_path):
    """A delayed background-flush retry firing after DROP must not
    resurrect SSTs under the destroyed region dir (nothing would ever
    collect them — a dropped region never reopens)."""
    from greptimedb_tpu.storage.engine import EngineConfig, StorageEngine
    from greptimedb_tpu.storage.write_batch import WriteBatch
    from torture import make_schema
    eng = StorageEngine(EngineConfig(data_home=str(tmp_path)))
    region = eng.create_region("r", make_schema())
    region.flush_size_bytes = 1
    region_dir = region.descriptor.region_dir
    with fp.cfg("flush_commit", "err"):
        wb = WriteBatch(region.schema)
        wb.put({"host": ["a"], "ts": [1000], "v": [1.0]})
        region.write(wb)               # bg flush fails, retry queued
        deadline = time.time() + 10
        while time.time() < deadline and \
                not region.bg_errors.get("flush"):
            time.sleep(0.02)
        assert region.bg_errors.get("flush")
        eng.drop_region("r")           # destroys the region dir
    time.sleep(0.5)                    # let any pending retry fire
    leaked = [k for k in eng.store.list(region_dir)]
    assert not leaked, f"flush retry resurrected files: {leaked}"
    eng.close()


def test_meta_kv_crash_preserves_previous_value(tmp_path):
    from greptimedb_tpu.meta.kv import FileKv
    path = str(tmp_path / "meta" / "kv.json")
    kv = FileKv(path)
    kv.put("route/a", b"v1")
    with fp.cfg("meta_kv_put", "crash"):
        with pytest.raises(fp.SimulatedCrash):
            kv.put("route/a", b"v2")
    kv2 = FileKv(path)                    # reopen from disk
    assert kv2.get("route/a") == b"v1"    # atomic: never half-written
    kv2.put("route/a", b"v3")
    assert FileKv(path).get("route/a") == b"v3"


# ---------------------------------------------------------------------------
# end-to-end surfaces + acceptance shape
# ---------------------------------------------------------------------------

@pytest.fixture()
def frontend(tmp_path):
    from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                  DatanodeOptions)
    from greptimedb_tpu.frontend.instance import FrontendInstance
    dn = DatanodeInstance(DatanodeOptions(
        data_home=str(tmp_path), register_numbers_table=False))
    dn.start()
    fe = FrontendInstance(dn)
    fe.start()
    yield fe
    fe.shutdown()


def _rows(out):
    return [tuple(r) for b in out.batches for r in b.rows()]


class TestSurfaces:
    def test_set_and_information_schema(self, frontend):
        from greptimedb_tpu.session import QueryContext
        ctx = QueryContext()
        frontend.do_query("SET failpoint_wal_append = '1x4*err'", ctx)
        out = frontend.do_query(
            "SELECT name, action FROM information_schema.failpoints "
            "WHERE name = 'wal_append'", ctx)[-1]
        assert _rows(out) == [("wal_append", "1x4*err")]
        frontend.do_query("SET failpoint_wal_append = 'off'", ctx)
        out = frontend.do_query(
            "SELECT action FROM information_schema.failpoints "
            "WHERE name = 'wal_append'", ctx)[-1]
        assert _rows(out) == [(None,)]
        with pytest.raises(Exception):
            frontend.do_query("SET failpoint_wal_append = 'bogus'", ctx)

    def test_objstore_retry_knobs_via_set(self, frontend):
        from greptimedb_tpu.session import QueryContext
        from greptimedb_tpu.storage import retry as retry_mod
        ctx = QueryContext()
        old = retry_mod.retry_settings()
        try:
            frontend.do_query("SET objstore_max_retries = 9", ctx)
            frontend.do_query("SET objstore_retry_base_ms = 21", ctx)
            assert retry_mod.retry_settings() == {"max_retries": 9,
                                                  "base_ms": 21}
        finally:
            retry_mod.configure_retry(**old)

    def test_http_failpoint_admin(self, frontend):
        from greptimedb_tpu.servers.http import HttpServer
        srv = HttpServer(frontend, addr="127.0.0.1:0")
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}/v1/admin/failpoints"
            q = urllib.parse.urlencode(
                {"name": "flush_commit", "action": "err"})
            with urllib.request.urlopen(
                    urllib.request.Request(f"{base}?{q}", method="POST"),
                    timeout=10) as resp:
                assert json.loads(resp.read())["code"] == 0
            with urllib.request.urlopen(base, timeout=10) as resp:
                doc = json.loads(resp.read())
            armed = {p["name"]: p["action"] for p in doc["failpoints"]}
            assert armed["flush_commit"] == "err"
            # a POST without 'action' must 400, NOT silently disarm
            q2 = urllib.parse.urlencode({"name": "flush_commit"})
            try:
                urllib.request.urlopen(
                    urllib.request.Request(f"{base}?{q2}", method="POST"),
                    timeout=10)
                pytest.fail("action-less POST accepted")
            except urllib.error.HTTPError as e:
                assert e.code == 400
            assert fp.active_count() == 1
            # /status surfaces the armed count
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/status",
                    timeout=10) as resp:
                status = json.loads(resp.read())
            assert status["failpoints_active"] >= 1
            with urllib.request.urlopen(
                    urllib.request.Request(base, method="DELETE"),
                    timeout=10) as resp:
                assert json.loads(resp.read())["code"] == 0
            assert fp.active_count() == 0
        finally:
            srv.shutdown()

    def test_ingest_flush_scan_through_one_in_three_faults(self, frontend):
        """Acceptance: 1-in-3 transient object-store faults on write AND
        read; bulk ingest + flush + cold scan all succeed through retry,
        and the retry counter is visible in runtime_metrics."""
        from greptimedb_tpu.query import stream_exec
        from greptimedb_tpu.session import QueryContext
        from greptimedb_tpu.storage.retry import configure_retry, \
            retry_settings
        ctx = QueryContext()
        frontend.do_query(
            "CREATE TABLE fi (host STRING, ts TIMESTAMP TIME INDEX, "
            "v DOUBLE, PRIMARY KEY(host))", ctx)
        table = frontend.catalog.table("greptime", "public", "fi")
        n = 4000
        old = retry_settings()
        saved_threshold = stream_exec.stream_threshold_rows()
        configure_retry(base_ms=1)
        try:
            with fp.cfg("objstore_write", "1x3*err(transient)"):
                table.bulk_load({
                    "host": np.repeat(
                        np.array(["a", "b"], dtype=object), n // 2),
                    "ts": np.arange(n, dtype=np.int64) * 1000,
                    "v": np.ones(n)})
                table.flush()
            # cold scan (streamed path) with injected read faults
            stream_exec.configure_streaming(threshold_rows=1)
            from greptimedb_tpu.query.tpu_exec import SCAN_CACHE
            SCAN_CACHE._entries.clear()
            with fp.cfg("objstore_read", "1x3*err(transient)"):
                out = frontend.do_query(
                    "SELECT count(*), sum(v) FROM fi", ctx)[-1]
            assert _rows(out) == [(n, float(n))]
            out = frontend.do_query(
                "SELECT value FROM information_schema.runtime_metrics "
                "WHERE metric_name = 'greptime_objstore_retry_total'",
                ctx)[-1]
            rows = _rows(out)
            assert rows and rows[0][0] > 0
        finally:
            configure_retry(**old)
            stream_exec.configure_streaming(threshold_rows=saved_threshold)

    def test_flow_fold_commit_crash_never_double_folds(self, frontend):
        """Crash between the sink fold write and the watermark persist;
        after recovery the re-fold must be idempotent (sink == raw)."""
        from greptimedb_tpu.session import QueryContext
        ctx = QueryContext()
        frontend.do_query(
            "CREATE TABLE src (host STRING, ts TIMESTAMP TIME INDEX, "
            "v DOUBLE, PRIMARY KEY(host))", ctx)
        frontend.do_query(
            "CREATE FLOW f1 AS SELECT host, "
            "date_bin(INTERVAL '1 minute', ts) AS b, sum(v) AS s, "
            "count(v) AS c FROM src GROUP BY host, b", ctx)
        frontend.do_query(
            "INSERT INTO src VALUES ('a', 1000, 1.0), ('a', 2000, 2.0), "
            "('b', 61000, 3.0)", ctx)
        fm = frontend.datanode.flow_manager
        with fp.cfg("flow_fold_commit", "crash"):
            with pytest.raises(fp.SimulatedCrash):
                fm.tick()
        # simulated restart of the flow layer: reload specs + watermarks
        # from the durable store (the pre-crash watermark was never
        # persisted, so the window re-folds)
        fm._flows.clear()
        fm.recover()
        frontend.do_query(
            "INSERT INTO src VALUES ('b', 62000, 4.0)", ctx)
        fm.tick()
        sink = frontend.do_query(
            "SELECT host, s, c FROM f1 ORDER BY host", ctx)[-1]
        assert sorted(_rows(sink)) == [("a", 3.0, 2), ("b", 7.0, 2)]
