"""Protocol server tests: HTTP API, ingest protocols, snappy, auth.

Mirrors the reference integration matrix (tests-integration/tests/http.rs)
against a live server on an ephemeral port.
"""

import json
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from greptimedb_tpu.datanode import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.frontend import FrontendInstance
from greptimedb_tpu.servers.auth import StaticUserProvider
from greptimedb_tpu.servers.http import HttpServer
from greptimedb_tpu.servers import prometheus as prom
from greptimedb_tpu.utils import snappy


@pytest.fixture()
def server(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path)))
    fe = FrontendInstance(dn)
    fe.start()
    srv = HttpServer(fe, addr="127.0.0.1:0")
    srv.start()
    yield srv
    srv.shutdown()
    fe.shutdown()


def req(server, path, method="GET", body=None, headers=None, params=None,
        raise_on_error=True):
    url = f"http://127.0.0.1:{server.port}{path}"
    if params:
        url += "?" + urllib.parse.urlencode(params, doseq=True)
    r = urllib.request.Request(url, data=body, method=method,
                               headers=headers or {})
    try:
        with urllib.request.urlopen(r, timeout=10) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        if raise_on_error and e.code == 401:
            raise
        return e.code, e.read()


def sql(server, stmt):
    status, body = req(server, "/v1/sql", "POST",
                       urllib.parse.urlencode({"sql": stmt}).encode(),
                       {"Content-Type": "application/x-www-form-urlencoded"})
    assert status == 200, body
    return json.loads(body)


class TestSnappy:
    def test_round_trip(self):
        for payload in (b"", b"a", b"hello world " * 100,
                        bytes(range(256)) * 50):
            assert snappy.decompress(snappy.compress(payload)) == payload

    def test_backreference_decode(self):
        # handcrafted: literal 'abcd' + copy(offset=4, len=4) → 'abcdabcd'
        data = bytes([8]) + bytes([(4 - 1) << 2]) + b"abcd" + \
            bytes([0x01 | ((4 - 4) << 2)]) + bytes([4])
        assert snappy.decompress(data) == b"abcdabcd"


class TestHttpSql:
    def test_sql_round_trip(self, server):
        out = sql(server, "CREATE TABLE m (host STRING, ts TIMESTAMP TIME "
                          "INDEX, cpu DOUBLE, PRIMARY KEY(host))")
        assert out["code"] == 0
        out = sql(server, "INSERT INTO m VALUES ('a', 1000, 0.5)")
        assert out["output"][0]["affectedrows"] == 1
        out = sql(server, "SELECT * FROM m")
        rec = out["output"][0]["records"]
        assert [c["name"] for c in rec["schema"]["column_schemas"]] == \
            ["host", "ts", "cpu"]
        assert rec["rows"] == [["a", 1000, 0.5]]

    def test_sql_error(self, server):
        status, body = req(
            server, "/v1/sql", "POST",
            urllib.parse.urlencode({"sql": "SELECT * FROM missing"}).encode(),
            {"Content-Type": "application/x-www-form-urlencoded"})
        assert status == 400
        assert "not found" in json.loads(body)["error"]

    def test_get_with_query_param(self, server):
        status, body = req(server, "/v1/sql", params={"sql": "SELECT 1"})
        assert status == 200
        assert json.loads(body)["output"][0]["records"]["rows"] == [[1]]

    def test_health_status_metrics(self, server):
        assert req(server, "/health")[0] == 200
        status, body = req(server, "/status")
        assert json.loads(body)["version"]
        status, body = req(server, "/metrics")
        assert status == 200

    def test_status_shape(self, server):
        """/status reports uptime, region count, cache health and the
        latest ingest/scan profile summaries (ISSUE 2 satellite)."""
        sql(server, "CREATE TABLE st (host STRING, ts TIMESTAMP TIME "
                    "INDEX, v DOUBLE, PRIMARY KEY(host))")
        sql(server, "INSERT INTO st VALUES ('a', 1000, 1.0)")
        t = server.frontend.catalog.table("greptime", "public", "st")
        region = next(iter(t.regions.values()))
        region.bulk_ingest({"host": np.array(["b"], dtype=object),
                            "ts": np.array([2000], dtype=np.int64),
                            "v": np.array([2.0])})
        status, body = req(server, "/status")
        assert status == 200
        data = json.loads(body)
        for key in ("version", "uptime_s", "region_count",
                    "read_cache_hit_ratio", "scan_cache_resident_bytes",
                    "last_ingest_profile", "last_scan_profile"):
            assert key in data, f"/status missing {key}"
        assert data["uptime_s"] >= 0
        assert data["region_count"] >= 1
        # the bulk ingest above left a stage profile behind
        assert "rows" in data["last_ingest_profile"]
        # a scan leaves the scan twin behind
        t.flush()
        from greptimedb_tpu.query import stream_exec, tpu_exec
        old = stream_exec.stream_threshold_rows()
        old_floor = tpu_exec.TPU_DISPATCH_MIN_ROWS
        old_dt = tpu_exec._observed_min_dt[0]
        stream_exec.configure_streaming(threshold_rows=1)
        tpu_exec.TPU_DISPATCH_MIN_ROWS = 1
        tpu_exec._observed_min_dt[0] = None
        try:
            sql(server, "SELECT host, avg(v) FROM st GROUP BY host")
        finally:
            stream_exec.configure_streaming(threshold_rows=old)
            tpu_exec.TPU_DISPATCH_MIN_ROWS = old_floor
            tpu_exec._observed_min_dt[0] = old_dt
        status, body = req(server, "/status")
        data = json.loads(body)
        assert data["last_scan_profile"] is not None
        assert data["last_scan_profile"].startswith("streamed:")

    def test_runtime_metrics_matches_metrics_endpoint(self, server):
        """SELECT over information_schema.runtime_metrics returns the
        same counters /metrics exports, with the same values (ISSUE 2
        acceptance)."""
        sql(server, "CREATE TABLE rmm (host STRING, ts TIMESTAMP TIME "
                    "INDEX, v DOUBLE, PRIMARY KEY(host))")
        sql(server, "INSERT INTO rmm VALUES ('a', 1000, 1.0)")
        out = sql(server, "SELECT metric_name, value FROM "
                          "information_schema.runtime_metrics")
        table_vals = {}
        for name, value in out["output"][0]["records"]["rows"]:
            table_vals[name] = value
        assert "greptime_region_write_rows_total" in table_vals
        status, body = req(server, "/metrics")
        exported = {}
        for line in body.decode().splitlines():
            if line.startswith("#") or " " not in line:
                continue
            name, _, value = line.rpartition(" ")
            if "{" in name:
                name = name[:name.index("{")]
            try:
                exported.setdefault(name, float(value))
            except ValueError:
                continue
        # every label-free counter the endpoint exports is queryable
        # over SQL; values may drift between the two reads only for
        # metrics the comparison itself bumps, so check a quiet one
        assert "greptime_region_write_rows_total" in exported
        # the SELECT ran before /metrics: the write counter is stable
        # between the two reads (no writes in between)
        assert table_vals["greptime_region_write_rows_total"] == \
            exported["greptime_region_write_rows_total"]
        # and the table is a superset modulo the engine gauges
        missing = [n for n in exported
                   if n.startswith("greptime_") and n not in table_vals]
        assert not missing, f"runtime_metrics missing {missing[:5]}"

    def test_db_param(self, server):
        sql(server, "CREATE DATABASE db9")
        status, _ = req(
            server, "/v1/sql", "POST",
            urllib.parse.urlencode({
                "sql": "CREATE TABLE t (ts TIMESTAMP TIME INDEX, v DOUBLE)",
            }).encode(),
            {"Content-Type": "application/x-www-form-urlencoded"},
            params={"db": "db9"})
        assert status == 200
        out = sql(server, "SHOW TABLES FROM db9")
        names = [r[0] for r in out["output"][0]["records"]["rows"]]
        assert "t" in names


class TestLatencyHistograms:
    """ISSUE 6: log-bucketed latency histograms on /metrics (proper
    Prometheus histogram text format) and their p50/p95/p99 summaries in
    information_schema.runtime_metrics."""

    def _histogram_series(self, text, family):
        """{labelkey: [(le, count)...]}, plus _sum/_count presence."""
        import re
        buckets = {}
        saw_sum = saw_count = False
        for line in text.splitlines():
            if line.startswith("#"):
                continue
            if line.startswith(f"{family}_sum"):
                saw_sum = True
            if line.startswith(f"{family}_count"):
                saw_count = True
            m = re.match(rf"{family}_bucket\{{(.*)\}} (\S+)", line)
            if not m:
                continue
            labels, value = m.group(1), float(m.group(2))
            le = re.search(r'le="([^"]+)"', labels).group(1)
            key = re.sub(r'le="[^"]+",?', "", labels).strip(",")
            buckets.setdefault(key, []).append((float(le), value))
        return buckets, saw_sum, saw_count

    def test_prometheus_text_format_compliance(self, server):
        """_bucket/_sum/_count with le labels; cumulative buckets are
        monotone non-decreasing and end at le=+Inf == _count."""
        sql(server, "SELECT 1")       # at least one stmt observation
        status, body = req(server, "/metrics")
        assert status == 200
        text = body.decode()
        family = "greptime_stmt_latency_seconds"
        assert f"# TYPE {family} histogram" in text
        buckets, saw_sum, saw_count = self._histogram_series(text, family)
        assert saw_sum and saw_count and buckets
        import re
        counts_by_labels = {}
        for line in text.splitlines():
            m = re.match(rf"{family}_count\{{(.*)\}} (\S+)", line)
            if m:
                counts_by_labels[m.group(1)] = float(m.group(2))
        for key, series in buckets.items():
            les = [le for le, _ in series]
            assert les == sorted(les)
            assert les[-1] == float("inf"), "le=+Inf bucket required"
            values = [v for _, v in series]
            assert values == sorted(values), \
                f"buckets must be cumulative monotone: {series}"
            assert values[-1] == counts_by_labels[key], \
                "+Inf bucket must equal _count"

    def test_log_bucket_layout(self, server):
        """The primitive is log-bucketed: consecutive finite bounds keep
        a constant ratio (×2), not the prometheus linear default."""
        sql(server, "SELECT 1")
        _, body = req(server, "/metrics")
        buckets, _, _ = self._histogram_series(
            body.decode(), "greptime_stmt_latency_seconds")
        series = next(iter(buckets.values()))
        finite = [le for le, _ in series if le != float("inf")]
        ratios = {round(b / a, 6) for a, b in zip(finite, finite[1:])}
        assert ratios == {2.0}, finite

    def test_runtime_metrics_serves_quantiles(self, server):
        sql(server, "SELECT 1")
        out = sql(server,
                  "SELECT metric_name, value, kind FROM "
                  "information_schema.runtime_metrics WHERE metric_name "
                  "LIKE 'greptime_stmt_latency_seconds_p%'")
        rows = out["output"][0]["records"]["rows"]
        names = {r[0] for r in rows}
        assert {"greptime_stmt_latency_seconds_p50",
                "greptime_stmt_latency_seconds_p95",
                "greptime_stmt_latency_seconds_p99"} <= names
        for name, value, kind in rows:
            assert kind == "summary"
            assert 0.0 <= value < 60.0

    def test_http_route_latency_recorded(self, server):
        sql(server, "SELECT 1")
        _, body = req(server, "/metrics")
        text = body.decode()
        assert "greptime_http_request_seconds_bucket" in text
        assert 'route="/v1/sql"' in text


class TestTraceparentHeader:
    def test_sql_joins_external_trace(self, server, caplog):
        """A client-supplied W3C traceparent header threads through the
        executor: the slow-query log reports the client's trace id."""
        import logging
        from greptimedb_tpu.common.telemetry import (
            set_slow_query_threshold_ms)
        trace = "beadfeedbeadfeedbeadfeedbeadfeed"
        set_slow_query_threshold_ms(1)
        try:
            with caplog.at_level(logging.WARNING,
                                 logger="greptimedb_tpu.slow_query"):
                status, _ = req(
                    server, "/v1/sql", "POST",
                    urllib.parse.urlencode(
                        {"sql": "SELECT count(*) AS c FROM numbers a "
                                "CROSS JOIN numbers b"}).encode(),
                    {"Content-Type": "application/x-www-form-urlencoded",
                     "traceparent":
                         f"00-{trace}-00f067aa0ba902b7-01"})
        finally:
            set_slow_query_threshold_ms(None)
        assert status == 200
        slow = [r.getMessage() for r in caplog.records
                if "slow query" in r.getMessage()]
        assert slow and f"trace={trace}" in slow[-1]

    def test_malformed_traceparent_ignored(self, server):
        status, _ = req(
            server, "/v1/sql", "POST",
            urllib.parse.urlencode({"sql": "SELECT 1"}).encode(),
            {"Content-Type": "application/x-www-form-urlencoded",
             "traceparent": "garbage-header"})
        assert status == 200


class TestInfluxIngest:
    def test_line_protocol_write(self, server):
        body = (b"weather,location=us-midwest temperature=82.5 "
                b"1465839830100400200\n"
                b"weather,location=us-east temperature=75,humidity=32i "
                b"1465839830100400200")
        status, _ = req(server, "/v1/influxdb/write", "POST", body)
        assert status == 204
        out = sql(server, "SELECT location, temperature, humidity FROM "
                          "weather ORDER BY location")
        rows = out["output"][0]["records"]["rows"]
        assert rows == [["us-east", 75.0, 32], ["us-midwest", 82.5, None]]

    def test_precision(self, server):
        status, _ = req(server, "/v1/influxdb/write", "POST",
                        b"m1 v=1 1700000000", params={"precision": "s"})
        assert status == 204
        out = sql(server, "SELECT greptime_timestamp FROM m1")
        assert out["output"][0]["records"]["rows"][0][0] == 1700000000000


class TestOpenTsdb:
    def test_http_put(self, server):
        body = json.dumps([
            {"metric": "sys.cpu", "timestamp": 1700000000, "value": 18.0,
             "tags": {"host": "web01"}},
            {"metric": "sys.cpu", "timestamp": 1700000001, "value": 19.5,
             "tags": {"host": "web02"}},
        ]).encode()
        status, _ = req(server, "/v1/opentsdb/api/put", "POST", body,
                        {"Content-Type": "application/json"})
        assert status == 200
        out = sql(server, 'SELECT host, greptime_value FROM "sys.cpu" '
                          "ORDER BY host")
        assert out["output"][0]["records"]["rows"] == [
            ["web01", 18.0], ["web02", 19.5]]


class TestOpenTsdbTelnet:
    def test_telnet_put_over_raw_tcp(self, server):
        """The reference serves telnet `put` on its own TCP port
        (src/servers/src/opentsdb.rs:60-120); datapoints land in the
        metric's table, errors answer as text lines."""
        import socket

        from greptimedb_tpu.servers.opentsdb import OpentsdbServer
        tsdb = OpentsdbServer(server.frontend, host="127.0.0.1", port=0)
        tsdb.start()
        try:
            with socket.create_connection(("127.0.0.1", tsdb.port),
                                          timeout=10) as s:
                f = s.makefile("rwb")
                f.write(b"put tsd.cpu 1700000000 41.5 host=web01 dc=east\n"
                        b"put tsd.cpu 1700000001 43.0 host=web02 dc=west\n")
                f.flush()
                # version answers a line; also proves the puts were read
                f.write(b"version\n")
                f.flush()
                assert b"net.opentsdb" in f.readline()
                # a bad line answers an error line
                f.write(b"put tsd.cpu not_a_ts 1.0 host=a\n")
                f.flush()
                assert f.readline().startswith(b"error:")
                f.write(b"exit\n")
                f.flush()
            # telnet puts are synchronous per line: rows are queryable
            out = sql(server, 'SELECT host, dc, greptime_value FROM '
                              '"tsd.cpu" ORDER BY host')
            assert out["output"][0]["records"]["rows"] == [
                ["web01", "east", 41.5], ["web02", "west", 43.0]]
        finally:
            tsdb.shutdown()


class TestPrometheusRemote:
    def test_write_then_read(self, server):
        series = [
            prom.TimeSeries(
                labels={"__name__": "up", "job": "api", "instance": "i1"},
                samples=[(1.0, 1000), (0.0, 2000)]),
            prom.TimeSeries(
                labels={"__name__": "up", "job": "api", "instance": "i2"},
                samples=[(1.0, 1500)]),
        ]
        body = prom.encode_write_request(series)
        status, _ = req(server, "/v1/prometheus/write", "POST", body)
        assert status == 204
        out = sql(server, "SELECT instance, job, greptime_value FROM up "
                          "ORDER BY greptime_timestamp")
        assert out["output"][0]["records"]["rows"] == [
            ["i1", "api", 1.0], ["i2", "api", 1.0], ["i1", "api", 0.0]]

        # remote read round trip
        read_q = (prom.pw.field_bytes(1, (
            prom.pw.field_varint(1, 0) + prom.pw.field_varint(2, 5000) +
            prom.pw.field_bytes(3, (
                prom.pw.field_varint(1, prom.MATCH_EQ) +
                prom.pw.field_bytes(2, b"__name__") +
                prom.pw.field_bytes(3, b"up"))))))
        status, body = req(server, "/v1/prometheus/read", "POST",
                           snappy.compress(bytes(read_q)))
        assert status == 200
        decoded = snappy.decompress(body)
        text = decoded.decode("latin1")
        assert "job" in text and "api" in text and "instance" in text

    def test_prom_metadata_endpoints(self, server):
        series = [prom.TimeSeries(
            labels={"__name__": "cpu_seconds", "host": "a"},
            samples=[(0.5, 1000)])]
        req(server, "/v1/prometheus/write", "POST",
            prom.encode_write_request(series))
        status, body = req(server, "/api/v1/labels")
        data = json.loads(body)["data"]
        assert "host" in data and "__name__" in data
        status, body = req(server, "/api/v1/label/host/values")
        assert json.loads(body)["data"] == ["a"]
        status, body = req(server, "/api/v1/series",
                           params={"match[]": "cpu_seconds"})
        assert json.loads(body)["data"] == [
            {"__name__": "cpu_seconds", "host": "a"}]


class TestAuth:
    def test_basic_auth_required(self, tmp_path):
        dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path)))
        fe = FrontendInstance(dn)
        fe.start()
        provider = StaticUserProvider({"admin": "pwd123"})
        srv = HttpServer(fe, provider, addr="127.0.0.1:0")
        srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                req(srv, "/v1/sql", params={"sql": "SELECT 1"})
            assert err.value.code == 401
            import base64
            token = base64.b64encode(b"admin:pwd123").decode()
            status, body = req(srv, "/v1/sql", params={"sql": "SELECT 1"},
                               headers={"Authorization": f"Basic {token}"})
            assert status == 200
            with pytest.raises(urllib.error.HTTPError) as err:
                bad = base64.b64encode(b"admin:nope").decode()
                req(srv, "/v1/sql", params={"sql": "SELECT 1"},
                    headers={"Authorization": f"Basic {bad}"})
            assert err.value.code == 401
        finally:
            srv.shutdown()
            fe.shutdown()


class TestCli:
    def test_load_options_from_toml_and_flags(self, tmp_path):
        from greptimedb_tpu.cmd.main import load_options
        cfg = tmp_path / "config.toml"
        cfg.write_text("""
[storage]
data_home = "/tmp/x"
[http]
addr = "0.0.0.0:9999"
[mysql]
enable = false
""")
        import argparse
        args = argparse.Namespace(config_file=str(cfg),
                                  data_home=None, http_addr=None,
                                  mysql_addr="127.0.0.1:1234",
                                  postgres_addr=None, grpc_addr=None,
                                  user_provider=None)
        opts = load_options(args)
        assert opts.data_home == "/tmp/x"
        assert opts.http_addr == "0.0.0.0:9999"
        assert opts.mysql_addr == "127.0.0.1:1234"
        assert opts.enable_mysql is False


class TestPromApiQuery:
    """/api/v1/query{,_range} + /v1/promql end-to-end (reference:
    src/servers/src/prom.rs:70-95 — the round-1 gap where routes crashed)."""

    def _seed(self, server):
        sql(server, "CREATE TABLE qcpu (host STRING, ts TIMESTAMP TIME "
                    "INDEX, val DOUBLE, PRIMARY KEY(host))")
        rows = ",".join(
            f"('h{j}', {i * 10_000}, {float(i * (j + 1))})"
            for i in range(30) for j in range(2))
        sql(server, f"INSERT INTO qcpu VALUES {rows}")

    def test_query_range(self, server):
        self._seed(server)
        status, body = req(server, "/api/v1/query_range", params={
            "query": "rate(qcpu[1m])", "start": "120", "end": "240",
            "step": "60"})
        assert status == 200, body
        data = json.loads(body)
        assert data["status"] == "success"
        res = data["data"]
        assert res["resultType"] == "matrix"
        by_host = {r["metric"]["host"]: r for r in res["result"]}
        for _, v in by_host["h0"]["values"]:
            assert abs(float(v) - 0.1) < 1e-9
        for _, v in by_host["h1"]["values"]:
            assert abs(float(v) - 0.2) < 1e-9

    def test_instant_query(self, server):
        self._seed(server)
        status, body = req(server, "/api/v1/query", params={
            "query": "sum(qcpu)", "time": "100"})
        assert status == 200, body
        data = json.loads(body)
        res = data["data"]
        assert res["resultType"] == "vector"
        assert float(res["result"][0]["value"][1]) == 30.0

    def test_query_error_shape(self, server):
        status, body = req(server, "/api/v1/query", params={
            "query": "rate(", "time": "100"}, raise_on_error=False)
        assert status == 422
        data = json.loads(body)
        assert data["status"] == "error"

    def test_v1_promql(self, server):
        self._seed(server)
        status, body = req(server, "/v1/promql", params={
            "query": "qcpu", "start": "100", "end": "100", "step": "10s"})
        assert status == 200, body

    def test_series_endpoint_still_works(self, server):
        self._seed(server)
        status, body = req(server, "/api/v1/series",
                           params={"match[]": "qcpu"})
        assert status == 200
        data = json.loads(body)
        hosts = {e.get("host") for e in data["data"]}
        assert hosts == {"h0", "h1"}

    def test_query_range_explain_param(self, server):
        """?explain=1 returns the plan/dispatch lines instead of data —
        the HTTP twin of TQL EXPLAIN (ISSUE 16)."""
        self._seed(server)
        status, body = req(server, "/api/v1/query_range", params={
            "query": "sum by (host) (rate(qcpu[1m]))", "start": "0",
            "end": "240", "step": "60", "explain": "1"})
        assert status == 200, body
        data = json.loads(body)
        assert data["status"] == "success"
        assert data["data"]["resultType"] == "explain"
        joined = "\n".join(data["data"]["result"])
        assert "PromSeriesScan: qcpu" in joined
        assert "Dispatch:" in joined


class TestAdminCompact:
    def test_flush_then_compact_endpoint(self, server):
        sql(server, "CREATE TABLE ac (host STRING, ts TIMESTAMP TIME INDEX,"
                    " cpu DOUBLE, PRIMARY KEY(host))")
        for gen in range(2):
            sql(server, f"INSERT INTO ac VALUES ('a', 1, {gen}.0)")
            req(server, "/v1/admin/flush?table=ac", "POST", b"")
        status, body = req(server, "/v1/admin/compact?table=ac", "POST", b"")
        assert status == 200
        t = server.frontend.catalog.table("greptime", "public", "ac")
        region = next(iter(t.regions.values()))
        assert len(region.version_control.current.ssts.levels[1]) == 1
        out = sql(server, "SELECT cpu FROM ac")
        assert out["output"][0]["records"]["rows"] == [[1.0]]


class TestAdminDownsample:
    def test_downsample_endpoint(self, server):
        sql(server, "CREATE TABLE ds_raw (host STRING, ts TIMESTAMP TIME"
                    " INDEX, v DOUBLE, PRIMARY KEY(host))")
        sql(server, "CREATE TABLE ds_agg (host STRING, ts TIMESTAMP TIME"
                    " INDEX, v DOUBLE, PRIMARY KEY(host))")
        rows = ",".join(f"('h{i % 2}', {i * 1000}, {float(i)})"
                        for i in range(240))
        sql(server, f"INSERT INTO ds_raw VALUES {rows}")
        status, body = req(server,
                           "/v1/admin/downsample?src=ds_raw&dst=ds_agg"
                           "&stride=60s&agg=avg", "POST", b"")
        assert status == 200, body
        data = json.loads(body)
        assert data["code"] == 0
        assert data["rows_written"] == 8      # 2 hosts x 4 minutes
        out = sql(server, "SELECT count(*) FROM ds_agg")
        assert out["output"][0]["records"]["rows"][0][0] == 8
        out = sql(server, "SELECT v FROM ds_agg WHERE host = 'h0'"
                          " ORDER BY ts LIMIT 1")
        # first minute of h0: even i in [0, 60) -> mean 29
        assert out["output"][0]["records"]["rows"][0][0] == 29.0

    def test_downsample_bad_args(self, server):
        status, body = req(server,
                           "/v1/admin/downsample?src=nope&dst=nope"
                           "&stride=60s", "POST", b"")
        assert status == 404
        status, body = req(server, "/v1/admin/downsample?src=a",
                           "POST", b"")
        assert status == 400
