"""Pallas kernel tests (interpret mode on the CPU mesh).

ops/pallas_window.py documents the measured outcome on real v5e: the
XLA fused compare-reduce stays the production window-bounds path. The
kernel itself must stay correct — it is the in-tree Pallas harness.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from greptimedb_tpu.ops.pallas_window import counts_leq_pallas  # noqa: E402


@pytest.mark.parametrize("shape,steps", [
    ((8, 512), 128),        # exact tiles
    ((20, 300), 97),        # ragged everything
    ((1, 1), 1),            # minimal
    ((130, 1030), 200),     # pad across both grid dims
])
def test_counts_leq_matches_oracle(shape, steps):
    rng = np.random.default_rng(hash(shape) % 2**32)
    b = np.sort(rng.integers(0, steps + 1, shape).astype(np.int32), axis=1)
    got = np.asarray(counts_leq_pallas(jnp.asarray(b), steps,
                                       interpret=True))
    want = (b[:, :, None] <= np.arange(steps)).sum(1)
    np.testing.assert_array_equal(got, want)


def test_out_of_range_buckets_excluded():
    b = np.array([[0, 2, 5, 5, 5]], np.int32)   # 5 == nsteps → no step
    got = np.asarray(counts_leq_pallas(jnp.asarray(b), 5, interpret=True))
    assert got[0].tolist() == [1, 1, 2, 2, 2]
