"""Crash-recovery torture harness: the failpoint-driven crash matrix.

For every registered crash point in the storage stack, one case:

1. build a region on disk and ingest acknowledged batches (interleaved
   writes, flushes, compactions — enough state that every recovery path
   has something to get wrong);
2. arm the crash point (``crash`` action) and drive the operation that
   reaches it until :class:`SimulatedCrash` fires;
3. simulate the kill: drop the region object with **no** close/flush —
   the only state the next lifetime may rely on is what hit disk;
4. reopen the region from the same home and assert the invariants:

   - **no acked row lost** — every acknowledged (host, ts) key is
     present with its written value;
   - **no row duplicated** — no (series, ts) key appears twice in a raw
     (pre-dedup) scan: a WAL entry replayed on top of its flushed copy,
     or a manifest edit applied twice, shows up here;
   - **unacked rows appear at most once, or not at all** — a batch that
     crashed mid-write may legally be durable (it hit the WAL) but must
     never be half-applied or doubled; rows whose commit point was never
     reached (bulk ingest) must be absent;
   - **manifest references only existing SSTs** — no dangling file names;
   - **no orphan SSTs** — files a crashed flush/compaction/bulk-ingest
     left behind are swept by the reopen;
5. prove the reopened region is alive: one more acked write + flush +
   scan round-trips.

tests/test_fault_injection.py parametrizes this matrix as tier-1 tests
(quick shapes) and as a `slow`-marked extended sweep (both WAL fsync
modes). The harness is importable on its own::

    python -c "import tests.torture as t; print(t.run_all('/tmp/tort'))"
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from greptimedb_tpu.common import failpoint as fp
from greptimedb_tpu.datatypes import Schema
from greptimedb_tpu.datatypes.data_type import (FLOAT64, STRING,
                                                TIMESTAMP_MILLISECOND)
from greptimedb_tpu.datatypes.schema import ColumnSchema, SemanticType
from greptimedb_tpu.storage.file_purger import FilePurger
from greptimedb_tpu.storage.object_store import FsObjectStore
from greptimedb_tpu.storage.region import Region, RegionDescriptor
from greptimedb_tpu.storage.wal import Wal
from greptimedb_tpu.storage.write_batch import WriteBatch

BASE_HOSTS = ("h0", "h1", "h2")
ROWS_PER_BATCH = 24


def make_schema() -> Schema:
    return Schema([
        ColumnSchema("host", STRING, nullable=False,
                     semantic_type=SemanticType.TAG),
        ColumnSchema("ts", TIMESTAMP_MILLISECOND, nullable=False,
                     semantic_type=SemanticType.TIMESTAMP),
        ColumnSchema("v", FLOAT64),
    ])


def make_batch(i: int, n: int = ROWS_PER_BATCH) -> Dict[Tuple[str, int], float]:
    """Batch i: unique (host, ts) keys whose ts ranges OVERLAP across
    batches (so compactions really merge instead of trivially moving
    disjoint files), plus one host this batch introduces (so every flush
    has fresh series and the dict-persist crash point is reachable)."""
    rows: Dict[Tuple[str, int], float] = {}
    hosts = BASE_HOSTS + (f"n{i}",)
    for j in range(n):
        host = hosts[j % len(hosts)]
        ts = i + j * 1000          # i < 1000 keeps keys globally unique
        rows[(host, ts)] = float(ts) * 0.5 + i
    return rows


class TortureRig:
    """One simulated datanode lifetime over a shared on-disk home.
    Synchronous everywhere (no scheduler) so an armed crash propagates
    to the driver instead of dying on a worker thread; purges run on
    demand with zero grace so the purger crash point is drivable."""

    def __init__(self, home: str, *, sync_wal: bool = False,
                 checkpoint_margin: int = 10):
        self.home = home
        self.sync_wal = sync_wal
        self.checkpoint_margin = checkpoint_margin
        self.store = FsObjectStore(os.path.join(home, "data"))
        self.purger = FilePurger(grace_s=0.0)
        self.schema = make_schema()
        self.region: Optional[Region] = None

    def _desc(self) -> RegionDescriptor:
        return RegionDescriptor(
            name="torture", schema=self.schema, region_dir="torture",
            wal_dir=os.path.join(self.home, "wal"))

    def _wal(self) -> Wal:
        return Wal(os.path.join(self.home, "wal"),
                   sync_on_write=self.sync_wal)

    def _kwargs(self) -> dict:
        return dict(wal=self._wal(), scheduler=None, purger=self.purger,
                    checkpoint_margin=self.checkpoint_margin,
                    max_l0_files=10_000)   # compaction only when driven

    def create(self) -> None:
        self.region = Region.create(self._desc(), self.store,
                                    **self._kwargs())

    def open(self) -> None:
        self.region = Region.open(self._desc(), self.store,
                                  **self._kwargs())
        assert self.region is not None, "region vanished across the crash"

    def write(self, rows: Dict[Tuple[str, int], float]) -> None:
        wb = WriteBatch(self.region.schema)
        wb.put({"host": [k[0] for k in rows],
                "ts": [k[1] for k in rows],
                "v": list(rows.values())})
        self.region.write(wb)

    def bulk(self, rows: Dict[Tuple[str, int], float]) -> None:
        self.region.bulk_ingest({
            "host": np.array([k[0] for k in rows], dtype=object),
            "ts": np.array([k[1] for k in rows], dtype=np.int64),
            "v": np.array(list(rows.values()), dtype=np.float64)})


# ---------------------------------------------------------------------------
# invariants
# ---------------------------------------------------------------------------

def recovered_rows(region: Region) -> Dict[Tuple[str, int], float]:
    """(host, ts) → v from a merged (MVCC-deduped) scan."""
    data = region.snapshot().read_merged()
    hosts = region.series_dict.decode_tag_column(data.series_ids, 0)
    vals = data.fields["v"][0]
    return {(h, int(t)): float(v)
            for h, t, v in zip(hosts, data.ts, vals)}


def check_invariants(region: Region,
                     acked: Dict[Tuple[str, int], float],
                     maybe: Dict[Tuple[str, int], float]) -> None:
    # 1. raw (pre-dedup) scan: every (series, ts) key at most once —
    #    unique-key ingest means ANY raw duplicate is a double-apply
    raw = region.snapshot().scan()
    raw_keys = list(zip(raw.series_ids.tolist(), raw.ts.tolist()))
    assert len(raw_keys) == len(set(raw_keys)), \
        "rows duplicated after recovery (replay on top of flushed data?)"
    got = recovered_rows(region)
    # 2. no acked row lost, values intact
    for key, v in acked.items():
        assert key in got, f"acked row {key} lost in the crash"
        assert got[key] == v, \
            f"acked row {key}: value {got[key]} != written {v}"
    # 3. nothing beyond acked ∪ maybe-durable-inflight
    for key in got:
        assert key in acked or key in maybe, \
            f"phantom row {key} appeared after recovery"
    # 4. manifest references only existing SSTs — and only existing
    #    index sidecars: a committed FileMeta must NEVER name a sidecar
    #    that is not on disk (matrix point 16: the sidecar is written
    #    before the manifest edit that references it, so a crash between
    #    SST data write and index publish leaves both unreferenced)
    for f in region.version_control.current.ssts.all_files():
        key = f"{region.descriptor.region_dir}/sst/{f.file_name}"
        assert region.store.exists(key), \
            f"manifest references missing SST {f.file_name}"
        if f.index_file is not None:
            ikey = f"{region.descriptor.region_dir}/sst/{f.index_file}"
            assert region.store.exists(ikey), \
                f"dangling index sidecar ref {f.index_file}"
    # 5. no orphan SSTs (or index sidecars) survive the reopen sweep
    referenced = set()
    for f in region.version_control.current.ssts.all_files():
        referenced.add(f.file_name)
        if f.index_file is not None:
            referenced.add(f.index_file)
    on_disk = {k.rsplit("/", 1)[-1]
               for k in region.store.list(
                   f"{region.descriptor.region_dir}/sst/")}
    orphans = on_disk - referenced
    assert not orphans, f"orphan SSTs survived reopen: {orphans}"


# ---------------------------------------------------------------------------
# drivers: reach each crash point from a realistic op sequence.
# Each returns the inflight rows that may LEGALLY be visible after
# recovery (durable before the crash but never acknowledged).
# ---------------------------------------------------------------------------

def _drive_write(rig: TortureRig, point: str, batch_no: int,
                 durable_ok: bool) -> Dict:
    rows = make_batch(batch_no)
    with fp.cfg(point, "crash"):
        try:
            rig.write(rows)
        except fp.SimulatedCrash:
            return rows if durable_ok else {}
    raise AssertionError(f"crash point {point} never fired")


def _drive_flush(rig: TortureRig, point: str, batch_no: int,
                 acked: Dict) -> Dict:
    rows = make_batch(batch_no)
    rig.write(rows)
    acked.update(rows)                    # write() returned: acked
    with fp.cfg(point, "crash"):
        try:
            rig.region.flush()
        except fp.SimulatedCrash:
            return {}
    raise AssertionError(f"crash point {point} never fired")


def _drive_bulk(rig: TortureRig, point: str, batch_no: int) -> Dict:
    rows = make_batch(batch_no)
    with fp.cfg(point, "crash"):
        try:
            rig.bulk(rows)
        except fp.SimulatedCrash:
            return {}                     # commit never landed: must vanish
    raise AssertionError(f"crash point {point} never fired")


def _drive_compact(rig: TortureRig, point: str) -> Dict:
    with fp.cfg(point, "crash"):
        try:
            rig.region.compact()
        except fp.SimulatedCrash:
            return {}
    raise AssertionError(f"crash point {point} never fired")


def _drive_purge(rig: TortureRig, point: str) -> Dict:
    rig.region.compact()                  # queues input files for purge
    with fp.cfg(point, "crash"):
        try:
            rig.purger.sweep()
        except fp.SimulatedCrash:
            return {}
    raise AssertionError(f"crash point {point} never fired")


#: point → (driver kind, durable_ok) — the full crash matrix
CRASH_POINTS: Dict[str, Tuple[str, bool]] = {
    "wal_append":           ("write", False),
    "wal_append_torn":      ("write", False),
    "wal_fsync":            ("write", True),   # record written pre-fsync
    # crash between a cohort's record write and the SHARED group-commit
    # fsync: the cohort (unacked) may be lost or may surface once —
    # never an acked row (the group-commit durability contract)
    "wal_group_commit":     ("write", True),
    "region_write_memtable": ("write", True),  # WAL holds it already
    "sst_write":            ("flush", False),
    "sst_write_after":      ("flush", False),
    # matrix point 16: crash between the SST data write and the index-
    # sidecar publish — reopen must see both or neither (the data file
    # is an unreferenced orphan the sweep collects; a committed manifest
    # can never carry a dangling index ref)
    "sst_index_write":      ("flush", False),
    "dict_persist":         ("flush", False),
    "flush_commit":         ("flush", False),
    "manifest_commit":      ("flush", False),
    "manifest_checkpoint":  ("flush", False),
    "objstore_write":       ("flush", False),
    "bulk_commit":          ("bulk", False),
    "compaction_commit":    ("compact", False),
    "purger_delete":        ("purge", False),
}


def run_crash_case(home: str, point: str, *,
                   sync_wal: bool = False,
                   baseline_batches: int = 3) -> Dict:
    """One cell of the crash matrix; raises AssertionError on any
    invariant violation. Returns a small result dict for reporting."""
    kind, durable_ok = CRASH_POINTS[point]
    if point in ("wal_fsync", "wal_group_commit"):
        sync_wal = True                   # the points only exist then
    if point == "wal_group_commit":
        # the cohort wait only runs with group commit on (the default;
        # pinned here so the case survives knob-twiddling tests)
        from greptimedb_tpu.storage.wal import configure_group_commit
        configure_group_commit(enabled=True)
    checkpoint_margin = 1 if point == "manifest_checkpoint" else 10
    fp.clear_all()
    rig = TortureRig(home, sync_wal=sync_wal,
                     checkpoint_margin=checkpoint_margin)
    rig.create()
    acked: Dict[Tuple[str, int], float] = {}
    # baseline: interleaved writes and flushes → overlapping L0 files,
    # rows in SSTs AND rows only in the WAL at crash time
    for i in range(baseline_batches):
        rows = make_batch(i)
        rig.write(rows)
        acked.update(rows)
        if i % 2 == 0:
            rig.region.flush()
    if kind in ("compact", "purge"):
        rig.region.flush()                # compactions need L0 inputs

    batch_no = baseline_batches
    if kind == "write":
        maybe = _drive_write(rig, point, batch_no, durable_ok)
    elif kind == "flush":
        maybe = _drive_flush(rig, point, batch_no, acked)
    elif kind == "bulk":
        maybe = _drive_bulk(rig, point, batch_no)
    elif kind == "compact":
        maybe = _drive_compact(rig, point)
    else:
        maybe = _drive_purge(rig, point)
    fp.clear_all()

    # simulated kill: the region object is abandoned un-closed; only
    # what is on disk carries over
    rig2 = TortureRig(home, sync_wal=sync_wal,
                      checkpoint_margin=checkpoint_margin)
    rig2.open()
    check_invariants(rig2.region, acked, maybe)

    # post-recovery liveness: ack one more batch through a full cycle
    rows = make_batch(batch_no + 1)
    rig2.write(rows)
    acked.update(rows)
    rig2.region.flush()
    check_invariants(rig2.region, acked, maybe)
    rig2.region.close()
    return {"point": point, "acked_rows": len(acked),
            "maybe_rows": len(maybe)}


def run_all(base_dir: str, *, sync_wal: bool = False) -> Dict[str, Dict]:
    """The whole matrix, one fresh home per point (CLI convenience)."""
    results = {}
    for point in CRASH_POINTS:
        home = os.path.join(base_dir, point)
        os.makedirs(home, exist_ok=True)
        results[point] = run_crash_case(home, point, sync_wal=sync_wal)
    return results
