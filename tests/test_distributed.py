"""Distributed plane tests: meta service, routes, failure detection,
DistTable DDL/insert/query with aggregate pushdown.

Mirrors the reference's in-process multi-node topology
(`MockDistributedInstance`: frontend + N datanode instances + meta over a
MemStore — src/frontend/src/tests.rs:60,264-330, meta-srv/src/mocks.rs)
and the phi-detector statistics tests (failure_detector.rs:180-546).
"""

import math
import time

import numpy as np
import pytest

from greptimedb_tpu.client import LocalDatanodeClient
from greptimedb_tpu.datanode import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.frontend.distributed import DistInstance, DistTable
from greptimedb_tpu.meta import (
    DatanodeStat, MemKv, MetaClient, MetaSrv, NoAliveDatanodeError, Peer,
    PhiAccrualFailureDetector)
from greptimedb_tpu.session import QueryContext
from greptimedb_tpu.sql import parse_sql


# ---------------------------------------------------------------------------
# failure detector (reference failure_detector.rs tests)
# ---------------------------------------------------------------------------

class TestPhiDetector:
    def test_regular_heartbeats_low_phi(self):
        d = PhiAccrualFailureDetector()
        t = 0.0
        for _ in range(50):
            d.heartbeat(t)
            t += 1000.0
        assert d.phi(t + 500) < 1.0
        assert d.is_available(t + 1000)

    def test_phi_grows_with_silence(self):
        rng = np.random.default_rng(1)
        d = PhiAccrualFailureDetector(acceptable_heartbeat_pause_ms=0.0)
        t = 0.0
        for _ in range(50):
            d.heartbeat(t)
            t += float(rng.normal(1000.0, 300.0))
        p1 = d.phi(t + 1500)
        p2 = d.phi(t + 2500)
        p3 = d.phi(t + 4000)
        assert p1 < p2 < p3
        assert not d.is_available(t + 60000)

    def test_irregular_interval_tolerance(self):
        rng = np.random.default_rng(3)
        d = PhiAccrualFailureDetector()
        t = 0.0
        for _ in range(200):
            d.heartbeat(t)
            t += float(rng.normal(1000.0, 200.0))
        # a pause within the acceptable envelope stays available
        assert d.is_available(t + 3000)

    def test_no_heartbeat_yet(self):
        d = PhiAccrualFailureDetector()
        assert d.phi(12345.0) == 0.0
        assert d.is_available(12345.0)


# ---------------------------------------------------------------------------
# meta service
# ---------------------------------------------------------------------------

class TestMetaSrv:
    def test_register_and_lease(self):
        srv = MetaSrv(datanode_lease_secs=10)
        srv.register_datanode(Peer(1, "dn1"))
        srv.register_datanode(Peer(2, "dn2"))
        now = time.time()
        assert {p.id for p in srv.alive_datanodes(now)} == {1, 2}
        # lease expiry
        assert srv.alive_datanodes(now + 100) == []

    def test_route_placement_load_based(self):
        srv = MetaSrv(selector="load_based")
        for i in (1, 2):
            srv.register_datanode(Peer(i))
            srv.handle_heartbeat(i)
        srv.handle_heartbeat(1, DatanodeStat(region_count=5))
        srv.handle_heartbeat(2, DatanodeStat(region_count=0))
        route = srv.create_table_route("c.s.t", [0, 1, 2])
        # node 2 (least loaded) gets the first region
        assert route.region_routes[0].leader.id == 2
        assert len(route.region_routes) == 3
        # spread across both nodes round-robin
        assert {r.leader.id for r in route.region_routes} == {1, 2}

    def test_route_persistence_and_duplicate(self):
        kv = MemKv()
        srv = MetaSrv(kv)
        srv.register_datanode(Peer(1))
        srv.handle_heartbeat(1)
        route = srv.create_table_route("c.s.t", [0])
        assert srv.table_route("c.s.t").table_id == route.table_id
        with pytest.raises(Exception):
            srv.create_table_route("c.s.t", [0])
        assert srv.delete_table_route("c.s.t")
        assert srv.table_route("c.s.t") is None

    def test_no_alive_datanodes(self):
        srv = MetaSrv()
        with pytest.raises(NoAliveDatanodeError):
            srv.create_table_route("c.s.t", [0])

    def test_table_id_sequence(self):
        srv = MetaSrv()
        a = srv.allocate_table_id()
        b = srv.allocate_table_id()
        assert b == a + 1 and a >= 1024

    def test_mailbox_rides_heartbeat(self):
        srv = MetaSrv()
        srv.register_datanode(Peer(1))
        srv.send_mailbox(1, {"type": "flush_table", "t": "x"})
        resp = srv.handle_heartbeat(1)
        assert resp.mailbox == [{"type": "flush_table", "t": "x"}]
        assert srv.handle_heartbeat(1).mailbox == []

    def test_failed_datanode_detection(self):
        srv = MetaSrv(phi_threshold=8.0)
        srv.register_datanode(Peer(1))
        t = time.time()
        for i in range(30):
            srv.handle_heartbeat(1, now=t + i)
        # an hour of silence → suspected
        assert [p.id for p in srv.failed_datanodes(t + 3600)] == [1]
        assert srv.alive_datanodes(t + 3600) == []


# ---------------------------------------------------------------------------
# distributed DDL / insert / query
# ---------------------------------------------------------------------------

@pytest.fixture()
def cluster(tmp_path):
    """Frontend + 2 in-process datanodes + meta over MemKv."""
    datanodes = {}
    clients = {}
    for i in (1, 2):
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / f"dn{i}"), node_id=i,
            register_numbers_table=False))
        dn.start()
        datanodes[i] = dn
        clients[i] = LocalDatanodeClient(dn)
    srv = MetaSrv(MemKv())
    meta = MetaClient(srv)
    for i, dn in datanodes.items():
        srv.register_datanode(Peer(i, f"dn{i}"))
        dn.start_heartbeat(meta, interval_s=3600)   # one immediate beat
    fe = DistInstance(meta, clients)
    yield fe, datanodes, srv
    for dn in datanodes.values():
        dn.shutdown()


DDL = """
CREATE TABLE dist (host STRING, ts TIMESTAMP TIME INDEX, cpu DOUBLE,
                   PRIMARY KEY(host))
PARTITION BY RANGE COLUMNS (host) (
  PARTITION r0 VALUES LESS THAN ('h5'),
  PARTITION r1 VALUES LESS THAN (MAXVALUE))
"""


class TestDistributedDDL:
    def test_create_places_regions_on_both_nodes(self, cluster):
        fe, datanodes, srv = cluster
        fe.do_query(DDL)
        route = srv.table_route("greptime.public.dist")
        assert route is not None
        owners = {r.leader.id for r in route.region_routes}
        assert owners == {1, 2}
        # each datanode hosts exactly its assigned region
        for i, dn in datanodes.items():
            t = dn.catalog.table("greptime", "public", "dist")
            assert t is not None
            assert set(t.regions) == set(route.regions_on(i))

    def test_drop_removes_everywhere(self, cluster):
        fe, datanodes, srv = cluster
        fe.do_query(DDL)
        fe.do_query("DROP TABLE dist")
        assert srv.table_route("greptime.public.dist") is None
        for dn in datanodes.values():
            assert dn.catalog.table("greptime", "public", "dist") is None

    def test_create_failure_rolls_back_route(self, cluster):
        fe, datanodes, srv = cluster
        # sabotage one datanode's DDL
        bad = fe.clients[2]
        orig = bad.ddl_create_table
        bad.ddl_create_table = lambda req: (_ for _ in ()).throw(
            RuntimeError("boom"))
        with pytest.raises(RuntimeError):
            fe.do_query(DDL)
        assert srv.table_route("greptime.public.dist") is None
        bad.ddl_create_table = orig
        fe.do_query(DDL)          # now succeeds


class TestDistributedData:
    def _seed(self, fe, n_hosts=8, rows_per=20):
        fe.do_query(DDL)
        vals = []
        for h in range(n_hosts):
            for i in range(rows_per):
                vals.append(f"('h{h}', {i * 1000}, {float(h * 100 + i)})")
        fe.do_query("INSERT INTO dist VALUES " + ",".join(vals))

    def test_insert_splits_by_rule(self, cluster):
        fe, datanodes, srv = cluster
        self._seed(fe)
        route = srv.table_route("greptime.public.dist")
        # region 0: hosts h0..h4, region 1: h5..h7 — on their owners
        counts = {}
        for i, dn in datanodes.items():
            t = dn.catalog.table("greptime", "public", "dist")
            for rn, region in t.regions.items():
                data = region.snapshot().read_merged()
                counts[rn] = data.num_rows
        assert counts[0] == 5 * 20 and counts[1] == 3 * 20

    def test_aggregate_pushdown_query(self, cluster):
        fe, datanodes, srv = cluster
        self._seed(fe)
        out = fe.do_query("SELECT host, avg(cpu) AS a, count(*) AS c "
                          "FROM dist GROUP BY host ORDER BY host")[-1]
        rows = out.batches[0].to_pylist()
        assert len(rows) == 8
        for h, r in enumerate(rows):
            assert r["host"] == f"h{h}" and r["c"] == 20
            assert math.isclose(r["a"], h * 100 + 9.5, rel_tol=1e-6)

    def test_pushdown_goes_through_clients(self, cluster):
        fe, datanodes, srv = cluster
        self._seed(fe)
        calls = []
        for c in fe.clients.values():
            orig = c.region_moments
            c.region_moments = (lambda *a, _o=orig, **kw: (calls.append(1),
                                                           _o(*a, **kw))[1])
        out = fe.do_query("SELECT count(*) AS c FROM dist")[-1]
        assert out.batches[0].to_pylist()[0]["c"] == 160
        assert len(calls) == 2, "pushdown did not fan out to both clients"

    def test_cross_region_first_last(self, cluster):
        fe, *_ = cluster
        fe.do_query(DDL)
        fe.do_query("INSERT INTO dist VALUES ('h1', 100, 111.0), "
                    "('h9', 50, 999.0), ('h9', 300, 7.0)")
        out = fe.do_query("SELECT first(cpu) AS f, last(cpu) AS l "
                          "FROM dist")[-1]
        row = out.batches[0].to_pylist()[0]
        assert row["f"] == 999.0 and row["l"] == 7.0

    def test_fallback_scan_path(self, cluster):
        fe, *_ = cluster
        self._seed(fe)
        out = fe.do_query("SELECT host, ts, cpu FROM dist "
                          "WHERE host = 'h6' ORDER BY ts LIMIT 3")[-1]
        rows = out.batches[0].to_pylist()
        assert [r["cpu"] for r in rows] == [600.0, 601.0, 602.0]

    def test_delete_routes_to_owner(self, cluster):
        fe, *_ = cluster
        fe.do_query(DDL)
        fe.do_query("INSERT INTO dist VALUES ('h1', 100, 1.0), "
                    "('h7', 100, 2.0)")
        fe.do_query("DELETE FROM dist WHERE host = 'h7'")
        out = fe.do_query("SELECT count(*) AS c FROM dist")[-1]
        assert out.batches[0].to_pylist()[0]["c"] == 1

    def test_promql_over_dist_table(self, cluster):
        fe, *_ = cluster
        fe.do_query(DDL)
        vals = []
        for h in ("h1", "h8"):
            for i in range(30):
                vals.append(f"('{h}', {i * 10_000}, {i * 2.0})")
        fe.do_query("INSERT INTO dist VALUES " + ",".join(vals))
        from greptimedb_tpu.promql.engine import PromqlEngine
        eng = PromqlEngine(fe.catalog)
        out = eng.query_to_prom_json("rate(dist[1m])", 120_000, 240_000,
                                     60_000, QueryContext())
        by_host = {r["metric"]["host"]: r for r in out["result"]}
        assert set(by_host) == {"h1", "h8"}
        for r in by_host.values():
            for _, v in r["values"]:
                assert abs(float(v) - 0.2) < 1e-6

    def test_restart_datanode_recovers_regions(self, cluster, tmp_path):
        fe, datanodes, srv = cluster
        self._seed(fe)
        dn1 = datanodes[1]
        dn1.shutdown()
        dn1b = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "dn1"), node_id=1,
            register_numbers_table=False))
        dn1b.start()
        datanodes[1] = dn1b
        fe.clients[1].datanode = dn1b
        out = fe.do_query("SELECT count(*) AS c FROM dist")[-1]
        assert out.batches[0].to_pylist()[0]["c"] == 160


class TestReviewRegressions:
    def test_if_not_exists_reattaches_after_frontend_restart(self, cluster):
        fe, datanodes, srv = cluster
        fe.do_query(DDL)
        fe.do_query("INSERT INTO dist VALUES ('h1', 1, 1.0)")
        # a fresh frontend (lost catalog) over the same meta + datanodes
        fe2 = DistInstance(fe.meta, fe.clients)
        fe2.do_query(DDL.replace("CREATE TABLE dist",
                                 "CREATE TABLE IF NOT EXISTS dist"))
        out = fe2.do_query("SELECT count(*) AS c FROM dist")[-1]
        assert out.batches[0].to_pylist()[0]["c"] == 1
        # plain CREATE still errors
        with pytest.raises(Exception):
            fe2.do_query(DDL)

    def test_insert_resolves_via_route_after_restart(self, cluster):
        fe, *_ = cluster
        fe.do_query(DDL)
        fe2 = DistInstance(fe.meta, fe.clients)
        fe2.do_query("INSERT INTO dist VALUES ('h1', 1, 1.0)")
        out = fe2.do_query("SELECT count(*) AS c FROM dist")[-1]
        assert out.batches[0].to_pylist()[0]["c"] == 1

    def test_drop_if_exists_noop(self, cluster):
        fe, *_ = cluster
        fe.do_query("DROP TABLE IF EXISTS nope")    # must not raise

    def test_datanode_local_insert_rejects_foreign_region(self, cluster):
        from greptimedb_tpu.errors import RegionNotFoundError
        fe, datanodes, srv = cluster
        fe.do_query(DDL)
        route = srv.table_route("greptime.public.dist")
        # find a host value owned by the OTHER node for each datanode
        for i, dn in datanodes.items():
            t = dn.catalog.table("greptime", "public", "dist")
            foreign = [rr.region_number for rr in route.region_routes
                       if rr.leader.id != i]
            host = "h0" if 0 in foreign else "h9"
            with pytest.raises(RegionNotFoundError):
                t.insert({"host": [host], "ts": [1], "cpu": [1.0]})

    def test_heartbeat_registers_unknown_peer(self):
        srv = MetaSrv()
        srv.handle_heartbeat(7)
        assert [p.id for p in srv.peers()] == [7]
        assert [p.id for p in srv.alive_datanodes()] == [7]
