"""Compressed file access for external tables and COPY TO/FROM.

Reference behavior: src/common/datasource/src/file_format/mod.rs +
compression.rs — the datasource layer decompresses CSV/JSON transparently
(gzip/zstd, inferred from the file extension or given explicitly) and
compresses on export. Parquet is excluded: its compression is internal
to the format. Implemented over pyarrow's codec streams so the CSV
reader consumes the decompressed bytes in C, not through Python shims.
"""

from __future__ import annotations

from typing import Optional

import pyarrow as pa

from ..errors import UnsupportedError

_EXT_CODECS = {
    ".gz": "gzip",
    ".gzip": "gzip",
    ".zst": "zstd",
    ".zstd": "zstd",
}

_KNOWN = {"gzip", "zstd"}


def file_codec(path: str, explicit: Optional[str] = None) -> Optional[str]:
    """Resolve the compression codec: explicit option first (``none``
    disables inference), else the file extension."""
    if explicit is not None:
        name = str(explicit).lower()
        if name in ("none", ""):
            return None
        if name == "gz":
            name = "gzip"
        if name not in _KNOWN:
            raise UnsupportedError(
                f"compression {explicit!r} (supported: gzip, zstd)")
        return name
    for ext, codec in _EXT_CODECS.items():
        if path.lower().endswith(ext):
            return codec
    return None


def open_compressed_in(path: str, codec: Optional[str]) -> "pa.NativeFile":
    """Readable stream over a possibly-compressed local file."""
    raw = pa.OSFile(path, "rb")
    if codec is None:
        return raw
    return pa.CompressedInputStream(raw, codec)


def open_compressed_out(path: str, codec: Optional[str]) -> "pa.NativeFile":
    """Writable stream producing a possibly-compressed local file."""
    raw = pa.OSFile(path, "wb")
    if codec is None:
        return raw
    return pa.CompressedOutputStream(raw, codec)
