"""Durable in-database trace store: tail-sampled span persistence.

Spans used to die as DEBUG log lines or leave the building via OTLP to
a collector nobody runs. This module persists them into the database
they describe — the Dapper-style tail-sampling pattern applied to a
TSDB that can eat its own traces (the PR 8 self-monitor precedent):

- ``TraceSink`` plugs into ``telemetry.span()`` exit (alongside the
  OTLP exporter) and buffers completed spans **per trace** in a
  bounded, drop-counting buffer.
- Sampling is **tail-based**: the retain/drop verdict happens at trace
  completion (the root span's exit) on the root span's node. A trace is
  retained iff it was slow (the slow-query threshold), errored, was
  cancelled/KILLed, touched a balancer op, or falls in the head-sample
  rate (``SET trace_sample_ratio`` / GREPTIME_TRACE_SAMPLE_RATIO,
  default 0.01 — deterministic per trace id, so every node would agree).
- Retained spans flush through the self-monitor ingest path (under
  ``telemetry.suppress_metrics()`` recursion guards) into the
  auto-created ``greptime_private.trace_spans`` table — history is
  ordinary data: SQL queries it, retention sweeps it
  (``SET trace_retention_ms``, default 3d).
- **Datanodes buffer blind.** A datanode sees only fragments of a trace
  (its ``dn_scan``/``dn_write_region`` spans) and cannot decide; it
  buffers spans keyed by trace_id until the frontend's verdict arrives
  piggybacked on subsequent RPCs (``trace_verdicts`` rides every
  outbound Flight body; retained spans return on the same RPC's
  response), or a TTL evicts them (GREPTIME_TRACE_BUFFER_TTL_S).
"""

from __future__ import annotations

import json
import logging
import time
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from .locks import TrackedLock
from .runtime import env_float, env_int
from .tracking import tracked_state

logger = logging.getLogger(__name__)

PRIVATE_SCHEMA = "greptime_private"
TRACE_SPANS_TABLE = "trace_spans"

#: wire key for buffered spans riding a Flight response (stream schema
#: metadata on do_get; a JSON field on do_put acks / action responses)
TRACE_SPANS_WIRE_KEY = b"gdb.trace_spans"
#: request-body key the frontend's verdicts piggyback on
TRACE_VERDICTS_BODY_KEY = "trace_verdicts"

_config_lock = TrackedLock("common.trace_store_config")

#: head-sample rate for traces with no tail-retention flag (0 = only
#: slow/error/cancelled/balancer traces persist; 1 = everything does)
_SAMPLE_RATIO: List[float] = [env_float("GREPTIME_TRACE_SAMPLE_RATIO",
                                        0.01)]
#: retention for greptime_private.trace_spans, ms; 0 disables the sweep.
#: Traces are bulkier than metrics — default 3d vs the metrics' 7d.
_RETENTION_MS: List[int] = [env_int("GREPTIME_TRACE_RETENTION_MS",
                                    3 * 24 * 3600 * 1000)]
#: datanode-side buffer TTL: spans of a trace whose verdict never
#: arrives (frontend died, no further RPCs) evict after this long
_BUFFER_TTL_S: List[int] = [env_int("GREPTIME_TRACE_BUFFER_TTL_S", 300)]


def configure(*, sample_ratio: Optional[float] = None,
              retention_ms: Optional[int] = None,
              buffer_ttl_s: Optional[int] = None) -> None:
    """SET trace_sample_ratio / trace_retention_ms knobs."""
    with _config_lock:
        if sample_ratio is not None:
            r = float(sample_ratio)
            if not 0.0 <= r <= 1.0:
                raise ValueError("trace_sample_ratio must be in [0, 1]")
            _SAMPLE_RATIO[0] = r
        if retention_ms is not None:
            _RETENTION_MS[0] = max(0, int(retention_ms))
        if buffer_ttl_s is not None:
            _BUFFER_TTL_S[0] = max(1, int(buffer_ttl_s))


def sample_ratio() -> float:
    return _SAMPLE_RATIO[0]


def retention_ms() -> int:
    return _RETENTION_MS[0]


def head_sampled(trace_id: str) -> bool:
    """Deterministic head-sample decision: a pure function of the trace
    id, so any process that re-derived it would agree (and tests can pin
    it with ratio 0/1)."""
    ratio = _SAMPLE_RATIO[0]
    if ratio <= 0.0:
        return False
    if ratio >= 1.0:
        return True
    h = zlib.crc32(trace_id.encode()) & 0xFFFFFFFF
    return h / 2**32 < ratio


class TraceSink:
    """Per-process span sink (one per node; ``install()`` makes it the
    telemetry hook).

    role="root"   — this process decides verdicts (frontends,
                    standalone): a completing span with no parent — or
                    with only a *remote* parent, i.e. an external
                    client's traceparent — completes its trace.
    role="buffer" — this process buffers remote-rooted traces until the
                    verdict arrives over the wire (datanodes); traces
                    genuinely rooted here (background jobs) still get a
                    local verdict, exported on the next RPC response.
    """

    MAX_TRACES = 512
    MAX_SPANS_PER_TRACE = 512
    MAX_PENDING = 8192
    MAX_EXPORT = 4096
    VERDICT_RING = 512
    #: verdicts piggybacked per outbound RPC (most recent first)
    PIGGYBACK_MAX = 32

    def __init__(self, node_label: str = "standalone",
                 service: str = "standalone", role: str = "root",
                 writer=None):
        self.node_label = node_label
        self.service = service
        self.role = role
        #: hosting frontend (handle_row_insert) — None on datanodes
        self.writer = writer
        self._lock = TrackedLock("common.trace_sink")
        #: trace_id -> {"spans": [row...], "flags": set, "t": monotonic}
        self._traces: "OrderedDict[str, dict]" = tracked_state(
            OrderedDict(), "trace_sink.traces")
        #: retained rows awaiting a local write (writer processes)
        self._pending: List[dict] = tracked_state(
            [], "trace_sink.pending")
        #: retained rows awaiting export on an RPC response (datanodes):
        #: (monotonic_t, row)
        self._export: List[Tuple[float, dict]] = tracked_state(
            [], "trace_sink.export")
        #: recent verdicts: trace_id -> (retained, monotonic_t). Late
        #: spans (pool workers finishing after the root) consult this;
        #: outbound RPCs piggyback the youngest entries.
        self._verdicts: "OrderedDict[str, tuple]" = tracked_state(
            OrderedDict(), "trace_sink.verdicts")
        self.last_retained: Optional[str] = None
        #: drops recorded under the lock but not yet published to the
        #: prometheus counter (published outside the lock — the counter
        #: takes the telemetry metrics lock)
        self._uncounted_drops = 0
        #: rate limit for the opportunistic TTL eviction buffer-role
        #: sinks run on their own RPC traffic (no SelfMonitor there)
        self._last_evict = 0.0
        self.stats: Dict[str, int] = tracked_state({
            "spans_recorded": 0, "spans_dropped": 0,
            "traces_retained": 0, "traces_sampled_out": 0,
            "traces_evicted": 0, "rows_written": 0, "write_errors": 0,
            "spans_exported": 0, "spans_absorbed": 0,
        }, "trace_sink.stats")

    # ------------------------------------------------------------------
    # span intake (called from telemetry.span() exit — keep it cheap)
    # ------------------------------------------------------------------
    def on_span_end(self, s: dict, elapsed_ms: float,
                    status: str) -> None:
        from .telemetry import slow_query_threshold_ms
        trace_id = s["trace_id"]
        attrs = s.get("attrs") or {}
        node = attrs.get("node")
        if isinstance(node, int):
            node = f"dn{node}"      # datanode spans attr their node id
        row = {
            "node": str(node) if node is not None else self.node_label,
            "service": self.service,
            "span_name": s["name"],
            "trace_id": trace_id,
            "span_id": s["span_id"],
            "parent_span_id": s.get("parent_id") or "",
            "ts": s.get("start_unix_ns", 0) // 1_000_000,
            "duration_ms": round(elapsed_ms, 3),
            "status": status,
            "attrs": json.dumps(attrs, default=str,
                                separators=(",", ":")) if attrs else "",
        }
        thr = slow_query_threshold_ms()
        flag = None
        if status in ("error", "cancelled"):
            flag = status
        elif thr is not None and elapsed_ms >= thr:
            flag = "slow"
        elif "balancer_op" in s["name"] or "balancer_step" in s["name"]:
            flag = "balancer"
        is_root = s.get("parent_id") is None or \
            (s.get("remote_parent") and self.role == "root")
        with self._lock:
            self.stats["spans_recorded"] += 1
            verdict = self._verdicts.get(trace_id)
            if verdict is not None:
                # late span of an already-decided trace (pool worker
                # finishing after the root): apply the verdict directly
                if verdict[0]:
                    self._stash(row)
            else:
                ent = self._traces.get(trace_id)
                if ent is None:
                    if len(self._traces) >= self.MAX_TRACES:
                        self._note_drop()
                    else:
                        ent = self._traces[trace_id] = {
                            "spans": [], "flags": set(),
                            "t": time.monotonic()}
                if ent is not None:
                    if len(ent["spans"]) >= self.MAX_SPANS_PER_TRACE:
                        self._note_drop()
                    else:
                        ent["spans"].append(row)
                    if flag:
                        ent["flags"].add(flag)
                if is_root:
                    self._decide(trace_id)
        self._publish_drops()
        if self.writer is None:
            # buffer-role processes have no SelfMonitor tick: TTL
            # eviction rides their own span traffic (rate-limited)
            self.maybe_evict()

    def _note_drop(self, n: int = 1) -> None:
        """Record n shed spans. Caller holds the lock; the prometheus
        counter is published by _publish_drops OUTSIDE it."""
        self.stats["spans_dropped"] += n
        self._uncounted_drops += n

    def _publish_drops(self) -> None:
        from .telemetry import increment_counter
        with self._lock:
            n, self._uncounted_drops = self._uncounted_drops, 0
        if n:
            increment_counter("trace_sink_dropped", n)

    def _stash(self, row: dict) -> None:
        """Queue one retained row for write (or wire export). Caller
        holds the lock."""
        if self.writer is not None:
            if len(self._pending) >= self.MAX_PENDING:
                self._note_drop()
                return
            self._pending.append(row)
        else:
            if len(self._export) >= self.MAX_EXPORT:
                del self._export[0]
                self._note_drop()
            self._export.append((time.monotonic(), row))

    def _decide(self, trace_id: str) -> None:
        """Tail verdict at trace completion. Caller holds the lock."""
        ent = self._traces.pop(trace_id, None)
        flags = ent["flags"] if ent is not None else set()
        retained = bool(flags) or head_sampled(trace_id)
        self._verdicts[trace_id] = (retained, time.monotonic())
        while len(self._verdicts) > self.VERDICT_RING:
            self._verdicts.popitem(last=False)
        if retained:
            self.stats["traces_retained"] += 1
            self.last_retained = trace_id
            for row in (ent["spans"] if ent is not None else []):
                self._stash(row)
        else:
            self.stats["traces_sampled_out"] += 1

    # ------------------------------------------------------------------
    # slow-query log annotation
    # ------------------------------------------------------------------
    def stored_verdict(self, trace_id: str) -> str:
        """'yes' / 'sampled-out' for the slow-query log line. Callable
        mid-trace: the retention flags accumulate per span and the
        head-sample decision is deterministic, so the answer is already
        known when the statement's span closes."""
        with self._lock:
            v = self._verdicts.get(trace_id)
            if v is not None:
                return "yes" if v[0] else "sampled-out"
            ent = self._traces.get(trace_id)
            if ent is not None and ent["flags"]:
                return "yes"
        return "yes" if head_sampled(trace_id) else "sampled-out"

    # ------------------------------------------------------------------
    # verdict piggyback (the frontend side)
    # ------------------------------------------------------------------
    def recent_verdicts(self) -> Dict[str, bool]:
        """Youngest verdicts to ride an outbound RPC body. Idempotent on
        the receiving datanode (applying twice is a no-op), so the same
        verdict repeats until it ages out of the ring."""
        ttl = _BUFFER_TTL_S[0]
        now = time.monotonic()
        out: Dict[str, bool] = {}
        with self._lock:
            for tid in reversed(self._verdicts):
                retained, t = self._verdicts[tid]
                if now - t > ttl:
                    break
                out[tid] = retained
                if len(out) >= self.PIGGYBACK_MAX:
                    break
        return out

    def push_verdict(self, trace_id: str, retained: bool = True) -> bool:
        """Re-announce a verdict as the YOUNGEST ring entry so the next
        RPC's piggyback window is guaranteed to carry it. The render
        path (ADMIN SHOW TRACE / /v1/trace) calls this — with stored
        rows as its evidence of retention — for the trace it is about
        to ping for: a verdict that aged out of the PIGGYBACK_MAX
        window minutes ago would otherwise never reach a datanode that
        received no RPC in that window, and its buffered spans would
        sit until TTL eviction — the waterfall would silently render
        without them. A trace the ring remembers as sampled-out is NOT
        resurrected (returns False)."""
        with self._lock:
            v = self._verdicts.get(trace_id)
            if v is not None and not v[0]:
                return False
            self._verdicts[trace_id] = (bool(retained), time.monotonic())
            self._verdicts.move_to_end(trace_id)
            while len(self._verdicts) > self.VERDICT_RING:
                self._verdicts.popitem(last=False)
        return True

    def known_verdict(self, trace_id: str) -> Optional[bool]:
        """The ring's memory of a trace's verdict, or None once it has
        aged out."""
        with self._lock:
            v = self._verdicts.get(trace_id)
        return None if v is None else bool(v[0])

    def absorb_spans(self, rows: List[dict]) -> None:
        """Spans a datanode returned on an RPC response: queue them for
        the local write (frontend side)."""
        if not rows:
            return
        keys = ("node", "service", "span_name", "trace_id", "span_id",
                "parent_span_id", "ts", "duration_ms", "status", "attrs")
        with self._lock:
            for r in rows:
                if not isinstance(r, dict) or "trace_id" not in r:
                    continue
                self._stash({k: r.get(k) for k in keys})
                self.stats["spans_absorbed"] += 1
        self._publish_drops()

    # ------------------------------------------------------------------
    # the datanode side
    # ------------------------------------------------------------------
    def apply_verdicts(self, verdicts: Dict[str, bool]) -> None:
        """Verdicts that arrived piggybacked on an inbound RPC: release
        (or discard) the matching buffered traces."""
        if not verdicts:
            return
        with self._lock:
            for tid, retained in verdicts.items():
                ent = self._traces.pop(tid, None)
                if ent is None:
                    continue
                if retained:
                    self.stats["traces_retained"] += 1
                    for row in ent["spans"]:
                        self._stash(row)
                else:
                    self.stats["traces_sampled_out"] += 1

    def take_export(self, limit: int = 512) -> List[dict]:
        """Drain retained spans awaiting export (they ride the RPC
        response back to the asking frontend)."""
        with self._lock:
            if not self._export:
                return []
            taken = self._export[:limit]
            del self._export[:limit]
            self.stats["spans_exported"] += len(taken)
            return [row for _, row in taken]

    def evict_expired(self, now: Optional[float] = None) -> int:
        """TTL eviction: traces whose verdict never arrived, and export
        rows nobody asked for. Every shed span counts on the drop
        metric."""
        ttl = _BUFFER_TTL_S[0]
        now = time.monotonic() if now is None else now
        evicted = 0
        with self._lock:
            for tid in [t for t, e in self._traces.items()
                        if now - e["t"] > ttl]:
                ent = self._traces.pop(tid, None)
                if ent is not None:
                    self._note_drop(len(ent["spans"]))
                evicted += 1
            if evicted:
                self.stats["traces_evicted"] += evicted
            keep = [(t, r) for t, r in self._export if now - t <= ttl]
            dropped = len(self._export) - len(keep)
            if dropped:
                self._export[:] = keep
                self._note_drop(dropped)
        self._publish_drops()
        return evicted

    #: opportunistic-eviction cadence for buffer-role sinks (seconds)
    EVICT_EVERY_S = 5.0

    def maybe_evict(self, now: Optional[float] = None) -> None:
        """Rate-limited evict_expired for processes with no
        SelfMonitor tick (datanodes, metasrv): rides their own span /
        RPC traffic so verdictless buffers cannot pin MAX_TRACES
        forever after a frontend restart loses its verdict ring."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if now - self._last_evict < self.EVICT_EVERY_S:
                return
            self._last_evict = now
        self.evict_expired(now)

    # ------------------------------------------------------------------
    # the write (self-monitor ingest path)
    # ------------------------------------------------------------------
    def flush(self) -> int:
        """Write pending retained spans into
        greptime_private.trace_spans through the hosting frontend's
        normal ingest path, under the recursion guards. Returns rows
        written. Never raises (the trace store must not break its
        host); failed rows are dropped and counted."""
        if self.writer is None:
            return 0
        with self._lock:
            rows, self._pending[:] = list(self._pending), []
        if not rows:
            return 0
        from . import admission
        from .telemetry import suppress_metrics
        from ..datatypes.data_type import FLOAT64, STRING
        from ..session import QueryContext
        cols = {k: [r.get(k) for r in rows] for k in (
            "node", "service", "span_name", "trace_id", "span_id",
            "parent_span_id", "ts", "duration_ms", "status", "attrs")}
        try:
            with suppress_metrics(), admission.exempt():
                n = self.writer.handle_row_insert(
                    TRACE_SPANS_TABLE, cols,
                    tag_columns=("node", "service", "span_name",
                                 "trace_id", "span_id"),
                    timestamp_column="ts",
                    types={"node": STRING, "service": STRING,
                           "span_name": STRING, "trace_id": STRING,
                           "span_id": STRING, "parent_span_id": STRING,
                           "duration_ms": FLOAT64, "status": STRING,
                           "attrs": STRING},
                    ctx=QueryContext(current_schema=PRIVATE_SCHEMA))
        except Exception as e:  # noqa: BLE001 — observer must not break
            logger.warning("trace flush failed (%d spans dropped): %s",
                           len(rows), e)
            with self._lock:
                self.stats["write_errors"] += 1
                self._note_drop(len(rows))
            self._publish_drops()
            return 0
        with self._lock:
            self.stats["rows_written"] += n
        return n

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def buffered_trace_count(self) -> int:
        with self._lock:
            return len(self._traces)

    def row(self) -> Dict[str, object]:
        with self._lock:
            out = dict(self.stats)
        out["node"] = self.node_label
        out["role"] = self.role
        out["sample_ratio"] = sample_ratio()
        out["retention_ms"] = retention_ms()
        return out


# ---------------------------------------------------------------------------
# process-wide sink
# ---------------------------------------------------------------------------

_SINK: List[Optional[TraceSink]] = [None]


def sink() -> Optional[TraceSink]:
    return _SINK[0]


def install(new_sink: Optional[TraceSink]) -> Optional[TraceSink]:
    """Make `new_sink` the process-wide sink telemetry.span() feeds
    (None uninstalls). Returns the previous sink (tests restore it)."""
    from . import telemetry
    with _config_lock:
        old, _SINK[0] = _SINK[0], new_sink
        telemetry.set_span_sink(new_sink)
    return old


# ---------------------------------------------------------------------------
# waterfall reassembly (ADMIN SHOW TRACE / /v1/trace/<id> /
# information_schema share one renderer)
# ---------------------------------------------------------------------------

def waterfall_rows(span_rows: List[dict]) -> List[dict]:
    """Reassemble stored span rows into the indented per-node tree:
    depth-first, children ordered by start ts, with self-time vs
    child-time split. `dist_rpc` spans' self-time is the network share
    (RPC wall minus the datanode-side span) — the node_ms/network_ms
    split the EXPLAIN ANALYZE node blocks compute."""
    by_id: Dict[str, dict] = {}
    for r in span_rows:
        if r.get("span_id"):
            by_id[str(r["span_id"])] = r
    children: Dict[str, List[dict]] = {}
    roots: List[dict] = []
    for r in span_rows:
        parent = str(r.get("parent_span_id") or "")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(r)
        else:
            roots.append(r)
    for lst in children.values():
        lst.sort(key=lambda r: (r.get("ts") or 0, str(r.get("span_id"))))
    roots.sort(key=lambda r: (r.get("ts") or 0, str(r.get("span_id"))))
    t0 = min((r.get("ts") or 0) for r in span_rows) if span_rows else 0
    out: List[dict] = []

    def emit(r: dict, depth: int) -> None:
        kids = children.get(str(r.get("span_id")), [])
        dur = float(r.get("duration_ms") or 0.0)
        child_ms = sum(float(k.get("duration_ms") or 0.0) for k in kids)
        self_ms = max(0.0, dur - child_ms)
        name = str(r.get("span_name"))
        indent = ("  " * depth + "└─ ") if depth else ""
        detail = str(r.get("attrs") or "")
        if name == "dist_rpc" and kids:
            detail = (f"network_ms={self_ms:.1f} " + detail).strip()
        out.append({
            "span": indent + name,
            "node": r.get("node"),
            "start_offset_ms": int((r.get("ts") or 0) - t0),
            "duration_ms": round(dur, 3),
            "self_ms": round(self_ms, 3),
            "status": r.get("status"),
            "detail": detail,
        })
        for k in kids:
            emit(k, depth + 1)

    for r in roots:
        emit(r, 0)
    return out


def fetch_trace(catalog_manager, trace_id: str) -> List[dict]:
    """All stored span rows of one trace, as plain dicts (the
    greptime_private.trace_spans scan every surface shares). The
    trace_id tag predicate is pushed into scan_batches when the table
    accepts filters (mito + DistTable do — the PR 13 secondary indexes
    then prune SSTs for the point lookup); the Python-side re-check
    keeps correctness on tables that ignore it (superset semantics)."""
    from .. import DEFAULT_CATALOG_NAME
    table = catalog_manager.table(DEFAULT_CATALOG_NAME, PRIVATE_SCHEMA,
                                  TRACE_SPANS_TABLE)
    if table is None:
        return []
    from ..sql.ast import BinaryOp, Column, Literal
    predicate = BinaryOp("=", Column("trace_id"),
                         Literal(trace_id, "string"))
    try:
        batches = table.scan_batches(filters=[predicate])
    except TypeError:      # virtual/file tables take no filters kwarg
        batches = table.scan_batches()
    rows: List[dict] = []
    for b in batches:
        d = b.to_pydict()
        n = len(d.get("trace_id", []))
        for i in range(n):
            if str(d["trace_id"][i]) != trace_id:
                continue
            # numpy scalars → natives (these rows go straight to JSON)
            rows.append({k: (v.item() if hasattr(v, "item") else v)
                         for k, v in ((c, d[c][i]) for c in d)})
    return rows


def sync_and_fetch(catalog_manager, trace_id: str,
                   clients=None) -> Tuple[Optional[str], List[dict]]:
    """The ONE render-path sequence behind ADMIN SHOW TRACE and
    GET /v1/trace/<id> (two surfaces, one behavior):

    1. resolve 'last' to the most recently retained trace id;
    2. read the stored rows — they (or a live ring verdict) are the
       EVIDENCE the trace was retained: an id the ring has forgotten
       AND storage has never seen is not resurrected into datanode
       buffers (a sampled-out trace must stay sampled out);
    3. given evidence, re-announce the verdict (push_verdict) so the
       pings' piggyback definitely carries it however long ago it was
       decided, ping each datanode (the ordinary RPC piggyback
       releases any spans still buffered for this trace onto the
       response), flush the sink, and re-read.

    Returns (resolved_trace_id, rows); (None, []) when 'last' has no
    referent, (tid, []) when the trace was never stored."""
    s = sink()
    if trace_id == "last":
        resolved = s.last_retained if s is not None else None
        if resolved is None:
            return None, []
        trace_id = resolved
    if s is not None:
        s.flush()              # this frontend's own pending spans first
    rows = fetch_trace(catalog_manager, trace_id)
    retained = bool(rows) or (s is not None
                              and s.known_verdict(trace_id) is True)
    if not retained or s is None:
        return trace_id, rows
    s.push_verdict(trace_id)
    for client in (clients or ()):
        ping = getattr(client, "ping", None)
        if ping is None:
            continue
        try:
            ping()
        except Exception as e:  # noqa: BLE001 — a dead datanode must
            logger.debug(       # not block rendering what we do have
                "trace span-sync ping failed: %s", e)
    if s.flush() == 0 and not clients:
        return trace_id, rows               # nothing new arrived
    return trace_id, fetch_trace(catalog_manager, trace_id)
