"""Runtime lock-order race detector (the dynamic half of greptlint).

Reference behavior: the reference leans on the Rust compiler + clippy +
loom for concurrency hygiene; a Python rebuild has none of those, so the
storage layer's ~10 locks are wrapped in :func:`TrackedLock` /
:func:`TrackedRLock`, a lockdep-style checker that builds a global
*lock-order graph* while tests run:

- Every **blocking** acquisition with other locks held records a
  directed edge ``held_class -> acquired_class`` (keyed by the lock's
  declared *name*, i.e. its class — two distinct regions' writer locks
  share a node, exactly like kernel lockdep).
- An edge that would close a cycle (``A -> B`` recorded while a path
  ``B ->* A`` exists) raises :class:`LockOrderError` **before blocking**
  — a potential ABBA deadlock is reported with both acquisition stacks
  instead of hanging the suite.
- Nesting two *different instances* of the same lock class is a
  self-edge and raises for the same reason (no instance ordering exists;
  re-entrant re-acquisition of the *same* instance is fine).
- While any lock created with ``io_ok=False`` (pure in-memory state:
  version transitions, memtable index, scheduler queue, purger queue)
  is held, reaching a *blocking-I/O failpoint site*
  (``objstore_*``, ``wal_fsync``, ``cache_read``, ...) raises
  :class:`IoUnderLockError` — the static analyzer cannot see through
  call chains, this catches I/O-under-lock at runtime.

Zero overhead in production, same pattern as ``common/failpoint.py``:
:func:`TrackedLock` is a **factory** that returns a plain
``threading.Lock`` unless the detector is enabled, so the inactive mode
costs literally nothing per acquire (bench.py asserts the differential).
Enablement is decided at import: ``GREPTIME_LOCK_CHECK=1`` forces on,
``GREPTIME_LOCK_CHECK=0`` forces off, and otherwise the detector turns
itself on when running under pytest (``pytest`` already imported).
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple, Union

logger = logging.getLogger(__name__)

__all__ = ["TrackedLock", "TrackedRLock", "LockOrderError",
           "IoUnderLockError", "enabled", "reset_graph", "order_edges",
           "held_locks", "IO_FAILPOINT_SITES"]


class LockOrderError(RuntimeError):
    """A lock acquisition would close a cycle in the lock-order graph —
    some other code path takes the same locks in the opposite order, so
    the two can deadlock against each other."""


class IoUnderLockError(LockOrderError):
    """A blocking-I/O failpoint site was reached while holding a lock
    declared ``io_ok=False`` (in-memory-only critical section)."""


def _env_enabled() -> bool:
    # greptsan (devtools/greptsan) derives its happens-before edges from
    # tracked acquire/release events, so forcing the race detector on
    # forces lock tracking on too — even over an explicit
    # GREPTIME_LOCK_CHECK=0 (raceless edges would report every
    # lock-protected access as a data race)
    r = os.environ.get("GREPTIME_RACE_CHECK")
    if r is not None and r.strip().lower() not in ("", "0", "false",
                                                   "off", "no"):
        return True
    v = os.environ.get("GREPTIME_LOCK_CHECK")
    if v is not None:
        return v.strip().lower() not in ("", "0", "false", "off", "no")
    return "pytest" in sys.modules


_ENABLED: bool = _env_enabled()

#: (on_acquire, on_release) installed by greptsan when the race detector
#: is enabled — every tracked acquisition/release (including the
#: Condition wait release/reacquire cycle) reports here so vector clocks
#: pick up the release->acquire happens-before edge. None otherwise:
#: one is-None branch on the tracked (test-only) path.
_RACE_HOOKS: Optional[Tuple] = None


def set_race_hooks(on_acquire, on_release) -> None:
    global _RACE_HOOKS
    _RACE_HOOKS = (on_acquire, on_release) \
        if on_acquire is not None else None

#: failpoint sites that sit on blocking-I/O paths; reaching one while an
#: ``io_ok=False`` lock is held is a bug even when no failpoint is armed
IO_FAILPOINT_SITES = frozenset({
    "objstore_read", "objstore_write", "objstore_delete",
    "objstore_request", "wal_append", "wal_fsync", "cache_read",
    "sst_write", "purger_delete", "scan_cache_incremental",
})

_tls = threading.local()

_graph_lock = threading.Lock()
#: adjacency: lock-class name -> set of lock-class names acquired while
#: the key was held (first blocking acquisition records the edge)
_edges: Dict[str, Set[str]] = {}
#: (a, b) -> formatted stack of the acquisition that first recorded a->b
_edge_stacks: Dict[Tuple[str, str], str] = {}


def _held() -> List["_Tracked"]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = []
        _tls.held = held
    return held


def enabled() -> bool:
    return _ENABLED


def reset_graph() -> None:
    """Forget every recorded edge (test isolation)."""
    with _graph_lock:
        _edges.clear()
        _edge_stacks.clear()


def order_edges() -> Dict[str, Set[str]]:
    """Snapshot of the lock-order graph (introspection / tests)."""
    with _graph_lock:
        return {k: set(v) for k, v in _edges.items()}


def held_locks() -> List[str]:
    """Names of the locks the calling thread currently holds."""
    return [lk.name for lk in _held()]


def _short_stack(skip: int = 3) -> str:
    return "".join(traceback.format_stack()[:-skip][-8:])


def _path_exists(src: str, dst: str) -> Optional[List[str]]:
    """DFS under _graph_lock: a path src ->* dst, or None."""
    stack: List[Tuple[str, List[str]]] = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


class _Tracked:
    """Active-mode wrapper. Never constructed when the detector is off —
    the TrackedLock/TrackedRLock factories return raw locks instead."""

    __slots__ = ("_inner", "name", "io_ok", "_reentrant", "_san_clock")

    def __init__(self, inner: Union[threading.Lock, threading.RLock],
                 name: str, io_ok: bool, reentrant: bool):
        self._inner = inner
        self.name = name
        self.io_ok = io_ok
        self._reentrant = reentrant
        #: greptsan's per-lock vector-clock snapshot (generation, clock);
        #: read/written only while the lock is held, so the lock itself
        #: is its synchronization
        self._san_clock = None

    # -- ordering ----------------------------------------------------
    def _check_order(self, held: List["_Tracked"]) -> None:
        """Record edges held->self and raise BEFORE blocking if any edge
        closes a cycle (so an ABBA pair reports instead of deadlocking)."""
        me = self.name
        stack_txt: Optional[str] = None
        for h in held:
            a = h.name
            if a == me:
                # two *instances* of the same class nested without any
                # ordering rule — the mirror nesting deadlocks
                raise LockOrderError(
                    f"nested acquisition of two {me!r} lock instances "
                    f"(no instance ordering exists)\n{_short_stack()}")
            with _graph_lock:
                if me in _edges.get(a, ()):
                    continue                      # edge already known
                path = _path_exists(me, a)
                if path is not None:
                    prior = "".join(
                        f"  {x} -> {y} first seen at:\n"
                        f"{_edge_stacks.get((x, y), '    <unknown>')}"
                        for x, y in zip(path, path[1:]))
                    raise LockOrderError(
                        f"lock-order cycle: acquiring {me!r} while "
                        f"holding {a!r}, but the inverse order "
                        f"{' -> '.join(path)} is already established:\n"
                        f"{prior}current acquisition:\n{_short_stack()}")
                if stack_txt is None:
                    stack_txt = _short_stack()
                _edges.setdefault(a, set()).add(me)
                _edge_stacks[(a, me)] = stack_txt

    # -- lock protocol ----------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held()
        already = any(h is self for h in held)
        if already and not self._reentrant:
            raise LockOrderError(
                f"non-reentrant lock {self.name!r} re-acquired by its "
                f"owner (self-deadlock)\n{_short_stack()}")
        if blocking and not already and held:
            self._check_order(held)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            held.append(self)
            if _RACE_HOOKS is not None:
                _RACE_HOOKS[0](self)
        return ok

    def release(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        if _RACE_HOOKS is not None:
            _RACE_HOOKS[1](self)       # while still holding: the clock
        self._inner.release()          # publish races with the release

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: object) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # -- threading.Condition protocol -------------------------------
    # Condition(lock) probes for these at construction; without them it
    # falls back to `acquire(False)` tricks that misread a tracked lock
    # (the owner probing its own non-reentrant lock looks like a
    # self-deadlock). Waiters keep the held-list consistent across the
    # release/park/reacquire cycle; the reacquire does NOT re-run order
    # checking — it restores an ordering that was already vetted.

    def _is_owned(self) -> bool:
        return any(h is self for h in _held())

    def _release_save(self):
        held = _held()
        count = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                count += 1
        if _RACE_HOOKS is not None:
            _RACE_HOOKS[1](self)       # cond.wait releases: a real edge
        if self._reentrant:
            return (self._inner._release_save(), count)
        self._inner.release()
        return (None, count)

    def _acquire_restore(self, state: tuple) -> None:
        inner_state, count = state
        if self._reentrant:
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        _held().extend([self] * count)
        if _RACE_HOOKS is not None:
            _RACE_HOOKS[0](self)       # waiter reacquired: join the clock

    def __repr__(self) -> str:
        kind = "TrackedRLock" if self._reentrant else "TrackedLock"
        return f"<{kind} {self.name!r} io_ok={self.io_ok}>"


def TrackedLock(name: str, *, io_ok: bool = True,
                force: bool = False) -> Union[threading.Lock, _Tracked]:
    """A mutex that participates in lock-order checking when the
    detector is enabled; a plain ``threading.Lock`` otherwise.

    ``name`` is the lock *class* (``"storage.cache"``), shared by every
    instance guarding the same kind of state. ``io_ok=False`` declares
    the critical section in-memory-only: blocking-I/O failpoint sites
    reached while held raise :class:`IoUnderLockError`."""
    if not (_ENABLED or force):
        return threading.Lock()
    return _Tracked(threading.Lock(), name, io_ok, reentrant=False)


def TrackedRLock(name: str, *, io_ok: bool = True,
                 force: bool = False) -> Union[threading.RLock, _Tracked]:
    """Re-entrant variant of :func:`TrackedLock`."""
    if not (_ENABLED or force):
        return threading.RLock()
    return _Tracked(threading.RLock(), name, io_ok, reentrant=True)


# -- blocking-I/O-under-lock check -----------------------------------

def note_io_site(site: str) -> None:
    """Called by ``failpoint.fail_point``/``fires`` on every evaluation
    while the detector is enabled: raise if an in-memory-only lock is
    held across a blocking-I/O site."""
    if site not in IO_FAILPOINT_SITES:
        return
    held = getattr(_tls, "held", None)
    if not held:
        return
    for lk in held:
        if not lk.io_ok:
            raise IoUnderLockError(
                f"blocking-I/O failpoint site {site!r} reached while "
                f"holding in-memory-only lock {lk.name!r} (held: "
                f"{[h.name for h in held]})\n{_short_stack()}")


def _install_io_hook() -> None:
    from . import failpoint
    failpoint.set_io_site_hook(note_io_site)


if _ENABLED:
    _install_io_hook()
    # the race detector (devtools/greptsan) decides its own enablement
    # (GREPTIME_RACE_CHECK / pytest); importing it here installs its
    # lock/thread/pool happens-before hooks without requiring every
    # entry point to know it exists. Guarded: a trimmed deployment that
    # ships common/ without devtools/ must still lock-check.
    try:
        from ..devtools.greptsan import detector as _greptsan  # noqa: F401
    except Exception as e:  # noqa: BLE001 — optional tooling, never fatal
        logger.debug("greptsan unavailable; lock-order checking only: %s", e)
