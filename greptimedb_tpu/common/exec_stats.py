"""Per-query execution statistics: the machinery behind EXPLAIN ANALYZE.

Reference behavior: DataFusion's `ExecutionPlan::metrics()` — every
physical operator accumulates row counts and elapsed time, and
`EXPLAIN ANALYZE` renders the annotated plan (the reference surfaces it
through src/query's DataFusion integration). Here an `ExecStats`
collector rides a thread-local during execution; each layer records its
stage with the SAME stage names the storage profilers use
(`Region.last_ingest_profile` / `Region.last_scan_profile`), so traces,
metrics, EXPLAIN ANALYZE and the profilers all tell one story.

Stage vocabulary (shared with the scan/ingest profilers):

- dispatch decision: ``cpu-small-scan`` / ``cpu-fallback`` /
  ``device-resident`` / ``streamed-cold`` / ``aggregate-pushdown``
- streamed scan: ``plan``, ``decode_reduce``, ``device_fetch``,
  ``fold`` (+ counters lean_slices / merged_slices / dedup_skip_slices)
- resident scan: ``scan_prep``, ``reduce``
- CPU fallback: ``scan``, ``filter``, ``aggregate``, ``project``
- shared tail: ``finalize``

The collector is installed per top-level query (`collect()`), is
thread-safe (streamed slices report from pool workers), and a missing
collector makes every record call a no-op, so hot paths pay only a
thread-local read when nobody is watching.

Cluster-wide (ISSUE 6): datanode-side stats cross the RPC boundary —
the Flight datanode server runs each scan/moments/write under its own
collector and ships `to_dict()` back in the response; the frontend's
per-RPC sub-collector `absorb()`s it, and `record_node()` hangs the
whole sub-collector off the statement's collector. `rows_table()` then
renders a per-node, per-stage tree under the dist_scatter line — each
node row naming its actual dispatch plus node-elapsed vs network time —
so a distributed EXPLAIN ANALYZE no longer collapses everything behind
the wire into one number.
"""

from __future__ import annotations

import contextlib
import re
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional

_tls = threading.local()

#: wire key for datanode-side ExecStats riding a Flight response (stream
#: schema metadata on do_get, the JSON ack on do_put) — one definition
#: shared by both sides of the protocol so they cannot drift
EXEC_STATS_WIRE_KEY = b"gdb.exec_stats"


@dataclass
class StageStat:
    stage: str
    rows: int = 0
    files: int = 0
    elapsed_s: float = 0.0
    detail: Dict[str, object] = field(default_factory=dict)

    def detail_str(self) -> str:
        return ", ".join(f"{k}={v}" for k, v in self.detail.items())


class ExecStats:
    """Accumulates per-stage counters for one statement execution."""

    def __init__(self):
        self._lock = threading.Lock()
        self.stages: "OrderedDict[str, StageStat]" = OrderedDict()
        self.dispatch: Optional[str] = None
        self.total_s: float = 0.0
        #: node label -> {"stats": ExecStats, "wall_ms": float} — one
        #: sub-collector per datanode RPC (DistTable._scatter)
        self.nodes: "OrderedDict[str, dict]" = OrderedDict()
        #: sum of remote-reported totals absorbed into THIS collector
        #: (wall - remote_total = wire/serialization cost)
        self.remote_total_ms: float = 0.0

    # ---- recording ----
    def record(self, stage: str, *, rows: int = 0, files: int = 0,
               elapsed_s: float = 0.0, **detail) -> None:
        with self._lock:
            st = self.stages.get(stage)
            if st is None:
                st = self.stages[stage] = StageStat(stage)
            st.rows += int(rows)
            st.files += int(files)
            st.elapsed_s += float(elapsed_s)
            for k, v in detail.items():
                old = st.detail.get(k)
                # numeric details accumulate across regions/slices so a
                # multi-region query reports totals, not the last region
                if isinstance(v, (int, float)) and not isinstance(v, bool) \
                        and isinstance(old, (int, float)) \
                        and not isinstance(old, bool):
                    st.detail[k] = old + v
                else:
                    st.detail[k] = v

    @contextlib.contextmanager
    def stage(self, name: str, **detail) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, elapsed_s=time.perf_counter() - t0, **detail)

    def set_dispatch(self, decision: str) -> None:
        """First decision wins: nested subqueries must not overwrite the
        top-level statement's dispatch line."""
        with self._lock:
            if self.dispatch is None:
                self.dispatch = decision

    def record_node(self, label: str, stats: "ExecStats",
                    wall_ms: float) -> None:
        """Attach one datanode RPC's sub-collector. `wall_ms` is the
        frontend-observed round trip; the node's own total (remote or
        summed stage time) subtracts out to the network share. A second
        scatter in the same statement reusing a label gets `#n`."""
        with self._lock:
            base, n = label, 1
            while label in self.nodes:
                n += 1
                label = f"{base}#{n}"
            self.nodes[label] = {"stats": stats, "wall_ms": float(wall_ms)}

    # ---- wire codec ----
    def to_dict(self) -> Dict:
        """JSON-safe snapshot for shipping over an RPC response."""
        with self._lock:
            return {
                "dispatch": self.dispatch,
                "total_ms": round(self.total_s * 1e3, 3),
                "stages": [{
                    "stage": st.stage, "rows": st.rows, "files": st.files,
                    "elapsed_ms": round(st.elapsed_s * 1e3, 3),
                    "detail": {k: _json_safe(v)
                               for k, v in st.detail.items()},
                } for st in self.stages.values()],
            }

    def absorb(self, d: Dict) -> None:
        """Replay a remote collector's to_dict() into this one (the
        frontend-side twin of the datanode's recording)."""
        if d.get("dispatch"):
            self.set_dispatch(d["dispatch"])
        for st in d.get("stages", ()):
            self.record(st.get("stage", "?"), rows=st.get("rows", 0),
                        files=st.get("files", 0),
                        elapsed_s=float(st.get("elapsed_ms", 0.0)) / 1e3,
                        **(st.get("detail") or {}))
        with self._lock:
            self.remote_total_ms += float(d.get("total_ms", 0.0))

    #: stages whose `rows` mean "rows scanned from storage". The three
    #: are mutually exclusive per region (cpu fallback / resident /
    #: streamed), so summing them never double-counts; `decode` is a
    #: sub-stage of stream_scan and stays out.
    _SCAN_STAGES = frozenset({"scan", "scan_prep", "stream_scan"})

    def totals(self) -> Dict[str, int]:
        """Running resource totals for the process list: rows scanned,
        bytes read off storage, datanode RPCs consumed. Accumulates as
        stages record — a live query reports its progress so far, not
        just a final number — and folds per-node sub-collectors in (a
        distributed scan's rows live on the node blocks)."""
        resident = streamed = streamed_live = 0
        io_bytes = decode_bytes = rpcs = 0
        partial_bytes = partial_wire = 0
        with self._lock:
            for st in self.stages.values():
                if st.stage == "stream_scan":
                    streamed += st.rows
                elif st.stage in self._SCAN_STAGES:
                    resident += st.rows
                if st.stage == "io_read":
                    io_bytes += int(st.detail.get("bytes", 0))
                if st.stage == "finalize":
                    # partial-aggregate frame bytes folded by this
                    # statement (the wire cost aggregate pushdown pays
                    # instead of raw rows), recorded when the fold runs
                    partial_bytes += int(st.detail.get("partial_bytes",
                                                       0))
                if st.stage == "partial_wire":
                    # per-RPC serialized partial bytes, recorded AS each
                    # Flight stream drains — the live floor while the
                    # statement still gathers (finalize lands at the end)
                    partial_wire += int(st.detail.get("bytes", 0))
                if st.stage == "decode":
                    # stream_rows = the streamed share of the decode
                    # rows (the lean reader tags them; the resident
                    # path's read_sst decode rows carry no tag and are
                    # already counted by scan/scan_prep)
                    streamed_live = int(st.detail.get("stream_rows", 0))
                    decode_bytes += int(st.detail.get("bytes", 0))
                rpcs += int(st.detail.get("rpcs", 0))
            nodes = [entry["stats"] for entry in self.nodes.values()]
        # while a streamed scan RUNS, its rows land on `decode` slice by
        # slice and `stream_scan` is only published at the end — the
        # live floor makes a long scan's progress visible in the
        # processes view instead of reading 0 until it finishes, and a
        # mixed resident+cold statement keeps counting its resident
        # rows while the cold region streams
        rows = resident + max(streamed, streamed_live)
        # io_read (object-store bytes) and decode (decoded batch bytes)
        # describe the SAME data at two stages — summing both would
        # double-bill a cold scan. Prefer the storage-side number;
        # decoded bytes stand in for cache-resident scans that never
        # touch the store.
        bytes_read = io_bytes if io_bytes else decode_bytes
        for ns in nodes:
            sub = ns.totals()
            rows += sub["rows_scanned"]
            bytes_read += sub["bytes_read"]
            rpcs += sub["rpcs"]
            # node sub-collectors carry the partial_wire stages their
            # RPCs recorded — the in-flight share of the partial bytes
            partial_wire += sub.get("partial_bytes", 0)
        # finalize (frontend-measured, complete) and partial_wire
        # (per-hop, live) describe the SAME frames at two moments —
        # take the larger, never the sum, so the processes view counts
        # partials while the gather runs without double-billing after
        return {"rows_scanned": rows, "bytes_read": bytes_read,
                "rpcs": rpcs,
                "partial_bytes": max(partial_bytes, partial_wire)}

    def node_elapsed_ms(self, wall_ms: float = 0.0) -> float:
        """The node-side share of a sub-collector: the remote-reported
        total when the stats crossed a wire; for an in-process RPC the
        round trip IS node work (no network), so the wall time itself.
        (Summing stage timings would double-count — a wrapper stage like
        'scan' overlaps the 'decode'/'prune' stages recorded inside its
        window.)"""
        with self._lock:
            if self.remote_total_ms > 0:
                return self.remote_total_ms
        return wall_ms

    # ---- rendering ----
    def summary(self) -> str:
        """One-line digest for the slow-query log."""
        with self._lock:
            parts = [f"dispatch={self.dispatch or 'n/a'}"]
            for st in self.stages.values():
                bit = f"{st.stage}={st.elapsed_s * 1e3:.1f}ms"
                if st.rows:
                    bit += f"/{st.rows}r"
                parts.append(bit)
            if self.nodes:
                parts.append("nodes=" + ",".join(
                    f"{k}:{v['wall_ms']:.1f}ms"
                    for k, v in sorted(self.nodes.items(),
                                       key=lambda kv: node_sort_key(
                                           kv[0]))))
            parts.append(f"total={self.total_s * 1e3:.1f}ms")
        return " ".join(parts)

    def rows_table(self) -> Dict[str, List]:
        """Column dict for the EXPLAIN ANALYZE per-stage batch."""
        cols: Dict[str, List] = {"stage": [], "rows": [], "files": [],
                                 "elapsed_ms": [], "detail": []}

        def add(stage: str, rows: int, files: int, elapsed_ms: float,
                detail: object) -> None:
            cols["stage"].append(stage)
            cols["rows"].append(int(rows))
            cols["files"].append(int(files))
            cols["elapsed_ms"].append(float(elapsed_ms))
            cols["detail"].append(detail)

        with self._lock:
            add("dispatch", 0, 0, 0.0, self.dispatch or "n/a")
            # node blocks sorted by label: gather completion order is
            # nondeterministic, golden files must not be
            node_items = sorted(self.nodes.items(),
                                key=lambda kv: node_sort_key(kv[0]))
            nodes_emitted = False
            for st in self.stages.values():
                add(st.stage, st.rows, st.files, st.elapsed_s * 1e3,
                    st.detail_str())
                if st.stage == "dist_scatter" and not nodes_emitted:
                    nodes_emitted = True
                    _add_node_rows(add, node_items)
            if node_items and not nodes_emitted:
                _add_node_rows(add, node_items)
            add("total", 0, 0, self.total_s * 1e3, "")
        return cols


def node_sort_key(label: str) -> List[object]:
    """Natural order for node labels: dn2 before dn10 (a lexicographic
    sort misorders clusters with 10+ datanodes). Shared by the ANALYZE
    tree, the slow-query nodes= digest, and the node_ms vector."""
    return [int(part) if part.isdigit() else part
            for part in re.split(r"(\d+)", label)]


def _json_safe(v: object) -> object:
    """Detail values may be numpy scalars (row counts summed by storage
    code); coerce to plain JSON types for the wire."""
    if isinstance(v, (str, bool, int, float)) or v is None:
        return v
    item = getattr(v, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:  # noqa: BLE001 — non-scalar .item(): fall back
            return str(v)
    return str(v)


def _add_node_rows(add: "Callable", node_items: "list") -> None:
    """Per-node blocks of the EXPLAIN ANALYZE tree: a header row naming
    the node's actual dispatch + node-vs-network split, then its stage
    rows indented underneath."""
    for label, entry in node_items:
        ns: "ExecStats" = entry["stats"]
        wall_ms = entry["wall_ms"]
        node_ms = ns.node_elapsed_ms(wall_ms)
        net_ms = max(0.0, wall_ms - node_ms)
        with ns._lock:
            stages = list(ns.stages.values())
            dispatch = ns.dispatch
        rows = max((st.rows for st in stages), default=0)
        files = sum(st.files for st in stages)
        add(f"  {label}", rows, files, wall_ms,
            f"dispatch={dispatch or 'n/a'}; node_ms={node_ms:.2f} "
            f"network_ms={net_ms:.2f}")
        for st in stages:
            add(f"    {st.stage}", st.rows, st.files, st.elapsed_s * 1e3,
                st.detail_str())


# ---------------------------------------------------------------------------
# thread-local collector plumbing
# ---------------------------------------------------------------------------

def current() -> Optional[ExecStats]:
    return getattr(_tls, "stats", None)


@contextlib.contextmanager
def collect(stats: Optional[ExecStats] = None) -> Iterator[ExecStats]:
    """Install a collector for the duration of one statement."""
    prev = getattr(_tls, "stats", None)
    s = stats if stats is not None else ExecStats()
    _tls.stats = s
    # publish to the process-list entry (if this statement is tracked):
    # the processes view reads live rows-scanned/bytes/RPC totals off
    # the collector WHILE the query runs
    from . import process_list as _pl
    entry = _pl.current()
    if entry is not None and entry.stats is None:
        entry.stats = s
    t0 = time.perf_counter()
    try:
        yield s
    finally:
        s.total_s += time.perf_counter() - t0
        _tls.stats = prev


@contextlib.contextmanager
def collect_into(stats: Optional[ExecStats]) -> Iterator[None]:
    """Install an EXISTING collector (possibly None) on this thread — no
    timing, no creation. Used by telemetry.propagate to carry the
    query's collector into pool workers."""
    prev = getattr(_tls, "stats", None)
    _tls.stats = stats
    try:
        yield
    finally:
        _tls.stats = prev


def record(stage: str, **kwargs) -> None:
    s = current()
    if s is not None:
        s.record(stage, **kwargs)


def absorb_remote(d) -> None:
    """Replay a remote to_dict() into the active collector, if any —
    what a wire client calls after parsing the response's stats."""
    s = current()
    if s is not None and d:
        s.absorb(d)


def set_dispatch(decision: str) -> None:
    s = current()
    if s is not None:
        s.set_dispatch(decision)


@contextlib.contextmanager
def stage(name: str, **detail) -> Iterator[None]:
    s = current()
    if s is None:
        yield
        return
    with s.stage(name, **detail):
        yield
