"""Per-query execution statistics: the machinery behind EXPLAIN ANALYZE.

Reference behavior: DataFusion's `ExecutionPlan::metrics()` — every
physical operator accumulates row counts and elapsed time, and
`EXPLAIN ANALYZE` renders the annotated plan (the reference surfaces it
through src/query's DataFusion integration). Here an `ExecStats`
collector rides a thread-local during execution; each layer records its
stage with the SAME stage names the storage profilers use
(`Region.last_ingest_profile` / `Region.last_scan_profile`), so traces,
metrics, EXPLAIN ANALYZE and the profilers all tell one story.

Stage vocabulary (shared with the scan/ingest profilers):

- dispatch decision: ``cpu-small-scan`` / ``cpu-fallback`` /
  ``device-resident`` / ``streamed-cold`` / ``aggregate-pushdown``
- streamed scan: ``plan``, ``decode_reduce``, ``device_fetch``,
  ``fold`` (+ counters lean_slices / merged_slices / dedup_skip_slices)
- resident scan: ``scan_prep``, ``reduce``
- CPU fallback: ``scan``, ``filter``, ``aggregate``, ``project``
- shared tail: ``finalize``

The collector is installed per top-level query (`collect()`), is
thread-safe (streamed slices report from pool workers), and a missing
collector makes every record call a no-op, so hot paths pay only a
thread-local read when nobody is watching.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

_tls = threading.local()


@dataclass
class StageStat:
    stage: str
    rows: int = 0
    files: int = 0
    elapsed_s: float = 0.0
    detail: Dict[str, object] = field(default_factory=dict)

    def detail_str(self) -> str:
        return ", ".join(f"{k}={v}" for k, v in self.detail.items())


class ExecStats:
    """Accumulates per-stage counters for one statement execution."""

    def __init__(self):
        self._lock = threading.Lock()
        self.stages: "OrderedDict[str, StageStat]" = OrderedDict()
        self.dispatch: Optional[str] = None
        self.total_s: float = 0.0

    # ---- recording ----
    def record(self, stage: str, *, rows: int = 0, files: int = 0,
               elapsed_s: float = 0.0, **detail) -> None:
        with self._lock:
            st = self.stages.get(stage)
            if st is None:
                st = self.stages[stage] = StageStat(stage)
            st.rows += int(rows)
            st.files += int(files)
            st.elapsed_s += float(elapsed_s)
            for k, v in detail.items():
                old = st.detail.get(k)
                # numeric details accumulate across regions/slices so a
                # multi-region query reports totals, not the last region
                if isinstance(v, (int, float)) and not isinstance(v, bool) \
                        and isinstance(old, (int, float)) \
                        and not isinstance(old, bool):
                    st.detail[k] = old + v
                else:
                    st.detail[k] = v

    @contextlib.contextmanager
    def stage(self, name: str, **detail) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, elapsed_s=time.perf_counter() - t0, **detail)

    def set_dispatch(self, decision: str) -> None:
        """First decision wins: nested subqueries must not overwrite the
        top-level statement's dispatch line."""
        with self._lock:
            if self.dispatch is None:
                self.dispatch = decision

    # ---- rendering ----
    def summary(self) -> str:
        """One-line digest for the slow-query log."""
        with self._lock:
            parts = [f"dispatch={self.dispatch or 'n/a'}"]
            for st in self.stages.values():
                bit = f"{st.stage}={st.elapsed_s * 1e3:.1f}ms"
                if st.rows:
                    bit += f"/{st.rows}r"
                parts.append(bit)
            parts.append(f"total={self.total_s * 1e3:.1f}ms")
        return " ".join(parts)

    def rows_table(self) -> Dict[str, List]:
        """Column dict for the EXPLAIN ANALYZE per-stage batch."""
        cols: Dict[str, List] = {"stage": [], "rows": [], "files": [],
                                 "elapsed_ms": [], "detail": []}

        def add(stage, rows, files, elapsed_ms, detail):
            cols["stage"].append(stage)
            cols["rows"].append(int(rows))
            cols["files"].append(int(files))
            cols["elapsed_ms"].append(float(elapsed_ms))
            cols["detail"].append(detail)

        with self._lock:
            add("dispatch", 0, 0, 0.0, self.dispatch or "n/a")
            for st in self.stages.values():
                add(st.stage, st.rows, st.files, st.elapsed_s * 1e3,
                    st.detail_str())
            add("total", 0, 0, self.total_s * 1e3, "")
        return cols


# ---------------------------------------------------------------------------
# thread-local collector plumbing
# ---------------------------------------------------------------------------

def current() -> Optional[ExecStats]:
    return getattr(_tls, "stats", None)


@contextlib.contextmanager
def collect(stats: Optional[ExecStats] = None) -> Iterator[ExecStats]:
    """Install a collector for the duration of one statement."""
    prev = getattr(_tls, "stats", None)
    s = stats if stats is not None else ExecStats()
    _tls.stats = s
    t0 = time.perf_counter()
    try:
        yield s
    finally:
        s.total_s += time.perf_counter() - t0
        _tls.stats = prev


@contextlib.contextmanager
def collect_into(stats: Optional[ExecStats]) -> Iterator[None]:
    """Install an EXISTING collector (possibly None) on this thread — no
    timing, no creation. Used by telemetry.propagate to carry the
    query's collector into pool workers."""
    prev = getattr(_tls, "stats", None)
    _tls.stats = stats
    try:
        yield
    finally:
        _tls.stats = prev


def record(stage: str, **kwargs) -> None:
    s = current()
    if s is not None:
        s.record(stage, **kwargs)


def set_dispatch(decision: str) -> None:
    s = current()
    if s is not None:
        s.set_dispatch(decision)


@contextlib.contextmanager
def stage(name: str, **detail) -> Iterator[None]:
    s = current()
    if s is None:
        yield
        return
    with s.stage(name, **detail):
        yield
