"""Named runtimes: shared thread pools + repeated tasks.

Reference behavior: src/common/runtime — named tokio runtimes with
`spawn_bg/spawn_read/spawn_write` globals (global.rs) and `RepeatedTask`
(repeated_task.rs). Python twin: shared ThreadPoolExecutors sized for
their roles; background storage jobs, scan fan-out, protocol write
handling, and the distributed scatter-gather each land on their own pool
so a flood of one cannot starve the others.

The ``dist`` pool is the long-lived executor behind the frontend's
datanode fan-out (frontend/distributed.py): RPCs to N datanodes overlap
instead of summing, and the per-query in-flight window is bounded by the
``dist_fanout`` knob (``SET dist_fanout`` / ``GREPTIME_DIST_FANOUT``)
so one wide query cannot monopolize every connection.
"""

from __future__ import annotations

import concurrent.futures
import os
import threading
from typing import Callable, Iterable, Iterator, Optional

from ..storage.scheduler import RepeatedTask  # canonical impl, re-export

__all__ = ["RepeatedTask", "spawn_bg", "spawn_read", "spawn_write",
           "bg_runtime", "read_runtime", "write_runtime", "dist_runtime",
           "dist_fanout", "configure_dist_fanout", "env_int",
           "shutdown_runtimes", "new_thread", "transient_executor",
           "spawn_on"]

_lock = threading.Lock()
_pools = {}

_SIZES = {"bg": 4, "read": 8, "write": 8, "dist": 16}


from ..utils import env_flag, env_float, env_int  # noqa: F401 — canonical
# impl in the utils leaf module (storage/ imports it too); re-exported
# here because runtime is where knob readers historically find env_int


#: per-query bound on concurrently in-flight datanode RPCs (the pool
#: above bounds the process; this bounds one statement's share)
_DIST_FANOUT = [max(1, env_int("GREPTIME_DIST_FANOUT", 8))]


def dist_fanout() -> int:
    return _DIST_FANOUT[0]


def configure_dist_fanout(n: int) -> None:
    """SET dist_fanout — 1 serializes the scatter (the pre-parallel
    behavior, kept for differential benchmarks and debugging)."""
    with _lock:
        _DIST_FANOUT[0] = max(1, int(n))


def _pool(name: str) -> concurrent.futures.ThreadPoolExecutor:
    with _lock:
        pool = _pools.get(name)
        if pool is None:
            pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=_SIZES[name],
                thread_name_prefix=f"gdb-{name}")
            _pools[name] = pool
        return pool


def bg_runtime() -> concurrent.futures.ThreadPoolExecutor:
    return _pool("bg")


def read_runtime() -> concurrent.futures.ThreadPoolExecutor:
    return _pool("read")


def write_runtime() -> concurrent.futures.ThreadPoolExecutor:
    return _pool("write")


def dist_runtime() -> concurrent.futures.ThreadPoolExecutor:
    return _pool("dist")


def spawn_bg(fn: Callable, *args: object,
             **kwargs: object) -> "concurrent.futures.Future":
    from .telemetry import propagate
    return bg_runtime().submit(propagate(fn), *args, **kwargs)


def spawn_read(fn: Callable, *args: object,
               **kwargs: object) -> "concurrent.futures.Future":
    from .telemetry import propagate
    return read_runtime().submit(propagate(fn), *args, **kwargs)


def spawn_write(fn: Callable, *args: object,
                **kwargs: object) -> "concurrent.futures.Future":
    from .telemetry import propagate
    return write_runtime().submit(propagate(fn), *args, **kwargs)


def new_thread(target: Callable, *, name: Optional[str] = None,
               daemon: bool = True, args: tuple = (),
               propagate_context: bool = True) -> threading.Thread:
    """The one sanctioned way to start a dedicated thread (greptlint
    GL06): the target is wrapped in ``telemetry.propagate()`` so the
    worker inherits the creating thread's span + ExecStats context
    instead of silently detaching from its query. Long-lived accept
    loops pass ``propagate_context=False`` — they outlive any request
    and must NOT pin the creator's trace."""
    if propagate_context:
        from .telemetry import propagate
        target = propagate(target)
    return threading.Thread(target=target, name=name, daemon=daemon,
                            args=args)


def transient_executor(max_workers: int,
                       name: str = "transient"
                       ) -> concurrent.futures.ThreadPoolExecutor:
    """A short-lived PLAIN pool: its ``.submit()`` does NOT carry trace
    context — submit through :func:`spawn_on`, or pre-wrap the callable
    in ``telemetry.propagate()`` (what query/stream_exec does). Prefer
    the named shared runtimes for steady-state work (a transient pool
    per call churns threads)."""
    return concurrent.futures.ThreadPoolExecutor(
        max_workers=max_workers, thread_name_prefix=f"gdb-{name}")


def spawn_on(pool: concurrent.futures.Executor, fn: Callable,
             *args: object, **kwargs: object) -> "concurrent.futures.Future":
    """submit() with telemetry context carried onto the worker."""
    from .telemetry import propagate
    return pool.submit(propagate(fn), *args, **kwargs)


def shutdown_runtimes(wait: bool = True) -> None:
    with _lock:
        pools, _pools_copy = dict(_pools), _pools.clear()
    for pool in pools.values():
        pool.shutdown(wait=wait)


def parallel_map(fn: Callable, items: "Iterable", *, max_workers: int = 8,
                 pool: Optional[concurrent.futures.Executor] = None) -> list:
    """Map fn over items with a thread pool; serial for <=1 item/worker.

    The storage IO fan-outs (SST read/decode, per-bucket SST encode/write)
    share this: parquet + zstd drop the GIL, so concurrent workers overlap
    IO and (de)compression. Pass ``pool`` (e.g. ``dist_runtime()``) to run
    on a shared long-lived executor instead of a transient one —
    ``max_workers`` then bounds this call's in-flight window, not the
    pool."""
    return list(parallel_imap(fn, items, max_workers=max_workers,
                              pool=pool))


def parallel_imap(fn: Callable, items: "Iterable", *,
                  max_workers: int = 8,
                  pool: Optional[concurrent.futures.Executor] = None
                  ) -> Iterator:
    """parallel_map but yielding results in order as they become ready, so
    the consumer can process-and-drop (pipelined gather) instead of
    barriering on the slowest item."""
    items = list(items)
    if len(items) <= 1 or max_workers <= 1:
        for x in items:
            yield fn(x)
        return
    from .telemetry import propagate
    fn = propagate(fn)       # workers stay parented to the caller's trace
    if pool is not None:
        yield from _bounded_ordered(pool, fn, items, max_workers)
        return
    from concurrent.futures import ThreadPoolExecutor
    with ThreadPoolExecutor(max_workers=min(max_workers, len(items))) as p:
        yield from p.map(fn, items)


def _bounded_ordered(pool: concurrent.futures.Executor, fn: Callable,
                     items, window: int) -> Iterator:
    """Ordered streaming map over a SHARED executor with at most `window`
    items of this call in flight (a transient pool gets the same bound
    from its worker count; a shared pool needs it explicitly, or one
    call could queue its whole fan-out ahead of everyone else's)."""
    from collections import deque
    it = iter(items)
    pending: "deque" = deque()
    for x in it:
        pending.append(pool.submit(fn, x))
        if len(pending) >= window:
            break
    try:
        while pending:
            res = pending.popleft().result()   # oldest first: ordered
            # refill only after the oldest completed, so in-flight never
            # exceeds the window (the others kept running meanwhile)
            for x in it:
                pending.append(pool.submit(fn, x))
                break
            yield res
    finally:
        # abort OR abandoned consumer (GeneratorExit at the yield):
        # cancel what hasn't started — orphaned work must not occupy the
        # SHARED pool's slots after the statement failed (already-running
        # futures finish; their results are dropped)
        for f in pending:
            f.cancel()
