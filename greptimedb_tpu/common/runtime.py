"""Named runtimes: shared thread pools + repeated tasks.

Reference behavior: src/common/runtime — named tokio runtimes with
`spawn_bg/spawn_read/spawn_write` globals (global.rs) and `RepeatedTask`
(repeated_task.rs). Python twin: three shared ThreadPoolExecutors sized
for their roles; background storage jobs, scan fan-out, and protocol
write handling each land on their own pool so a flood of one cannot
starve the others.
"""

from __future__ import annotations

import concurrent.futures
import threading
from typing import Callable, Optional

from ..storage.scheduler import RepeatedTask  # canonical impl, re-export

__all__ = ["RepeatedTask", "spawn_bg", "spawn_read", "spawn_write",
           "bg_runtime", "read_runtime", "write_runtime",
           "shutdown_runtimes"]

_lock = threading.Lock()
_pools = {}

_SIZES = {"bg": 4, "read": 8, "write": 8}


def _pool(name: str) -> concurrent.futures.ThreadPoolExecutor:
    with _lock:
        pool = _pools.get(name)
        if pool is None:
            pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=_SIZES[name],
                thread_name_prefix=f"gdb-{name}")
            _pools[name] = pool
        return pool


def bg_runtime() -> concurrent.futures.ThreadPoolExecutor:
    return _pool("bg")


def read_runtime() -> concurrent.futures.ThreadPoolExecutor:
    return _pool("read")


def write_runtime() -> concurrent.futures.ThreadPoolExecutor:
    return _pool("write")


def spawn_bg(fn: Callable, *args, **kwargs):
    from .telemetry import propagate
    return bg_runtime().submit(propagate(fn), *args, **kwargs)


def spawn_read(fn: Callable, *args, **kwargs):
    from .telemetry import propagate
    return read_runtime().submit(propagate(fn), *args, **kwargs)


def spawn_write(fn: Callable, *args, **kwargs):
    from .telemetry import propagate
    return write_runtime().submit(propagate(fn), *args, **kwargs)


def shutdown_runtimes(wait: bool = True) -> None:
    with _lock:
        pools, _pools_copy = dict(_pools), _pools.clear()
    for pool in pools.values():
        pool.shutdown(wait=wait)


def parallel_map(fn: Callable, items, *, max_workers: int = 8) -> list:
    """Map fn over items with a transient thread pool; serial for <=1 item.

    The storage IO fan-outs (SST read/decode, per-bucket SST encode/write)
    share this: parquet + zstd drop the GIL, so concurrent workers overlap
    IO and (de)compression."""
    items = list(items)
    if len(items) <= 1:
        return [fn(x) for x in items]
    from concurrent.futures import ThreadPoolExecutor
    from .telemetry import propagate
    fn = propagate(fn)       # workers stay parented to the caller's trace
    with ThreadPoolExecutor(max_workers=min(max_workers, len(items))) as p:
        return list(p.map(fn, items))


def parallel_imap(fn: Callable, items, *, max_workers: int = 8):
    """parallel_map but yielding results in order as they become ready, so
    the consumer can process-and-drop instead of holding every result."""
    items = list(items)
    if len(items) <= 1:
        for x in items:
            yield fn(x)
        return
    from concurrent.futures import ThreadPoolExecutor
    from .telemetry import propagate
    fn = propagate(fn)
    with ThreadPoolExecutor(max_workers=min(max_workers, len(items))) as p:
        yield from p.map(fn, items)
