"""tracked_state indirection: core runtime must not hard-depend on
devtools.

Every engine/meta/frontend structure that opts into greptsan race
detection imports :func:`tracked_state` from HERE, not from
``devtools.greptsan`` directly — a trimmed deployment that ships the
runtime without ``devtools/`` degrades to the identity function (no
tracking, no crash at import), the same contract as common/locks.py's
guarded greptsan import.
"""

from __future__ import annotations

from typing import Any

try:
    from ..devtools.greptsan import tracked_state as tracked_state
# the defined fallback IS the degraded value; GL01's walker cannot see
# a def as "handled", hence the inline suppression
except Exception:  # noqa: BLE001  # greptlint: disable=GL01
    def tracked_state(obj: Any, name: str) -> Any:
        """Identity fallback: devtools absent, nothing is tracked."""
        return obj

__all__ = ["tracked_state"]
