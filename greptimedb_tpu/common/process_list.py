"""Active-statement registry: `information_schema.processes`, `SHOW
PROCESSLIST`, and cooperative `KILL <id>`.

Reference behavior: GreptimeDB's process-list manager (the
`PROCESS_LIST` information-schema table fed by a per-frontend catalog
of running statements, each carrying its query text, start time and a
cancellation handle that `KILL` trips). Here the registry is
process-global — one per Python process, shared by the standalone and
distributed frontends and by every protocol server, since they all
funnel through `do_query`.

Mechanics:

- both frontends wrap each statement in :func:`track`, which registers
  an entry (id, statement text, protocol, trace id, start time) and
  installs it on a thread-local; ``telemetry.propagate()`` carries the
  entry into pool workers, so cancellation checks deep in the streamed
  scan fire even on prefetch threads.
- the entry holds a live reference to the statement's ExecStats
  collector (``common/exec_stats.collect`` publishes it the moment the
  query installs one), so ``processes`` reports rows-scanned /
  bytes-read / RPCs *while the query runs*, not just at the end.
- ``KILL <id>`` sets the entry's cancel event; the scan / scatter
  loops call :func:`check_cancelled` at batch boundaries and raise
  :class:`~..errors.QueryCancelledError`. Aborted gathers cancel their
  queued futures (common/runtime._bounded_ordered's finally), so a
  killed fan-out releases its dist-pool slots instead of orphaning
  work.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from typing import Dict, Iterator, List, Optional

from ..errors import InvalidArgumentsError, QueryCancelledError

_tls = threading.local()

#: thread ident -> the entry currently installed on that thread. The
#: thread-local answers "what is MY statement" (cancellation checks);
#: this map answers the profiler's inverse question — "whose statement
#: is THAT thread running" — for stacks sampled from outside.
from .locks import TrackedLock as _TrackedLock
from .tracking import tracked_state as _tracked_state

_threads_lock = _TrackedLock("common.process_list_threads")
_BY_THREAD: Dict[int, "ProcessEntry"] = _tracked_state(
    {}, "process_list.by_thread")


def _bind_thread(entry: Optional["ProcessEntry"]) -> None:
    tid = threading.get_ident()
    with _threads_lock:
        if entry is not None:
            _BY_THREAD[tid] = entry
        else:
            _BY_THREAD.pop(tid, None)


def entries_by_thread() -> Dict[int, "ProcessEntry"]:
    """Snapshot for the stack sampler: which thread runs which
    statement right now (frontend threads via track(), pool workers via
    telemetry.propagate -> install())."""
    with _threads_lock:
        return dict(_BY_THREAD)


class ProcessEntry:
    """One running statement."""

    __slots__ = ("id", "query", "protocol", "catalog", "schema", "node",
                 "trace_id", "start", "start_unix_ms", "_cancel", "stats")

    def __init__(self, pid: int, query: str, protocol: str, catalog: str,
                 schema: str, node: str, trace_id: Optional[str]):
        self.id = pid
        self.query = query
        self.protocol = protocol
        self.catalog = catalog
        self.schema = schema
        self.node = node
        self.trace_id = trace_id
        self.start = time.perf_counter()
        self.start_unix_ms = int(time.time() * 1000)
        self._cancel = threading.Event()
        #: the statement's live ExecStats collector (set by
        #: exec_stats.collect when the query installs one); running
        #: resource totals for the processes view read off it
        self.stats = None

    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def kill(self) -> None:
        self._cancel.set()

    def elapsed_ms(self) -> float:
        return (time.perf_counter() - self.start) * 1e3

    def state(self) -> str:
        return "cancelling" if self.cancelled() else "running"

    def totals(self) -> Dict[str, int]:
        stats = self.stats
        if stats is None:
            return {"rows_scanned": 0, "bytes_read": 0, "rpcs": 0,
                    "partial_bytes": 0}
        return stats.totals()

    def row(self) -> Dict[str, object]:
        t = self.totals()
        return {
            "id": self.id, "node": self.node, "catalog": self.catalog,
            "schema": self.schema, "query": self.query,
            "protocol": self.protocol, "state": self.state(),
            "trace_id": self.trace_id or "",
            "elapsed_ms": self.elapsed_ms(),
            "rows_scanned": t["rows_scanned"],
            "bytes_read": t["bytes_read"], "rpcs": t["rpcs"],
            "partial_bytes": t.get("partial_bytes", 0),
        }


class ProcessRegistry:
    """All running statements of this process, keyed by id."""

    def __init__(self, node: str = "standalone"):
        from .tracking import tracked_state
        from .locks import TrackedLock
        self._lock = TrackedLock("common.process_registry")
        self._entries: Dict[int, ProcessEntry] = tracked_state(
            {}, "process_list.entries")
        self._ids = itertools.count(1)
        self.node = node

    def register(self, query: str, protocol: str, catalog: str,
                 schema: str, trace_id: Optional[str]) -> ProcessEntry:
        entry = ProcessEntry(next(self._ids), query, protocol, catalog,
                             schema, self.node, trace_id)
        with self._lock:
            self._entries[entry.id] = entry
        return entry

    def deregister(self, entry: ProcessEntry) -> None:
        with self._lock:
            self._entries.pop(entry.id, None)

    def kill(self, pid: int) -> None:
        """Trip a statement's cancel event. Unknown (or already
        finished) ids are a clean user error, never a crash. The kill
        counter lives HERE so every path — SQL KILL, mysql
        COM_PROCESS_KILL — counts alike."""
        with self._lock:
            entry = self._entries.get(pid)
        if entry is None:
            raise InvalidArgumentsError(
                f"KILL {pid}: no such running query (it may have "
                f"already finished)")
        entry.kill()
        from .telemetry import increment_counter
        increment_counter("kill")

    def rows(self) -> List[Dict[str, object]]:
        """One snapshot dict per running statement, id-ordered — the
        builder behind information_schema.processes and SHOW
        PROCESSLIST."""
        with self._lock:
            entries = sorted(self._entries.values(), key=lambda e: e.id)
        return [e.row() for e in entries]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


#: the process-wide registry every frontend + protocol server shares
REGISTRY = ProcessRegistry()


def configure_node(label: str) -> None:
    """Name this process in the `node` column of the processes view —
    the frontends call it at construction ("standalone" / "frontend"),
    so a cluster operator can tell which frontend owns a statement
    before issuing KILL (the registry, and therefore KILL, is
    per-process)."""
    REGISTRY.node = label


def current() -> Optional[ProcessEntry]:
    return getattr(_tls, "entry", None)


@contextlib.contextmanager
def install(entry: Optional[ProcessEntry]) -> Iterator[None]:
    """Install an EXISTING entry (possibly None) on this thread — what
    telemetry.propagate uses to carry the statement's handle into pool
    workers."""
    prev = getattr(_tls, "entry", None)
    _tls.entry = entry
    _bind_thread(entry)
    try:
        yield
    finally:
        _tls.entry = prev
        _bind_thread(prev)


@contextlib.contextmanager
def track(query: str, *, protocol: str = "http",
          catalog: str = "", schema: str = "",
          trace_id: Optional[str] = None) -> Iterator[ProcessEntry]:
    """Register one statement for its execution window and expose it on
    this thread for cancellation checks."""
    entry = REGISTRY.register(query, protocol, catalog, schema, trace_id)
    prev = getattr(_tls, "entry", None)
    _tls.entry = entry
    _bind_thread(entry)
    try:
        yield entry
    finally:
        _tls.entry = prev
        _bind_thread(prev)
        REGISTRY.deregister(entry)


def check_cancelled() -> None:
    """Cooperative cancellation point: raise when the current statement
    was killed. A no-op (one thread-local read) outside any tracked
    statement — safe on hot paths."""
    entry = getattr(_tls, "entry", None)
    if entry is not None and entry.cancelled():
        raise QueryCancelledError(
            f"query {entry.id} was killed (KILL {entry.id})")
