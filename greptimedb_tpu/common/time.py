"""Timestamps with multiple units and time ranges.

Reference behavior: src/common/time/src/{timestamp.rs,range.rs} — a
`Timestamp` is an i64 value plus a unit (s/ms/us/ns); conversions between
units; `TimestampRange` is a half-open [start, end) range used for SST
pruning and window queries.
"""

from __future__ import annotations

import datetime as _dt
import enum
import re
from dataclasses import dataclass
from typing import Optional


class TimeUnit(enum.Enum):
    SECOND = "s"
    MILLISECOND = "ms"
    MICROSECOND = "us"
    NANOSECOND = "ns"

    @property
    def factor(self) -> int:
        """Ticks of this unit per second... inverted: number of this unit in one second."""
        return _FACTORS[self]

    def short_name(self) -> str:
        return self.value


_FACTORS = {
    TimeUnit.SECOND: 1,
    TimeUnit.MILLISECOND: 1_000,
    TimeUnit.MICROSECOND: 1_000_000,
    TimeUnit.NANOSECOND: 1_000_000_000,
}

_EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)


@dataclass(frozen=True, order=False, eq=False)
class Timestamp:
    value: int
    unit: TimeUnit = TimeUnit.MILLISECOND

    def convert_to(self, unit: TimeUnit) -> "Timestamp":
        """Convert to another unit. Down-conversion truncates toward
        negative infinity (floor), matching integer arithmetic on the
        storage path."""
        if unit == self.unit:
            return self
        sf, tf = self.unit.factor, unit.factor
        if tf >= sf:
            mul = tf // sf
            return Timestamp(self.value * mul, unit)
        div = sf // tf
        # floor division keeps ordering for negative timestamps
        return Timestamp(self.value // div, unit)

    def to_millis(self) -> int:
        return self.convert_to(TimeUnit.MILLISECOND).value

    def to_datetime(self) -> _dt.datetime:
        # integer path: microsecond resolution is datetime's limit anyway
        us = Timestamp(self.value, self.unit).convert_to(TimeUnit.MICROSECOND).value
        return _EPOCH + _dt.timedelta(microseconds=us)

    def to_iso8601(self) -> str:
        return self.to_datetime().isoformat()

    @staticmethod
    def from_datetime(dt: _dt.datetime, unit: TimeUnit = TimeUnit.MILLISECOND) -> "Timestamp":
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=_dt.timezone.utc)
        delta = dt - _EPOCH
        # integer arithmetic: float total_seconds() loses ns/us precision
        us = (delta.days * 86_400 + delta.seconds) * 1_000_000 + delta.microseconds
        return Timestamp(us, TimeUnit.MICROSECOND).convert_to(unit)

    @staticmethod
    def from_str(s: str, unit: TimeUnit = TimeUnit.MILLISECOND) -> "Timestamp":
        """Parse '2023-01-02 03:04:05[.fff]' / ISO8601 / raw integer strings."""
        s = s.strip()
        if re.fullmatch(r"[+-]?\d+", s):
            return Timestamp(int(s), unit)
        txt = s.replace("T", " ")
        # strip timezone suffix 'Z' or +hh:mm
        tz = _dt.timezone.utc
        m = re.search(r"([+-]\d{2}:?\d{2}|Z)$", txt)
        if m:
            suffix = m.group(1)
            txt = txt[: m.start()].strip()
            if suffix not in ("Z", "+00:00", "+0000"):
                sign = 1 if suffix[0] == "+" else -1
                hh = int(suffix[1:3])
                mm = int(suffix[-2:])
                tz = _dt.timezone(sign * _dt.timedelta(hours=hh, minutes=mm))
        fmts = ["%Y-%m-%d %H:%M:%S.%f", "%Y-%m-%d %H:%M:%S", "%Y-%m-%d"]
        for fmt in fmts:
            try:
                dt = _dt.datetime.strptime(txt, fmt).replace(tzinfo=tz)
                return Timestamp.from_datetime(dt, unit)
            except ValueError:
                continue
        raise ValueError(f"invalid timestamp literal: {s!r}")

    # ordering/equality/hash all compare the actual instant, across units
    def _cmp_key(self) -> int:
        return self.convert_to(TimeUnit.NANOSECOND).value

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Timestamp):
            return NotImplemented
        return self._cmp_key() == other._cmp_key()

    def __hash__(self) -> int:
        return hash(self._cmp_key())

    def __lt__(self, other: "Timestamp") -> bool:
        return self._cmp_key() < other._cmp_key()

    def __le__(self, other: "Timestamp") -> bool:
        return self._cmp_key() <= other._cmp_key()

    def __gt__(self, other: "Timestamp") -> bool:
        return self._cmp_key() > other._cmp_key()

    def __ge__(self, other: "Timestamp") -> bool:
        return self._cmp_key() >= other._cmp_key()


@dataclass(frozen=True)
class TimestampRange:
    """Half-open range [start, end) in a single unit; None = unbounded."""

    start: Optional[int] = None
    end: Optional[int] = None
    unit: TimeUnit = TimeUnit.MILLISECOND

    def is_empty(self) -> bool:
        return self.start is not None and self.end is not None and self.start >= self.end

    def contains(self, value: int) -> bool:
        if self.start is not None and value < self.start:
            return False
        if self.end is not None and value >= self.end:
            return False
        return True

    def intersects(self, other: "TimestampRange") -> bool:
        assert self.unit == other.unit, "unit mismatch"
        lo = max(x for x in (self.start, other.start) if x is not None) \
            if (self.start is not None or other.start is not None) else None
        hi = min(x for x in (self.end, other.end) if x is not None) \
            if (self.end is not None or other.end is not None) else None
        if lo is None or hi is None:
            return True
        return lo < hi

    def intersect(self, other: "TimestampRange") -> "TimestampRange":
        assert self.unit == other.unit
        starts = [x for x in (self.start, other.start) if x is not None]
        ends = [x for x in (self.end, other.end) if x is not None]
        return TimestampRange(max(starts) if starts else None,
                              min(ends) if ends else None, self.unit)


_DURATION_RE = re.compile(
    r"(?P<value>\d+(?:\.\d+)?)(?P<unit>ms|us|ns|[smhdwy])")


def parse_duration_ms(s: str) -> int:
    """Parse PromQL/humantime-style durations ('5m', '1h30m', '100ms') → ms."""
    s = s.strip()
    if not s:
        raise ValueError("empty duration")
    pos = 0
    total = 0.0
    for m in _DURATION_RE.finditer(s):
        if m.start() != pos:
            raise ValueError(f"invalid duration: {s!r}")
        pos = m.end()
        v = float(m.group("value"))
        u = m.group("unit")
        mult = {
            "ns": 1e-6, "us": 1e-3, "ms": 1.0, "s": 1e3, "m": 6e4,
            "h": 3.6e6, "d": 8.64e7, "w": 6.048e8, "y": 3.1536e10,
        }[u]
        total += v * mult
    if pos != len(s):
        raise ValueError(f"invalid duration: {s!r}")
    return int(total)


def parse_prom_time(v, default: Optional[float] = None) -> Optional[int]:
    """Prometheus API time parameter: unix seconds (float/str) or RFC3339
    → epoch ms (reference: src/servers/src/prom.rs query params)."""
    if v is None or v == "":
        if default is None:
            return None
        return int(float(default) * 1000)
    if isinstance(v, (int, float)):
        return int(float(v) * 1000)
    s = str(v).strip().strip("'\"")
    try:
        return int(float(s) * 1000)
    except ValueError:
        pass
    import pandas as pd
    return int(pd.Timestamp(s).value // 1_000_000)


def parse_prom_duration(v) -> int:
    """Prometheus step/duration parameter: '15s' / '1m' / bare seconds → ms."""
    if isinstance(v, (int, float)):
        return int(float(v) * 1000)
    s = str(v).strip().strip("'\"")
    try:
        return int(float(s) * 1000)
    except ValueError:
        pass
    try:
        return parse_duration_ms(s)
    except ValueError as e:
        from ..errors import InvalidArgumentsError
        raise InvalidArgumentsError(f"invalid duration {v!r}") from e
