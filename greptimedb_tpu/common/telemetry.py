"""Telemetry: logging init, tracing spans, timer metrics.

Reference behavior: src/common/telemetry — tracing-subscriber logging
with rolling files + env filter (logging.rs:83-150), `timer!` macros
feeding the metrics recorder (metric.rs, macros.rs), and a panic hook.
Python twin:

- `init_logging(level, dir)` — console + size-rotated file handlers.
- `span(name, **attrs)` — nested tracing spans carried in a thread-local
  (trace_id/span_id/parent), logged on exit with duration; the active
  trace context rides log records via a logging.Filter.
- `timer(name)` — histogram observation (prometheus_client, the same
  registry the /metrics endpoint exports).
- `install_panic_hook()` — top-level excepthook that logs crashes.
"""

from __future__ import annotations

import contextlib
import logging
import logging.handlers
import os
import sys
import threading
import time
import uuid
from typing import Dict, Iterator, Optional

logger = logging.getLogger(__name__)

_tls = threading.local()


# ---------------------------------------------------------------------------
# logging init (reference: logging.rs init w/ rolling appenders)
# ---------------------------------------------------------------------------

_FORMAT = ("%(asctime)s %(levelname)s %(name)s "
           "[%(trace_id)s/%(span_id)s] %(message)s")


class _TraceContextFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        span = current_span()
        record.trace_id = span["trace_id"] if span else "-"
        record.span_id = span["span_id"] if span else "-"
        return True


def init_logging(level: str = "info", log_dir: Optional[str] = None,
                 max_bytes: int = 64 * 1024 * 1024,
                 backups: int = 4) -> None:
    root = logging.getLogger()
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    for h in list(root.handlers):
        root.removeHandler(h)
    handlers = [logging.StreamHandler()]
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        handlers.append(logging.handlers.RotatingFileHandler(
            os.path.join(log_dir, "greptimedb.log"),
            maxBytes=max_bytes, backupCount=backups))
    for h in handlers:
        h.setFormatter(logging.Formatter(_FORMAT))
        h.addFilter(_TraceContextFilter())
        root.addHandler(h)


def install_panic_hook() -> None:
    """Log uncaught exceptions before dying (reference: panic_hook.rs)."""
    prev = sys.excepthook

    def hook(exc_type, exc, tb):
        logging.getLogger("panic").critical(
            "uncaught exception", exc_info=(exc_type, exc, tb))
        prev(exc_type, exc, tb)

    sys.excepthook = hook


# ---------------------------------------------------------------------------
# tracing spans
# ---------------------------------------------------------------------------

def current_span() -> Optional[Dict]:
    stack = getattr(_tls, "spans", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def span(name: str, **attrs) -> Iterator[Dict]:
    """Nested span: inherits trace_id from the parent, logs duration on
    exit at DEBUG (the in-process analog of the Jaeger pipeline)."""
    stack = getattr(_tls, "spans", None)
    if stack is None:
        stack = _tls.spans = []
    parent = stack[-1] if stack else None
    s = {
        "name": name,
        "trace_id": parent["trace_id"] if parent else uuid.uuid4().hex[:16],
        "span_id": uuid.uuid4().hex[:8],
        "parent_id": parent["span_id"] if parent else None,
        "attrs": attrs,
        "start": time.perf_counter(),
    }
    stack.append(s)
    try:
        yield s
    finally:
        stack.pop()
        elapsed_ms = (time.perf_counter() - s["start"]) * 1e3
        logger.debug("span %s finished in %.2fms attrs=%s", name,
                     elapsed_ms, attrs)
        _observe(f"span_{name}", elapsed_ms / 1e3)


# ---------------------------------------------------------------------------
# timer metrics (prometheus registry shared with /metrics)
# ---------------------------------------------------------------------------

_metrics_lock = threading.Lock()
_histograms: Dict[str, object] = {}
_counters: Dict[str, object] = {}


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _observe(name: str, seconds: float) -> None:
    try:
        from prometheus_client import Histogram
    except ImportError:  # pragma: no cover
        return
    key = _sanitize(name)
    with _metrics_lock:
        h = _histograms.get(key)
        if h is None:
            h = Histogram(f"greptime_{key}_seconds", f"timer {name}")
            _histograms[key] = h
    h.observe(seconds)


def increment_counter(name: str, value: int = 1) -> None:
    try:
        from prometheus_client import Counter
    except ImportError:  # pragma: no cover
        return
    key = _sanitize(name)
    with _metrics_lock:
        c = _counters.get(key)
        if c is None:
            c = Counter(f"greptime_{key}_total", f"counter {name}")
            _counters[key] = c
    c.inc(value)


@contextlib.contextmanager
def timer(name: str) -> Iterator[None]:
    """reference `timer!` macro: records elapsed seconds on exit."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _observe(name, time.perf_counter() - t0)
