"""Telemetry: logging init, tracing spans, timer metrics.

Reference behavior: src/common/telemetry — tracing-subscriber logging
with rolling files + env filter (logging.rs:83-150), `timer!` macros
feeding the metrics recorder (metric.rs, macros.rs), and a panic hook.
Python twin:

- `init_logging(level, dir)` — console + size-rotated file handlers.
- `span(name, **attrs)` — nested tracing spans carried in a thread-local
  (trace_id/span_id/parent), logged on exit with duration; the active
  trace context rides log records via a logging.Filter.
- `current_traceparent()` / `remote_context(header)` — W3C-traceparent
  wire propagation: every cross-process RPC (Flight scan/moments/write,
  SQL-over-Flight, meta actions, HTTP `traceparent` header) carries the
  caller's trace context, and the receiving process installs it so its
  spans JOIN the caller's trace instead of minting a fresh one. One
  statement = one trace id across frontend, datanodes and meta.
- `propagate(fn)` — capture the caller's span stack at submit time and
  re-install it around `fn` in whatever worker thread runs it, so spans
  opened on the `common/runtime` pools stay parented to the trace.
- `timer(name)` — histogram observation (prometheus_client, the same
  registry the /metrics endpoint exports).
- `slow_query_threshold_ms()` — the SET/env-configurable threshold the
  frontend checks per statement (None = slow-query log off).
- `install_panic_hook()` — top-level excepthook that logs crashes.
"""

from __future__ import annotations

import contextlib
import logging
import logging.handlers
import os
import sys
import threading
import time
import uuid
from typing import (Callable, Dict, Iterator, List, Optional, Sequence,
                    Tuple)

logger = logging.getLogger(__name__)

_tls = threading.local()


# ---------------------------------------------------------------------------
# logging init (reference: logging.rs init w/ rolling appenders)
# ---------------------------------------------------------------------------

_FORMAT = ("%(asctime)s %(levelname)s %(name)s "
           "[%(trace_id)s/%(span_id)s] %(message)s")


class _TraceContextFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        span = current_span()
        record.trace_id = span["trace_id"] if span else "-"
        record.span_id = span["span_id"] if span else "-"
        return True


def init_logging(level: str = "info", log_dir: Optional[str] = None,
                 max_bytes: int = 64 * 1024 * 1024,
                 backups: int = 4) -> None:
    root = logging.getLogger()
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    for h in list(root.handlers):
        root.removeHandler(h)
    handlers = [logging.StreamHandler()]
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        handlers.append(logging.handlers.RotatingFileHandler(
            os.path.join(log_dir, "greptimedb.log"),
            maxBytes=max_bytes, backupCount=backups))
    for h in handlers:
        h.setFormatter(logging.Formatter(_FORMAT))
        h.addFilter(_TraceContextFilter())
        root.addHandler(h)


def install_panic_hook() -> None:
    """Log uncaught exceptions before dying (reference: panic_hook.rs)."""
    prev = sys.excepthook

    def hook(exc_type: type, exc: BaseException, tb: object) -> None:
        logging.getLogger("panic").critical(
            "uncaught exception", exc_info=(exc_type, exc, tb))
        prev(exc_type, exc, tb)

    sys.excepthook = hook


# ---------------------------------------------------------------------------
# tracing spans
# ---------------------------------------------------------------------------

def current_span() -> Optional[Dict]:
    stack = getattr(_tls, "spans", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def span(name: str, **attrs: object) -> Iterator[Dict]:
    """Nested span: inherits trace_id from the parent, logs duration on
    exit at DEBUG, and (when configured) ships to an OTLP collector."""
    stack = getattr(_tls, "spans", None)
    if stack is None:
        stack = _tls.spans = []
    parent = stack[-1] if stack else None
    # full 16-byte trace / 8-byte span ids: they travel verbatim in W3C
    # traceparent headers, so both processes log the SAME hex string
    s = {
        "name": name,
        "trace_id": parent["trace_id"] if parent else uuid.uuid4().hex,
        "span_id": uuid.uuid4().hex[:16],
        "parent_id": parent["span_id"] if parent else None,
        # a parent installed by remote_context() means the trace ROOT
        # lives in another process — the trace sink's tail-sampling
        # verdict logic keys off this (a frontend decides for traces an
        # external client rooted; a datanode buffers them)
        "remote_parent": bool(parent
                              and (parent.get("attrs") or {}).get("remote")),
        "attrs": attrs,
        "start": time.perf_counter(),
        "start_unix_ns": time.time_ns(),
    }
    stack.append(s)
    status = "ok"
    try:
        yield s
    except BaseException as e:  # greptlint: disable=GL02 — classified,
        status = _exc_status(e)  # re-raised untouched
        raise
    finally:
        stack.pop()
        elapsed_ms = (time.perf_counter() - s["start"]) * 1e3
        logger.debug("span %s finished in %.2fms attrs=%s", name,
                     elapsed_ms, attrs)
        _observe(f"span_{name}", elapsed_ms / 1e3)
        if not metrics_suppressed():
            exporter = _OTLP[0]
            if exporter is not None:
                exporter.enqueue(s, int(elapsed_ms * 1e6))
            sink = _SPAN_SINK[0]
            if sink is not None:
                try:
                    sink.on_span_end(s, elapsed_ms, status)
                except Exception:  # noqa: BLE001 — the sink must never
                    logger.exception(    # break the traced path
                        "trace sink rejected span %s", name)


def _exc_status(e: BaseException) -> str:
    """Span status for an exception crossing the span boundary: KILLed
    statements read as 'cancelled' (they are tail-retained like errors,
    but an operator filters them apart)."""
    from ..errors import QueryCancelledError
    return "cancelled" if isinstance(e, QueryCancelledError) else "error"


@contextlib.contextmanager
def root_span(name: str, **attrs: object) -> Iterator[Dict]:
    """Open a span that ROOTS a fresh trace regardless of the ambient
    context, restoring the caller's stack afterward. Background jobs
    (flush, compaction, flow folds, balancer steps) use this: the work
    belongs to no statement's trace, and rooting it makes the trace
    sink's tail verdict fire at ITS completion."""
    prev = getattr(_tls, "spans", None)
    _tls.spans = []
    try:
        with span(name, **attrs) as s:
            yield s
    finally:
        _tls.spans = prev if prev is not None else []


#: pluggable span sink (common/trace_store.TraceSink): completed spans
#: feed the tail-sampled durable trace store, alongside the OTLP export
_SPAN_SINK: list = [None]


def set_span_sink(sink) -> None:
    with _metrics_lock:
        _SPAN_SINK[0] = sink


def propagate(fn: Callable) -> Callable:
    """Capture the calling thread's span stack NOW and return a callable
    that re-installs it around `fn` wherever it runs.

    `_tls.spans` is thread-local, so a stage submitted to a worker pool
    detaches from its parent trace: spans it opens start a fresh
    trace_id and the OTLP export shows them orphaned. Wrapping the
    submitted callable fixes that — the capture happens at submit (the
    moment the parent span is live), not at execution. The parent span
    dicts are shared read-only; the worker appends to its own list, so
    concurrent workers never see each other's nesting.

    The active ExecStats collector (common/exec_stats.py) rides along
    for the same reason: per-stage EXPLAIN ANALYZE counters recorded by
    pool workers (SST reads, slice decodes) land on the query's
    collector instead of vanishing. ExecStats methods are lock-guarded,
    so concurrent workers may share one collector.

    The active process-list entry (common/process_list.py) and the
    metric-suppression flag travel too: a KILL must be observable from
    a prefetch worker's cancellation check, and the self-monitoring
    scraper's pooled writes must stay excluded from the counters it
    scrapes."""
    from . import exec_stats as _es
    from . import process_list as _pl
    stack = getattr(_tls, "spans", None)
    stats = _es.current()
    entry = _pl.current()
    suppressed = metrics_suppressed()
    if not stack and stats is None and entry is None and not suppressed:
        return fn
    captured = list(stack) if stack else []
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):  # type: ignore[no-untyped-def]
        prev = getattr(_tls, "spans", None)
        prev_sup = getattr(_tls, "suppress_metrics", False)
        _tls.spans = list(captured)
        _tls.suppress_metrics = suppressed
        with _es.collect_into(stats), _pl.install(entry):
            try:
                return fn(*args, **kwargs)
            finally:
                _tls.spans = prev if prev is not None else []
                _tls.suppress_metrics = prev_sup
    return wrapped


# ---------------------------------------------------------------------------
# wire trace propagation (W3C traceparent: 00-<trace>-<span>-<flags>)
# ---------------------------------------------------------------------------

def current_traceparent() -> Optional[str]:
    """W3C traceparent header for the active span, or None outside a
    trace. Attach this to every outbound RPC (Flight ticket / action
    body / do_put command, HTTP header) so the receiving process joins
    this trace."""
    s = current_span()
    if s is None:
        return None
    trace = s["trace_id"][:32].ljust(32, "0")
    span_id = s["span_id"][:16].ljust(16, "0")
    return f"00-{trace}-{span_id}-01"


def parse_traceparent(header: Optional[str]) -> Optional[tuple]:
    """(trace_id, parent_span_id) from a traceparent header; None when
    absent or malformed (propagation is advisory — a bad header must
    never fail a request)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    version, trace, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if len(version) != 2 or len(trace) != 32 or len(span_id) != 16 \
            or len(flags) != 2:
        return None
    try:
        int(version, 16), int(trace, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    # W3C: version 0xff is forbidden; version 00 has exactly 4 fields
    # (higher versions may append more — parse their known prefix);
    # all-zero trace/parent ids are invalid and must be treated as absent
    if version.lower() == "ff" or (version == "00" and len(parts) != 4) \
            or int(trace, 16) == 0 or int(span_id, 16) == 0:
        return None
    return trace, span_id


@contextlib.contextmanager
def remote_context(traceparent: Optional[str]) -> Iterator[Optional[Dict]]:
    """Install a remote caller's trace context on this thread for the
    duration: spans opened underneath inherit the remote trace_id and
    parent onto the caller's span, and log records carry the shared
    trace id. A missing/malformed header is a no-op (fresh trace)."""
    parsed = parse_traceparent(traceparent)
    if parsed is None:
        yield None
        return
    trace_id, span_id = parsed
    stack = getattr(_tls, "spans", None)
    if stack is None:
        stack = _tls.spans = []
    frame = {
        "name": "remote",
        "trace_id": trace_id,
        "span_id": span_id,
        "parent_id": None,
        "attrs": {"remote": True},
        "start": time.perf_counter(),
        "start_unix_ns": time.time_ns(),
    }
    stack.append(frame)
    try:
        yield frame
    finally:
        if stack and stack[-1] is frame:
            stack.pop()
        elif frame in stack:          # defensive: unbalanced nesting
            stack.remove(frame)


# ---------------------------------------------------------------------------
# slow-query log threshold (reference: the slow-query timer in
# src/common/telemetry logging options — statements slower than the
# threshold log at WARN with their trace id and stage stats)
# ---------------------------------------------------------------------------

def _env_slow_query_ms() -> Optional[int]:
    raw = os.environ.get("GREPTIME_SLOW_QUERY_MS")
    if not raw:
        return None
    try:
        v = int(raw)
    except ValueError:
        return None
    return v if v > 0 else None


_SLOW_QUERY_MS: list = [_env_slow_query_ms()]


def slow_query_threshold_ms() -> Optional[int]:
    """Current slow-query threshold in ms; None = disabled (default,
    unless the GREPTIME_SLOW_QUERY_MS env/config set one)."""
    return _SLOW_QUERY_MS[0]


def set_slow_query_threshold_ms(value: Optional[int]) -> None:
    """SET slow_query_threshold_ms — 0 or negative disables."""
    if value is not None and value <= 0:
        value = None
    with _metrics_lock:
        _SLOW_QUERY_MS[0] = value


# ---------------------------------------------------------------------------
# OTLP trace export (reference: the OpenTelemetry pipeline wired in
# src/common/telemetry/src/logging.rs:83-150 — tracing-opentelemetry
# layer + otlp exporter behind config)
# ---------------------------------------------------------------------------

_OTLP: list = [None]


class OtlpExporter:
    """Background OTLP/HTTP-JSON span exporter: bounded queue, batched
    POSTs to `{endpoint}/v1/traces`, dropped (and counted) rather than
    ever blocking the traced path."""

    def __init__(self, endpoint: str, service_name: str = "greptimedb",
                 flush_interval: float = 2.0, max_queue: int = 4096):
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self.flush_interval = flush_interval
        self.max_queue = max_queue
        self.dropped = 0
        self.exported = 0
        self._buf: list = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="otlp-exporter")
        self._thread.start()

    def enqueue(self, s: Dict, duration_ns: int) -> None:
        start_ns = s.get("start_unix_ns") or time.time_ns()
        rec = {
            # OTLP requires 16-byte trace / 8-byte span ids (hex)
            "traceId": s["trace_id"].ljust(32, "0"),
            "spanId": s["span_id"].ljust(16, "0"),
            "name": s["name"],
            "kind": 1,                            # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(start_ns + duration_ns),
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in (s.get("attrs") or {}).items()],
        }
        if s.get("parent_id"):
            rec["parentSpanId"] = s["parent_id"].ljust(16, "0")
        with self._lock:
            if len(self._buf) >= self.max_queue:
                self.dropped += 1
                full = True
            else:
                self._buf.append(rec)
                full = False
        if full:
            # beyond the one-shot debug log: a silently-shedding exporter
            # must be visible in runtime_metrics / the scrape tables
            increment_counter("trace_export_dropped")

    def _run(self) -> None:
        while not self._stop.wait(self.flush_interval):
            self.flush()
        self.flush()

    def flush(self) -> None:
        with self._lock:
            batch, self._buf = self._buf, []
        if not batch:
            return
        import json as _json
        import urllib.request
        doc = {"resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": self.service_name}}]},
            "scopeSpans": [{
                "scope": {"name": "greptimedb_tpu"},
                "spans": batch,
            }],
        }]}
        req = urllib.request.Request(
            self.endpoint + "/v1/traces",
            data=_json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=5):
                pass
            self.exported += len(batch)
        except Exception as e:  # noqa: BLE001 — export must never break
            self.dropped += len(batch)
            increment_counter("trace_export_dropped", len(batch))
            logger.debug("otlp export failed: %s", e)

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


def configure_otlp(endpoint: Optional[str],
                   service_name: str = "greptimedb",
                   flush_interval: float = 2.0) -> Optional[OtlpExporter]:
    """Enable (or, with endpoint=None, disable) OTLP span export."""
    with _metrics_lock:
        old, _OTLP[0] = _OTLP[0], None
    if old is not None:
        old.shutdown()        # flushes over the network: outside the lock
    exporter = None
    if endpoint:
        exporter = OtlpExporter(endpoint, service_name=service_name,
                                flush_interval=flush_interval)
        with _metrics_lock:
            _OTLP[0] = exporter
    return exporter


# ---------------------------------------------------------------------------
# metric suppression (self-monitoring recursion guard)
# ---------------------------------------------------------------------------

def metrics_suppressed() -> bool:
    return getattr(_tls, "suppress_metrics", False)


@contextlib.contextmanager
def suppress_metrics() -> Iterator[None]:
    """Make every metric observation on this thread a no-op for the
    duration (timers, counters, latency histograms, OTLP span export).

    The self-monitoring scraper writes its registry snapshot through the
    NORMAL ingest path; without this guard those writes would bump the
    very counters the next tick scrapes (stmt/ingest/WAL counters), so
    an idle cluster's metrics would grow forever from the act of
    recording them. propagate() carries the flag into pool workers, so
    the exclusion covers fanned-out parts of a system-table write too."""
    prev = getattr(_tls, "suppress_metrics", False)
    _tls.suppress_metrics = True
    try:
        yield
    finally:
        _tls.suppress_metrics = prev


# ---------------------------------------------------------------------------
# timer metrics (prometheus registry shared with /metrics)
# ---------------------------------------------------------------------------

from .locks import TrackedLock as _TrackedLock
from .tracking import tracked_state as _tracked_state

_metrics_lock = _TrackedLock("common.telemetry_metrics")
_histograms: Dict[str, object] = _tracked_state(
    {}, "telemetry.histograms")
_counters: Dict[str, object] = _tracked_state({}, "telemetry.counters")
#: sanitized key → the original name that claimed it. Distinct originals
#: sanitizing to one key ("a.b" and "a-b" → "a_b") used to silently share
#: one time series; now the newcomer is deterministically disambiguated
#: (crc suffix) and the collision is logged.
_sanitized_owners: Dict[str, str] = _tracked_state(
    {}, "telemetry.sanitized_owners")


def _sanitize(name: str) -> str:
    # takes _metrics_lock itself (callers call it BEFORE their own
    # acquire): two threads first-time-sanitizing colliding names must
    # agree on one owner, and the collision remap below is check-then-set
    key = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    with _metrics_lock:
        owner = _sanitized_owners.setdefault(key, name)
        collided = owner != name
    if collided:
        import zlib
        crc = zlib.crc32(name.encode()) & 0xFFFF
        key2 = f"{key}_x{crc:04x}"
        with _metrics_lock:
            first_remap = key2 not in _sanitized_owners
            if first_remap:
                _sanitized_owners[key2] = name
        if first_remap:
            logger.error(
                "metric name collision: %r and %r both sanitize to %r; "
                "recording %r as %r instead", owner, name, key, name, key2)
        return key2
    return key


def _observe(name: str, seconds: float) -> None:
    if metrics_suppressed():
        return
    try:
        from prometheus_client import Histogram
    except ImportError:  # pragma: no cover
        return
    key = _sanitize(name)
    with _metrics_lock:
        h = _histograms.get(key)
        if h is None:
            h = Histogram(f"greptime_{key}_seconds", f"timer {name}")
            _histograms[key] = h
    h.observe(seconds)


def increment_counter(name: str, value: int = 1) -> None:
    if metrics_suppressed():
        return
    try:
        from prometheus_client import Counter
    except ImportError:  # pragma: no cover
        return
    key = _sanitize(name)
    with _metrics_lock:
        c = _counters.get(key)
        if c is None:
            c = Counter(f"greptime_{key}_total", f"counter {name}")
            _counters[key] = c
    c.inc(value)


@contextlib.contextmanager
def timer(name: str) -> Iterator[None]:
    """reference `timer!` macro: records elapsed seconds on exit."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _observe(name, time.perf_counter() - t0)


# ---------------------------------------------------------------------------
# latency histograms (log-bucketed; reference: the HISTOGRAM_* statics in
# src/servers/src/metrics.rs — per-protocol request latency distributions
# exported in Prometheus histogram text format)
# ---------------------------------------------------------------------------

#: geometric (×2) bucket bounds, 100µs … ~52s: log-spaced so one layout
#: resolves both a 300µs cache hit and a 30s cold scan with bounded
#: relative error; exported as cumulative `le` buckets on /metrics.
LATENCY_BUCKETS = tuple(1e-4 * (2.0 ** k) for k in range(20))

#: sanitized key → (Histogram, labelnames) for observe_latency metrics
_latency_hists: Dict[str, tuple] = _tracked_state(
    {}, "telemetry.latency_hists")

#: (key, labelnames) pairs already warned about — mismatches log once
_latency_label_mismatches: set = _tracked_state(
    set(), "telemetry.latency_label_mismatches")


def observe_latency(name: str, seconds: float,
                    **labels: object) -> None:
    """Record one observation on the log-bucketed latency histogram
    `greptime_<name>_seconds{**labels}`. Label NAMES must be stable per
    metric (prometheus fixes them at creation); a mismatched call is
    dropped with an error instead of raising on a hot path."""
    if metrics_suppressed():
        return
    try:
        from prometheus_client import Histogram
    except ImportError:  # pragma: no cover
        return
    key = _sanitize(name)
    labelnames = tuple(sorted(labels))
    with _metrics_lock:
        entry = _latency_hists.get(key)
        if entry is None:
            try:
                h = Histogram(f"greptime_{key}_seconds", f"latency {name}",
                              labelnames=labelnames,
                              buckets=LATENCY_BUCKETS)
            except ValueError:
                # name already registered (e.g. a timer() minted
                # greptime_<key>_seconds first): drop observations
                # instead of raising on the request hot path, and cache
                # the verdict so only the first call pays the logging
                logger.error(
                    "latency metric %r collides with an existing "
                    "greptime_%s_seconds series; observations dropped",
                    name, key)
                h = None
            entry = _latency_hists[key] = (h, labelnames)
    h, created_names = entry
    if h is None:
        return
    if created_names != labelnames:
        # log once per (metric, label-set) pair, not once per statement:
        # a mismatched hot-path call site would otherwise flood the log
        # at request rate
        warn_key = (key, labelnames)
        with _metrics_lock:
            seen = warn_key in _latency_label_mismatches
            _latency_label_mismatches.add(warn_key)
        if not seen:
            logger.error("latency metric %r called with labels %r but "
                         "created with %r; observations dropped", name,
                         labelnames, created_names)
        return
    (h.labels(**labels) if labelnames else h).observe(float(seconds))


# ---------------------------------------------------------------------------
# registry snapshot (the ONE reader behind /metrics-equivalent views:
# information_schema.runtime_metrics and the self-monitoring scraper both
# consume this, so what lands in greptime_private.node_metrics is exactly
# what the endpoint would have served at that instant)
# ---------------------------------------------------------------------------

def collect_families() -> list:
    """One walk of the default Prometheus registry (the same registry
    prometheus_client.generate_latest serves on /metrics)."""
    try:
        from prometheus_client import REGISTRY
    except ImportError:  # pragma: no cover — prometheus is baked in
        return []
    return list(REGISTRY.collect())


def registry_snapshot(families: Optional[list] = None
                      ) -> List[Tuple[str, str, float, str]]:
    """Every sample in the registry as (name, labels_str, value, kind)
    rows. Pass pre-collected `families` to share one registry walk with
    other consumers (runtime_metrics reuses it for the pXX rows)."""
    if families is None:
        families = collect_families()
    rows = []
    for family in families:
        for s in family.samples:
            labels = "{" + ", ".join(
                f'{k}="{v}"' for k, v in sorted(s.labels.items())) + "}" \
                if s.labels else ""
            rows.append((s.name, labels, float(s.value), family.type))
    return rows


def latency_summaries(quantiles: Sequence[float] = (0.5, 0.95, 0.99),
                      families: Optional[list] = None
                      ) -> List[Tuple[str, str, float]]:
    """(name_pNN, labels_str, value_seconds) estimates for every
    histogram in the registry, interpolated from its cumulative buckets —
    the p50/p95/p99 rows information_schema.runtime_metrics serves next
    to the raw counters. Pass `families` (pre-collected metric families)
    to reuse one registry walk for both the raw samples and these
    summaries."""
    if families is None:
        try:
            from prometheus_client import REGISTRY
        except ImportError:  # pragma: no cover
            return []
        families = REGISTRY.collect()
    out = []
    for family in families:
        if family.type != "histogram":
            continue
        groups: Dict[tuple, list] = {}
        for s in family.samples:
            if not s.name.endswith("_bucket"):
                continue
            key = tuple(sorted((k, v) for k, v in s.labels.items()
                               if k != "le"))
            groups.setdefault(key, []).append(
                (float(s.labels["le"]), float(s.value)))
        for key, buckets in groups.items():
            buckets.sort()
            total = buckets[-1][1]
            if total <= 0:
                continue
            labels = "{" + ", ".join(f'{k}="{v}"' for k, v in key) + "}" \
                if key else ""
            for q in quantiles:
                target = q * total
                prev_le, prev_c = 0.0, 0.0
                value = buckets[-1][0]
                for le, c in buckets:
                    if c >= target:
                        if le == float("inf"):
                            # open-ended tail: clamp at the last finite
                            # bound instead of inventing a magnitude
                            value = prev_le
                        else:
                            frac = (target - prev_c) / max(c - prev_c,
                                                           1e-12)
                            value = prev_le + (le - prev_le) * frac
                        break
                    prev_le, prev_c = le, c
                out.append((f"{family.name}_seconds_p{int(q * 100)}"
                            if not family.name.endswith("_seconds")
                            else f"{family.name}_p{int(q * 100)}",
                            labels, value))
    return out
