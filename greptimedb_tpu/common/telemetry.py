"""Telemetry: logging init, tracing spans, timer metrics.

Reference behavior: src/common/telemetry — tracing-subscriber logging
with rolling files + env filter (logging.rs:83-150), `timer!` macros
feeding the metrics recorder (metric.rs, macros.rs), and a panic hook.
Python twin:

- `init_logging(level, dir)` — console + size-rotated file handlers.
- `span(name, **attrs)` — nested tracing spans carried in a thread-local
  (trace_id/span_id/parent), logged on exit with duration; the active
  trace context rides log records via a logging.Filter.
- `timer(name)` — histogram observation (prometheus_client, the same
  registry the /metrics endpoint exports).
- `install_panic_hook()` — top-level excepthook that logs crashes.
"""

from __future__ import annotations

import contextlib
import logging
import logging.handlers
import os
import sys
import threading
import time
import uuid
from typing import Dict, Iterator, Optional

logger = logging.getLogger(__name__)

_tls = threading.local()


# ---------------------------------------------------------------------------
# logging init (reference: logging.rs init w/ rolling appenders)
# ---------------------------------------------------------------------------

_FORMAT = ("%(asctime)s %(levelname)s %(name)s "
           "[%(trace_id)s/%(span_id)s] %(message)s")


class _TraceContextFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        span = current_span()
        record.trace_id = span["trace_id"] if span else "-"
        record.span_id = span["span_id"] if span else "-"
        return True


def init_logging(level: str = "info", log_dir: Optional[str] = None,
                 max_bytes: int = 64 * 1024 * 1024,
                 backups: int = 4) -> None:
    root = logging.getLogger()
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    for h in list(root.handlers):
        root.removeHandler(h)
    handlers = [logging.StreamHandler()]
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        handlers.append(logging.handlers.RotatingFileHandler(
            os.path.join(log_dir, "greptimedb.log"),
            maxBytes=max_bytes, backupCount=backups))
    for h in handlers:
        h.setFormatter(logging.Formatter(_FORMAT))
        h.addFilter(_TraceContextFilter())
        root.addHandler(h)


def install_panic_hook() -> None:
    """Log uncaught exceptions before dying (reference: panic_hook.rs)."""
    prev = sys.excepthook

    def hook(exc_type, exc, tb):
        logging.getLogger("panic").critical(
            "uncaught exception", exc_info=(exc_type, exc, tb))
        prev(exc_type, exc, tb)

    sys.excepthook = hook


# ---------------------------------------------------------------------------
# tracing spans
# ---------------------------------------------------------------------------

def current_span() -> Optional[Dict]:
    stack = getattr(_tls, "spans", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def span(name: str, **attrs) -> Iterator[Dict]:
    """Nested span: inherits trace_id from the parent, logs duration on
    exit at DEBUG, and (when configured) ships to an OTLP collector."""
    stack = getattr(_tls, "spans", None)
    if stack is None:
        stack = _tls.spans = []
    parent = stack[-1] if stack else None
    s = {
        "name": name,
        "trace_id": parent["trace_id"] if parent else uuid.uuid4().hex[:16],
        "span_id": uuid.uuid4().hex[:8],
        "parent_id": parent["span_id"] if parent else None,
        "attrs": attrs,
        "start": time.perf_counter(),
        "start_unix_ns": time.time_ns(),
    }
    stack.append(s)
    try:
        yield s
    finally:
        stack.pop()
        elapsed_ms = (time.perf_counter() - s["start"]) * 1e3
        logger.debug("span %s finished in %.2fms attrs=%s", name,
                     elapsed_ms, attrs)
        _observe(f"span_{name}", elapsed_ms / 1e3)
        exporter = _OTLP[0]
        if exporter is not None:
            exporter.enqueue(s, int(elapsed_ms * 1e6))


# ---------------------------------------------------------------------------
# OTLP trace export (reference: the OpenTelemetry pipeline wired in
# src/common/telemetry/src/logging.rs:83-150 — tracing-opentelemetry
# layer + otlp exporter behind config)
# ---------------------------------------------------------------------------

_OTLP: list = [None]


class OtlpExporter:
    """Background OTLP/HTTP-JSON span exporter: bounded queue, batched
    POSTs to `{endpoint}/v1/traces`, dropped (and counted) rather than
    ever blocking the traced path."""

    def __init__(self, endpoint: str, service_name: str = "greptimedb",
                 flush_interval: float = 2.0, max_queue: int = 4096):
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self.flush_interval = flush_interval
        self.max_queue = max_queue
        self.dropped = 0
        self.exported = 0
        self._buf: list = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="otlp-exporter")
        self._thread.start()

    def enqueue(self, s: Dict, duration_ns: int) -> None:
        start_ns = s.get("start_unix_ns") or time.time_ns()
        rec = {
            # OTLP requires 16-byte trace / 8-byte span ids (hex)
            "traceId": s["trace_id"].ljust(32, "0"),
            "spanId": s["span_id"].ljust(16, "0"),
            "name": s["name"],
            "kind": 1,                            # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(start_ns + duration_ns),
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in (s.get("attrs") or {}).items()],
        }
        if s.get("parent_id"):
            rec["parentSpanId"] = s["parent_id"].ljust(16, "0")
        with self._lock:
            if len(self._buf) >= self.max_queue:
                self.dropped += 1
                return
            self._buf.append(rec)

    def _run(self) -> None:
        while not self._stop.wait(self.flush_interval):
            self.flush()
        self.flush()

    def flush(self) -> None:
        with self._lock:
            batch, self._buf = self._buf, []
        if not batch:
            return
        import json as _json
        import urllib.request
        doc = {"resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": self.service_name}}]},
            "scopeSpans": [{
                "scope": {"name": "greptimedb_tpu"},
                "spans": batch,
            }],
        }]}
        req = urllib.request.Request(
            self.endpoint + "/v1/traces",
            data=_json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=5):
                pass
            self.exported += len(batch)
        except Exception as e:  # noqa: BLE001 — export must never break
            self.dropped += len(batch)
            logger.debug("otlp export failed: %s", e)

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


def configure_otlp(endpoint: Optional[str],
                   service_name: str = "greptimedb",
                   flush_interval: float = 2.0) -> Optional[OtlpExporter]:
    """Enable (or, with endpoint=None, disable) OTLP span export."""
    old = _OTLP[0]
    if old is not None:
        old.shutdown()
        _OTLP[0] = None
    if endpoint:
        _OTLP[0] = OtlpExporter(endpoint, service_name=service_name,
                                flush_interval=flush_interval)
    return _OTLP[0]


# ---------------------------------------------------------------------------
# timer metrics (prometheus registry shared with /metrics)
# ---------------------------------------------------------------------------

_metrics_lock = threading.Lock()
_histograms: Dict[str, object] = {}
_counters: Dict[str, object] = {}


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _observe(name: str, seconds: float) -> None:
    try:
        from prometheus_client import Histogram
    except ImportError:  # pragma: no cover
        return
    key = _sanitize(name)
    with _metrics_lock:
        h = _histograms.get(key)
        if h is None:
            h = Histogram(f"greptime_{key}_seconds", f"timer {name}")
            _histograms[key] = h
    h.observe(seconds)


def increment_counter(name: str, value: int = 1) -> None:
    try:
        from prometheus_client import Counter
    except ImportError:  # pragma: no cover
        return
    key = _sanitize(name)
    with _metrics_lock:
        c = _counters.get(key)
        if c is None:
            c = Counter(f"greptime_{key}_total", f"counter {name}")
            _counters[key] = c
    c.inc(value)


@contextlib.contextmanager
def timer(name: str) -> Iterator[None]:
    """reference `timer!` macro: records elapsed seconds on exit."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _observe(name, time.perf_counter() - t0)
