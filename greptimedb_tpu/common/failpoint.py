"""Process-wide failpoint registry for fault injection.

Reference behavior: the reference hardens its LSM write path with
`fail`-crate failpoints (src/storage/src/flush.rs `fail_point!` macros,
tests-integration fail-point tests). This is the Python twin: hot
mutation paths call :func:`fail_point` with a stable name; an operator
(or the torture harness, tests/torture.py) arms a point with an action
and the next evaluation fires it.

Activation surfaces (all feed :func:`configure`):

- env: ``GREPTIME_FAILPOINTS="wal_append=err;flush_commit=crash"``
  (parsed at import; ``refresh_from_env()`` re-reads it)
- SQL: ``SET failpoint_<name> = 'action'`` (``'off'`` clears)
- HTTP: ``POST /v1/admin/failpoints?name=<name>&action=<action>``

Action grammar (``parse_action``)::

    spec   := [ N 'x' M '*' ] kind [ '(' arg ')' ]
    kind   := 'err' | 'crash' | 'delay' | 'off'

- ``err`` / ``err(msg)`` — raise :class:`FailpointError`;
  ``err(transient)`` marks it retryable (RetryingObjectStore retries it).
- ``crash`` — raise :class:`SimulatedCrash`, a BaseException standing in
  for ``kill -9``: no ``except Exception`` recovery path may swallow it;
  only the torture harness catches it and then reopens from disk.
- ``delay(ms)`` — sleep that many milliseconds, then continue.
- ``NxM*`` prefix — fire on N of every M evaluations (``1x3*err`` =
  one-in-three failure rate). Without it every evaluation fires.

Zero overhead when inactive: every entry point checks the module-level
``_ACTIVE`` bool first — one global load + branch per instrumented call,
no dict lookup, no lock (BASELINE.md publishes the bench delta).
Evaluation while armed takes a lock; failpoints are a test/debug surface,
never a production hot path.
"""

from __future__ import annotations

import contextlib
import logging
import os
import re
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ..errors import GreptimeError

logger = logging.getLogger(__name__)


class FailpointError(GreptimeError):
    """Error injected by an armed failpoint (action ``err``)."""

    def __init__(self, msg: str, transient: bool = False):
        super().__init__(msg)
        self.transient = transient


class SimulatedCrash(BaseException):
    """Simulated process kill (action ``crash``).

    Derives from BaseException so generic ``except Exception`` recovery
    code cannot swallow it — exactly like a real SIGKILL, the only thing
    the process gets to rely on afterwards is what already hit disk."""


_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_SPEC_RE = re.compile(r"^(?:(\d+)x(\d+)\*)?([a-z]+)(?:\((.*)\))?$")

_lock = threading.Lock()
#: every point the codebase registered (import time) or that was ever
#: configured — the information_schema.failpoints view lists these
_points: "Dict[str, _Point]" = {}
#: module-level fast-path guard: False ⇔ no failpoint is armed anywhere
_ACTIVE = False
#: optional observer invoked with the site name on EVERY evaluation
#: (armed or not) — common/locks.py installs its blocking-I/O-under-lock
#: check here when the lock-order detector is enabled. None in
#: production: the inactive fast path stays one extra is-None branch.
_IO_HOOK = None


def set_io_site_hook(hook: "Optional[Callable[[str], None]]") -> None:
    """Install (or with None remove) the per-evaluation site observer."""
    global _IO_HOOK
    _IO_HOOK = hook


class _Point:
    __slots__ = ("name", "spec", "kind", "arg", "fire_n", "window_m",
                 "hits", "fires", "_count")

    def __init__(self, name: str):
        self.name = name
        self.spec: Optional[str] = None   # raw action string, None = off
        self.kind: Optional[str] = None
        self.arg: Optional[str] = None
        self.fire_n = 1
        self.window_m = 1
        self.hits = 0                     # evaluations while armed
        self.fires = 0                    # actions actually triggered
        self._count = 0                   # rolling NxM window position


def parse_action(spec: str) -> "Tuple[str, Optional[str], int, int]":
    """Parse an action spec; returns (kind, arg, fire_n, window_m).
    Raises ValueError on malformed input (the SET/HTTP surfaces turn
    that into a user error instead of arming garbage)."""
    m = _SPEC_RE.match(spec.strip())
    if not m:
        raise ValueError(f"malformed failpoint action {spec!r}")
    n_s, m_s, kind, arg = m.groups()
    if kind not in ("err", "crash", "delay", "off"):
        raise ValueError(f"unknown failpoint action {kind!r}")
    fire_n = int(n_s) if n_s else 1
    window_m = int(m_s) if m_s else 1
    if window_m < 1 or fire_n < 1 or fire_n > window_m:
        raise ValueError(f"bad NxM prefix in {spec!r} (need 1<=N<=M)")
    if kind == "delay":
        try:
            float(arg)
        except (TypeError, ValueError):
            raise ValueError(f"delay needs a millisecond arg: {spec!r}")
    return kind, arg, fire_n, window_m


def register(name: str) -> None:
    """Declare a failpoint name at import time so the
    information_schema.failpoints view lists it before it is ever armed."""
    if not _NAME_RE.match(name):
        raise ValueError(f"bad failpoint name {name!r}")
    with _lock:
        _points.setdefault(name, _Point(name))


def configure(name: str, spec: Optional[str]) -> None:
    """Arm (or with None/''/'off' disarm) a failpoint."""
    global _ACTIVE
    if not _NAME_RE.match(name):
        raise ValueError(f"bad failpoint name {name!r}")
    parsed = None
    if spec and spec.strip().lower() != "off":
        parsed = parse_action(spec)   # raises BEFORE any state change
        if parsed[0] == "off":
            parsed = None
    with _lock:
        unknown = name not in _points
        p = _points.setdefault(name, _Point(name))
        if parsed is None:
            p.spec = p.kind = p.arg = None
            p.fire_n = p.window_m = 1
        else:
            p.spec = spec.strip()
            p.kind, p.arg, p.fire_n, p.window_m = parsed
        p._count = 0
        _ACTIVE = any(q.kind is not None for q in _points.values())
    if parsed is not None:
        if unknown:
            # arming before the instrumented module imports and registers
            # is legal (GREPTIME_FAILPOINTS parses at first import), but a
            # typo'd name would otherwise fail silently forever — say so
            logger.warning(
                "failpoint %s is not registered by any instrumented site "
                "(yet); if this is a typo the experiment will never fire",
                name)
        logger.info("failpoint %s armed: %s", name, p.spec)


def clear_all() -> None:
    """Disarm everything (test teardown); registrations and counters stay."""
    global _ACTIVE
    with _lock:
        for p in _points.values():
            p.spec = p.kind = p.arg = None
            p.fire_n = p.window_m = 1
            p._count = 0
        _ACTIVE = False


def reset() -> None:
    """Disarm everything AND zero hit/fire counters (test isolation)."""
    clear_all()
    with _lock:
        for p in _points.values():
            p.hits = p.fires = 0


def active_count() -> int:
    with _lock:
        return sum(1 for p in _points.values() if p.kind is not None)


def list_points() -> List[dict]:
    """Snapshot for information_schema.failpoints and the admin API."""
    with _lock:
        return [{"name": p.name, "action": p.spec, "hits": p.hits,
                 "fires": p.fires}
                for p in sorted(_points.values(), key=lambda q: q.name)]


def refresh_from_env() -> None:
    """(Re)apply GREPTIME_FAILPOINTS=name=action[;name=action...]."""
    raw = os.environ.get("GREPTIME_FAILPOINTS", "")
    for pair in re.split(r"[;,]", raw):
        pair = pair.strip()
        if not pair:
            continue
        name, _, spec = pair.partition("=")
        try:
            configure(name.strip(), spec.strip())
        except ValueError as e:
            logger.error("GREPTIME_FAILPOINTS: %s", e)


def _should_fire(name: str) -> Optional[_Point]:
    """Count a hit and decide whether the armed action fires (locked)."""
    with _lock:
        p = _points.get(name)
        if p is None or p.kind is None:
            return None
        p.hits += 1
        idx = p._count
        p._count = (p._count + 1) % p.window_m
        if idx >= p.fire_n:
            return None
        p.fires += 1
        # snapshot the action under the lock: a concurrent disarm must
        # not turn a decided fire into an AttributeError
        snap = _Point(name)
        snap.kind, snap.arg = p.kind, p.arg
        return snap


def fires(name: str) -> bool:
    """True when the armed action fires NOW — for sites that implement a
    bespoke fault (e.g. the WAL writing a deliberately torn record before
    crashing) instead of the standard raise/delay behaviors. The armed
    action's kind is ignored; the call only consumes one firing slot."""
    if _IO_HOOK is not None:
        _IO_HOOK(name)
    if not _ACTIVE:
        return False
    return _should_fire(name) is not None


def fail_point(name: str) -> None:
    """Evaluate a failpoint: no-op unless armed, else run its action."""
    if _IO_HOOK is not None:
        _IO_HOOK(name)
    if not _ACTIVE:
        return
    p = _should_fire(name)
    if p is None:
        return
    if p.kind == "delay":
        time.sleep(float(p.arg) / 1e3)
        return
    if p.kind == "crash":
        logger.warning("failpoint %s: simulating process crash", name)
        raise SimulatedCrash(name)
    # err
    transient = p.arg == "transient"
    msg = p.arg if p.arg and not transient else f"injected by failpoint {name}"
    raise FailpointError(msg, transient=transient)


@contextlib.contextmanager
def cfg(name: str, spec: str) -> "Iterator[None]":
    """Arm a failpoint for a with-block (tests), disarming on exit."""
    configure(name, spec)
    try:
        yield
    finally:
        configure(name, "off")


refresh_from_env()
