"""Persistent XLA compilation cache for server processes.

First jit compile of a kernel family costs ~10-40 s on TPU; a restarted
server (or a fresh maintenance-job process) pays it again. JAX ships a
persistent on-disk cache — this enables it under the node's data_home so
restarts and short-lived jobs reuse compiled executables. The reference
has no analogue (no JIT), so this is a TPU-first operational concern:
cold-start latency is compile-bound, not IO-bound.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)


def enable_compile_cache(data_home: str) -> bool:
    """Best-effort: point JAX's persistent compilation cache under
    data_home. Safe to call before or after backend init; failures are
    logged and ignored (the cache is an optimization, never required)."""
    try:
        import jax
        cache_dir = os.path.join(data_home, "xla_cache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache everything that took XLA real work; tiny kernels skip
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        return True
    except Exception as e:  # noqa: BLE001 — optional accelerator feature
        logger.debug("compile cache unavailable: %s", e)
        return False
