"""Continuous profiling: an always-on wall-clock stack sampler with
live query/job attribution and in-database retention.

The trace store (common/trace_store.py) answers *where time went
between nodes* and exec stats answer *which stage*; this module answers
*which code*. A daemon thread samples every Python thread's stack via
``sys._current_frames()`` at a low default rate (~19 Hz, the pprof
convention of a prime just under 20), folds each stack into one
semicolon-joined line, and attributes it **at sample time**:

- to the owning statement through the process registry
  (``process_list.entries_by_thread`` — ``track()`` on the frontend
  thread, ``telemetry.propagate`` → ``install()`` on pool workers),
- to background work through the job registry
  (``background_jobs.jobs_by_thread`` — flush/compaction/flow/
  balancer/...); anything else is honest ``idle``,
- to the executing node through :func:`node_context` (the in-process
  datanode client wraps its data-plane calls, so a 4-datanode test
  cluster in ONE process still attributes samples per node).

Aggregated folded stacks flush through the self-monitor ingest path
(``suppress_metrics`` + ``admission.exempt``, like trace spans) into
the auto-created ``greptime_private.profile_samples`` table — profile
history is ordinary data: SQL queries it, retention sweeps it
(``SET profile_retention_ms``), and trace ids join it to
``trace_spans`` so a slow query's flamegraph sits next to its
waterfall. Datanode processes run a writer-less sampler whose rows
ride the Flight ``profile`` action back to the asking frontend.

Knobs (SET name / env twin):
    profiling            GREPTIME_PROFILING            default off
    profile_hz           GREPTIME_PROFILE_HZ           default 19 Hz
    profile_retention_ms GREPTIME_PROFILE_RETENTION_MS default 1d
"""

from __future__ import annotations

import contextlib
import logging
import sys
import threading
import time
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

from .failpoint import register as _fp_register
from .locks import TrackedLock
from .tracking import tracked_state
from ..utils import env_flag, env_float, env_int

logger = logging.getLogger(__name__)

PRIVATE_SCHEMA = "greptime_private"
PROFILE_SAMPLES_TABLE = "profile_samples"

#: evaluated inside Profiler.flush — a 'panic' spec drops that flush's
#: pending samples (counted on write_errors + dropped), never the host
_fp_register("profiler_flush")

_config_lock = TrackedLock("common.profiler_config")

#: master switch for the continuous sampler (bursts ignore it)
_ENABLED: List[bool] = [env_flag("GREPTIME_PROFILING", False)]
#: continuous sampling rate; 19 Hz = the pprof-style prime just under
#: 20, cheap enough for always-on yet ~1k samples/min of signal
_HZ: List[float] = [env_float("GREPTIME_PROFILE_HZ", 19.0)]
#: retention for greptime_private.profile_samples, ms; 0 disables the
#: sweep. Profiles age faster than traces — default 1d vs traces' 3d.
_RETENTION_MS: List[int] = [env_int("GREPTIME_PROFILE_RETENTION_MS",
                                    24 * 3600 * 1000)]

MIN_HZ, MAX_HZ = 1.0, 250.0


def configure(*, enabled: Optional[bool] = None,
              hz: Optional[float] = None,
              retention_ms: Optional[int] = None) -> None:
    """SET profiling / profile_hz / profile_retention_ms knobs."""
    with _config_lock:
        if enabled is not None:
            _ENABLED[0] = bool(enabled)
        if hz is not None:
            h = float(hz)
            if not MIN_HZ <= h <= MAX_HZ:
                raise ValueError(
                    f"profile_hz must be in [{MIN_HZ:g}, {MAX_HZ:g}]")
            _HZ[0] = h
        if retention_ms is not None:
            _RETENTION_MS[0] = max(0, int(retention_ms))
    s = _SAMPLER[0]
    if s is not None and _ENABLED[0]:
        s.ensure_running()


def enabled() -> bool:
    return _ENABLED[0]


def hz() -> float:
    return _HZ[0]


def retention_ms() -> int:
    return _RETENTION_MS[0]


# ---------------------------------------------------------------------------
# per-thread node attribution (the in-process cluster case)
# ---------------------------------------------------------------------------

_node_lock = TrackedLock("common.profiler_nodes")
#: thread ident -> stack of node labels (LocalDatanodeClient pushes
#: "dn<k>" around its data-plane calls; innermost wins)
_NODE_BY_THREAD: Dict[int, List[str]] = tracked_state(
    {}, "profiler.node_by_thread")


def sampling_active() -> bool:
    """True while samples are actually being taken (knob on, or a burst
    in flight) — the cheap gate for per-call attribution bookkeeping."""
    s = _SAMPLER[0]
    return s is not None and (_ENABLED[0] or s.has_bursts())


@contextlib.contextmanager
def node_context(label: str) -> Iterator[None]:
    """Attribute this thread's samples to `label` (e.g. "dn2") for the
    duration — how in-process datanode work gets per-node flamegraph
    rows. A no-op while nothing samples."""
    if not sampling_active():
        yield
        return
    tid = threading.get_ident()
    with _node_lock:
        _NODE_BY_THREAD.setdefault(tid, []).append(str(label))
    try:
        yield
    finally:
        with _node_lock:
            stack = _NODE_BY_THREAD.get(tid)
            if stack:
                stack.pop()
            if not stack:
                _NODE_BY_THREAD.pop(tid, None)


def node_overrides() -> Dict[int, str]:
    with _node_lock:
        return {t: s[-1] for t, s in _NODE_BY_THREAD.items() if s}


# ---------------------------------------------------------------------------
# stack folding
# ---------------------------------------------------------------------------

MAX_STACK_DEPTH = 64


def _frame_label(code) -> str:
    fn = code.co_filename
    i = fn.rfind("greptimedb_tpu")
    if i >= 0:
        short = fn[i:].replace("\\", "/")
    else:
        short = fn.rsplit("/", 1)[-1].rsplit("\\", 1)[-1]
    return f"{short}:{code.co_name}"


def fold_stack(frame) -> str:
    """One sampled thread stack, root-first, semicolon-joined — the
    Brendan Gregg folded format every flamegraph tool eats."""
    parts: List[str] = []
    while frame is not None and len(parts) < MAX_STACK_DEPTH:
        parts.append(_frame_label(frame.f_code))
        frame = frame.f_back
    parts.reverse()
    return ";".join(parts)


def stack_id(stack: str) -> str:
    """Stable short id for one folded stack — a tag column, so distinct
    stacks of one (node, kind, id) never collide on the primary key."""
    return format(zlib.crc32(stack.encode()) & 0xFFFFFFFF, "08x")


def _normalize_kind(kind: str) -> str:
    if kind.startswith("balancer"):
        return "balancer"
    if kind.startswith("flow"):
        return "flow"
    return kind


class Profiler:
    """Per-process sampler (one per node; :func:`install` makes it THE
    process sampler).

    writer present  — frontends/standalone: aggregated rows flush into
                      greptime_private.profile_samples locally.
    writer None     — datanodes: rows accumulate bounded in memory and
                      drain over the Flight ``profile`` action.
    """

    #: distinct (node, kind, id, trace_id, stack) keys held between
    #: flushes; beyond this new stacks shed (drop-counted, never blocks)
    MAX_KEYS = 8192
    #: absorbed remote rows awaiting the local write
    MAX_ABSORBED = 16384
    #: poll cadence while the knob is off and no burst runs
    IDLE_POLL_S = 0.25
    #: burst bounds (the HTTP/Flight on-demand surface)
    BURST_MAX_S = 60.0
    BURST_DEFAULT_HZ = 99.0

    def __init__(self, node_label: str = "standalone", writer=None):
        self.node_label = node_label
        #: hosting frontend (handle_row_insert) — None on datanodes
        self.writer = writer
        self._lock = TrackedLock("common.profiler")
        #: (node, kind, id, trace_id, stack) -> sample count
        self._agg: Dict[Tuple[str, str, str, str, str], int] = \
            tracked_state({}, "profiler.agg")
        self._window_start_ms: List[Optional[int]] = tracked_state(
            [None], "profiler.window_start")
        #: remote rows (Flight profile drains) awaiting the local write
        self._absorbed: List[dict] = tracked_state(
            [], "profiler.absorbed")
        #: live burst collectors: {"agg": {...}, "hz": float}
        self._bursts: List[dict] = tracked_state([], "profiler.bursts")
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        #: wakes the loop out of its idle poll the moment a burst
        #: registers, so a short burst never loses its window to a
        #: stale 250ms sleep
        self._kick = threading.Event()
        #: trace id of the most recently sampled query — what
        #: ADMIN SHOW PROFILE 'last' resolves to
        self.last_query_trace: Optional[str] = None
        self.stats: Dict[str, int] = tracked_state({
            "samples": 0, "dropped": 0, "flushes": 0, "rows_written": 0,
            "write_errors": 0, "overhead_ns": 0, "rows_absorbed": 0,
        }, "profiler.stats")

    # ------------------------------------------------------------------
    # sampler thread lifecycle
    # ------------------------------------------------------------------
    def ensure_running(self) -> None:
        """Start the daemon sampler thread if it isn't running. Lazy on
        purpose: with the knob off (the default) no thread exists at
        all — zero always-on cost until someone asks for profiles."""
        from .runtime import new_thread
        with self._lock:
            t = self._thread
            if t is not None and t.is_alive():
                return
            self._stop = threading.Event()
            self._kick = threading.Event()
            t = new_thread(self._loop,
                           name=f"profiler-{self.node_label}",
                           daemon=True, propagate_context=False)
            self._thread = t
        t.start()

    def stop(self, join: bool = True) -> None:
        with self._lock:
            t, self._thread = self._thread, None
            self._stop.set()
            self._kick.set()
        if t is not None and join:
            t.join(timeout=2)

    def has_bursts(self) -> bool:
        with self._lock:
            return bool(self._bursts)

    def _interval(self) -> float:
        with self._lock:
            rates = [b["hz"] for b in self._bursts]
        if enabled():
            rates.append(hz())
        if not rates:
            return self.IDLE_POLL_S
        return 1.0 / max(rates)

    def _loop(self) -> None:
        stop, kick = self._stop, self._kick
        while True:
            kick.wait(self._interval())
            kick.clear()
            if stop.is_set():
                return
            if enabled() or self.has_bursts():
                self.sample_once()

    # ------------------------------------------------------------------
    # one sampling pass
    # ------------------------------------------------------------------
    def sample_once(self) -> int:
        """Sample every thread's stack once, attribute, aggregate.
        Returns the number of samples taken. Never raises."""
        t0 = time.perf_counter_ns()
        me = threading.get_ident()
        try:
            frames = sys._current_frames()
            from . import background_jobs, process_list
            jobs = background_jobs.jobs_by_thread()
            procs = process_list.entries_by_thread()
            nodes = node_overrides()
            now_ms = int(time.time() * 1000)
            keys: List[Tuple[str, str, str, str, str]] = []
            for tid, frame in frames.items():
                if tid == me:
                    continue
                stack = fold_stack(frame)
                if not stack:
                    continue
                node = nodes.get(tid, self.node_label)
                job = jobs.get(tid)
                if job is not None:
                    kind = _normalize_kind(str(job.get("kind") or ""))
                    ident = str(job.get("job_id") or "")
                    trace = str(job.get("trace_id") or "")
                else:
                    entry = procs.get(tid)
                    if entry is not None:
                        kind = "query"
                        ident = str(entry.id)
                        trace = entry.trace_id or ""
                    else:
                        kind, ident, trace = "idle", "", ""
                keys.append((node, kind, ident, trace, stack))
        except Exception:  # noqa: BLE001 — the sampler must not die
            logger.exception("profiler sampling pass failed")
            return 0
        finally:
            frames = None       # drop frame refs promptly
        dropped = 0
        with self._lock:
            if self._window_start_ms[0] is None:
                self._window_start_ms[0] = now_ms
            for key in keys:
                if key in self._agg:
                    self._agg[key] += 1
                elif len(self._agg) < self.MAX_KEYS:
                    self._agg[key] = 1
                else:
                    dropped += 1
                if key[1] == "query" and key[3]:
                    self.last_query_trace = key[3]
            for b in self._bursts:
                bagg = b["agg"]
                for key in keys:
                    if key in bagg:
                        bagg[key] += 1
                    elif len(bagg) < self.MAX_KEYS:
                        bagg[key] = 1
                    else:
                        dropped += 1
            self.stats["samples"] += len(keys)
            self.stats["dropped"] += dropped
            overhead = time.perf_counter_ns() - t0
            self.stats["overhead_ns"] += overhead
        self._publish(len(keys), dropped, overhead)
        return len(keys)

    def _publish(self, samples: int, dropped: int,
                 overhead_ns: int) -> None:
        """Prometheus counters, outside self._lock (increment_counter
        takes the telemetry metrics lock)."""
        from .telemetry import increment_counter
        if samples:
            increment_counter("profiler_samples", samples)
        if dropped:
            increment_counter("profiler_dropped", dropped)
        if overhead_ns:
            increment_counter("profiler_overhead_ns", overhead_ns)

    # ------------------------------------------------------------------
    # on-demand bursts (GET /debug/prof/cpu, Flight `profile`)
    # ------------------------------------------------------------------
    def collect_burst(self, seconds: float,
                      burst_hz: Optional[float] = None) -> List[dict]:
        """Sample at a high rate for `seconds` on the CALLER's clock
        (the request thread sleeps here) and return that window's rows
        only. Independent of the `profiling` knob; the continuous
        aggregation keeps running untouched."""
        seconds = min(max(float(seconds), 0.05), self.BURST_MAX_S)
        h = float(burst_hz) if burst_hz else self.BURST_DEFAULT_HZ
        h = min(max(h, MIN_HZ), 997.0)
        start_ms = int(time.time() * 1000)
        b = {"agg": {}, "hz": h}
        with self._lock:
            self._bursts.append(b)
        self.ensure_running()
        self._kick.set()     # cut any in-flight idle poll short
        try:
            time.sleep(seconds)
        finally:
            with self._lock:
                if b in self._bursts:
                    self._bursts.remove(b)
        return self._rows_from(list(b["agg"].items()), start_ms)

    # ------------------------------------------------------------------
    # drain / absorb / flush (the write path)
    # ------------------------------------------------------------------
    @staticmethod
    def _rows_from(items, ts_ms: Optional[int]) -> List[dict]:
        ts = int(ts_ms) if ts_ms is not None else int(time.time() * 1000)
        return [{"node": k[0], "kind": k[1], "id": k[2],
                 "trace_id": k[3], "stack_id": stack_id(k[4]),
                 "ts": ts, "stack": k[4], "count": int(c)}
                for k, c in items]

    def drain_rows(self) -> List[dict]:
        """Take the continuous aggregation window as rows (clearing it)
        — what the Flight `profile` action exports from a datanode."""
        with self._lock:
            items = list(self._agg.items())
            self._agg.clear()
            ts0, self._window_start_ms[0] = self._window_start_ms[0], None
        return self._rows_from(items, ts0)

    def absorb_rows(self, rows: List[dict]) -> None:
        """Rows a datanode returned over the wire: queue them for the
        local write (frontend side)."""
        if not rows:
            return
        keys = ("node", "kind", "id", "trace_id", "stack_id", "ts",
                "stack", "count")
        dropped = 0
        with self._lock:
            for r in rows:
                if not isinstance(r, dict) or not r.get("stack"):
                    continue
                if len(self._absorbed) >= self.MAX_ABSORBED:
                    dropped += 1
                    self.stats["dropped"] += 1
                    continue
                self._absorbed.append({k: r.get(k) for k in keys})
                self.stats["rows_absorbed"] += 1
        if dropped:
            from .telemetry import increment_counter
            increment_counter("profiler_dropped", dropped)

    def flush(self) -> int:
        """Write the aggregation window (plus any absorbed remote rows)
        into greptime_private.profile_samples through the hosting
        frontend's normal ingest path, under the recursion guards.
        Returns rows written. Never raises (the profiler must not break
        its host); failed rows are dropped and counted."""
        if self.writer is None:
            return 0
        rows = self.drain_rows()
        with self._lock:
            rows.extend(self._absorbed)
            self._absorbed[:] = []
        if not rows:
            return 0
        from . import admission
        from .failpoint import fail_point
        from .telemetry import increment_counter, suppress_metrics
        from ..datatypes.data_type import INT64, STRING
        from ..session import QueryContext
        now_ms = int(time.time() * 1000)
        for r in rows:
            if not isinstance(r.get("ts"), int):
                r["ts"] = now_ms
        cols = {k: [r.get(k) for r in rows] for k in (
            "node", "kind", "id", "trace_id", "stack_id", "ts",
            "stack", "count")}
        try:
            fail_point("profiler_flush")
            with suppress_metrics(), admission.exempt():
                n = self.writer.handle_row_insert(
                    PROFILE_SAMPLES_TABLE, cols,
                    tag_columns=("node", "kind", "id", "trace_id",
                                 "stack_id"),
                    timestamp_column="ts",
                    types={"node": STRING, "kind": STRING, "id": STRING,
                           "trace_id": STRING, "stack_id": STRING,
                           "stack": STRING, "count": INT64},
                    ctx=QueryContext(current_schema=PRIVATE_SCHEMA))
        except Exception as e:  # noqa: BLE001 — observer must not break
            logger.warning("profile flush failed (%d rows dropped): %s",
                           len(rows), e)
            with self._lock:
                self.stats["write_errors"] += 1
                self.stats["dropped"] += len(rows)
            increment_counter("profiler_dropped", len(rows))
            return 0
        with self._lock:
            self.stats["rows_written"] += n
            self.stats["flushes"] += 1
        return n

    # ------------------------------------------------------------------
    # slow-query annotation
    # ------------------------------------------------------------------
    def top_frames(self, trace_id: str, n: int = 3
                   ) -> List[Tuple[str, int]]:
        """Top-n self-time (leaf) frames of one query's live samples —
        the slow-query log's "why" one-liner. Reads the un-flushed
        aggregation only: it is called the moment the statement closes,
        before any flush could have run."""
        leaf_counts: Dict[str, int] = {}
        with self._lock:
            for (node, kind, ident, trace, stack), c in \
                    self._agg.items():
                if kind != "query" or trace != trace_id:
                    continue
                leaf = stack.rsplit(";", 1)[-1]
                leaf_counts[leaf] = leaf_counts.get(leaf, 0) + c
        return sorted(leaf_counts.items(),
                      key=lambda kv: (-kv[1], kv[0]))[:n]

    def pending_count(self) -> int:
        with self._lock:
            return len(self._agg) + len(self._absorbed)

    def row(self) -> Dict[str, object]:
        with self._lock:
            out: Dict[str, object] = dict(self.stats)
        out["node"] = self.node_label
        out["enabled"] = enabled()
        out["hz"] = hz()
        out["retention_ms"] = retention_ms()
        return out


# ---------------------------------------------------------------------------
# process-wide sampler
# ---------------------------------------------------------------------------

_SAMPLER: List[Optional[Profiler]] = [None]


def sampler() -> Optional[Profiler]:
    return _SAMPLER[0]


def install(new_sampler: Optional[Profiler]) -> Optional[Profiler]:
    """Make `new_sampler` the process-wide sampler (None uninstalls).
    The previous sampler's thread is stopped so construct-heavy test
    suites never accumulate 19 Hz threads. Returns the previous
    sampler (tests restore it)."""
    with _config_lock:
        old, _SAMPLER[0] = _SAMPLER[0], new_sampler
    if old is not None and old is not new_sampler:
        old.stop(join=False)
    if new_sampler is not None and _ENABLED[0]:
        new_sampler.ensure_running()
    return old


def slow_query_suffix(trace_id: str) -> str:
    """The slow-query WARN's "why" fragment: the query's top-3
    self-time frames, e.g. ` profile_top=[a(12);b(4);c(1)]`. Empty when
    nothing sampled (knob off, or the query too fast to catch)."""
    s = _SAMPLER[0]
    if s is None or not _ENABLED[0]:
        return ""
    top = s.top_frames(trace_id, 3)
    if not top:
        return ""
    return " profile_top=[" + ";".join(
        f"{frame}({c})" for frame, c in top) + "]"


# ---------------------------------------------------------------------------
# folded-output helpers (HTTP burst formats)
# ---------------------------------------------------------------------------

def folded_text(rows: List[dict]) -> str:
    """`stack count` lines, stacks merged across attribution — feedable
    straight into any flamegraph.pl-compatible tool."""
    agg: Dict[str, int] = {}
    for r in rows:
        agg[str(r.get("stack") or "")] = \
            agg.get(str(r.get("stack") or ""), 0) + int(r.get("count") or 0)
    return "\n".join(f"{s} {c}" for s, c in
                     sorted(agg.items(), key=lambda kv: (-kv[1], kv[0]))
                     if s) + "\n"


def flamegraph_svg(rows: List[dict], title: str = "cpu") -> str:
    """Self-contained SVG flamegraph (icicle layout, root on top) from
    sample rows — no external tooling needed to look at a burst. Width
    is proportional to total samples; hover shows frame + counts."""
    import html as _html
    root: Dict[str, dict] = {}
    total = 0
    for r in rows:
        stack = str(r.get("stack") or "")
        if not stack:
            continue
        c = int(r.get("count") or 0)
        total += c
        children = root
        for frame in stack.split(";"):
            b = children.get(frame)
            if b is None:
                b = children[frame] = {"total": 0, "children": {}}
            b["total"] += c
            children = b["children"]
    width, row_h = 1200.0, 16
    palette = ("#e5674b", "#e08a3c", "#d9a441", "#c8b04a", "#e07a55")
    rects: List[str] = []
    depth_max = [0]

    def _emit(children: Dict[str, dict], x: float, depth: int) -> None:
        depth_max[0] = max(depth_max[0], depth)
        for frame, b in sorted(children.items(),
                               key=lambda kv: (-kv[1]["total"], kv[0])):
            w = width * b["total"] / total
            if w < 0.5:
                x += w
                continue
            y = depth * row_h
            fill = palette[(hash(frame) & 0x7fffffff) % len(palette)]
            label = _html.escape(frame, quote=True)
            pct = 100.0 * b["total"] / total
            text = ""
            if w > 40:
                shown = _html.escape(
                    frame[-max(3, int(w / 7)):], quote=False)
                text = (f'<text x="{x + 2:.1f}" y="{y + 11}" '
                        f'font-size="10" font-family="monospace">'
                        f'{shown}</text>')
            rects.append(
                f'<g><title>{label} — {b["total"]} samples '
                f'({pct:.1f}%)</title>'
                f'<rect x="{x:.1f}" y="{y}" width="{w:.1f}" '
                f'height="{row_h - 1}" fill="{fill}"/>{text}</g>')
            _emit(b["children"], x, depth + 1)
            x += w

    if total:
        _emit(root, 0.0, 1)
    height = (depth_max[0] + 1) * row_h + 4
    head = (f'<text x="4" y="12" font-size="11" '
            f'font-family="monospace">{_html.escape(title)} — '
            f'{total} samples</text>')
    return (f'<svg xmlns="http://www.w3.org/2000/svg" '
            f'width="{int(width)}" height="{height}" '
            f'style="background:#fff">{head}{"".join(rects)}</svg>\n')


# ---------------------------------------------------------------------------
# top-down tree rendering (ADMIN SHOW PROFILE / HTTP flamegraph)
# ---------------------------------------------------------------------------

def profile_tree_rows(rows: List[dict]) -> List[dict]:
    """Stored sample rows → an indented per-node top-down tree with
    self/total sample counts (heaviest subtree first). One renderer for
    ADMIN SHOW PROFILE on both frontends."""
    by_node: Dict[str, List[dict]] = {}
    for r in rows:
        by_node.setdefault(str(r.get("node") or ""), []).append(r)
    out: List[dict] = []
    for node in sorted(by_node):
        root: Dict[str, dict] = {}

        def _bucket(children: Dict[str, dict], frame: str) -> dict:
            b = children.get(frame)
            if b is None:
                b = children[frame] = {"total": 0, "self": 0,
                                       "children": {}}
            return b

        for r in by_node[node]:
            frames = str(r.get("stack") or "").split(";")
            c = int(r.get("count") or 0)
            children = root
            for i, frame in enumerate(frames):
                b = _bucket(children, frame)
                b["total"] += c
                if i == len(frames) - 1:
                    b["self"] += c
                children = b["children"]

        def _emit(children: Dict[str, dict], depth: int) -> None:
            order = sorted(children.items(),
                           key=lambda kv: (-kv[1]["total"], kv[0]))
            for frame, b in order:
                indent = ("  " * depth + "└─ ") if depth else ""
                out.append({"frame": indent + frame, "node": node,
                            "self_samples": b["self"],
                            "total_samples": b["total"]})
                _emit(b["children"], depth + 1)

        _emit(root, 0)
    return out


# ---------------------------------------------------------------------------
# stored-profile reads (ADMIN SHOW PROFILE / information_schema /
# /v1 surfaces share these)
# ---------------------------------------------------------------------------

def fetch_samples(catalog_manager, *, trace_id: Optional[str] = None,
                  query_id: Optional[str] = None) -> List[dict]:
    """Stored profile rows for one trace or one query id, as plain
    dicts. The tag predicate pushes into scan_batches where the table
    accepts filters; the Python-side re-check keeps correctness on
    tables that ignore it (superset semantics)."""
    from .. import DEFAULT_CATALOG_NAME
    table = catalog_manager.table(DEFAULT_CATALOG_NAME, PRIVATE_SCHEMA,
                                  PROFILE_SAMPLES_TABLE)
    if table is None:
        return []
    from ..sql.ast import BinaryOp, Column, Literal
    if trace_id is not None:
        predicate = BinaryOp("=", Column("trace_id"),
                             Literal(trace_id, "string"))
    else:
        predicate = BinaryOp("=", Column("id"),
                             Literal(str(query_id), "string"))
    try:
        batches = table.scan_batches(filters=[predicate])
    except TypeError:      # virtual/file tables take no filters kwarg
        batches = table.scan_batches()
    rows: List[dict] = []
    for b in batches:
        d = b.to_pydict()
        n = len(d.get("stack_id", []))
        for i in range(n):
            if trace_id is not None:
                if str(d["trace_id"][i]) != trace_id:
                    continue
            elif str(d["id"][i]) != str(query_id) or \
                    str(d["kind"][i]) != "query":
                continue
            rows.append({k: (v.item() if hasattr(v, "item") else v)
                         for k, v in ((c, d[c][i]) for c in d)})
    return rows


def sync_and_fetch(catalog_manager, ident: str,
                   clients=None) -> Tuple[Optional[str], List[dict]]:
    """The ONE render-path sequence behind ADMIN SHOW PROFILE:

    1. resolve 'last' to the most recently sampled query's trace id;
    2. drain every datanode's sampler over the Flight `profile` action
       (absorbed into the local pending set) and flush locally, so the
       stored table is complete at render time;
    3. read rows by trace id (32-hex / anything non-numeric) or by
       query id (numeric — the process-list id the slow-query log and
       SHOW PROCESSLIST print).

    Returns (resolved_ident, rows); (None, []) when 'last' has no
    referent."""
    s = sampler()
    if ident == "last":
        resolved = s.last_query_trace if s is not None else None
        if resolved is None:
            return None, []
        ident = resolved
    if s is not None:
        for client in (clients or ()):
            profile = getattr(client, "profile", None)
            if profile is None:
                continue
            try:
                s.absorb_rows(profile(drain=True))
            except Exception as e:  # noqa: BLE001 — a dead datanode
                logger.debug(       # must not block rendering the rest
                    "profile drain failed: %s", e)
        s.flush()
    if ident.isdigit():
        return ident, fetch_samples(catalog_manager, query_id=ident)
    return ident, fetch_samples(catalog_manager, trace_id=ident)
