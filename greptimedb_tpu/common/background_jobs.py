"""Background-job visibility: root spans + a live registry.

Counters tell an operator *how many* flushes ran; they cannot answer
"what background work is running RIGHT NOW, on which region, and how
long has it been at it" — the question that matters when a compaction
storm causes p99 pain. Every background entry point (flush, compaction,
TTL/retention sweeps, flow folds, balancer op steps, WAL group-commit
leader flushes) wraps itself in :func:`job`, which

1. opens a **root span** (``telemetry.root_span``) so the work gets its
   own trace id — background work belongs to no statement's trace, and
   with the durable trace store (common/trace_store.py) a slow or
   failed compaction's span history survives into
   ``greptime_private.trace_spans`` exactly like a slow query's;
2. registers a live entry in the process-wide :class:`JobRegistry`
   served by ``information_schema.background_jobs`` (running jobs plus
   the last-N completed with durations and outcomes).

greptlint GL13 enforces the contract statically: a callback handed to
``RepeatedTask``/``LocalScheduler.submit`` must reach a ``job()`` /
``root_span()`` call.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Dict, Iterator, List, Optional

from .locks import TrackedLock
from .tracking import tracked_state

#: completed jobs kept for the view, PER KIND (rings; oldest evicted).
#: Per-kind because the rates differ by orders of magnitude: a WAL
#: group-commit fsync job fires tens to hundreds of times per second
#: under sync ingest and would evict every completed compaction from a
#: shared ring within seconds — exactly when an operator is asking
#: "what did the last compactions cost".
COMPLETED_KEEP_PER_KIND = 32

_lock = TrackedLock("common.background_jobs")
_running: Dict[int, dict] = tracked_state({}, "background_jobs.running")
_completed: Dict[str, List[dict]] = tracked_state(
    {}, "background_jobs.completed")
_next_id = [1]
_node_label = ["standalone"]


def configure_node(label: str) -> None:
    """Name this process in the `node` column of background_jobs (the
    frontends and cmd entry points call it alongside
    process_list.configure_node)."""
    with _lock:
        _node_label[0] = label


def _start(kind: str, table: Optional[str], region: Optional[str],
           trace_id: str, attrs: Dict[str, object]) -> dict:
    entry = {
        "job_id": 0, "kind": kind, "table_name": table, "region": region,
        "node": _node_label[0], "state": "running", "trace_id": trace_id,
        "start_ms": int(time.time() * 1000), "duration_ms": None,
        "error": None,
        "detail": json.dumps(attrs, default=str, separators=(",", ":"))
        if attrs else "",
        "_t0": time.perf_counter(),
        # the running thread, so the stack sampler (common/profiler.py)
        # can attribute that thread's samples to THIS job
        "_thread": threading.get_ident(),
    }
    with _lock:
        entry["job_id"] = _next_id[0]
        _next_id[0] += 1
        _running[entry["job_id"]] = entry
    return entry


def _finish(entry: dict, state: str, error: Optional[str] = None) -> None:
    entry["state"] = state
    entry["error"] = error
    entry["duration_ms"] = round(
        (time.perf_counter() - entry.pop("_t0")) * 1e3, 3)
    with _lock:
        _running.pop(entry["job_id"], None)
        ring = _completed.setdefault(entry["kind"], [])
        ring.append(entry)
        if len(ring) > COMPLETED_KEEP_PER_KIND:
            del ring[:len(ring) - COMPLETED_KEEP_PER_KIND]


@contextlib.contextmanager
def job(kind: str, *, table: Optional[str] = None,
        region: Optional[str] = None, **attrs: object) -> Iterator[dict]:
    """Run one background job under a fresh ROOT span + a registry entry.

    The span detaches from any ambient trace on purpose: a flush
    triggered synchronously by ADMIN FLUSH TABLE is the same work as one
    the write path queued, and both must be findable as their own trace
    (the registry entry records the trace id). The caller's trace
    context is restored on exit."""
    from .telemetry import increment_counter, root_span
    span_attrs = dict(attrs)
    if table is not None:
        span_attrs["table"] = table
    if region is not None:
        span_attrs["region"] = region
    with root_span(f"job_{kind}", **span_attrs) as sp:
        entry = _start(kind, table, region, sp["trace_id"], span_attrs)
        try:
            yield entry
        except BaseException as e:  # greptlint: disable=GL02 — re-raised
            _finish(entry, "failed", f"{type(e).__name__}: {e}")
            increment_counter(f"bg_job_{kind}_failed")
            raise
        else:
            _finish(entry, "done")


def rows() -> List[dict]:
    """Snapshot for information_schema.background_jobs: running jobs
    first (most recent last), then completed newest-first (merged
    across the per-kind rings)."""
    with _lock:
        running = [dict(e) for e in _running.values()]
        done = sorted((dict(e) for ring in _completed.values()
                       for e in ring),
                      key=lambda e: e["job_id"], reverse=True)
    now = time.perf_counter()
    out = []
    for e in running:
        t0 = e.pop("_t0", None)
        e.pop("_thread", None)
        if t0 is not None:
            e["duration_ms"] = round((now - t0) * 1e3, 3)
        out.append(e)
    for e in done:
        e.pop("_t0", None)
        e.pop("_thread", None)
        out.append(e)
    return out


def jobs_by_thread() -> Dict[int, dict]:
    """Snapshot for the stack sampler: which thread runs which
    background job right now (entry dicts, not copies — read-only)."""
    with _lock:
        return {e["_thread"]: e for e in _running.values()
                if "_thread" in e}


def reset() -> None:
    """Test/sqlness hook: forget all history (ids restart)."""
    with _lock:
        _running.clear()
        _completed.clear()
        _next_id[0] = 1
