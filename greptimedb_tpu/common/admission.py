"""Admission control: a bounded per-frontend gate over new work.

Reference behavior: the reference front door sheds load at the protocol
servers instead of collapsing — past a configured limit new statements
are rejected with a retryable "server busy" error while work already in
flight runs to completion. Here the gate is process-wide (one per
frontend process, like the process registry it reads):

- **in-flight statements** — fed by PR 8's live process registry
  (``common/process_list.REGISTRY``): when ``admission_max_inflight``
  is set and that many statements are already running, a new statement
  is rejected with :class:`~..errors.OverloadedError` (HTTP 429 +
  ``Retry-After``, MySQL 1040 server-busy, PG SQLSTATE 53300).
- **queued ingest bytes** — protocol bulk bodies (Prometheus remote
  write, InfluxDB lines, OpenTSDB puts) reserve their payload size for
  the duration of the request; past ``admission_max_queued_bytes`` new
  bodies are rejected the same way.

Design rules (the "never deadlock" contract):

- the gate REJECTS, it never queues — rejected work holds nothing, so
  it cannot deadlock against work already holding WAL group-commit
  cohort slots;
- ``KILL`` and ``SET`` statements are always admitted: the operator's
  way OUT of an overload must not be behind the gate it is clearing;
- the self-monitor's own ``greptime_private`` writes are exempt via the
  thread-local :func:`exempt` context (suppress-style, like
  ``telemetry.suppress_metrics``) — observability must keep flowing
  exactly when the node is overloaded.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Iterator, Optional

from ..errors import OverloadedError
from ..utils import env_int as _env_int
from .locks import TrackedLock

_tls = threading.local()


class AdmissionGate:
    """Process-wide admission state. Limits of 0 disable a dimension
    (the default: the gate is opt-in via ``SET admission_*`` or the
    ``GREPTIME_ADMISSION_*`` env knobs)."""

    def __init__(self) -> None:
        self._lock = TrackedLock("common.admission")
        self.max_inflight = _env_int("GREPTIME_ADMISSION_MAX_INFLIGHT", 0)
        self.max_queued_bytes = _env_int(
            "GREPTIME_ADMISSION_MAX_QUEUED_BYTES", 0)
        self.retry_after_s = max(
            1, _env_int("GREPTIME_ADMISSION_RETRY_AFTER_S", 1))
        self._queued_bytes = 0
        self._rejected = 0

    # ---- configuration (SET admission_*) ----
    def configure(self, *, max_inflight: Optional[int] = None,
                  max_queued_bytes: Optional[int] = None,
                  retry_after_s: Optional[int] = None) -> None:
        with self._lock:
            if max_inflight is not None:
                if max_inflight < 0:
                    raise ValueError("admission_max_inflight must be >= 0")
                self.max_inflight = int(max_inflight)
            if max_queued_bytes is not None:
                if max_queued_bytes < 0:
                    raise ValueError(
                        "admission_max_queued_bytes must be >= 0")
                self.max_queued_bytes = int(max_queued_bytes)
            if retry_after_s is not None:
                if retry_after_s < 1:
                    raise ValueError("admission_retry_after_s must be >= 1")
                self.retry_after_s = int(retry_after_s)

    #: statement kinds admitted even at the limit: the operator's way
    #: out of an overload (KILL a hog, raise the limit) must not be
    #: behind the gate it is clearing
    EXEMPT_STMTS = frozenset({"Kill", "SetVariable"})

    # ---- statement gate ----
    def admit_statement(self, stmt_kind: str = "") -> None:
        """Reject (typed, retryable) when the live process registry is
        already at the in-flight limit. Never blocks, never queues.
        `stmt_kind` is the parsed AST class name (``type(s).__name__``)
        so exemptions key on what the statement IS, not text sniffing."""
        limit = self.max_inflight
        if limit <= 0 or is_exempt():
            return
        if stmt_kind in self.EXEMPT_STMTS:
            return
        from . import process_list
        inflight = len(process_list.REGISTRY)
        if inflight < limit:
            return
        self._reject(
            f"admission limit reached: {inflight} statements in flight "
            f">= admission_max_inflight={limit}; retry after "
            f"{self.retry_after_s}s")

    # ---- ingest byte gate ----
    @contextlib.contextmanager
    def admit_ingest(self, nbytes: int) -> Iterator[None]:
        """Reserve `nbytes` of the queued-ingest budget for the duration
        of one protocol bulk request; reject when the reservation would
        cross the limit. Admitted work ALWAYS releases its reservation
        (the finally), so rejection pressure subsides as in-flight
        bodies drain."""
        limit = self.max_queued_bytes
        if limit <= 0 or is_exempt():
            yield
            return
        with self._lock:
            over = self._queued_bytes + nbytes > limit
            if over and self._queued_bytes == 0:
                # a single body larger than the whole budget is still
                # admitted when the gate is idle — rejecting it forever
                # would be a livelock, and one body IS the queue
                over = False
            queued = self._queued_bytes if over else None
            if not over:
                self._queued_bytes += nbytes
        if queued is not None:
            self._reject(
                f"admission limit reached: {queued} ingest bytes queued "
                f"+ {nbytes} new > admission_max_queued_bytes={limit}; "
                f"retry after {self.retry_after_s}s")
        try:
            yield
        finally:
            with self._lock:
                self._queued_bytes -= nbytes

    def _reject(self, msg: str) -> None:
        from .telemetry import increment_counter
        with self._lock:
            self._rejected += 1
        increment_counter("admission_rejected")
        raise OverloadedError(msg, retry_after_s=self.retry_after_s)

    # ---- introspection (status/tests) ----
    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"max_inflight": self.max_inflight,
                    "max_queued_bytes": self.max_queued_bytes,
                    "queued_bytes": self._queued_bytes,
                    "rejected_total": self._rejected,
                    "retry_after_s": self.retry_after_s}


#: the process-wide gate every frontend + protocol server shares
GATE = AdmissionGate()


def is_exempt() -> bool:
    return getattr(_tls, "exempt", 0) > 0


@contextlib.contextmanager
def exempt() -> Iterator[None]:
    """Mark this thread's work as gate-exempt (the self-monitor's own
    ``greptime_private`` writes: shedding the observer during overload
    would blind the operator exactly when they need the data)."""
    _tls.exempt = getattr(_tls, "exempt", 0) + 1
    try:
        yield
    finally:
        _tls.exempt -= 1
