"""Plugins: a typed any-map shared across components.

Reference behavior: src/common/base/src/lib.rs — `Plugins` is an anymap
that layers (frontend, servers) consult for optional extensions (user
provider, query interceptors, meters). Lookup is by type.
"""

from __future__ import annotations

import threading
from typing import Optional, Type, TypeVar

T = TypeVar("T")


class Plugins:
    def __init__(self):
        self._by_type = {}
        self._lock = threading.Lock()

    def insert(self, value: object) -> None:
        with self._lock:
            self._by_type[type(value)] = value

    def get(self, cls: Type[T]) -> Optional[T]:
        with self._lock:
            v = self._by_type.get(cls)
            if v is not None:
                return v
            # subclass-aware lookup: a request for the base type finds a
            # registered specialization
            for t, inst in self._by_type.items():
                if issubclass(t, cls):
                    return inst
        return None

    def __contains__(self, cls: type) -> bool:
        return self.get(cls) is not None
