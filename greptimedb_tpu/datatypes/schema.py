"""Schemas with TIME INDEX and primary-key (tag) semantics.

Reference behavior: src/datatypes/src/schema/ — `ColumnSchema` carries name,
type, nullability, default constraint and a timestamp-index flag; `Schema`
carries the ordered columns plus the timestamp index and a version used for
read-compat across ALTERs. Semantic types (TAG/TIMESTAMP/FIELD) follow the
time-series model of the mito engine (src/storage/src/metadata.rs).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import pyarrow as pa

from .data_type import ConcreteDataType, from_arrow_type, parse_type_name
from .vector import Vector


class SemanticType(enum.Enum):
    TAG = "TAG"            # member of the primary key
    TIMESTAMP = "TIMESTAMP"  # the TIME INDEX column
    FIELD = "FIELD"


@dataclass(frozen=True)
class ColumnDefaultConstraint:
    """Either a constant value or the function 'current_timestamp()'."""

    value: Any = None
    function: Optional[str] = None  # e.g. "current_timestamp"

    def resolve(self, dtype: ConcreteDataType, now_ms: Optional[int] = None) -> Any:
        if self.function is not None:
            fn = self.function.lower().rstrip("()")
            if fn in ("current_timestamp", "now"):
                import time as _t
                ms = now_ms if now_ms is not None else int(_t.time() * 1000)
                if dtype.is_timestamp:
                    from ..common.time import Timestamp, TimeUnit
                    return Timestamp(ms, TimeUnit.MILLISECOND).convert_to(dtype.time_unit).value
                return ms
            raise ValueError(f"unsupported default function {self.function!r}")
        if self.value is None:
            return None
        return dtype.cast_value(self.value)


@dataclass
class ColumnSchema:
    name: str
    dtype: ConcreteDataType
    nullable: bool = True
    semantic_type: SemanticType = SemanticType.FIELD
    default: Optional[ColumnDefaultConstraint] = None
    comment: str = ""

    @property
    def is_time_index(self) -> bool:
        return self.semantic_type == SemanticType.TIMESTAMP

    @property
    def is_tag(self) -> bool:
        return self.semantic_type == SemanticType.TAG

    def create_default_vector(self, n: int) -> Optional[Vector]:
        """Vector used to fill this column when an INSERT omits it."""
        if self.default is not None:
            v = self.default.resolve(self.dtype)
            return Vector.constant(v, n, self.dtype)
        if self.nullable:
            return Vector.nulls(n, self.dtype)
        return None

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "type": self.dtype.name,
            "nullable": self.nullable,
            "semantic_type": self.semantic_type.value,
        }
        if self.default is not None:
            d["default"] = {"value": self.default.value, "function": self.default.function}
        if self.comment:
            d["comment"] = self.comment
        return d

    @staticmethod
    def from_dict(d: dict) -> "ColumnSchema":
        default = None
        if d.get("default") is not None:
            default = ColumnDefaultConstraint(
                value=d["default"].get("value"), function=d["default"].get("function"))
        return ColumnSchema(
            name=d["name"],
            dtype=parse_type_name(d["type"]),
            nullable=d.get("nullable", True),
            semantic_type=SemanticType(d.get("semantic_type", "FIELD")),
            default=default,
            comment=d.get("comment", ""),
        )


class Schema:
    """Ordered column schemas + time index + version."""

    def __init__(self, column_schemas: Sequence[ColumnSchema], version: int = 0):
        self.column_schemas: List[ColumnSchema] = list(column_schemas)
        self.version = version
        self._index: Dict[str, int] = {c.name: i for i, c in enumerate(self.column_schemas)}
        ts = [i for i, c in enumerate(self.column_schemas) if c.is_time_index]
        if len(ts) > 1:
            raise ValueError("multiple TIME INDEX columns")
        self.timestamp_index: Optional[int] = ts[0] if ts else None
        if self.timestamp_index is not None:
            tc = self.column_schemas[self.timestamp_index]
            if tc.nullable:
                raise ValueError(
                    f"TIME INDEX column {tc.name!r} must be non-nullable")
            if not tc.dtype.is_timestamp:
                raise ValueError(
                    f"TIME INDEX column {tc.name!r} must be a timestamp type")

    # ---- access ----
    def __len__(self) -> int:
        return len(self.column_schemas)

    def names(self) -> List[str]:
        return [c.name for c in self.column_schemas]

    def column_index(self, name: str) -> int:
        if name not in self._index:
            raise KeyError(name)
        return self._index[name]

    def contains(self, name: str) -> bool:
        return name in self._index

    def column_schema(self, name: str) -> ColumnSchema:
        return self.column_schemas[self.column_index(name)]

    @property
    def timestamp_column(self) -> Optional[ColumnSchema]:
        if self.timestamp_index is None:
            return None
        return self.column_schemas[self.timestamp_index]

    def tag_columns(self) -> List[ColumnSchema]:
        return [c for c in self.column_schemas if c.is_tag]

    def field_columns(self) -> List[ColumnSchema]:
        return [c for c in self.column_schemas
                if c.semantic_type == SemanticType.FIELD]

    def tag_names(self) -> List[str]:
        return [c.name for c in self.tag_columns()]

    def field_names(self) -> List[str]:
        return [c.name for c in self.field_columns()]

    def project(self, names: Sequence[str]) -> "Schema":
        return Schema([self.column_schema(n) for n in names], self.version)

    # ---- interop ----
    def to_arrow(self) -> pa.Schema:
        fields = []
        for c in self.column_schemas:
            meta = {b"semantic_type": c.semantic_type.value.encode()}
            fields.append(pa.field(c.name, c.dtype.pa_type, nullable=c.nullable,
                                   metadata=meta))
        return pa.schema(fields, metadata={b"greptime:version": str(self.version).encode()})

    @staticmethod
    def from_arrow(s: pa.Schema) -> "Schema":
        cols = []
        for f in s:
            sem = SemanticType.FIELD
            if f.metadata and b"semantic_type" in f.metadata:
                sem = SemanticType(f.metadata[b"semantic_type"].decode())
            cols.append(ColumnSchema(f.name, from_arrow_type(f.type),
                                     nullable=f.nullable, semantic_type=sem))
        version = 0
        if s.metadata and b"greptime:version" in s.metadata:
            version = int(s.metadata[b"greptime:version"])
        return Schema(cols, version)

    def to_dict(self) -> dict:
        return {"version": self.version,
                "columns": [c.to_dict() for c in self.column_schemas]}

    @staticmethod
    def from_dict(d: dict) -> "Schema":
        return Schema([ColumnSchema.from_dict(c) for c in d["columns"]],
                      version=d.get("version", 0))

    def __eq__(self, other) -> bool:
        if not isinstance(other, Schema):
            return NotImplemented
        return self.to_dict() == other.to_dict()

    def __repr__(self) -> str:  # pragma: no cover
        cols = ", ".join(f"{c.name}:{c.dtype.name}" for c in self.column_schemas)
        return f"Schema[v{self.version}]({cols})"
