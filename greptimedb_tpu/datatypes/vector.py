"""Columnar vectors: host SoA arrays with Arrow interop.

Reference behavior: src/datatypes/src/vectors/ — a `Vector` is a typed,
nullable column. The TPU-first design keeps the canonical host representation
as numpy arrays (object arrays for strings) plus an optional validity bitmap,
so columns move to the device with zero reshaping; Arrow is the interchange
format (Parquet, Flight, IPC/WAL payloads).
"""

from __future__ import annotations

from typing import Any, Iterable, Optional, Sequence

import numpy as np
import pyarrow as pa

from . import data_type as dt
from .data_type import ConcreteDataType, from_arrow_type


def null_column(dtype: ConcreteDataType, n: int):
    """(data, all-false validity) pair for an absent/null column — the single
    place that knows the host representation of nulls per dtype."""
    npdt = dtype.np_dtype if dtype.np_dtype is not None else object
    if npdt == object:
        data = np.full(n, None, dtype=object)
    else:
        data = np.zeros(n, dtype=npdt)
    return data, np.zeros(n, dtype=bool)


class Vector:
    """A typed nullable column.

    data: np.ndarray — for String/Binary this is an object array; for
          timestamps an int64 array of ticks in the type's unit.
    validity: optional boolean np.ndarray, True = valid. None = all valid.
    """

    __slots__ = ("dtype", "data", "validity")

    def __init__(self, dtype: ConcreteDataType, data: np.ndarray,
                 validity: Optional[np.ndarray] = None):
        self.dtype = dtype
        self.data = data
        if validity is not None and validity.all():
            validity = None
        self.validity = validity

    # ---- constructors ----
    @staticmethod
    def from_pylist(values: Sequence[Any], dtype: ConcreteDataType) -> "Vector":
        if isinstance(values, np.ndarray) and values.dtype != object \
                and not (dtype.is_string or dtype.is_binary):
            # numeric ndarray fast path: no per-value cast, no nulls
            return Vector(dtype,
                          np.ascontiguousarray(values, dtype=dtype.np_dtype))
        if isinstance(values, np.ndarray) and values.dtype.kind == "U" \
                and dtype.is_string:
            # fixed-width unicode arrays (np.repeat of str lists) carry
            # no nulls; store as object for Arrow interop
            return Vector(dtype, values.astype(object))
        if isinstance(values, np.ndarray) and values.dtype == object \
                and dtype.is_string:
            # string object-array fast path: vectorized null scan, cast
            # only the (rare) non-str entries
            import pandas as pd
            isnull = pd.isnull(values)
            if not isnull.any():
                if all(type(v) is str for v in values[:64]):
                    data = values
                    if not all(type(v) is str for v in values):
                        data = np.array([v if type(v) is str else
                                         dtype.cast_value(v)
                                         for v in values], dtype=object)
                    return Vector(dtype, data)
            else:
                data = np.array([dtype.default_value() if m else
                                 (v if type(v) is str
                                  else dtype.cast_value(v))
                                 for v, m in zip(values, isnull)],
                                dtype=object)
                return Vector(dtype, data, ~isnull)
        n = len(values)
        if isinstance(values, list) and n and not dtype.is_string \
                and not dtype.is_binary and dtype.np_dtype is not None \
                and not any(v is None for v in values):
            # clean numeric lists convert at C speed; np.asarray silently
            # coerces None to NaN for float dtypes (no exception), so the
            # NULL scan above is mandatory — mixed non-None content still
            # raises and falls through to the validating per-value loop
            try:
                return Vector(dtype, np.asarray(values,
                                                dtype=dtype.np_dtype))
            except (ValueError, TypeError):
                pass
        validity = np.ones(n, dtype=bool)
        if dtype.is_string or dtype.is_binary:
            data = np.empty(n, dtype=object)
            for i, v in enumerate(values):
                if v is None:
                    validity[i] = False
                    data[i] = dtype.default_value()
                else:
                    data[i] = dtype.cast_value(v)
        else:
            np_dtype = dtype.np_dtype
            data = np.zeros(n, dtype=np_dtype)
            for i, v in enumerate(values):
                if v is None:
                    validity[i] = False
                else:
                    data[i] = dtype.cast_value(v)
        return Vector(dtype, data, None if validity.all() else validity)

    @staticmethod
    def from_numpy(arr: np.ndarray, dtype: ConcreteDataType,
                   validity: Optional[np.ndarray] = None) -> "Vector":
        if not (dtype.is_string or dtype.is_binary):
            arr = np.ascontiguousarray(arr, dtype=dtype.np_dtype)
        return Vector(dtype, arr, validity)

    @staticmethod
    def constant(value: Any, n: int, dtype: ConcreteDataType) -> "Vector":
        if value is None:
            return Vector.nulls(n, dtype)
        v = dtype.cast_value(value)
        if dtype.is_string or dtype.is_binary:
            data = np.empty(n, dtype=object)
            data[:] = v
        else:
            data = np.full(n, v, dtype=dtype.np_dtype)
        return Vector(dtype, data)

    @staticmethod
    def nulls(n: int, dtype: ConcreteDataType) -> "Vector":
        if dtype.is_string or dtype.is_binary:
            data = np.empty(n, dtype=object)
            data[:] = dtype.default_value()
        else:
            data = np.zeros(n, dtype=dtype.np_dtype)
        return Vector(dtype, data, np.zeros(n, dtype=bool))

    @staticmethod
    def from_arrow(arr: pa.Array | pa.ChunkedArray) -> "Vector":
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if pa.types.is_dictionary(arr.type):
            arr = arr.dictionary_decode()
        dtype = from_arrow_type(arr.type)
        n = len(arr)
        validity = None
        if arr.null_count:
            validity = np.asarray(arr.is_valid())
        if dtype.is_string or dtype.is_binary:
            # zero_copy_only=False yields an object ndarray with None at
            # nulls — filled vectorized (the per-value loop cost ~0.4s/2M)
            data = arr.to_numpy(zero_copy_only=False)
            if data.dtype != object:
                data = data.astype(object)
            else:
                data = data.copy()
            if validity is not None:
                data[~validity] = dtype.default_value()
        elif dtype.is_timestamp:
            data = np.asarray(arr.cast(pa.int64()).fill_null(0), dtype=np.int64)
        elif dtype is dt.DATE:
            data = np.asarray(arr.cast(pa.int32()).fill_null(0), dtype=np.int32)
        else:
            if arr.null_count:
                arr = arr.fill_null(dtype.default_value())
            data = np.asarray(arr)
            if dtype.np_dtype is not None:
                data = data.astype(dtype.np_dtype, copy=False)
        return Vector(dtype, data, validity)

    # ---- conversions ----
    def to_arrow(self) -> pa.Array:
        mask = None if self.validity is None else ~self.validity
        if self.dtype.is_string or self.dtype.is_binary:
            if isinstance(self.data, np.ndarray):
                # pa.array consumes object/<U ndarrays + mask at C speed;
                # the list() round trip costs ~0.5s per 2M rows
                return pa.array(self.data, type=self.dtype.pa_type,
                                mask=mask)
            vals = list(self.data)
            if mask is not None:
                vals = [None if m else v for v, m in zip(vals, mask)]
            return pa.array(vals, type=self.dtype.pa_type)
        if self.dtype.is_timestamp:
            base = pa.array(self.data.astype(np.int64), mask=mask)
            return base.cast(self.dtype.pa_type)
        if self.dtype is dt.DATE:
            base = pa.array(self.data.astype(np.int32), mask=mask)
            return base.cast(self.dtype.pa_type)
        return pa.array(self.data, type=self.dtype.pa_type, mask=mask)

    def to_pylist(self) -> list:
        if self.validity is None:
            if self.dtype.is_boolean:
                return [bool(v) for v in self.data]
            return [v.item() if isinstance(v, np.generic) else v for v in self.data]
        out = []
        for v, ok in zip(self.data, self.validity):
            if not ok:
                out.append(None)
            elif isinstance(v, np.generic):
                out.append(v.item())
            else:
                out.append(v)
        return out

    # ---- access / ops ----
    def __len__(self) -> int:
        return len(self.data)

    def get(self, i: int) -> Any:
        if self.validity is not None and not self.validity[i]:
            return None
        v = self.data[i]
        return v.item() if isinstance(v, np.generic) else v

    def is_null(self, i: int) -> bool:
        return self.validity is not None and not bool(self.validity[i])

    @property
    def null_count(self) -> int:
        return 0 if self.validity is None else int((~self.validity).sum())

    def slice(self, start: int, length: int) -> "Vector":
        v = None if self.validity is None else self.validity[start:start + length]
        return Vector(self.dtype, self.data[start:start + length], v)

    def take(self, indices: np.ndarray) -> "Vector":
        v = None if self.validity is None else self.validity[indices]
        return Vector(self.dtype, self.data[indices], v)

    def filter(self, mask: np.ndarray) -> "Vector":
        v = None if self.validity is None else self.validity[mask]
        return Vector(self.dtype, self.data[mask], v)

    def cast(self, target: ConcreteDataType) -> "Vector":
        if target == self.dtype:
            return self
        if target.is_string:
            data = np.empty(len(self), dtype=object)
            for i, v in enumerate(self.to_pylist()):
                data[i] = "" if v is None else str(v)
            return Vector(target, data, self.validity)
        if self.dtype.is_string or self.dtype.is_binary:
            return Vector.from_pylist(
                [None if v is None else target.cast_value(v) for v in self.to_pylist()],
                target)
        if self.dtype.is_timestamp and target.is_timestamp:
            sf, tf = self.dtype.time_unit.factor, target.time_unit.factor
            if tf >= sf:
                data = self.data * (tf // sf)
            else:
                data = self.data // (sf // tf)
            return Vector(target, data.astype(np.int64), self.validity)
        return Vector(target, self.data.astype(target.np_dtype), self.validity)

    @staticmethod
    def concat(vectors: Iterable["Vector"]) -> "Vector":
        vs = list(vectors)
        assert vs, "cannot concat zero vectors"
        dtype = vs[0].dtype
        data = np.concatenate([v.data for v in vs])
        if any(v.validity is not None for v in vs):
            validity = np.concatenate([
                v.validity if v.validity is not None else np.ones(len(v), dtype=bool)
                for v in vs])
        else:
            validity = None
        return Vector(dtype, data, validity)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Vector<{self.dtype.name}>[{len(self)}]"


def compat_column(col_schema, n: int):
    """(data, validity) for a column absent from an old run/SST: filled
    from the column's DEFAULT constraint, else nulls (reference: schema
    read-compat matrices, src/storage/src/schema/compat.rs:611 — readers
    adapt old files to the current schema by synthesizing added columns).
    Raises for a non-nullable column with no default: the file is
    genuinely incompatible."""
    vec = col_schema.create_default_vector(n)
    if vec is None:
        from ..errors import StorageError
        raise StorageError(
            f"column {col_schema.name!r} is non-nullable with no default; "
            f"cannot read data written before it was added")
    return vec.data, vec.validity
