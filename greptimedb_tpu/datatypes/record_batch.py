"""RecordBatch: a schema + equal-length vectors.

Reference behavior: src/common/recordbatch/src/ — the unit of data flowing
between scan, compute and protocol layers. Interops with pyarrow for
Parquet/Flight/IPC, and exposes the SoA numpy view the device path consumes.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from .schema import Schema
from .vector import Vector


class RecordBatch:
    def __init__(self, schema: Schema, columns: Sequence[Vector]):
        assert len(schema) == len(columns), \
            f"schema has {len(schema)} cols, got {len(columns)} vectors"
        lens = {len(c) for c in columns}
        assert len(lens) <= 1, f"ragged columns: {lens}"
        self.schema = schema
        self.columns: List[Vector] = list(columns)

    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, idx_or_name) -> Vector:
        if isinstance(idx_or_name, str):
            return self.columns[self.schema.column_index(idx_or_name)]
        return self.columns[idx_or_name]

    # ---- constructors ----
    @staticmethod
    def from_pydict(schema: Schema, data: Dict[str, Sequence[Any]]) -> "RecordBatch":
        cols = []
        for c in schema.column_schemas:
            v = data[c.name]
            if not isinstance(v, (list, np.ndarray)):
                v = list(v)
            cols.append(Vector.from_pylist(v, c.dtype))
        return RecordBatch(schema, cols)

    @staticmethod
    def empty(schema: Schema) -> "RecordBatch":
        return RecordBatch(schema, [Vector.from_pylist([], c.dtype)
                                    for c in schema.column_schemas])

    @staticmethod
    def from_arrow(batch: pa.RecordBatch | pa.Table,
                   schema: Optional[Schema] = None) -> "RecordBatch":
        if schema is None:
            schema = Schema.from_arrow(batch.schema)
        cols = [Vector.from_arrow(batch.column(i)) for i in range(batch.num_columns)]
        return RecordBatch(schema, cols)

    # ---- conversions ----
    def to_arrow(self) -> pa.RecordBatch:
        return pa.RecordBatch.from_arrays(
            [c.to_arrow() for c in self.columns], schema=self.schema.to_arrow())

    def to_pydict(self) -> Dict[str, list]:
        return {c.name: v.to_pylist()
                for c, v in zip(self.schema.column_schemas, self.columns)}

    def to_pylist(self) -> List[dict]:
        cols = self.to_pydict()
        names = self.schema.names()
        return [dict(zip(names, row)) for row in zip(*[cols[n] for n in names])]

    def rows(self) -> Iterable[tuple]:
        lists = [c.to_pylist() for c in self.columns]
        return zip(*lists) if lists else iter(())

    # ---- ops ----
    def project(self, names: Sequence[str]) -> "RecordBatch":
        idxs = [self.schema.column_index(n) for n in names]
        return RecordBatch(self.schema.project(names), [self.columns[i] for i in idxs])

    def slice(self, start: int, length: int) -> "RecordBatch":
        return RecordBatch(self.schema, [c.slice(start, length) for c in self.columns])

    def filter(self, mask: np.ndarray) -> "RecordBatch":
        return RecordBatch(self.schema, [c.filter(mask) for c in self.columns])

    def take(self, indices: np.ndarray) -> "RecordBatch":
        return RecordBatch(self.schema, [c.take(indices) for c in self.columns])

    @staticmethod
    def concat(batches: Sequence["RecordBatch"]) -> "RecordBatch":
        assert batches, "cannot concat zero batches"
        if len(batches) == 1:
            return batches[0]
        schema = batches[0].schema
        cols = [Vector.concat([b.columns[i] for b in batches])
                for i in range(len(schema))]
        return RecordBatch(schema, cols)

    def __repr__(self) -> str:  # pragma: no cover
        return f"RecordBatch[{self.num_rows}x{self.num_columns}]"


def pretty_print(batches: Sequence[RecordBatch]) -> str:
    """Render batches as an ASCII table (for CLI / sqlness-style tests)."""
    if not batches:
        return "(empty)"
    schema = batches[0].schema
    names = schema.names()
    rows: List[List[str]] = []
    for b in batches:
        for row in b.rows():
            rows.append(["NULL" if v is None else _fmt(v, schema.column_schemas[i])
                         for i, v in enumerate(row)])
    widths = [len(n) for n in names]
    for r in rows:
        for i, v in enumerate(r):
            widths[i] = max(widths[i], len(v))
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    out = [sep, "|" + "|".join(f" {n:<{w}} " for n, w in zip(names, widths)) + "|", sep]
    for r in rows:
        out.append("|" + "|".join(f" {v:<{w}} " for v, w in zip(r, widths)) + "|")
    out.append(sep)
    return "\n".join(out)


def arrow_to_ingest_columns(tbl: pa.Table | pa.RecordBatch,
                            schema: Schema,
                            extra: str = "drop") -> Dict[str, Any]:
    """Arrow table → ingest columns shaped for the bulk-load fast path.

    The raw path in Region.bulk_ingest skips all per-value validation
    when every column arrives as a typed ndarray, so this converter
    keeps columns in columnar form end to end: timestamps cast to the
    schema unit and viewed as int64, numerics handed over zero-copy
    when null-free, string tags as one object array. Only null-bearing
    numeric columns fall back to python lists (Nones carry validity
    through the validating WriteBatch path). Columns absent from the
    schema are dropped by default (reference: COPY FROM column pruning,
    src/operator/src/statement/copy_table_from.rs); extra="keep" passes
    them through as python lists for auto-ALTER ingest paths."""
    out: Dict[str, Any] = {}
    for name in tbl.schema.names:
        col = tbl.column(name)
        if isinstance(col, pa.ChunkedArray):
            col = col.combine_chunks()
        if not schema.contains(name):
            if extra == "keep":
                # unknown columns survive as python lists so the caller's
                # auto-ALTER sees them (the Flight bulk path matches
                # insert()'s create/alter-on-demand contract)
                out[name] = col.to_pylist()
            continue
        cs = schema.column_schema(name)
        if cs.dtype.is_string or cs.dtype.is_binary:
            if pa.types.is_dictionary(col.type):
                col = col.dictionary_decode()
            out[name] = col.to_numpy(zero_copy_only=False)
        elif cs.dtype.is_timestamp:
            # cast to the schema unit FIRST (to_pylist of a timestamp
            # column yields datetime objects the validating path cannot
            # cast; int64 epoch values round-trip for both branches)
            want = cs.dtype.pa_type
            if col.type != want:
                col = col.cast(want)
            ints = col.cast(pa.int64())
            out[name] = ints.to_pylist() if col.null_count \
                else np.asarray(ints, dtype=np.int64)
        elif col.null_count:
            # Nones must survive into the validating path (numpy would
            # silently coerce them to NaN for float dtypes)
            out[name] = col.to_pylist()
        else:
            want = cs.dtype.np_dtype
            arr = col.to_numpy(zero_copy_only=False)
            if want is not None and arr.dtype != want:
                arr = arr.astype(want)
            out[name] = arr
    return out


def _fmt(v: Any, col) -> str:
    if col.dtype.is_timestamp:
        from ..common.time import Timestamp
        return Timestamp(v, col.dtype.time_unit).to_datetime().strftime(
            "%Y-%m-%dT%H:%M:%S.%f")[:-3]
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, (bool, np.bool_)):
        return "true" if v else "false"
    return str(v)
