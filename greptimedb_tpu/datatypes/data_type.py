"""Concrete data types bridging SQL types, numpy, pyarrow and JAX.

Reference behavior: src/datatypes/src/data_type.rs — `ConcreteDataType`
enumerates the storable types (bool, int/uint 8-64, float 32/64, string,
binary, date, timestamps at 4 units) and knows its Arrow mapping. Here each
type additionally knows its numpy dtype (host SoA buffers) and its device
dtype (what the column looks like in HBM; strings are dictionary-encoded to
int32 tag ids before they ever reach the device).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np
import pyarrow as pa

from ..common.time import TimeUnit


@dataclass(frozen=True)
class ConcreteDataType:
    name: str
    np_dtype: Optional[np.dtype]  # None for string/binary (object arrays host-side)
    pa_type: pa.DataType = field(compare=False)
    time_unit: Optional[TimeUnit] = None

    # ---- classification ----
    @property
    def is_timestamp(self) -> bool:
        return self.time_unit is not None

    @property
    def is_string(self) -> bool:
        return self.name == "String"

    @property
    def is_binary(self) -> bool:
        return self.name == "Binary"

    @property
    def is_numeric(self) -> bool:
        return self.np_dtype is not None and np.issubdtype(self.np_dtype, np.number) \
            and not self.is_timestamp

    @property
    def is_float(self) -> bool:
        return self.np_dtype is not None and np.issubdtype(self.np_dtype, np.floating)

    @property
    def is_boolean(self) -> bool:
        return self.name == "Boolean"

    # ---- device mapping ----
    def device_np_dtype(self) -> np.dtype:
        """Dtype of this column once resident on device. Strings/binary are
        dictionary ids (int32); timestamps are int64 ticks; bools are int8."""
        if self.is_string or self.is_binary:
            return np.dtype(np.int32)
        if self.is_timestamp:
            return np.dtype(np.int64)
        if self.is_boolean:
            return np.dtype(np.int8)
        assert self.np_dtype is not None
        return self.np_dtype

    def default_value(self) -> Any:
        if self.is_string:
            return ""
        if self.is_binary:
            return b""
        if self.is_boolean:
            return False
        if self.is_float:
            return 0.0
        return 0

    def cast_value(self, v: Any) -> Any:
        """Cast a python value into this type's canonical python repr."""
        if v is None:
            return None
        if self.is_string:
            return str(v)
        if self.is_binary:
            return bytes(v)
        if self.is_boolean:
            if isinstance(v, str):
                return v.lower() in ("true", "1", "t", "yes")
            return bool(v)
        if self.is_timestamp:
            from ..common.time import Timestamp
            if isinstance(v, Timestamp):
                return v.convert_to(self.time_unit).value
            if isinstance(v, str):
                return Timestamp.from_str(v, self.time_unit).value
            return int(v)
        if self.is_float:
            return float(v)
        return int(v)

    def __str__(self) -> str:  # pragma: no cover - trivial
        return self.name


def _ts_patype(unit: TimeUnit) -> pa.DataType:
    return pa.timestamp(unit.value)


BOOLEAN = ConcreteDataType("Boolean", np.dtype(np.bool_), pa.bool_())
INT8 = ConcreteDataType("Int8", np.dtype(np.int8), pa.int8())
INT16 = ConcreteDataType("Int16", np.dtype(np.int16), pa.int16())
INT32 = ConcreteDataType("Int32", np.dtype(np.int32), pa.int32())
INT64 = ConcreteDataType("Int64", np.dtype(np.int64), pa.int64())
UINT8 = ConcreteDataType("UInt8", np.dtype(np.uint8), pa.uint8())
UINT16 = ConcreteDataType("UInt16", np.dtype(np.uint16), pa.uint16())
UINT32 = ConcreteDataType("UInt32", np.dtype(np.uint32), pa.uint32())
UINT64 = ConcreteDataType("UInt64", np.dtype(np.uint64), pa.uint64())
FLOAT32 = ConcreteDataType("Float32", np.dtype(np.float32), pa.float32())
FLOAT64 = ConcreteDataType("Float64", np.dtype(np.float64), pa.float64())
STRING = ConcreteDataType("String", None, pa.string())
BINARY = ConcreteDataType("Binary", None, pa.binary())
DATE = ConcreteDataType("Date", np.dtype(np.int32), pa.date32())
TIMESTAMP_SECOND = ConcreteDataType(
    "TimestampSecond", np.dtype(np.int64), _ts_patype(TimeUnit.SECOND), TimeUnit.SECOND)
TIMESTAMP_MILLISECOND = ConcreteDataType(
    "TimestampMillisecond", np.dtype(np.int64), _ts_patype(TimeUnit.MILLISECOND),
    TimeUnit.MILLISECOND)
TIMESTAMP_MICROSECOND = ConcreteDataType(
    "TimestampMicrosecond", np.dtype(np.int64), _ts_patype(TimeUnit.MICROSECOND),
    TimeUnit.MICROSECOND)
TIMESTAMP_NANOSECOND = ConcreteDataType(
    "TimestampNanosecond", np.dtype(np.int64), _ts_patype(TimeUnit.NANOSECOND),
    TimeUnit.NANOSECOND)

_TS_BY_UNIT = {
    TimeUnit.SECOND: TIMESTAMP_SECOND,
    TimeUnit.MILLISECOND: TIMESTAMP_MILLISECOND,
    TimeUnit.MICROSECOND: TIMESTAMP_MICROSECOND,
    TimeUnit.NANOSECOND: TIMESTAMP_NANOSECOND,
}


def timestamp_type(unit: TimeUnit) -> ConcreteDataType:
    return _TS_BY_UNIT[unit]


ALL_TYPES = [
    BOOLEAN, INT8, INT16, INT32, INT64, UINT8, UINT16, UINT32, UINT64,
    FLOAT32, FLOAT64, STRING, BINARY, DATE,
    TIMESTAMP_SECOND, TIMESTAMP_MILLISECOND, TIMESTAMP_MICROSECOND,
    TIMESTAMP_NANOSECOND,
]

_BY_NAME = {t.name.lower(): t for t in ALL_TYPES}

# SQL-facing aliases (CREATE TABLE type names).
_SQL_ALIASES = {
    "bool": BOOLEAN, "boolean": BOOLEAN,
    "tinyint": INT8, "int8": INT8,
    "smallint": INT16, "int16": INT16,
    "int": INT32, "integer": INT32, "int32": INT32,
    "bigint": INT64, "int64": INT64,
    "tinyint unsigned": UINT8, "uint8": UINT8,
    "smallint unsigned": UINT16, "uint16": UINT16,
    "int unsigned": UINT32, "uint32": UINT32,
    "bigint unsigned": UINT64, "uint64": UINT64,
    "float": FLOAT32, "float32": FLOAT32, "real": FLOAT32,
    "double": FLOAT64, "float64": FLOAT64,
    "string": STRING, "text": STRING, "varchar": STRING, "char": STRING,
    "binary": BINARY, "varbinary": BINARY, "blob": BINARY, "bytea": BINARY,
    "date": DATE,
    "timestamp": TIMESTAMP_MILLISECOND,
    "timestamp_s": TIMESTAMP_SECOND, "timestamp(0)": TIMESTAMP_SECOND,
    "timestamp_ms": TIMESTAMP_MILLISECOND, "timestamp(3)": TIMESTAMP_MILLISECOND,
    "timestamp_us": TIMESTAMP_MICROSECOND, "timestamp(6)": TIMESTAMP_MICROSECOND,
    "timestamp_ns": TIMESTAMP_NANOSECOND, "timestamp(9)": TIMESTAMP_NANOSECOND,
    "datetime": TIMESTAMP_MILLISECOND,
}


def parse_type_name(name: str) -> ConcreteDataType:
    key = " ".join(name.strip().lower().split())
    if key in _SQL_ALIASES:
        return _SQL_ALIASES[key]
    if key in _BY_NAME:
        return _BY_NAME[key]
    raise ValueError(f"unknown data type: {name!r}")


def from_arrow_type(t: pa.DataType) -> ConcreteDataType:
    if pa.types.is_timestamp(t):
        unit = {"s": TimeUnit.SECOND, "ms": TimeUnit.MILLISECOND,
                "us": TimeUnit.MICROSECOND, "ns": TimeUnit.NANOSECOND}[t.unit]
        return timestamp_type(unit)
    for c in ALL_TYPES:
        if c.pa_type.equals(t):
            return c
    if pa.types.is_large_string(t) or pa.types.is_string_view(t):
        return STRING
    if pa.types.is_large_binary(t):
        return BINARY
    if pa.types.is_dictionary(t):
        return from_arrow_type(t.value_type)
    raise ValueError(f"unsupported arrow type: {t}")
