from .data_type import (  # noqa: F401
    ConcreteDataType,
    BOOLEAN, INT8, INT16, INT32, INT64, UINT8, UINT16, UINT32, UINT64,
    FLOAT32, FLOAT64, STRING, BINARY, DATE,
    TIMESTAMP_SECOND, TIMESTAMP_MILLISECOND, TIMESTAMP_MICROSECOND,
    TIMESTAMP_NANOSECOND, timestamp_type, parse_type_name,
)
from .vector import Vector  # noqa: F401
from .schema import ColumnSchema, Schema, SemanticType, ColumnDefaultConstraint  # noqa: F401
from .record_batch import RecordBatch  # noqa: F401
