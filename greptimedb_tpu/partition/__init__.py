"""Table partitioning: range rules, write splitting, region pruning.

Reference behavior: src/partition — `PartitionRule` trait
(src/partition/src/partition.rs:30), `RangePartitionRule` over one column
(src/partition/src/range.rs:64), `RangeColumnsPartitionRule` over several
(src/partition/src/columns.rs:49), `WriteSplitter` routing insert/delete rows
to regions (src/partition/src/splitter.rs:35-100), and predicate-based
region pruning (`find_regions_by_filters`, src/partition/src/manager.rs:192).
"""

from .rule import (
    MAXVALUE,
    HashPartitionRule,
    PartitionRule,
    RangeColumnsPartitionRule,
    RangePartitionRule,
    rule_from_partitions,
)
from .splitter import split_rows

__all__ = [
    "MAXVALUE",
    "HashPartitionRule",
    "PartitionRule",
    "RangePartitionRule",
    "RangeColumnsPartitionRule",
    "rule_from_partitions",
    "split_rows",
]
