"""Partition rules mapping rows → region numbers.

Range semantics follow MySQL RANGE COLUMNS as the reference does
(src/partition/src/columns.rs:49): regions are ordered by their exclusive
upper bounds; a row belongs to the first region whose bound tuple is
strictly greater than the row's partition-column tuple. MAXVALUE sorts
above everything. Hash semantics follow MySQL PARTITION BY HASH with a
process-independent hash (crc32 over a canonical encoding — Python's
builtin `hash` is salted per process and would scatter a table's rows
differently on every datanode restart).

`find_regions_by_filters` prunes the region set by the query's
predicates (reference: src/partition/src/manager.rs:192). It may return
an EMPTY list — contradictory predicates (`host < 'a' AND host > 'z'`)
prove no region can hold a matching row, and the distributed scatter
then contacts nobody.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple


class _MaxValue:
    """Sorts above every concrete value (singleton MAXVALUE sentinel)."""

    def __repr__(self) -> str:
        return "MAXVALUE"


MAXVALUE = _MaxValue()


def _lt(a: Any, b: Any) -> bool:
    """value < bound, where bound may be MAXVALUE."""
    if b is MAXVALUE:
        return True
    if a is MAXVALUE:
        return False
    return a < b


def _tuple_lt(row: Sequence, bound: Sequence) -> bool:
    for a, b in zip(row, bound):
        if _lt(a, b):
            return True
        if b is not MAXVALUE and a == b:
            continue
        return False
    return False


class PartitionRule:
    """Maps a row (tuple of partition-column values) to a region number."""

    def partition_columns(self) -> List[str]:
        raise NotImplementedError

    def find_region(self, values: Sequence) -> int:
        raise NotImplementedError

    def region_numbers(self) -> List[int]:
        raise NotImplementedError

    def find_regions_by_filters(self, filters: Sequence) -> List[int]:
        """Prune regions by simple predicates (reference:
        src/partition/src/manager.rs:192). May return an empty list when
        the predicates are contradictory. Default: no pruning."""
        return self.region_numbers()


@dataclass
class RangePartitionRule(PartitionRule):
    """Single-column range rule: bounds are exclusive upper bounds, sorted
    ascending, last may be MAXVALUE (reference: src/partition/src/range.rs:64)."""

    column: str
    bounds: List[Any]                  # len == number of regions
    regions: List[int]                 # region number per bound

    def partition_columns(self) -> List[str]:
        return [self.column]

    def region_numbers(self) -> List[int]:
        return list(self.regions)

    def find_region(self, values: Sequence) -> int:
        v = values[0] if isinstance(values, (list, tuple)) else values
        for bound, region in zip(self.bounds, self.regions):
            if _lt(v, bound):
                return region
        raise ValueError(
            f"value {v!r} above all partition bounds of {self.column!r} "
            f"(missing MAXVALUE partition)")

    def find_regions_by_filters(self, filters: Sequence) -> List[int]:
        from ..sql.ast import BinaryOp, Column, Literal
        cand = _equality_candidates(filters, [self.column])
        if self.column in cand:
            # equality / IN pins the column to a finite value set: map
            # each value to its region (a value above all bounds of a
            # MAXVALUE-less table matches no region at all)
            hit = set()
            for v in cand[self.column]:
                try:
                    hit.add(self.find_region(v))
                except ValueError:
                    pass
            return [r for r in self.regions if r in hit]
        lo: Optional[Any] = None       # conservative AND-only pruning
        hi: Optional[Any] = None
        hi_strict = False              # v < hi (True) vs v <= hi (False)

        def visit(e: Any) -> None:
            nonlocal lo, hi, hi_strict
            if isinstance(e, BinaryOp):
                if e.op == "and":
                    visit(e.left)
                    visit(e.right)
                    return
                col, lit, op = None, None, e.op
                if isinstance(e.left, Column) and isinstance(e.right, Literal):
                    col, lit = e.left, e.right
                elif isinstance(e.right, Column) and isinstance(e.left, Literal):
                    col, lit = e.right, e.left
                    op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
                if col is None or col.name != self.column or lit.value is None:
                    return
                v = lit.value
                if op in ("<", "<="):
                    if hi is None or v < hi:
                        hi, hi_strict = v, op == "<"
                    elif v == hi and op == "<":
                        hi_strict = True
                elif op in (">", ">="):
                    lo = v if lo is None else max(lo, v)
                elif op == "=":
                    lo = v
                    if hi is None or v < hi:
                        hi, hi_strict = v, False

        for f in filters or ():
            visit(f)
        out = []
        prev_bound: Optional[Any] = None
        for bound, region in zip(self.bounds, self.regions):
            # region covers [prev_bound, bound)
            keep = True
            if lo is not None and not _lt(lo, bound):
                keep = False               # all region values <= lo
            if hi is not None and prev_bound is not None:
                if _lt(hi, prev_bound) or (hi == prev_bound and hi_strict):
                    keep = False           # all region values > hi
            if keep:
                out.append(region)
            prev_bound = bound
        return out


@dataclass
class RangeColumnsPartitionRule(PartitionRule):
    """Multi-column range rule with tuple bounds
    (reference: src/partition/src/columns.rs:49)."""

    columns: List[str]
    bounds: List[Tuple]                # tuple upper bound per region
    regions: List[int]

    def partition_columns(self) -> List[str]:
        return list(self.columns)

    def region_numbers(self) -> List[int]:
        return list(self.regions)

    def find_region(self, values: Sequence) -> int:
        for bound, region in zip(self.bounds, self.regions):
            if _tuple_lt(values, bound):
                return region
        raise ValueError(
            f"value {tuple(values)!r} above all partition bounds "
            f"(missing MAXVALUE partition)")

    def find_regions_by_filters(self, filters: Sequence) -> List[int]:
        if len(self.columns) == 1:
            return RangePartitionRule(
                self.columns[0], [b[0] for b in self.bounds],
                list(self.regions)).find_regions_by_filters(filters)
        return self.region_numbers()


def _equality_candidates(filters: Sequence,
                         columns: Sequence[str]) -> dict:
    """Per-column candidate value sets proven by the filters' equality /
    IN conjuncts: {col: set(values)} — a column absent means the filters
    do not pin it. Conservative AND-only walk; OR and non-literal shapes
    contribute nothing. An empty set means contradictory equalities."""
    from ..sql.ast import BinaryOp, Column, InList, Literal
    colset = set(columns)
    cand: dict = {}

    def narrow(name: str, values: set) -> None:
        cur = cand.get(name)
        cand[name] = values if cur is None else (cur & values)

    def visit(e: Any) -> None:
        if isinstance(e, BinaryOp):
            if e.op == "and":
                visit(e.left)
                visit(e.right)
                return
            if e.op != "=":
                return
            col, lit = None, None
            if isinstance(e.left, Column) and isinstance(e.right, Literal):
                col, lit = e.left, e.right
            elif isinstance(e.right, Column) and isinstance(e.left, Literal):
                col, lit = e.right, e.left
            if col is not None and col.name in colset and \
                    lit.value is not None:
                narrow(col.name, {lit.value})
            return
        if isinstance(e, InList) and not e.negated and \
                isinstance(e.expr, Column) and e.expr.name in colset:
            vals = set()
            for item in e.items:
                if not isinstance(item, Literal):
                    return             # non-literal member: unprovable
                if item.value is not None:
                    vals.add(item.value)
            narrow(e.expr.name, vals)

    for f in filters or ():
        visit(f)
    return cand


def _stable_hash_bytes(v: Any) -> bytes:
    """Canonical bytes for hashing a partition value: identical across
    processes, across int/float representations of the same number, and
    across numpy scalars vs Python builtins (ingest routes np.int64
    array values; query pruning routes Python literals — they MUST land
    in the same bucket)."""
    if hasattr(v, "item") and not isinstance(v, (str, bytes)):
        v = v.item()                   # numpy scalar → Python builtin
    if isinstance(v, bool):
        v = int(v)                     # True == 1 must bucket like 1
    if isinstance(v, float) and v.is_integer():
        v = int(v)
    if isinstance(v, int):
        return b"i" + str(v).encode()
    if isinstance(v, bytes):
        return b"y" + v
    return b"s" + str(v).encode()


#: cap on how many equality-candidate combinations hash pruning will
#: enumerate — an adversarial IN list must not turn pruning into work
_MAX_HASH_COMBOS = 256


@dataclass
class HashPartitionRule(PartitionRule):
    """MySQL-style PARTITION BY HASH (col, ...) PARTITIONS n: a row maps
    to region crc32(values) % n. Equality / IN predicates covering every
    hash column prune to exactly the regions their value combinations
    hash to — the distributed point-query fast path."""

    columns: List[str]
    regions: List[int]                 # len == number of hash buckets

    def partition_columns(self) -> List[str]:
        return list(self.columns)

    def region_numbers(self) -> List[int]:
        return list(self.regions)

    def _bucket(self, values: Sequence) -> int:
        h = 0
        for v in values:
            h = zlib.crc32(_stable_hash_bytes(v), h)
        return h % len(self.regions)

    def find_region(self, values: Sequence) -> int:
        if not isinstance(values, (list, tuple)):
            values = (values,)
        if len(values) != len(self.columns):
            raise ValueError(
                f"hash rule over {self.columns} got {len(values)} values")
        return self.regions[self._bucket(values)]

    def find_regions_by_filters(self, filters: Sequence) -> List[int]:
        import itertools
        cand = _equality_candidates(filters, self.columns)
        if any(c in cand and not cand[c] for c in self.columns):
            return []                  # contradictory equalities: no rows
        if not all(c in cand for c in self.columns):
            return self.region_numbers()
        combos = 1
        for c in self.columns:
            combos *= len(cand[c])
        if combos > _MAX_HASH_COMBOS:
            return self.region_numbers()
        hit = {self.regions[self._bucket(vals)]
               for vals in itertools.product(
                   *(sorted(cand[c], key=repr) for c in self.columns))}
        return [r for r in self.regions if r in hit]


def refine_range_rule(rule: PartitionRule, region: int, at_value: Any,
                      children: Sequence[int]) -> PartitionRule:
    """Split one region of a range rule into two children at `at_value`:
    the region covering [prev_bound, bound) is replaced by
    [prev_bound, at_value) -> children[0] and [at_value, bound) ->
    children[1]. Returns a NEW rule — rules are shared by live tables
    whose callers (find_regions_by_filters, SHOW CREATE TABLE) assume
    the bounds/regions lists never mutate in place.

    Raises ValueError unless `at_value` falls strictly inside the
    region's range (an empty child region would be a routing dead end).
    Hash rules cannot refine one bucket (the modulus is global); multi-
    column range rules are not refinable yet."""
    if len(children) != 2:
        raise ValueError(f"refine needs exactly 2 children, got {children}")
    single_col: Optional[RangePartitionRule] = None
    if isinstance(rule, RangePartitionRule):
        single_col = rule
    elif isinstance(rule, RangeColumnsPartitionRule) and \
            len(rule.columns) == 1:
        single_col = RangePartitionRule(
            rule.columns[0], [b[0] for b in rule.bounds],
            list(rule.regions))
    if single_col is None:
        kind = "hash" if isinstance(rule, HashPartitionRule) \
            else type(rule).__name__
        raise ValueError(
            f"cannot refine a {kind} partition rule: only single-column "
            f"range rules split region-locally")
    if region not in single_col.regions:
        raise ValueError(f"region {region} not in rule {single_col.regions}")
    idx = single_col.regions.index(region)
    lo = single_col.bounds[idx - 1] if idx > 0 else None
    hi = single_col.bounds[idx]
    if at_value is MAXVALUE or at_value is None:
        raise ValueError("split value must be a concrete literal")
    if lo is not None and not _lt(lo, at_value):
        raise ValueError(
            f"split value {at_value!r} not above the region's lower "
            f"bound {lo!r}")
    if not _lt(at_value, hi):
        raise ValueError(
            f"split value {at_value!r} not below the region's upper "
            f"bound {hi!r}")
    bounds = list(single_col.bounds)
    regions = list(single_col.regions)
    bounds[idx:idx + 1] = [at_value, hi]
    regions[idx:idx + 1] = [children[0], children[1]]
    refined = RangePartitionRule(single_col.column, bounds, regions)
    if isinstance(rule, RangeColumnsPartitionRule):
        return RangeColumnsPartitionRule(
            list(rule.columns), [(b,) for b in bounds], regions)
    return refined


def rule_from_partitions(partitions: Any,
                         region_numbers: Optional[List[int]] = None
                         ) -> PartitionRule:
    """Build a rule from a parsed `sql.ast.Partitions` clause."""
    if getattr(partitions, "kind", "range") == "hash":
        n = int(partitions.num_partitions or 0)
        if n < 1:
            raise ValueError("PARTITION BY HASH requires PARTITIONS >= 1")
        regions = list(region_numbers) if region_numbers is not None \
            else list(range(n))
        if len(regions) != n:
            raise ValueError(
                f"hash rule needs {n} regions, got {len(regions)}")
        return HashPartitionRule(list(partitions.columns), regions)
    regions = list(region_numbers) if region_numbers is not None \
        else list(range(len(partitions.entries)))
    bounds = []
    for e in partitions.entries:
        bounds.append(tuple(MAXVALUE if v == "MAXVALUE" else v
                            for v in e.values))
    if len(partitions.columns) == 1:
        return RangePartitionRule(partitions.columns[0],
                                  [b[0] for b in bounds], regions)
    return RangeColumnsPartitionRule(list(partitions.columns), bounds, regions)
