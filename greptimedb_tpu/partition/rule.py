"""Range partition rules mapping rows → region numbers.

Semantics follow MySQL RANGE COLUMNS as the reference does
(src/partition/src/columns.rs:49): regions are ordered by their exclusive
upper bounds; a row belongs to the first region whose bound tuple is
strictly greater than the row's partition-column tuple. MAXVALUE sorts
above everything.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple


class _MaxValue:
    """Sorts above every concrete value (singleton MAXVALUE sentinel)."""

    def __repr__(self):
        return "MAXVALUE"


MAXVALUE = _MaxValue()


def _lt(a, b) -> bool:
    """value < bound, where bound may be MAXVALUE."""
    if b is MAXVALUE:
        return True
    if a is MAXVALUE:
        return False
    return a < b


def _tuple_lt(row: Sequence, bound: Sequence) -> bool:
    for a, b in zip(row, bound):
        if _lt(a, b):
            return True
        if b is not MAXVALUE and a == b:
            continue
        return False
    return False


class PartitionRule:
    """Maps a row (tuple of partition-column values) to a region number."""

    def partition_columns(self) -> List[str]:
        raise NotImplementedError

    def find_region(self, values: Sequence) -> int:
        raise NotImplementedError

    def region_numbers(self) -> List[int]:
        raise NotImplementedError

    def find_regions_by_filters(self, filters) -> List[int]:
        """Prune regions by simple predicates (reference:
        src/partition/src/manager.rs:192). Default: no pruning."""
        return self.region_numbers()


@dataclass
class RangePartitionRule(PartitionRule):
    """Single-column range rule: bounds are exclusive upper bounds, sorted
    ascending, last may be MAXVALUE (reference: src/partition/src/range.rs:64)."""

    column: str
    bounds: List[Any]                  # len == number of regions
    regions: List[int]                 # region number per bound

    def partition_columns(self) -> List[str]:
        return [self.column]

    def region_numbers(self) -> List[int]:
        return list(self.regions)

    def find_region(self, values: Sequence) -> int:
        v = values[0] if isinstance(values, (list, tuple)) else values
        for bound, region in zip(self.bounds, self.regions):
            if _lt(v, bound):
                return region
        raise ValueError(
            f"value {v!r} above all partition bounds of {self.column!r} "
            f"(missing MAXVALUE partition)")

    def find_regions_by_filters(self, filters) -> List[int]:
        from ..sql.ast import BinaryOp, Column, Literal
        lo: Optional[Any] = None       # conservative AND-only pruning
        hi: Optional[Any] = None
        hi_strict = False              # v < hi (True) vs v <= hi (False)

        def visit(e):
            nonlocal lo, hi, hi_strict
            if isinstance(e, BinaryOp):
                if e.op == "and":
                    visit(e.left)
                    visit(e.right)
                    return
                col, lit, op = None, None, e.op
                if isinstance(e.left, Column) and isinstance(e.right, Literal):
                    col, lit = e.left, e.right
                elif isinstance(e.right, Column) and isinstance(e.left, Literal):
                    col, lit = e.right, e.left
                    op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
                if col is None or col.name != self.column or lit.value is None:
                    return
                v = lit.value
                if op in ("<", "<="):
                    if hi is None or v < hi:
                        hi, hi_strict = v, op == "<"
                    elif v == hi and op == "<":
                        hi_strict = True
                elif op in (">", ">="):
                    lo = v if lo is None else max(lo, v)
                elif op == "=":
                    lo = v
                    if hi is None or v < hi:
                        hi, hi_strict = v, False

        for f in filters or ():
            visit(f)
        out = []
        prev_bound: Optional[Any] = None
        for bound, region in zip(self.bounds, self.regions):
            # region covers [prev_bound, bound)
            keep = True
            if lo is not None and not _lt(lo, bound):
                keep = False               # all region values <= lo
            if hi is not None and prev_bound is not None:
                if _lt(hi, prev_bound) or (hi == prev_bound and hi_strict):
                    keep = False           # all region values > hi
            if keep:
                out.append(region)
            prev_bound = bound
        return out or list(self.regions)


@dataclass
class RangeColumnsPartitionRule(PartitionRule):
    """Multi-column range rule with tuple bounds
    (reference: src/partition/src/columns.rs:49)."""

    columns: List[str]
    bounds: List[Tuple]                # tuple upper bound per region
    regions: List[int]

    def partition_columns(self) -> List[str]:
        return list(self.columns)

    def region_numbers(self) -> List[int]:
        return list(self.regions)

    def find_region(self, values: Sequence) -> int:
        for bound, region in zip(self.bounds, self.regions):
            if _tuple_lt(values, bound):
                return region
        raise ValueError(
            f"value {tuple(values)!r} above all partition bounds "
            f"(missing MAXVALUE partition)")

    def find_regions_by_filters(self, filters) -> List[int]:
        if len(self.columns) == 1:
            return RangePartitionRule(
                self.columns[0], [b[0] for b in self.bounds],
                list(self.regions)).find_regions_by_filters(filters)
        return self.region_numbers()


def rule_from_partitions(partitions, region_numbers=None) -> PartitionRule:
    """Build a rule from a parsed `sql.ast.Partitions` clause."""
    regions = list(region_numbers) if region_numbers is not None \
        else list(range(len(partitions.entries)))
    bounds = []
    for e in partitions.entries:
        bounds.append(tuple(MAXVALUE if v == "MAXVALUE" else v
                            for v in e.values))
    if len(partitions.columns) == 1:
        return RangePartitionRule(partitions.columns[0],
                                  [b[0] for b in bounds], regions)
    return RangeColumnsPartitionRule(list(partitions.columns), bounds, regions)
