"""Row → region splitting for inserts and deletes.

Reference behavior: src/partition/src/splitter.rs:35-100 — `WriteSplitter`
computes a region number per row from the partition rule and groups rows.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .rule import PartitionRule


def split_rows(rule: Optional[PartitionRule],
               columns: Dict[str, Sequence],
               num_rows: int) -> Dict[int, np.ndarray]:
    """Return region number → row-index array.

    With no rule (single-region table) every row goes to region 0. Missing
    partition columns raise — the reference requires them on every insert
    (splitter.rs:46-80).
    """
    if rule is None:
        return {0: np.arange(num_rows)}
    pcols = rule.partition_columns()
    for c in pcols:
        if c not in columns:
            raise ValueError(f"insert missing partition column {c!r}")
    vals = [columns[c] for c in pcols]
    regions: Dict[int, List[int]] = {}
    for i in range(num_rows):
        r = rule.find_region(tuple(v[i] for v in vals))
        regions.setdefault(r, []).append(i)
    return {r: np.asarray(ix) for r, ix in regions.items()}
