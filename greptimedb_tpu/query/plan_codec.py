"""Plan shipping: serialize TPU aggregate plans (and the expression subset
they carry) for the router→worker boundary.

Reference behavior: src/common/substrait — `DFLogicalSubstraitConvertor`
encodes the pushed-down plan so the datanode can decode and execute it
against its local catalog (df_substrait.rs:31, consumed by
src/datanode/src/instance/grpc.rs:62-83). Here the shipped plan is the
TpuPlan (tag groups + time bucket + moments + predicates) — the unit of
aggregate pushdown — encoded as JSON-safe dicts.

Rolling upgrades: every front end (SQL, PromQL, flows) now ships plans
through this codec, so skew handling is uniform. Decode validates each
moment/final op against KNOWN_*_OPS and fails closed — an old datanode
rejects a plan carrying an op it predates (typed UnsupportedError, the
WIRE_UNSUPPORTED_MARKER survives Flight), and the frontend degrades
that one statement to the raw-row path for a correct (slower) answer.
Upgrade datanodes before frontends: the window where new plan shapes
degrade is exactly the rollout window. Adding an op = add it to the
reducers AND these sets in the same release; never reuse a name with
different semantics.
"""

from __future__ import annotations

from typing import Optional

from ..errors import UnsupportedError
from ..sql.ast import (
    Between, BinaryOp, Column, Expr, FunctionCall, InList, Interval, IsNull,
    Literal, UnaryOp,
)
from .tpu_exec import BucketGroup, FieldFilter, Moment, TagGroup, TpuPlan

#: every moment op this build's reducers implement, and every final op
#: _finalize knows how to render. plan_from_dict VALIDATES against these
#: on decode so version skew fails closed: a datanode that predates a
#: new op rejects the plan with a typed UnsupportedError (carrying
#: WIRE_UNSUPPORTED_MARKER across Flight), the frontend degrades the
#: statement to the raw-row path, and no stale reducer ever folds a
#: moment it half-understands into a wrong answer.
KNOWN_MOMENT_OPS = frozenset({
    "sum", "sum_sq", "count", "min", "max", "first", "last",
    "min_ts", "max_ts", "distinct", "tdigest", "reset_corr"})
KNOWN_FINAL_OPS = frozenset({
    "sum", "avg", "count", "min", "max", "first", "last", "stddev",
    "variance", "count_distinct", "approx_distinct", "approx_percentile",
    "moment"})

#: substring marker that survives Flight's string-flattened errors —
#: client/flight.py rebuilds UnsupportedError from it, the same scheme
#: StaleRouteError / OverloadedError use
WIRE_UNSUPPORTED_MARKER = "unsupported shipped plan"


def expr_to_dict(e: Optional[Expr]) -> Optional[dict]:
    if e is None:
        return None
    if isinstance(e, Literal):
        return {"k": "lit", "v": e.value}
    if isinstance(e, Column):
        return {"k": "col", "name": e.name}
    if isinstance(e, BinaryOp):
        return {"k": "bin", "op": e.op, "l": expr_to_dict(e.left),
                "r": expr_to_dict(e.right)}
    if isinstance(e, UnaryOp):
        return {"k": "un", "op": e.op, "e": expr_to_dict(e.operand)}
    if isinstance(e, InList):
        return {"k": "in", "e": expr_to_dict(e.expr), "neg": e.negated,
                "items": [expr_to_dict(i) for i in e.items]}
    if isinstance(e, Between):
        return {"k": "between", "e": expr_to_dict(e.expr),
                "neg": e.negated, "lo": expr_to_dict(e.low),
                "hi": expr_to_dict(e.high)}
    if isinstance(e, IsNull):
        return {"k": "isnull", "e": expr_to_dict(e.expr), "neg": e.negated}
    if isinstance(e, FunctionCall):
        return {"k": "fn", "name": e.name,
                "args": [expr_to_dict(a) for a in e.args]}
    if isinstance(e, Interval):
        return {"k": "interval", "text": e.text}
    raise UnsupportedError(f"cannot ship expression {type(e).__name__}")


def expr_from_dict(d: Optional[dict]) -> Optional[Expr]:
    if d is None:
        return None
    k = d["k"]
    if k == "lit":
        return Literal(d["v"])
    if k == "col":
        return Column(d["name"])
    if k == "bin":
        return BinaryOp(d["op"], expr_from_dict(d["l"]),
                        expr_from_dict(d["r"]))
    if k == "un":
        return UnaryOp(d["op"], expr_from_dict(d["e"]))
    if k == "in":
        return InList(expr_from_dict(d["e"]),
                      [expr_from_dict(i) for i in d["items"]], d["neg"])
    if k == "between":
        return Between(expr_from_dict(d["e"]), expr_from_dict(d["lo"]),
                       expr_from_dict(d["hi"]), d["neg"])
    if k == "isnull":
        return IsNull(expr_from_dict(d["e"]), d["neg"])
    if k == "fn":
        return FunctionCall(d["name"],
                            [expr_from_dict(a) for a in d["args"]])
    if k == "interval":
        return Interval(d["text"])
    raise UnsupportedError(f"unknown shipped expression kind {k!r}")


def plan_to_dict(plan: TpuPlan) -> dict:
    return {
        "tag_groups": [{"name": t.name, "tag_index": t.tag_index}
                       for t in plan.tag_groups],
        "bucket": None if plan.bucket is None else {
            "stride_ms": plan.bucket.stride_ms,
            "origin": plan.bucket.origin,
            "expr_key": plan.bucket.expr_key},
        "moments": [{"op": m.op, "column": m.column, "slot": m.slot}
                    for m in plan.moments],
        "finals": [[slot, op, list(mslots)]
                   for slot, op, mslots in plan.finals],
        "time_lo": plan.time_lo,
        "time_hi": plan.time_hi,
        "tag_predicates": [expr_to_dict(p) for p in plan.tag_predicates],
        "field_filters": [{"column": f.column, "op": f.op,
                           "value": f.value}
                          for f in plan.field_filters],
        # expression-arg moments + sketch finals (ISSUE 14): virtual
        # moment columns each datanode evaluates from its stored
        # fields, and per-final literal params (approx_percentile's p)
        "field_exprs": {k: expr_to_dict(e)
                        for k, e in plan.field_exprs.items()},
        "agg_params": {k: list(v) for k, v in plan.agg_params.items()},
    }


def plan_from_dict(d: dict) -> TpuPlan:
    for m in d["moments"]:
        if m["op"] not in KNOWN_MOMENT_OPS:
            raise UnsupportedError(
                f"{WIRE_UNSUPPORTED_MARKER}: moment op {m['op']!r} "
                f"(datanode predates it; upgrade datanodes first)")
    for _slot, op, _mslots in d["finals"]:
        if op not in KNOWN_FINAL_OPS:
            raise UnsupportedError(
                f"{WIRE_UNSUPPORTED_MARKER}: final op {op!r} "
                f"(datanode predates it; upgrade datanodes first)")
    return TpuPlan(
        tag_groups=[TagGroup(t["name"], t["tag_index"])
                    for t in d["tag_groups"]],
        bucket=None if d["bucket"] is None else BucketGroup(
            d["bucket"]["stride_ms"], d["bucket"]["origin"],
            d["bucket"]["expr_key"]),
        moments=[Moment(m["op"], m["column"], m["slot"])
                 for m in d["moments"]],
        finals=[(slot, op, list(mslots)) for slot, op, mslots in
                d["finals"]],
        time_lo=d["time_lo"],
        time_hi=d["time_hi"],
        tag_predicates=[expr_from_dict(p) for p in d["tag_predicates"]],
        field_filters=[FieldFilter(f["column"], f["op"], f["value"])
                       for f in d["field_filters"]],
        # .get: a NEW datanode tolerates a pre-sketch frontend's plans.
        # The reverse direction is NOT degradable — a pre-sketch
        # datanode would drop field_exprs and fail the scan — so roll
        # datanodes before frontends when upgrading across this codec
        field_exprs={k: expr_from_dict(e)
                     for k, e in (d.get("field_exprs") or {}).items()},
        agg_params={k: tuple(v)
                    for k, v in (d.get("agg_params") or {}).items()},
    )
