"""SQL window function execution over pandas frames (CPU fallback path).

Plays the role DataFusion's WindowAggExec plays for the reference
(src/query/src/datafusion.rs:61-232 delegates OVER (...) to DataFusion).
Each WindowCall is evaluated on the post-WHERE (and, for grouped queries,
post-aggregate) frame: rows are ordered by the spec inside each partition,
the function runs positionally, and results land back on the original row
order via index alignment, filling the call's `__win{i}` slot column.

Semantics notes:
- Default frame with ORDER BY is RANGE UNBOUNDED PRECEDING..CURRENT ROW:
  peer rows (ties on the order key) share the frame, so running aggregates
  are adjusted to the value at the last peer of each tie group.
- ROWS frames use exact row offsets (rolling windows).
- NULL order keys sort as the largest value (Postgres default: NULLS LAST
  for ASC, NULLS FIRST for DESC) and are peers of each other.
"""

from __future__ import annotations

from typing import List

import numpy as np
import pandas as pd

from ..errors import PlanError, UnsupportedError
from .expr import Evaluator
from .planner import Analysis, WindowCall

_NEEDS_ORDER = {"rank", "dense_rank", "percent_rank", "cume_dist",
                "lag", "lead", "ntile"}


def compute_windows(df: pd.DataFrame, a: Analysis) -> pd.DataFrame:
    """Return df with one extra column per WindowCall (its slot name)."""
    if not a.window_calls:
        return df
    df = df.copy()
    if len(df) == 0:
        for wc in a.window_calls:
            df[wc.slot] = pd.Series(dtype=float)
        return df
    ev = Evaluator(df)
    for wc in a.window_calls:
        df[wc.slot] = _one_window(df, ev, wc)
        ev = Evaluator(df)
    return df


def _one_window(df: pd.DataFrame, ev: Evaluator, wc: WindowCall) -> pd.Series:
    spec = wc.spec
    if wc.op in _NEEDS_ORDER and not spec.order_by:
        raise PlanError(f"{wc.op}() requires ORDER BY in its OVER clause")

    work = pd.DataFrame(index=df.index)
    pkeys: List[str] = []
    for j, pe in enumerate(spec.partition_by):
        work[f"__p{j}"] = ev.series(ev.eval(pe))
        pkeys.append(f"__p{j}")
    okeys: List[str] = []
    asc: List[bool] = []
    for j, (oe, up) in enumerate(spec.order_by):
        work[f"__o{j}"] = ev.series(ev.eval(oe))
        okeys.append(f"__o{j}")
        asc.append(up)
    for j, arg in enumerate(wc.args):
        work[f"__a{j}"] = ev.series(ev.eval(arg))

    # order within partitions: stable sort by (partition, order) so rows of
    # one partition are contiguous and ordered. NULL order keys follow the
    # Postgres default (NULLS LAST for ASC, NULLS FIRST for DESC): pandas
    # has one global na_position, so each order key gets an isna flag key
    # sorted in the key's own direction (nulls sort as the "largest" value).
    if pkeys or okeys:
        sort_cols = pkeys[:]
        sort_asc = [True] * len(pkeys)
        for j, up in enumerate(asc):
            work[f"__on{j}"] = work[f"__o{j}"].isna()
            sort_cols += [f"__on{j}", f"__o{j}"]
            sort_asc += [up, up]
        work = work.sort_values(sort_cols, ascending=sort_asc,
                                kind="stable", na_position="last")
    n = len(work)
    pos = np.arange(n)

    # partition starts / tie-group starts as boolean flags over sorted rows
    if pkeys:
        pvals = work[pkeys]
        pstart = _neq_prev(pvals)
    else:
        pstart = np.zeros(n, dtype=bool)
    pstart[0] = True
    if okeys:
        tie_start = _neq_prev(work[okeys]) | pstart
    else:
        tie_start = pstart.copy()

    # per-row partition id (for grouped ops) and row number
    pid = np.cumsum(pstart) - 1
    pid_s = pd.Series(pid, index=work.index)
    rn = pos - _ffill_at(pos, pstart) + 1          # 1-based row_number

    out = _eval_fn(wc, work, pid_s, pstart, tie_start, rn, pos)
    if not isinstance(out, pd.Series):
        out = pd.Series(out, index=work.index)
    else:
        out.index = work.index
    return out.reindex(df.index)


def _neq_prev(frame: pd.DataFrame) -> np.ndarray:
    """Row differs from the previous row on any column (NaNs are equal)."""
    cur, prev = frame, frame.shift()
    eq = (cur == prev) | (cur.isna() & prev.isna())
    return np.array((~eq.all(axis=1)).to_numpy())


def _ffill_at(vals: np.ndarray, flags: np.ndarray) -> np.ndarray:
    """vals where flags, carried forward (flags[0] must be True)."""
    idx = np.where(flags, np.arange(len(vals)), 0)
    idx = np.maximum.accumulate(idx)
    return vals[idx]


def _bfill_at(vals: np.ndarray, flags: np.ndarray) -> np.ndarray:
    """vals where flags, carried backward (flags[-1] must be True)."""
    n = len(vals)
    idx = np.where(flags, np.arange(n), n - 1)
    idx = np.minimum.accumulate(idx[::-1])[::-1]
    return vals[idx]


def _eval_fn(wc: WindowCall, work: pd.DataFrame, pid: pd.Series,
             pstart: np.ndarray, tie_start: np.ndarray, rn: np.ndarray,
             pos: np.ndarray):
    op = wc.op
    n = len(work)
    pend = np.empty(n, dtype=bool)        # last row of each partition
    pend[:-1] = pstart[1:]
    pend[-1] = True
    tie_end = np.empty(n, dtype=bool)     # last peer of each tie group
    tie_end[:-1] = tie_start[1:]
    tie_end[-1] = True
    psize = _bfill_at(rn, pend)           # partition row count, per row

    if op == "row_number":
        return rn.astype(np.int64)
    if op in ("rank", "dense_rank", "percent_rank", "cume_dist"):
        if op == "dense_rank":
            dr = np.cumsum(tie_start) - _ffill_at(np.cumsum(tie_start),
                                                  pstart) + 1
            return dr.astype(np.int64)
        rank = _ffill_at(rn, tie_start)
        if op == "rank":
            return rank.astype(np.int64)
        if op == "percent_rank":
            denom = np.maximum(psize - 1, 1)
            return np.where(psize > 1, (rank - 1) / denom, 0.0)
        # cume_dist: rows <= last peer / partition size
        peers_end = _bfill_at(rn, tie_end)
        return peers_end / psize
    if op == "ntile":
        if not wc.args:
            raise PlanError("ntile() needs a bucket count")
        k = int(work["__a0"].iloc[0])
        if k <= 0:
            raise PlanError("ntile() bucket count must be positive")
        return ((rn - 1) * k // psize + 1).astype(np.int64)
    if op in ("lag", "lead"):
        ser = work["__a0"]
        off = 1
        if len(wc.args) >= 2:
            off = int(work["__a1"].iloc[0])
        default = None
        if len(wc.args) >= 3:
            default = work["__a2"].iloc[0]
        shift = off if op == "lag" else -off
        shifted = ser.shift(shift)
        # mask rows whose source crossed a partition boundary
        src_pid = pid.shift(shift)
        bad = src_pid.isna() | (src_pid != pid)
        shifted = shifted.where(~bad, default)
        return shifted
    if op in ("first_value", "last_value"):
        ser = work["__a0"]
        vals = ser.to_numpy()
        lo, hi = wc.spec.frame if wc.spec.frame is not None else (
            (None, 0) if wc.spec.order_by else (None, None))
        start = _ffill_at(pos, pstart)
        end = _bfill_at(pos, pend)
        s = start if lo is None else np.maximum(pos + lo, start)
        e = end if hi is None else np.minimum(pos + hi, end)
        if wc.spec.frame is None and wc.spec.order_by:
            # default RANGE frame ends at the last peer, not the row
            e = _bfill_at(pos, tie_end)
        src = s if op == "first_value" else e
        out = pd.Series(vals[np.clip(src, 0, n - 1)], index=work.index)
        return out.mask(s > e)     # empty frame → NULL

    # ---- aggregates over the window frame ----
    if op in ("sum", "avg", "min", "max", "count", "stddev", "variance"):
        return _window_aggregate(wc, work, pid, pstart, tie_end)
    raise UnsupportedError(f"window function {op!r}")


def _window_aggregate(wc: WindowCall, work: pd.DataFrame, pid: pd.Series,
                      pstart: np.ndarray, tie_end: np.ndarray) -> pd.Series:
    """Aggregate over each row's frame, exact at partition edges.

    Every frame shape reduces to per-row [s, e] index bounds inside the
    partition; sum/avg/count/stddev/variance read prefix-sum differences,
    min/max combine a backward and a forward windowed extreme."""
    op = wc.op
    n = len(work)
    count_star = op == "count" and "__a0" not in work
    ser = work["__a0"] if "__a0" in work else pd.Series(1.0,
                                                       index=work.index)
    frame = wc.spec.frame
    ordered = bool(wc.spec.order_by)
    if frame is None:
        lo, hi = (None, 0) if ordered else (None, None)
    else:
        lo, hi = frame

    pos = np.arange(n)
    start = _ffill_at(pos, pstart)
    pend = np.empty(n, dtype=bool)
    pend[:-1] = pstart[1:]
    pend[-1] = True
    end = _bfill_at(pos, pend)

    # frame bounds per row, clamped to the partition
    s = start if lo is None else np.maximum(pos + lo, start)
    e = end if hi is None else np.minimum(pos + hi, end)
    if frame is None and ordered:
        # default RANGE frame ends at the last peer of the row's tie group
        e = _bfill_at(pos, tie_end)
    empty = s > e

    if not count_star and ser.dtype == object and op != "count":
        raise UnsupportedError(f"window {op} over non-numeric values")

    if count_star:
        out = (e - s + 1).astype(np.int64)
        out[empty] = 0
        return pd.Series(out, index=work.index)

    valid = ser.notna().to_numpy()
    if op in ("min", "max"):
        return _window_extreme(op, ser, pid, lo, hi, s, e, empty,
                               frame is None and ordered, work.index)

    x = pd.to_numeric(ser, errors="coerce").to_numpy(dtype=np.float64)
    filled = np.where(valid, x, 0.0)
    # per-partition inclusive prefix sums via global cumsum minus the
    # value accumulated before each partition start
    def prefix(vals):
        g = np.cumsum(vals)
        base = g[start] - vals[start]
        lo_excl = np.where(s > start, g[np.maximum(s - 1, 0)], base)
        return g[e] - lo_excl

    cnt = prefix(valid.astype(np.float64))
    if op == "count":
        out = np.where(empty, 0, cnt).astype(np.int64)
        return pd.Series(out, index=work.index)
    total = prefix(filled)
    if op == "sum":
        out = np.where(empty | (cnt == 0), np.nan, total)
        return pd.Series(out, index=work.index)
    if op == "avg":
        out = np.where(empty | (cnt == 0), np.nan,
                       total / np.maximum(cnt, 1))
        return pd.Series(out, index=work.index)
    if op in ("stddev", "variance"):
        sq = prefix(filled * filled)
        mean = total / np.maximum(cnt, 1)
        var = (sq - cnt * mean * mean) / np.maximum(cnt - 1, 1)
        out = np.where(empty | (cnt < 2), np.nan, var)
        if op == "stddev":
            out = np.sqrt(np.maximum(out, 0.0))
            out = np.where(empty | (cnt < 2), np.nan, out)
        return pd.Series(out, index=work.index)
    raise UnsupportedError(f"window aggregate {op!r}")


def _window_extreme(op: str, ser: pd.Series, pid: pd.Series, lo, hi,
                    s: np.ndarray, e: np.ndarray, empty: np.ndarray,
                    range_default: bool, index) -> pd.Series:
    """min/max over per-row frames [s, e] (already partition-clamped)."""
    n = len(ser)
    x = pd.to_numeric(ser, errors="coerce")
    if lo is None:
        # frame starts at the partition start: running extreme indexed at e
        cum = (x.groupby(pid, sort=False).cummin() if op == "min"
               else x.groupby(pid, sort=False).cummax())
        cum = cum.groupby(pid, sort=False).ffill().to_numpy()
        out = np.where(empty, np.nan, cum[np.maximum(e, 0)])
        return pd.Series(out, index=index)
    if lo > 0 or (hi is not None and hi < 0):
        raise UnsupportedError(
            "min/max over a frame that excludes the current row")
    # backward part [s, pos]: rolling extreme of width -lo+1 per partition
    roll = x.groupby(pid, sort=False).rolling(-lo + 1, min_periods=1)
    back = (roll.min() if op == "min" else roll.max()) \
        .reset_index(level=0, drop=True).reindex(ser.index).to_numpy()
    if hi == 0:
        out = np.where(empty, np.nan, back)
        return pd.Series(out, index=index)
    # forward part [pos, e]: extreme over the reversed series
    xr = x.iloc[::-1]
    pr = pid.iloc[::-1]
    if hi is None and not range_default:
        fwd = (xr.groupby(pr, sort=False).cummin() if op == "min"
               else xr.groupby(pr, sort=False).cummax())
        fwd = fwd.groupby(pr, sort=False).ffill()
    else:
        width = int(hi) + 1 if hi is not None else None
        if width is None:
            # range_default with hi None cannot happen (e set to tie end)
            raise UnsupportedError("unsupported window frame")
        rollr = xr.groupby(pr, sort=False).rolling(width, min_periods=1)
        fwd = (rollr.min() if op == "min" else rollr.max()) \
            .reset_index(level=0, drop=True)
    fwd = fwd.iloc[::-1].reindex(ser.index).to_numpy()
    comb = np.fmin(back, fwd) if op == "min" else np.fmax(back, fwd)
    out = np.where(empty, np.nan, comb)
    return pd.Series(out, index=index)
