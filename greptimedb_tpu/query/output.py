"""Query output container.

Reference behavior: src/common/query — `Output::{AffectedRows,
RecordBatches, Stream}`. Streams collapse to eager batch lists here; the
protocol servers chunk them on the way out.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..datatypes.record_batch import RecordBatch, pretty_print
from ..datatypes.schema import Schema


@dataclass
class Output:
    affected_rows: Optional[int] = None
    batches: Optional[List[RecordBatch]] = None
    schema: Optional[Schema] = None

    @staticmethod
    def rows(n: int) -> "Output":
        return Output(affected_rows=n)

    @staticmethod
    def record_batches(batches: List[RecordBatch],
                       schema: Optional[Schema] = None) -> "Output":
        if schema is None and batches:
            schema = batches[0].schema
        return Output(batches=batches, schema=schema)

    @property
    def is_batches(self) -> bool:
        return self.batches is not None

    @property
    def num_rows(self) -> int:
        if self.batches is not None:
            return sum(b.num_rows for b in self.batches)
        return self.affected_rows or 0

    def pretty(self) -> str:
        if self.batches is not None:
            return pretty_print(self.batches)
        return f"Affected Rows: {self.affected_rows}"
