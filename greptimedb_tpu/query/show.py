"""SHOW / DESCRIBE statement implementations.

Reference behavior: src/query/src/sql.rs:441 + sql/show.rs:337 — SHOW
DATABASES/TABLES with LIKE/WHERE, SHOW CREATE TABLE, DESCRIBE with the
Column/Type/Null/Key/Default/Semantic Type layout.
"""

from __future__ import annotations

import re
from typing import List

import pandas as pd

from ..datatypes import data_type as dt
from ..datatypes.record_batch import RecordBatch
from ..datatypes.schema import ColumnSchema, Schema
from ..errors import TableNotFoundError
from ..session import QueryContext
from .expr import Evaluator, like_to_regex
from .output import Output

_SQL_TYPE_NAMES = {
    "Boolean": "Boolean", "Int8": "Int8", "Int16": "Int16", "Int32": "Int32",
    "Int64": "Int64", "UInt8": "UInt8", "UInt16": "UInt16",
    "UInt32": "UInt32", "UInt64": "UInt64", "Float32": "Float32",
    "Float64": "Float64", "String": "String", "Binary": "Binary",
    "Date": "Date", "TimestampSecond": "TimestampSecond",
    "TimestampMillisecond": "TimestampMillisecond",
    "TimestampMicrosecond": "TimestampMicrosecond",
    "TimestampNanosecond": "TimestampNanosecond",
}


def _one_col(name: str, values: List[str]) -> Output:
    schema = Schema([ColumnSchema(name, dt.STRING)])
    return Output.record_batches(
        [RecordBatch.from_pydict(schema, {name: values})], schema)


def _filter_names(names: List[str], like, where, col_name: str) -> List[str]:
    if like:
        rx = re.compile(like_to_regex(like))
        names = [n for n in names if rx.match(n)]
    if where is not None:
        df = pd.DataFrame({col_name: names})
        mask = Evaluator(df).eval(where)
        if isinstance(mask, pd.Series):
            names = [n for n, ok in zip(names, mask.fillna(False)) if ok]
        elif not mask:
            names = []
    return names


def show_databases(engine, stmt, ctx: QueryContext) -> Output:
    names = engine.catalog.schema_names(ctx.current_catalog)
    names = _filter_names(names, stmt.like, stmt.where, "Database")
    return _one_col("Databases", names)


def show_tables(engine, stmt, ctx: QueryContext) -> Output:
    schema_name = stmt.database or ctx.current_schema
    names = engine.catalog.table_names(ctx.current_catalog, schema_name)
    names = _filter_names(names, stmt.like, stmt.where, "Table")
    return _one_col("Tables", names)


def describe_table(engine, stmt, ctx: QueryContext) -> Output:
    table = engine.resolve_table(stmt.table, ctx)
    pks = set(table.info.meta.primary_key_names)
    cols, types, nulls, defaults, keys, semantics = [], [], [], [], [], []
    for cs in table.schema.column_schemas:
        cols.append(cs.name)
        types.append(_SQL_TYPE_NAMES.get(cs.dtype.name, cs.dtype.name))
        nulls.append("YES" if cs.nullable else "NO")
        if cs.default is None:
            defaults.append("")
        elif cs.default.function:
            defaults.append(f"{cs.default.function}()")
        else:
            defaults.append(str(cs.default.value))
        if cs.is_time_index:
            keys.append("TIME INDEX")
            semantics.append("TIMESTAMP")
        elif cs.name in pks or cs.is_tag:
            keys.append("PRI")
            semantics.append("TAG")
        else:
            keys.append("")
            semantics.append("FIELD")
    schema = Schema([ColumnSchema(n, dt.STRING) for n in
                     ("Column", "Type", "Null", "Key", "Default",
                      "Semantic Type")])
    rb = RecordBatch.from_pydict(schema, {
        "Column": cols, "Type": types, "Null": nulls, "Key": keys,
        "Default": defaults, "Semantic Type": semantics})
    return Output.record_batches([rb], schema)


def show_create_table(engine, stmt, ctx: QueryContext) -> Output:
    table = engine.resolve_table(stmt.table, ctx)
    # elastic regions refine partition rules AFTER create (balancer
    # split): a distributed table re-pulls its rule from meta so the
    # rendered PARTITION clause matches the live layout — the data path
    # refreshes on StaleRouteError, but SHOW CREATE never scans
    refresh = getattr(table, "refresh_route", None)
    if callable(refresh):
        refresh()
    info = table.info
    lines = [f"CREATE TABLE IF NOT EXISTS {info.name} ("]
    defs = []
    for cs in table.schema.column_schemas:
        d = f"  {cs.name} {_SQL_TYPE_NAMES.get(cs.dtype.name, cs.dtype.name)}"
        if not cs.nullable:
            d += " NOT NULL"
        if cs.default is not None:
            if cs.default.function:
                d += f" DEFAULT {cs.default.function}()"
            else:
                d += f" DEFAULT {cs.default.value!r}"
        defs.append(d)
    tc = table.schema.timestamp_column
    if tc is not None:
        defs.append(f"  TIME INDEX ({tc.name})")
    pks = info.meta.primary_key_names
    if pks:
        defs.append(f"  PRIMARY KEY ({', '.join(pks)})")
    lines.append(",\n".join(defs))
    lines.append(")")
    rule = getattr(table, "partition_rule", None)
    from ..partition.rule import HashPartitionRule
    if isinstance(rule, HashPartitionRule):
        cols = ", ".join(rule.partition_columns())
        lines.append(f"PARTITION BY HASH ({cols}) "
                     f"PARTITIONS {len(rule.regions)}")
    elif rule is not None and getattr(rule, "bounds", None):
        # render the partition clause (reference SHOW CREATE TABLE
        # includes it, src/sql/src/statements/create.rs)
        cols = ", ".join(rule.partition_columns())

        def bound_text(b):
            vals = b if isinstance(b, tuple) else (b,)
            parts = []
            for v in vals:
                if v is None or (isinstance(v, str) and
                                 v.upper() == "MAXVALUE"):
                    parts.append("MAXVALUE")
                elif isinstance(v, str):
                    parts.append("'" + v.replace("'", "''") + "'")
                else:
                    parts.append(str(v))
            return ", ".join(parts)
        entries = ",\n".join(
            f"  PARTITION p{i} VALUES LESS THAN ({bound_text(b)})"
            for i, b in enumerate(rule.bounds))
        lines.append(f"PARTITION BY RANGE COLUMNS ({cols}) (\n{entries}\n)")
    lines.append(f"ENGINE={info.meta.engine}")
    if info.meta.options:
        opts = ", ".join(f"{k}={v!r}" for k, v in info.meta.options.items())
        lines.append(f"WITH({opts})")
    ddl = "\n".join(lines)
    schema = Schema([ColumnSchema("Table", dt.STRING),
                     ColumnSchema("Create Table", dt.STRING)])
    rb = RecordBatch.from_pydict(schema, {"Table": [info.name],
                                          "Create Table": [ddl]})
    return Output.record_batches([rb], schema)


def show_processlist(engine, stmt, ctx: QueryContext) -> Output:
    """SHOW [FULL] PROCESSLIST over the process-wide active-statement
    registry (common/process_list.py) — the same rows
    information_schema.processes serves. Non-FULL truncates the
    statement text at 100 chars, the MySQL `Info` convention."""
    from ..common import process_list
    rows = process_list.REGISTRY.rows()
    schema = Schema([
        ColumnSchema("Id", dt.INT64),
        ColumnSchema("Node", dt.STRING),
        ColumnSchema("Db", dt.STRING),
        ColumnSchema("Protocol", dt.STRING),
        ColumnSchema("State", dt.STRING),
        ColumnSchema("Elapsed_ms", dt.INT64),
        ColumnSchema("Rows_scanned", dt.INT64),
        ColumnSchema("Bytes_read", dt.INT64),
        ColumnSchema("Trace_id", dt.STRING),
        ColumnSchema("Info", dt.STRING),
    ])
    full = bool(getattr(stmt, "full", False))
    rb = RecordBatch.from_pydict(schema, {
        "Id": [r["id"] for r in rows],
        "Node": [r["node"] for r in rows],
        "Db": [r["schema"] for r in rows],
        "Protocol": [r["protocol"] for r in rows],
        "State": [r["state"] for r in rows],
        "Elapsed_ms": [int(r["elapsed_ms"]) for r in rows],
        "Rows_scanned": [r["rows_scanned"] for r in rows],
        "Bytes_read": [r["bytes_read"] for r in rows],
        "Trace_id": [r["trace_id"] for r in rows],
        "Info": [r["query"] if full else r["query"][:100]
                 for r in rows],
    })
    return Output.record_batches([rb], schema)


def show_variable(engine, stmt, ctx: QueryContext) -> Output:
    """MySQL-compat surface: SHOW VARIABLES / FULL TABLES etc. return an
    empty-ish answer rather than erroring (reference: mysql federated)."""
    name = (stmt.name or "").strip().lower()
    if name.startswith("variables"):
        schema = Schema([ColumnSchema("Variable_name", dt.STRING),
                         ColumnSchema("Value", dt.STRING)])
        rb = RecordBatch.from_pydict(
            schema, {"Variable_name": ["system_time_zone"],
                     "Value": [ctx.time_zone]})
        return Output.record_batches([rb], schema)
    return _one_col("Value", [])
