"""Query analysis: classify a parsed SELECT and extract aggregate structure.

The analog of the reference's logical planning (sqlparser AST → DataFusion
LogicalPlan via src/query/src/planner.rs): here the AST is analyzed into an
`Analysis` that either the TPU executor (tpu_exec.py) or the CPU fallback
(engine.py) runs. Aggregate calls inside projections/HAVING/ORDER BY are
rewritten to slot references so post-aggregation expressions evaluate over
the grouped frame.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..errors import PlanError, UnsupportedError
from ..sql.ast import (
    Between, BinaryOp, Case, Cast, Column, Expr, FunctionCall, InList,
    IsNull, Literal, Query, SelectItem, Star, Subquery, UnaryOp, WindowSpec,
)
from .expr import expr_name
from .functions import AGGREGATE_FUNCTIONS

AGG_NAMES = set(AGGREGATE_FUNCTIONS) | {"first", "last", "first_value",
                                        "last_value"}
_AGG_CANON = {"mean": "avg", "first_value": "first", "last_value": "last"}

#: ranking / navigation functions valid only with OVER
WINDOW_ONLY_NAMES = {"row_number", "rank", "dense_rank", "percent_rank",
                     "cume_dist", "ntile", "lag", "lead", "first_value",
                     "last_value"}
#: aggregates that may also run as window functions
WINDOW_AGG_NAMES = {"sum", "avg", "mean", "min", "max", "count", "stddev",
                    "variance"}


@dataclass
class AggCall:
    op: str                       # canonical op name
    arg: Optional[Expr]           # None for count(*)
    distinct: bool = False
    params: Tuple = ()            # literal extras (percentile p, ...)
    slot: str = ""                # column name in the grouped frame

    @property
    def is_count_star(self) -> bool:
        return self.op == "count" and self.arg is None


@dataclass
class WindowCall:
    """One windowed function: computed over the (post-agg) result frame and
    exposed to projections as `slot` (mirrors DataFusion's WindowExpr)."""
    op: str                       # lowercase function name (mean→avg)
    args: List[Expr] = field(default_factory=list)
    spec: WindowSpec = field(default_factory=WindowSpec)
    slot: str = ""


@dataclass
class Analysis:
    query: Query
    projections: List[SelectItem] = field(default_factory=list)  # rewritten
    group_exprs: List[Expr] = field(default_factory=list)
    agg_calls: List[AggCall] = field(default_factory=list)
    window_calls: List[WindowCall] = field(default_factory=list)
    having: Optional[Expr] = None                                # rewritten
    order_by: List[Tuple[Expr, bool]] = field(default_factory=list)
    column_refs: List[str] = field(default_factory=list)

    @property
    def is_aggregate(self) -> bool:
        return bool(self.agg_calls) or bool(self.group_exprs)


def _walk_columns(e: Expr, out: set) -> None:
    if isinstance(e, Column):
        out.add(e.name)
    for attr in ("left", "right", "operand", "expr", "low", "high"):
        child = getattr(e, attr, None)
        if isinstance(child, Expr):
            _walk_columns(child, out)
    if isinstance(e, FunctionCall):
        for a in e.args:
            _walk_columns(a, out)
        if e.over is not None:
            for p in e.over.partition_by:
                _walk_columns(p, out)
            for oe, _ in e.over.order_by:
                _walk_columns(oe, out)
    if isinstance(e, InList):
        for a in e.items:
            _walk_columns(a, out)
    if isinstance(e, Case):
        if e.operand:
            _walk_columns(e.operand, out)
        for c, v in e.whens:
            _walk_columns(c, out)
            _walk_columns(v, out)
        if e.else_:
            _walk_columns(e.else_, out)


def map_expr_children(e: Expr, f) -> Expr:
    """Rebuild e with f applied to each child expression."""
    if isinstance(e, BinaryOp):
        return BinaryOp(e.op, f(e.left), f(e.right))
    if isinstance(e, UnaryOp):
        return UnaryOp(e.op, f(e.operand))
    if isinstance(e, Cast):
        return Cast(f(e.expr), e.type_name)
    if isinstance(e, Between):
        return Between(f(e.expr), f(e.low), f(e.high), e.negated)
    if isinstance(e, InList):
        return InList(f(e.expr), [f(i) for i in e.items], e.negated)
    if isinstance(e, IsNull):
        return IsNull(f(e.expr), e.negated)
    if isinstance(e, Case):
        return Case(
            f(e.operand) if e.operand else None,
            [(f(c), f(v)) for c, v in e.whens],
            f(e.else_) if e.else_ else None)
    if isinstance(e, FunctionCall):
        return FunctionCall(e.name, [f(a) for a in e.args], e.distinct,
                            e.over)
    return e


class _WindowRewriter:
    """Replaces windowed FunctionCalls with slot Columns, collecting calls."""

    def __init__(self):
        self.calls: List[WindowCall] = []
        self._seen: Dict[str, str] = {}

    def rewrite(self, e: Expr) -> Expr:
        if isinstance(e, FunctionCall) and e.over is not None:
            key = expr_name(e)
            if key in self._seen:
                return Column(self._seen[key])
            op = "avg" if e.name == "mean" else e.name
            if op not in WINDOW_ONLY_NAMES and op not in WINDOW_AGG_NAMES:
                raise UnsupportedError(f"window function {op!r}")
            if e.distinct:
                raise UnsupportedError("DISTINCT in window functions")
            for a in e.args:
                if _contains_window(a):
                    raise PlanError("nested window functions")
            args = list(e.args)
            if args and isinstance(args[0], Star):
                if op != "count":
                    raise PlanError(f"{op}(*) is not valid")
                args = []        # count(*) counts frame rows
            slot = f"__win{len(self.calls)}"
            self.calls.append(WindowCall(op=op, args=args,
                                         spec=e.over, slot=slot))
            self._seen[key] = slot
            return Column(slot)
        return map_expr_children(e, self.rewrite)


def _contains_window(e: Expr) -> bool:
    if isinstance(e, FunctionCall) and e.over is not None:
        return True
    if isinstance(e, FunctionCall):
        return any(_contains_window(a) for a in e.args)
    for attr in ("left", "right", "operand", "expr", "low", "high"):
        child = getattr(e, attr, None)
        if isinstance(child, Expr) and _contains_window(child):
            return True
    if isinstance(e, InList):
        return any(_contains_window(i) for i in e.items)
    if isinstance(e, Case):
        parts = ([e.operand] if e.operand else []) + \
            [x for cv in e.whens for x in cv] + \
            ([e.else_] if e.else_ else [])
        return any(_contains_window(p) for p in parts)
    return False


class _AggRewriter:
    """Replaces aggregate FunctionCalls with slot Columns, collecting calls."""

    def __init__(self):
        self.calls: List[AggCall] = []
        self._seen: Dict[str, str] = {}

    def rewrite(self, e: Expr) -> Expr:
        if isinstance(e, FunctionCall) and e.name in AGG_NAMES \
                and e.over is None:
            key = expr_name(e)
            if key in self._seen:
                return Column(self._seen[key])
            op = _AGG_CANON.get(e.name, e.name)
            arg: Optional[Expr] = None
            params: Tuple = ()
            if e.args and isinstance(e.args[0], Star):
                if op != "count":
                    raise PlanError(f"{op}(*) is not valid")
            elif e.args:
                arg = self.rewrite_inner_check(e.args[0])
                params = tuple(a.value for a in e.args[1:]
                               if isinstance(a, Literal))
            elif op != "count":
                raise PlanError(f"{op}() needs an argument")
            slot = f"__agg{len(self.calls)}"
            call = AggCall(op=op, arg=arg, distinct=e.distinct,
                           params=params, slot=slot)
            self.calls.append(call)
            self._seen[key] = slot
            return Column(slot)
        return map_expr_children(e, self.rewrite)

    def rewrite_inner_check(self, e: Expr) -> Expr:
        if isinstance(e, FunctionCall) and e.name in AGG_NAMES \
                and e.over is None:
            raise PlanError("nested aggregate functions are not allowed")
        return e


def contains_aggregate(e: Expr) -> bool:
    if isinstance(e, FunctionCall) and e.name in AGG_NAMES \
            and e.over is None:
        return True
    if isinstance(e, FunctionCall):
        return any(contains_aggregate(a) for a in e.args)
    for attr in ("left", "right", "operand", "expr", "low", "high"):
        child = getattr(e, attr, None)
        if isinstance(child, Expr) and contains_aggregate(child):
            return True
    if isinstance(e, InList):
        return any(contains_aggregate(i) for i in e.items)
    if isinstance(e, Case):
        parts = ([e.operand] if e.operand else []) + \
            [x for cv in e.whens for x in cv] + \
            ([e.else_] if e.else_ else [])
        return any(contains_aggregate(p) for p in parts)
    return False


def analyze(query: Query) -> Analysis:
    """Resolve GROUP BY / ORDER BY ordinals+aliases and extract aggregates."""
    a = Analysis(query=query)
    alias_map: Dict[str, Expr] = {}
    for item in query.projections:
        if item.alias:
            alias_map[item.alias.lower()] = item.expr

    def resolve_ref(e: Expr) -> Expr:
        if isinstance(e, Literal) and isinstance(e.value, int):
            idx = e.value - 1
            if not (0 <= idx < len(query.projections)):
                raise PlanError(f"ordinal {e.value} out of range")
            return query.projections[idx].expr
        if isinstance(e, Column) and e.table is None and \
                e.name.lower() in alias_map:
            return alias_map[e.name.lower()]
        return e

    a.group_exprs = [resolve_ref(g) for g in query.group_by]
    for g in a.group_exprs:
        if contains_aggregate(g):
            raise PlanError("aggregate functions are not allowed in GROUP BY")

    for e in ([query.where] if query.where is not None else []) + \
            list(query.group_by) + \
            ([query.having] if query.having is not None else []):
        if _contains_window(e):
            raise PlanError("window functions are only allowed in the "
                            "SELECT list and ORDER BY")

    rw = _AggRewriter()
    wrw = _WindowRewriter()
    group_names = {expr_name(g) for g in a.group_exprs}

    def rewrite_top(e: Expr) -> Expr:
        # a projection identical to a group expr passes through
        if expr_name(e) in group_names:
            return Column(_group_slot(expr_name(e)))
        return rw.rewrite(wrw.rewrite(e))

    a.projections = []
    for item in query.projections:
        if isinstance(item.expr, Star):
            a.projections.append(item)
            continue
        # keep the pre-rewrite display name: `avg(cpu)` not `__agg0`
        alias = item.alias or expr_name(item.expr)
        a.projections.append(SelectItem(rewrite_top(item.expr), alias))
    if query.having is not None:
        a.having = rewrite_top(query.having)
    a.order_by = []
    for e, asc in query.order_by:
        e = resolve_ref(e)
        a.order_by.append((rewrite_top(e)
                           if (rw.calls or a.group_exprs or wrw.calls
                               or _contains_window(e))
                           else e, asc))
    a.agg_calls = rw.calls
    a.window_calls = wrw.calls
    # window args / PARTITION BY / ORDER BY may reference aggregates in a
    # grouped query (e.g. rank() OVER (ORDER BY sum(v) DESC)) — rewrite
    # them to agg slots so they evaluate over the grouped frame
    for wc in a.window_calls:
        wc.args = [rewrite_top(x) for x in wc.args]
        wc.spec = WindowSpec(
            [rewrite_top(x) for x in wc.spec.partition_by],
            [(rewrite_top(x), asc) for x, asc in wc.spec.order_by],
            wc.spec.frame)

    refs: set = set()
    for item in query.projections:
        if not isinstance(item.expr, Star):
            _walk_columns(item.expr, refs)
    for g in query.group_by:
        _walk_columns(g, refs)
    if query.where is not None:
        _walk_columns(query.where, refs)
    if query.having is not None:
        _walk_columns(query.having, refs)
    for e, _ in query.order_by:
        _walk_columns(e, refs)
    a.column_refs = sorted(refs)

    if a.is_aggregate:
        star = [p for p in a.projections if isinstance(p.expr, Star)]
        if star:
            raise PlanError("'*' projection is not valid with GROUP BY")
    return a


def _group_slot(name: str) -> str:
    return f"__key__{name}"


def convert_time_literals(e: Optional[Expr], schema) -> Optional[Expr]:
    """String/second-precision literals compared against timestamp columns
    are coerced to the column's native unit (reference: TypeConversionRule
    analyzer, src/query/src/optimizer.rs:33 — DataFusion literals become
    timestamps before planning)."""
    if e is None or schema is None:
        return e

    def ts_unit(col: Expr):
        if isinstance(col, Column) and schema.contains(col.name):
            dtype = schema.column_schema(col.name).dtype
            if dtype.is_timestamp:
                return dtype.time_unit
        return None

    def coerce(lit: Expr, unit):
        if isinstance(lit, Literal) and isinstance(lit.value, str):
            from ..common.time import Timestamp
            try:
                return Literal(Timestamp.from_str(lit.value, unit).value)
            except (ValueError, TypeError):
                return lit
        return lit

    def walk(node: Expr) -> Expr:
        if isinstance(node, BinaryOp):
            if node.op in ("=", "!=", "<>", "<", "<=", ">", ">="):
                unit = ts_unit(node.left)
                if unit is not None:
                    return dataclasses.replace(
                        node, right=coerce(node.right, unit))
                unit = ts_unit(node.right)
                if unit is not None:
                    return dataclasses.replace(
                        node, left=coerce(node.left, unit))
                return node
            return dataclasses.replace(node, left=walk(node.left),
                                       right=walk(node.right))
        if isinstance(node, UnaryOp):
            return dataclasses.replace(node, operand=walk(node.operand))
        if isinstance(node, Between):
            unit = ts_unit(node.expr)
            if unit is not None:
                return dataclasses.replace(node, low=coerce(node.low, unit),
                                           high=coerce(node.high, unit))
            return node
        if isinstance(node, InList):
            unit = ts_unit(node.expr)
            if unit is not None:
                return dataclasses.replace(
                    node, items=[coerce(i, unit) for i in node.items])
            return node
        return node

    return walk(e)
