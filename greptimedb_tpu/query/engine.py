"""QueryEngine: statement dispatch, CPU fallback executor, TPU fast path.

Reference behavior: src/query/src/datafusion.rs — the engine optimizes and
executes logical plans, streaming record batches. Here `execute` dispatches
on statement type; SELECTs try the TPU aggregate path first
(tpu_exec.try_execute) and otherwise run the pandas columnar fallback.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np
import pandas as pd

from ..catalog import CatalogManager
from ..common import exec_stats
from ..common.time import TimeUnit
from ..datatypes import data_type as dt
from ..datatypes.data_type import parse_type_name
from ..datatypes.record_batch import RecordBatch
from ..datatypes.schema import ColumnSchema, Schema, SemanticType
from ..errors import (
    ColumnNotFoundError, PlanError, TableNotFoundError, UnsupportedError)
from ..session import QueryContext
from ..sql.ast import (
    Column, DescribeTable, Explain, Expr, FunctionCall, InList, Literal,
    Query, SetQuery, ShowCreateTable, ShowDatabases, ShowProcessList,
    ShowTables, ShowVariable, Star, Statement, TableRef, WindowSpec)
from ..table.table import Table
from .expr import Evaluator, expr_name, like_to_regex
from .functions import AGGREGATE_FUNCTIONS
from .output import Output
from .planner import (Analysis, analyze, convert_time_literals,
                      _group_slot)
from . import show as show_impl
from . import tpu_exec


class QueryEngine:
    """Executes read statements against the catalog."""

    def __init__(self, catalog: CatalogManager):
        self.catalog = catalog
        #: ExecStats of the most recent top-level query this thread ran —
        #: the slow-query log and /status read it (diagnostic only; a
        #: concurrent server sees the latest finished query's stats)
        self.last_exec_stats: Optional[exec_stats.ExecStats] = None
        #: set by the hosting instance when flows exist; enables the
        #: transparent rollup rewrite (flow/rewrite.py)
        self.flow_manager = None

    # ---- dispatch ----
    def execute(self, stmt: Statement, ctx: Optional[QueryContext] = None
                ) -> Output:
        ctx = ctx or QueryContext()
        if isinstance(stmt, Query):
            return self.execute_query(stmt, ctx)
        if isinstance(stmt, SetQuery):
            return self.execute_set_query(stmt, ctx)
        if isinstance(stmt, ShowDatabases):
            return show_impl.show_databases(self, stmt, ctx)
        if isinstance(stmt, ShowTables):
            return show_impl.show_tables(self, stmt, ctx)
        if isinstance(stmt, ShowCreateTable):
            return show_impl.show_create_table(self, stmt, ctx)
        if isinstance(stmt, ShowVariable):
            return show_impl.show_variable(self, stmt, ctx)
        if isinstance(stmt, ShowProcessList):
            return show_impl.show_processlist(self, stmt, ctx)
        if isinstance(stmt, DescribeTable):
            return show_impl.describe_table(self, stmt, ctx)
        if isinstance(stmt, Explain):
            return self.explain(stmt, ctx)
        raise UnsupportedError(
            f"query engine cannot execute {type(stmt).__name__}")

    def resolve_table(self, ref, ctx: QueryContext) -> Table:
        if isinstance(ref, TableRef):
            ref = ref.name
        catalog, schema, name = ctx.resolve(ref)
        if schema.lower() == "information_schema":
            from ..catalog.information_schema import (
                information_schema_table)
            virtual = information_schema_table(self.catalog, catalog, name)
            if virtual is not None:
                return virtual
        table = self.catalog.table(catalog, schema, name)
        if table is None:
            raise TableNotFoundError(
                f"table {catalog}.{schema}.{name} not found")
        return table

    # ---- EXPLAIN ----
    def explain(self, stmt: Explain, ctx: QueryContext) -> Output:
        inner = stmt.statement
        lines: List[str] = []
        if isinstance(inner, Query):
            a = analyze(inner)
            table = None
            if inner.from_ is not None and inner.from_.name is not None:
                table = self.resolve_table(inner.from_, ctx)
            # rollup rewrite first (no fold on plain EXPLAIN): the plan
            # below then describes the statement actually executed —
            # against the flow sink — with the rewrite as the dispatch.
            # `inner` stays the original so EXPLAIN ANALYZE re-enters the
            # execution path (which rewrites again, with a refresh fold).
            pq, rollup_note = inner, None
            if table is not None:
                # same literal→timestamp coercion the execution path
                # applies, so the explained dispatch (incl. the rewrite's
                # aligned-time-range check) matches the executed one
                inner.where = convert_time_literals(inner.where,
                                                    table.schema)
                rw = self._maybe_rollup_rewrite(table, a, inner, ctx,
                                                refresh=False)
                if rw is not None:
                    table, pq, a, rollup_note = rw
            plan = tpu_exec.plan_for(table, a, pq) if table else None
            if plan is not None:
                # pin the dispatch decision (sqlness explain goldens):
                # pushdown / cpu-small-scan / streamed-cold / resident.
                # Uses the STATIC dispatch floor, not the latency-adaptive
                # one (_dispatch_min_rows), so the plan text is
                # deterministic across processes and runs.
                est = tpu_exec._estimated_table_rows(table)
                if hasattr(table, "execute_tpu_plan"):
                    lines.append("TpuAggregateExec: " + plan.describe())
                    lines.append(
                        "  Dispatch: " +
                        tpu_exec.dispatch_decision_for_pushdown(table,
                                                                plan))
                elif est is not None and \
                        est < tpu_exec.TPU_DISPATCH_MIN_ROWS:
                    lines.append("CpuAggregateExec: " + plan.describe())
                    lines.append(
                        f"  Dispatch: cpu-small-scan (est_rows={est} < "
                        f"dispatch_floor={tpu_exec.TPU_DISPATCH_MIN_ROWS})")
                else:
                    # mirror execution exactly: the decision string is
                    # built by the same helper region_moment_frames
                    # records into ExecStats (per-REGION decision, on
                    # rows OR decoded-bytes vs the scan-cache budget)
                    lines.append("TpuAggregateExec: " + plan.describe())
                    lines.append("  Dispatch: " +
                                 tpu_exec.local_dispatch_decision(
                                     table, plan=plan))
            elif a.is_aggregate:
                lines.append("CpuAggregateExec: groups=" + ", ".join(
                    expr_name(g) for g in a.group_exprs))
            else:
                lines.append("CpuProjectionExec")
            if rollup_note is not None:
                # the rewrite is the outermost dispatch decision; the
                # underlying device/CPU decision for the sink follows
                lines.insert(1, f"  Dispatch: rollup-rewrite "
                                f"({rollup_note})")
            if pq.where is not None:
                lines.append("  Filter: " + expr_name(pq.where))
            if table is not None:
                lines.append(f"  TableScan: {table.name}")
        else:
            lines.append(type(inner).__name__)
        if stmt.analyze:
            return self._explain_analyze(inner, lines, ctx)
        schema = Schema([ColumnSchema("plan_type", dt.STRING),
                         ColumnSchema("plan", dt.STRING)])
        rb = RecordBatch.from_pydict(schema, {
            "plan_type": ["logical_plan"], "plan": ["\n".join(lines)]})
        return Output.record_batches([rb])

    def _explain_analyze(self, inner, plan_lines: List[str],
                         ctx: QueryContext) -> Output:
        """EXPLAIN ANALYZE: actually execute the statement under an
        ExecStats collector and render the per-stage breakdown — stage,
        rows, files, elapsed ms, and the path facts (dispatch decision,
        lean/dedup-skip vs merged slices, cache hit) under the same
        stage names the storage profilers use, so this table, the
        tracing spans and Region.last_scan_profile agree (reference:
        DataFusion's EXPLAIN ANALYZE over operator metrics)."""
        stats = exec_stats.ExecStats()
        out_rows = 0
        with exec_stats.collect(stats):
            if isinstance(inner, Query):
                out = self._execute_query_inner(inner, ctx)
                out_rows = out.num_rows or 0
        self.last_exec_stats = stats
        cols = stats.rows_table()
        # lead with the plan so the dispatch line stays next to the plan
        # shape it annotates
        cols["stage"].insert(0, "plan")
        cols["rows"].insert(0, out_rows)
        cols["files"].insert(0, 0)
        cols["elapsed_ms"].insert(0, 0.0)
        cols["detail"].insert(0, "\n".join(plan_lines))
        schema = Schema([ColumnSchema("stage", dt.STRING),
                         ColumnSchema("rows", dt.INT64),
                         ColumnSchema("files", dt.INT64),
                         ColumnSchema("elapsed_ms", dt.FLOAT64),
                         ColumnSchema("detail", dt.STRING)])
        rb = RecordBatch.from_pydict(schema, cols)
        return Output.record_batches([rb], schema)

    # ---- SELECT ----
    def execute_query(self, query: Query, ctx: QueryContext) -> Output:
        """Top-level entry installs an ExecStats collector (nested calls —
        subqueries, UNION arms, join sides — record into the active one),
        so every statement leaves a per-stage breakdown behind for the
        slow-query log and EXPLAIN ANALYZE."""
        if exec_stats.current() is not None:
            return self._execute_query_inner(query, ctx)
        with exec_stats.collect() as st:
            out = self._execute_query_inner(query, ctx)
        self.last_exec_stats = st
        return out

    def _execute_query_inner(self, query: Query, ctx: QueryContext
                             ) -> Output:
        from ..common import process_list
        process_list.check_cancelled()     # KILL between sub-statements
        if isinstance(query, SetQuery):     # e.g. a UNION-bodied CTE /
            return self.execute_set_query(query, ctx)  # derived table
        self._rewrite_query_subqueries(query, ctx)
        a = analyze(query)
        if query.joins:
            return self._execute_join(query, a, ctx)

        table: Optional[Table] = None
        if query.from_ is not None:
            if query.from_.subquery is not None:
                inner = self.execute_query(query.from_.subquery, ctx)
                df = _batches_to_df(inner.batches)
                return self._run_on_frame(df, a, query, None)
            table = self.resolve_table(query.from_, ctx)

        if table is None:
            df = pd.DataFrame(index=[0])
            return self._run_on_frame(df, a, query, None)

        # literal→timestamp coercion needs the table schema, so it runs
        # post-resolution (reference: TypeConversionRule, optimizer.rs:33)
        query.where = convert_time_literals(query.where, table.schema)

        # transparent rollup rewrite: a compatible GROUP BY date_bin is
        # re-targeted at a flow's rollup sink (after an incremental
        # refresh fold, so answers equal the raw scan); the rewritten
        # statement then takes the normal dispatch chain below
        rw = self._maybe_rollup_rewrite(table, a, query, ctx, refresh=True)
        if rw is not None:
            table, query, a, _ = rw

        # TPU fast path
        result = tpu_exec.try_execute(table, a, query)
        if result is not None:
            return self._finish_aggregate_frame(result, a, query, table)

        # CPU fallback: the per-version cached frame when the table is
        # region-backed (repeat queries skip scan+convert entirely),
        # else scan the needed columns
        exec_stats.set_dispatch("cpu-fallback")
        cached = True
        with exec_stats.stage("scan"):
            df = None
            try:
                df = tpu_exec.cached_table_frame(table)
            except Exception:  # noqa: BLE001 — cache is an optimization;
                # df=None takes the uncached scan below
                from ..common.telemetry import increment_counter
                increment_counter("scan_cache_errors")
                df = None
            if df is None:
                cached = False
                needed = None
                if a.column_refs and not self._needs_all(a, query):
                    refs = set(a.column_refs)
                    if any(c.op in ("first", "last")
                           for c in a.agg_calls):
                        # _aggregate sorts by the time index so
                        # first/last are time-ordered — keep it in the
                        # projection even when the query doesn't
                        # reference it
                        tc = table.schema.timestamp_column
                        if tc is not None:
                            refs.add(tc.name)
                    needed = [c for c in table.schema.names()
                              if c in refs]
                if getattr(table, "supports_filter_pushdown", False):
                    # distributed tables: thread the WHERE conjuncts in
                    # (region pruning + wire-side tag filtering) and the
                    # LIMIT when no later stage can change which rows
                    # qualify (_run_on_frame still re-filters/limits —
                    # pushdown only sheds rows, never decides)
                    conj = tpu_exec._conjuncts(query.where)
                    push_limit = None
                    if query.limit is not None and not query.order_by \
                            and not query.distinct and not a.is_aggregate \
                            and not a.window_calls and not query.offset:
                        push_limit = query.limit
                    batches = table.scan_batches(
                        projection=needed, filters=conj or None,
                        limit=push_limit)
                else:
                    batches = table.scan_batches(projection=needed)
                df = _batches_to_df(batches)
        exec_stats.record("scan", rows=len(df), cached=cached)
        return self._run_on_frame(df, a, query, table)

    # ---- rollup rewrite (flows) ----
    def _maybe_rollup_rewrite(self, table, a: Analysis, query: Query,
                              ctx: QueryContext, *, refresh: bool):
        """(table, query, analysis, note) for a flow-sink rewrite of this
        statement, or None. refresh=True first folds source rows past the
        flow's watermark into the sink (skipped for plain EXPLAIN)."""
        manager = getattr(self, "flow_manager", None)
        if manager is None:
            return None
        from ..flow import rewrite as flow_rewrite
        try:
            rw = flow_rewrite.try_rewrite(manager, table, a, query, ctx)
        except Exception:  # noqa: BLE001 — the rewrite must never break
            import logging                 # a query; fall back to raw
            logging.getLogger(__name__).exception("rollup rewrite failed")
            return None
        if rw is None:
            return None
        if refresh:
            try:
                manager.refresh(rw.flow)
            except Exception:  # noqa: BLE001 — a sink that cannot catch
                import logging             # up may be arbitrarily wrong
                logging.getLogger(__name__).exception(  # (even empty);
                    "flow %s refresh failed; serving the raw scan",
                    rw.flow.name)          # answer from the raw table
                return None
        try:
            sink_table = self.resolve_table(rw.query.from_, ctx)
        except TableNotFoundError:
            # sink dropped while the flow still exists: the raw scan
            # must keep answering (fold_flow skips the same way)
            return None
        exec_stats.set_dispatch(f"rollup-rewrite ({rw.note})")
        exec_stats.record("rollup_rewrite", flow=rw.flow.name,
                          sink=rw.sink)
        return sink_table, rw.query, analyze(rw.query), rw.note

    # ---- UNION [ALL] ----
    def execute_set_query(self, sq: SetQuery, ctx: QueryContext) -> Output:
        """Same collector discipline as execute_query: a top-level UNION
        installs one ExecStats for the whole statement so both arms
        record into it (each arm alone would otherwise overwrite
        last_exec_stats with a partial view)."""
        if exec_stats.current() is not None:
            return self._execute_set_query_inner(sq, ctx)
        with exec_stats.collect() as st:
            out = self._execute_set_query_inner(sq, ctx)
        self.last_exec_stats = st
        return out

    def _execute_set_query_inner(self, sq: SetQuery, ctx: QueryContext
                                 ) -> Output:
        left = self.execute(sq.left, ctx)
        right = self.execute(sq.right, ctx)
        if not (left.is_batches and right.is_batches):
            raise PlanError("UNION operands must be queries")
        lb, rb = left.batches, right.batches
        lschema = lb[0].schema if lb else None
        ldf = _batches_to_df(lb)
        rdf = _batches_to_df(rb)
        if len(ldf.columns) != len(rdf.columns):
            raise PlanError(
                f"UNION operands have {len(ldf.columns)} vs "
                f"{len(rdf.columns)} columns")
        rdf.columns = ldf.columns        # names come from the left side
        df = pd.concat([ldf, rdf], ignore_index=True)
        if not sq.all:
            df = df.drop_duplicates()
        if sq.order_by:
            ev = Evaluator(df)
            keys, ascs = [], []
            frame = df.copy()
            for i, (e, asc) in enumerate(sq.order_by):
                name = expr_name(e)
                if name not in frame.columns:
                    v = ev.eval(e)
                    name = f"__uord{i}"
                    frame[name] = v
                keys.append(name)
                ascs.append(asc)
            nulls_spec = getattr(sq, "order_nulls", [])
            sort_cols, sort_asc = [], []
            for i, (name, asc) in enumerate(zip(keys, ascs)):
                nf = nulls_spec[i] if i < len(nulls_spec) else None
                if nf is None:
                    nf = not asc     # Postgres default (see Query sort)
                frame[f"__unull{i}"] = frame[name].isna()
                sort_cols += [f"__unull{i}", name]
                sort_asc += [not nf, asc]
            frame = frame.sort_values(sort_cols, ascending=sort_asc,
                                      kind="stable")
            df = df.loc[frame.index]
        if sq.offset:
            df = df.iloc[sq.offset:]
        if sq.limit is not None:
            df = df.iloc[:sq.limit]
        schema = lschema if lschema is not None and all(
            df[c].dtype == ldf[c].dtype for c in df.columns) else \
            _infer_schema(df, None, {})
        return Output.record_batches([_df_to_batch(df, schema)], schema)

    # ---- joins (CPU fallback; reference delegates to DataFusion's
    # hash joins, src/query/src/datafusion.rs) ----
    def _execute_join(self, query: Query, a: Analysis,
                      ctx: QueryContext) -> Output:
        from ..sql.ast import BinaryOp as B

        sources = [query.from_] + [j.table for j in query.joins]
        frames: List[pd.DataFrame] = []
        aliases: List[str] = []
        for ref in sources:
            if ref.subquery is not None:
                inner = self.execute_query(ref.subquery, ctx)
                df = _batches_to_df(inner.batches)
                alias = ref.alias or f"_sub{len(aliases)}"
            else:
                table = self.resolve_table(ref, ctx)
                df = _batches_to_df(table.scan_batches())
                alias = ref.alias or ref.name.table
            frames.append(df.rename(
                columns={c: f"{alias}.{c}" for c in df.columns}))
            aliases.append(alias)

        def resolve_label(col: Column, columns) -> str:
            if col.table is not None:
                cand = f"{col.table}.{col.name}"
                if cand in columns:
                    return cand
                raise PlanError(f"column {cand!r} not found in join")
            matches = [c for c in columns if c.endswith(f".{col.name}")]
            if len(matches) == 1:
                return matches[0]
            if not matches:
                raise PlanError(f"column {col.name!r} not found in join")
            raise PlanError(f"column {col.name!r} is ambiguous: {matches}")

        joined = frames[0]
        for j, right in zip(query.joins, frames[1:]):
            if j.kind == "cross" or j.on is None:
                if j.kind != "cross" and j.on is None:
                    raise PlanError(f"{j.kind} JOIN requires ON")
                joined = joined.merge(right, how="cross")
                continue
            left_on, right_on, residual = [], [], []
            for c in _conjunct_list(j.on):
                ok = (isinstance(c, B) and c.op == "=" and
                      isinstance(c.left, Column) and
                      isinstance(c.right, Column))
                if ok:
                    l, r = c.left, c.right
                    try:
                        ll = resolve_label(l, joined.columns)
                        rl = resolve_label(r, right.columns)
                    except PlanError:
                        ll = resolve_label(r, joined.columns)
                        rl = resolve_label(l, right.columns)
                    left_on.append(ll)
                    right_on.append(rl)
                else:
                    residual.append(c)
            if not left_on:
                raise UnsupportedError(
                    "JOIN ON must contain at least one equality between "
                    "the joined tables")
            if residual and j.kind != "inner":
                raise UnsupportedError(
                    "non-equi conditions are only supported on INNER JOIN")
            # SQL semantics: NULL = NULL is not true, but pandas merge
            # matches NaN keys to each other. Null-keyed rows are removed
            # from any side whose rows must *match* to survive, and for
            # preserved sides re-enter as unmatched rows.
            lnull = joined[left_on].isna().any(axis=1)
            rnull = right[right_on].isna().any(axis=1)
            if j.kind == "full":
                merged = joined[~lnull].merge(
                    right[~rnull], how="outer", left_on=left_on,
                    right_on=right_on)
                joined = pd.concat(
                    [merged, joined[lnull], right[rnull]],
                    ignore_index=True)
            else:
                lkeys = joined[~lnull] if j.kind in ("inner", "right") \
                    else joined
                rkeys = right[~rnull] if j.kind in ("inner", "left") \
                    else right
                joined = lkeys.merge(rkeys, how=j.kind, left_on=left_on,
                                     right_on=right_on)
            for c in residual:
                ev = Evaluator(joined)
                mask = ev.eval(_qualify_columns(c, joined.columns))
                if isinstance(mask, pd.Series):
                    joined = joined[mask.fillna(False).astype(bool)]
                elif not mask:
                    joined = joined.iloc[0:0]

        # plain names for columns unique across sources (SELECT host, ...)
        plain_counts: Dict[str, int] = {}
        for c in joined.columns:
            plain = c.split(".", 1)[1] if "." in c else c
            plain_counts[plain] = plain_counts.get(plain, 0) + 1
        renames = {c: c.split(".", 1)[1] for c in joined.columns
                   if "." in c and plain_counts[c.split(".", 1)[1]] == 1}
        joined = joined.rename(columns=renames)
        return self._run_on_frame(joined, a, query, None)

    def _needs_all(self, a: Analysis, query: Query) -> bool:
        return any(isinstance(p.expr, Star) for p in query.projections)

    # ---- expression subqueries (IN / EXISTS / scalar) ----
    def _rewrite_query_subqueries(self, query: Query,
                                  ctx: QueryContext) -> None:
        """Execute uncorrelated expression subqueries up front and
        substitute their results as literals. The reference gets these
        from DataFusion's subquery decorrelation; the literal form also
        lets the TPU plan see IN lists as ordinary tag predicates."""
        if query.where is not None:
            query.where = self._rewrite_subqueries(query.where, ctx)
        if query.having is not None:
            query.having = self._rewrite_subqueries(query.having, ctx)
        for item in query.projections:
            item.expr = self._rewrite_subqueries(item.expr, ctx)
        query.group_by = [self._rewrite_subqueries(e, ctx)
                          for e in query.group_by]
        query.order_by = [(self._rewrite_subqueries(e, ctx), asc)
                          for e, asc in query.order_by]

    def _rewrite_subqueries(self, e, ctx: QueryContext):
        from ..sql.ast import Subquery
        if e is None or isinstance(e, (Literal, Column, Star)):
            return e
        if isinstance(e, Subquery):        # scalar subquery
            vals = self._subquery_values(e.query, ctx, what="scalar")
            if len(vals) > 1:
                raise PlanError(
                    "more than one row returned by a scalar subquery")
            return Literal(vals[0] if vals else None)
        if isinstance(e, InList) and any(
                isinstance(i, Subquery) for i in e.items):
            # expand every subquery item in place, keeping literal items
            items: list = []
            has_null = False
            for i in e.items:
                if isinstance(i, Subquery):
                    for v in self._subquery_values(i.query, ctx, what="IN"):
                        if v is None:
                            has_null = True
                        else:
                            items.append(Literal(v))
                else:
                    items.append(self._rewrite_subqueries(i, ctx))
            e.expr = self._rewrite_subqueries(e.expr, ctx)
            if not items and not has_null:
                # IN (empty) is FALSE, NOT IN (empty) is TRUE
                return Literal(bool(e.negated))
            if has_null:
                # three-valued logic: a NULL in the list means "no match"
                # is UNKNOWN, never FALSE — so IN is TRUE-or-NULL and
                # NOT IN is FALSE-or-NULL (kills the whole NOT IN filter)
                from ..sql.ast import Case
                match = InList(e.expr, items, negated=False) if items \
                    else Literal(False)
                hit = Literal(not e.negated)
                return Case(operand=None, whens=[(match, hit)],
                            else_=Literal(None))
            e.items = items
            return e
        if isinstance(e, FunctionCall) and e.name == "exists" and \
                e.args and isinstance(e.args[0], Subquery):
            import copy as _copy
            q = _copy.deepcopy(e.args[0].query)
            self._reject_correlated(q, "EXISTS")
            if isinstance(q, Query) and q.limit is None:
                q.limit = 1                # existence needs one row, but
            try:                           # honor an explicit LIMIT 0
                out = self.execute_query(q, ctx)
            except ColumnNotFoundError as err:
                # an unqualified outer-column reference slipped past the
                # qualified-name check — but this also catches plain
                # typos, so keep the original diagnostic visible
                raise UnsupportedError(
                    "correlated EXISTS subqueries are not supported "
                    f"(if the column is not an outer reference: {err})"
                ) from err
            return Literal(out.num_rows > 0)
        for name, v in vars(e).items():
            if isinstance(v, Expr):
                setattr(e, name, self._rewrite_subqueries(v, ctx))
            elif isinstance(v, WindowSpec):
                v.partition_by = [self._rewrite_subqueries(x, ctx)
                                  for x in v.partition_by]
                v.order_by = [(self._rewrite_subqueries(x, ctx), asc)
                              for x, asc in v.order_by]
            elif isinstance(v, list):
                setattr(e, name, [
                    self._rewrite_subqueries(x, ctx) if isinstance(x, Expr)
                    else tuple(self._rewrite_subqueries(y, ctx)
                               if isinstance(y, Expr) else y for y in x)
                    if isinstance(x, tuple) else x
                    for x in v])
        return e

    def _reject_correlated(self, q, what: str) -> None:
        """Refuse subqueries whose qualified column refs name a table or
        alias not defined inside the subquery itself — those are outer
        references, and running them against inner scope silently drops
        the correlation (the bare-name case resolves innermost-first,
        which matches SQL scoping and needs no check)."""
        defined: set = set()
        quals: set = set()

        def walk_expr(e) -> None:
            if e is None or isinstance(e, (Literal, Star)):
                return
            if isinstance(e, Column):
                if e.table:
                    quals.add(e.table.lower())
                return
            from ..sql.ast import Subquery
            if isinstance(e, Subquery):
                walk_query(e.query)
                return
            for v in vars(e).values():
                if isinstance(v, Expr):
                    walk_expr(v)
                elif isinstance(v, WindowSpec):
                    for x in v.partition_by:
                        walk_expr(x)
                    for x, _ in v.order_by:
                        walk_expr(x)
                elif isinstance(v, list):
                    for x in v:
                        if isinstance(x, Expr):
                            walk_expr(x)
                        elif isinstance(x, tuple):
                            for y in x:
                                if isinstance(y, Expr):
                                    walk_expr(y)

        def walk_query(node) -> None:
            if isinstance(node, SetQuery):
                walk_query(node.left)
                walk_query(node.right)
                for e, _ in node.order_by:
                    walk_expr(e)
                return
            if not isinstance(node, Query):
                return
            for ref in [node.from_] + [j.table for j in node.joins]:
                if ref is None:
                    continue
                if ref.alias:
                    defined.add(ref.alias.lower())
                if ref.name is not None:
                    defined.add(ref.name.table.lower())
                if ref.subquery is not None:
                    walk_query(ref.subquery)
            for item in node.projections:
                walk_expr(item.expr)
            for e in (node.where, node.having):
                walk_expr(e)
            for e in node.group_by:
                walk_expr(e)
            for e, _ in node.order_by:
                walk_expr(e)
            for j in node.joins:
                walk_expr(j.on)

        walk_query(q)
        outer = quals - defined
        if outer:
            raise UnsupportedError(
                f"correlated {what} subqueries are not supported "
                f"(outer reference{'s' if len(outer) > 1 else ''}: "
                f"{', '.join(sorted(outer))})")

    def _subquery_values(self, q: Query, ctx: QueryContext,
                         what: str) -> list:
        """Run an uncorrelated subquery, returning its single column."""
        self._reject_correlated(q, what)
        try:
            out = self.execute_query(q, ctx)
        except ColumnNotFoundError as err:
            raise UnsupportedError(
                f"correlated {what} subqueries are not supported "
                f"(if the column is not an outer reference: {err})"
            ) from err
        cols = out.batches[0].columns if out.batches else []
        if out.batches and len(cols) != 1:
            raise PlanError(
                f"{what} subquery must return exactly one column, "
                f"got {len(cols)}")
        vals: list = []
        for rb in out.batches:
            vals.extend(rb.columns[0].to_pylist())
        return vals

    # ---- fallback execution over a DataFrame ----
    def _run_on_frame(self, df: pd.DataFrame, a: Analysis, query: Query,
                      table: Optional[Table]) -> Output:
        if query.where is not None:
            with exec_stats.stage("filter", rows_in=len(df)):
                ev = Evaluator(df)
                mask = ev.eval(query.where)
                if not isinstance(mask, pd.Series):
                    mask = pd.Series([bool(mask)] * len(df),
                                     index=df.index)
                df = df[mask.fillna(False).astype(bool)]
            exec_stats.record("filter", rows=len(df))

        if a.is_aggregate:
            with exec_stats.stage("aggregate", rows_in=len(df)):
                grouped = self._aggregate(df, a, table)
            exec_stats.record("aggregate", rows=len(grouped))
            return self._finish_aggregate_frame(grouped, a, query, table)

        return self._project_and_finish(df, a, query, table)

    def _aggregate(self, df: pd.DataFrame, a: Analysis,
                   table: Optional[Table]) -> pd.DataFrame:
        ev = Evaluator(df)
        # order rows by time index so first/last are time-ordered
        ts_col = None
        if table is not None:
            tc = table.schema.timestamp_column
            ts_col = tc.name if tc is not None else None
        if ts_col and ts_col in df.columns:
            df = df.sort_values(ts_col, kind="stable")
            ev = Evaluator(df)

        key_cols = []
        for g in a.group_exprs:
            name = _group_slot(expr_name(g))
            df = df.assign(**{name: ev.eval(g)})
            key_cols.append(name)
        ev = Evaluator(df)

        arg_cols = []
        for i, call in enumerate(a.agg_calls):
            cname = f"__arg{i}"
            if call.arg is None:
                df = df.assign(**{cname: np.ones(len(df))})
            else:
                df = df.assign(**{cname: ev.eval(call.arg)})
            arg_cols.append(cname)
            ev = Evaluator(df)

        def compute(group: pd.DataFrame) -> pd.Series:
            out = {}
            for i, call in enumerate(a.agg_calls):
                vals = group[f"__arg{i}"]
                if call.op == "count" and call.arg is None:
                    out[call.slot] = len(group)
                elif call.distinct and call.op == "count":
                    out[call.slot] = int(vals.dropna().nunique())
                elif call.op == "first":
                    nn = vals.dropna()
                    out[call.slot] = nn.iloc[0] if len(nn) else None
                elif call.op == "last":
                    nn = vals.dropna()
                    out[call.slot] = nn.iloc[-1] if len(nn) else None
                else:
                    fn = AGGREGATE_FUNCTIONS.get(call.op)
                    if fn is None:
                        raise UnsupportedError(f"aggregate {call.op!r}")
                    v = vals.dropna() if call.distinct else vals
                    if call.distinct:
                        v = v.drop_duplicates()
                    out[call.slot] = fn(v.to_numpy(), *call.params)
            return pd.Series(out)

        if key_cols:
            if len(df) == 0:
                return pd.DataFrame(columns=key_cols +
                                    [c.slot for c in a.agg_calls])
            fast = self._vectorized_aggregate(df, a, key_cols, arg_cols)
            if fast is not None:
                return fast
            grouped = df.groupby(key_cols, dropna=False, sort=False) \
                .apply(compute, include_groups=False).reset_index()
        else:
            grouped = compute(df).to_frame().T
        return grouped

    #: ops pandas can run as vectorized groupby reductions with matching
    #: NULL semantics (sum over all-null = NULL via min_count, sample
    #: stddev/variance via ddof=1, first/last skip nulls in row order)
    _FAST_GROUP_OPS = frozenset(
        {"count", "sum", "avg", "min", "max", "stddev", "variance",
         "first", "last"})
    _NUMERIC_ONLY_OPS = frozenset({"sum", "avg", "stddev", "variance"})

    def _vectorized_aggregate(self, df: pd.DataFrame, a: Analysis,
                              key_cols, arg_cols) -> Optional[pd.DataFrame]:
        """Vectorized twin of the per-group compute() closure: the
        groupby.apply Python loop dominates small-query latency
        (BASELINE config 1), so the common op set reduces through
        pandas' cython paths instead."""
        for i, call in enumerate(a.agg_calls):
            if call.distinct or call.params or \
                    call.op not in self._FAST_GROUP_OPS:
                return None
            if call.op in self._NUMERIC_ONLY_OPS and not call.is_count_star \
                    and not pd.api.types.is_numeric_dtype(df[f"__arg{i}"]):
                return None
        gb = df.groupby(key_cols, dropna=False, sort=False)
        res = {}
        for i, call in enumerate(a.agg_calls):
            if call.is_count_star:
                res[call.slot] = gb.size()
                continue
            s = gb[f"__arg{i}"]
            op = call.op
            if op == "count":
                r = s.count()
            elif op == "sum":
                r = s.sum(min_count=1)
            elif op == "avg":
                r = s.mean()
            elif op == "min":
                r = s.min()
            elif op == "max":
                r = s.max()
            elif op == "stddev":
                r = s.std(ddof=1)
            elif op == "variance":
                r = s.var(ddof=1)
            elif op == "first":
                r = s.first()
            else:
                r = s.last()
            res[call.slot] = r
        if not res:
            return None
        return pd.DataFrame(res).reset_index()

    def _finish_aggregate_frame(self, grouped: pd.DataFrame, a: Analysis,
                                query: Query, table: Optional[Table]
                                ) -> Output:
        ev = Evaluator(grouped)
        if a.having is not None:
            mask = ev.eval(a.having)
            if isinstance(mask, pd.Series):
                grouped = grouped[mask.fillna(False).astype(bool)]
            elif not mask:
                grouped = grouped.iloc[0:0]
            ev = Evaluator(grouped)
        return self._project_and_finish(grouped, a, query, table,
                                        aggregated=True)

    def _project_and_finish(self, df: pd.DataFrame, a: Analysis, query: Query,
                            table: Optional[Table], aggregated: bool = False
                            ) -> Output:
        if a.window_calls:
            from .window import compute_windows
            # windows over non-aggregate queries follow the time index so
            # unordered specs still see rows in scan order
            ts_col = None
            if not aggregated and table is not None:
                tc = table.schema.timestamp_column
                if tc is not None and tc.name in df.columns:
                    ts_col = tc.name
            if ts_col is not None:
                df = df.sort_values(ts_col, kind="stable")
            df = compute_windows(df, a)
        ev = Evaluator(df)
        out_cols: Dict[str, Any] = {}
        out_names: List[str] = []
        source_cols: Dict[str, Optional[str]] = {}
        dtype_overrides: Dict[str, dt.ConcreteDataType] = {}
        for item in (a.projections if aggregated or a.is_aggregate
                     or a.window_calls else query.projections):
            if isinstance(item.expr, Star):
                cols = list(df.columns) if table is None else \
                    [c for c in table.schema.names() if c in df.columns]
                for c in cols:
                    out_cols[c] = df[c]
                    out_names.append(c)
                    source_cols[c] = c
                continue
            name = item.alias or expr_name(item.expr)
            if aggregated and isinstance(item.expr, Column) and \
                    item.expr.name.startswith("__key__"):
                name = item.alias or item.expr.name[len("__key__"):]
            if name in out_cols:
                # self-join shape: SELECT l.host, r.host — qualify the
                # collision (pandas frames cannot carry duplicate labels)
                qualified = str(item.expr)
                name = qualified if qualified not in out_cols \
                    else f"{name}_{len(out_names)}"
            override = _result_dtype_override(item.expr, a, table)
            if override is not None:
                dtype_overrides[name] = override
            v = ev.eval(item.expr)
            if isinstance(v, pd.Series):
                out_cols[name] = v
            elif isinstance(v, np.ndarray) and v.ndim == 1 and \
                    len(v) == len(df):
                # vectorized evaluators (CAST over a column) may return a
                # bare ndarray — one value per row, not a scalar
                out_cols[name] = pd.Series(v, index=df.index)
            else:
                out_cols[name] = pd.Series([v] * len(df), index=df.index)
            out_names.append(name)
            src = None
            if isinstance(item.expr, Column):
                src = item.expr.name
                if aggregated and src.startswith("__key__"):
                    src = None
            source_cols[name] = src

        proj = pd.DataFrame(out_cols, index=df.index if len(df) else None)
        proj = proj[out_names] if out_names else proj

        if query.distinct:
            proj = proj.drop_duplicates()

        # ORDER BY over the result frame (may reference hidden columns,
        # which are evaluated against the pre-projection frame)
        if query.order_by:
            pairs = a.order_by if (aggregated or a.is_aggregate
                                   or a.window_calls) else query.order_by
            sort_frame = proj.copy()
            keys: List[str] = []
            ascs: List[bool] = []
            base_ev = Evaluator(df)
            for i, (e, asc) in enumerate(pairs):
                target = None
                if isinstance(e, Column) and e.name in proj.columns:
                    target = e.name
                elif expr_name(e) in proj.columns:
                    target = expr_name(e)
                if target is None:
                    target = f"__ord{i}"
                    v = base_ev.eval(e)
                    sort_frame[target] = v if isinstance(v, pd.Series) \
                        else pd.Series([v] * len(sort_frame),
                                       index=sort_frame.index)
                keys.append(target)
                ascs.append(asc)
            if keys and len(sort_frame):
                # per-key NULL placement (pandas has one global
                # na_position): an isna flag key ahead of each value key.
                # Default is the Postgres rule — NULLS LAST for ASC,
                # NULLS FIRST for DESC — overridden by NULLS FIRST/LAST.
                nulls_spec = getattr(query, "order_nulls", [])
                sort_cols: List[str] = []
                sort_asc: List[bool] = []
                for i, (target, asc) in enumerate(zip(keys, ascs)):
                    nf = nulls_spec[i] if i < len(nulls_spec) else None
                    if nf is None:
                        nf = not asc
                    flag = f"__nullord{i}"
                    sort_frame[flag] = sort_frame[target].isna()
                    sort_cols += [flag, target]
                    sort_asc += [not nf, asc]
                sort_frame = sort_frame.sort_values(sort_cols,
                                                    ascending=sort_asc,
                                                    kind="stable")
                proj = proj.loc[sort_frame.index]

        if query.offset:
            proj = proj.iloc[query.offset:]
        if query.limit is not None:
            proj = proj.iloc[:query.limit]

        schema = _infer_schema(proj, table, source_cols, dtype_overrides)
        exec_stats.record("project", rows=len(proj))
        return Output.record_batches([_df_to_batch(proj, schema)], schema)


def _conjunct_list(e):
    from ..sql.ast import BinaryOp
    if isinstance(e, BinaryOp) and e.op == "and":
        return _conjunct_list(e.left) + _conjunct_list(e.right)
    return [e]


def _qualify_columns(e, columns):
    """Rewrite unqualified Columns to the (unique) qualified join label so
    residual ON conditions evaluate against the merged frame."""
    import dataclasses

    from ..sql.ast import Between, BinaryOp, FunctionCall, InList, UnaryOp
    if isinstance(e, Column):
        if e.table is not None:
            return Column(f"{e.table}.{e.name}") \
                if f"{e.table}.{e.name}" in columns else e
        matches = [c for c in columns if c.endswith(f".{e.name}")]
        if len(matches) == 1:
            return Column(matches[0])
        if len(matches) > 1:
            raise PlanError(f"column {e.name!r} is ambiguous: {matches}")
        return e
    if isinstance(e, BinaryOp):
        return dataclasses.replace(
            e, left=_qualify_columns(e.left, columns),
            right=_qualify_columns(e.right, columns))
    if isinstance(e, UnaryOp):
        return dataclasses.replace(
            e, operand=_qualify_columns(e.operand, columns))
    if isinstance(e, FunctionCall):
        return dataclasses.replace(
            e, args=[_qualify_columns(x, columns) for x in e.args])
    if isinstance(e, Between):
        return dataclasses.replace(
            e, expr=_qualify_columns(e.expr, columns),
            low=_qualify_columns(e.low, columns),
            high=_qualify_columns(e.high, columns))
    if isinstance(e, InList):
        return dataclasses.replace(
            e, expr=_qualify_columns(e.expr, columns),
            items=[_qualify_columns(x, columns) for x in e.items])
    return e


# ---------------------------------------------------------------------------
# frame <-> batch conversion
# ---------------------------------------------------------------------------

def _batches_to_df(batches: Optional[List[RecordBatch]]) -> pd.DataFrame:
    if not batches:
        return pd.DataFrame()
    frames = []
    for b in batches:
        df = pd.DataFrame(b.to_pydict())
        if not len(df):
            # an empty pylist column defaults to float64, and a later
            # WHERE re-filter would then compare float64 vs str (pushed
            # tag filters can legitimately empty every batch) — pin
            # string/binary columns to object dtype from the schema
            for cs in b.schema.column_schemas:
                if (cs.dtype.is_string or cs.dtype.is_binary) and \
                        cs.name in df.columns:
                    df[cs.name] = df[cs.name].astype(object)
        frames.append(df)
    df = pd.concat(frames, ignore_index=True) if frames else pd.DataFrame()
    return df


def _infer_schema(df: pd.DataFrame, table: Optional[Table],
                  source_cols: Dict[str, Optional[str]],
                  dtype_overrides: Optional[Dict[str, object]] = None
                  ) -> Schema:
    cols = []
    for name in df.columns:
        if dtype_overrides and name in dtype_overrides:
            cols.append(ColumnSchema(name, dtype_overrides[name],
                                     nullable=True))
            continue
        src = source_cols.get(name)
        if table is not None and src is not None and \
                table.schema.contains(src):
            # keep the source dtype but not storage semantics: result sets
            # are not storage tables (a nullable TIME INDEX is invalid)
            cs = table.schema.column_schema(src)
            cols.append(ColumnSchema(name, cs.dtype, nullable=True))
            continue
        cols.append(ColumnSchema(name, _np_to_type(df[name])))
    return Schema(cols)


def _np_to_type(s: pd.Series):
    kind = s.dtype.kind
    if kind == "b":
        return dt.BOOLEAN
    if kind == "i":
        return dt.INT64
    if kind == "u":
        return dt.UINT64
    if kind == "f":
        return dt.FLOAT64
    if kind == "M":
        return dt.TIMESTAMP_MILLISECOND
    return dt.STRING


def _df_to_batch(df: pd.DataFrame, schema: Schema) -> RecordBatch:
    # column-at-a-time vectorized conversion: per-value python loops here
    # used to cost more than the whole streamed fold on wide group-bys
    # (0.37s at 136k output rows)
    from ..datatypes.vector import Vector
    cols = []
    for cs in schema.column_schemas:
        s = df[cs.name]
        if cs.dtype.is_string:
            vals = [None if v is None or (isinstance(v, float) and np.isnan(v))
                    else str(v) if not isinstance(v, str) else v
                    for v in s.tolist()]
            cols.append(Vector.from_pylist(vals, cs.dtype))
        elif s.dtype.kind == "M":
            cols.append(Vector(
                cs.dtype,
                np.ascontiguousarray(s.to_numpy(np.int64) // 1_000_000,
                                     dtype=cs.dtype.np_dtype)))
        elif s.dtype.kind == "f":
            a = s.to_numpy()
            nan = np.isnan(a)
            has_nan = bool(nan.any())
            if cs.dtype.np_dtype.kind in "iu" or cs.dtype.is_timestamp:
                # declared integral (int aggregate / time bucket) but the
                # accumulator ran in float: cast back, NaN -> NULL
                ints = np.round(np.where(nan, 0.0, a)).astype(
                    cs.dtype.np_dtype if cs.dtype.np_dtype is not None
                    else np.int64)
                cols.append(Vector(cs.dtype, ints,
                                   ~nan if has_nan else None))
            else:
                # SQL convention (as in pandas-backed systems): NaN is NULL
                cols.append(Vector(
                    cs.dtype,
                    np.ascontiguousarray(a, dtype=cs.dtype.np_dtype),
                    ~nan if has_nan else None))
        elif s.dtype == object:
            cols.append(Vector.from_pylist(s.tolist(), cs.dtype))
        else:
            cols.append(Vector(
                cs.dtype,
                np.ascontiguousarray(s.to_numpy(), dtype=cs.dtype.np_dtype)))
    return RecordBatch(schema, cols)


_INT_TYPE_NAMES = {"Int8", "Int16", "Int32", "Int64",
                   "UInt8", "UInt16", "UInt32", "UInt64"}


def _result_dtype_override(expr, a: Analysis, table: Optional[Table]):
    """Result types that must not decay to float64 (reference: DataFusion
    keeps integer sums as Int64, min/max/first/last as the source type,
    and date_bin/date_trunc results as timestamps)."""
    if isinstance(expr, Column) and expr.name.startswith("__key__"):
        target = expr.name[len("__key__"):]
        for g in a.group_exprs:
            if expr_name(g) == target:
                expr = g
                break
    if isinstance(expr, Column) and table is not None:
        for call in a.agg_calls:
            if call.slot != expr.name:
                continue
            if call.op in ("count", "approx_distinct"):
                # distinct counts are cardinalities: Int64 even when the
                # per-group fallback frame decayed to float (a mixed
                # int/float agg row upcasts under groupby.apply)
                return dt.INT64
            if call.op in ("sum", "min", "max", "first", "last") and \
                    isinstance(call.arg, Column) and \
                    table.schema.contains(call.arg.name):
                src = table.schema.column_schema(call.arg.name).dtype
                if src.is_timestamp:
                    return src
                if src.name in _INT_TYPE_NAMES:
                    return dt.INT64 if call.op == "sum" else src
            return None
        return None
    if isinstance(expr, FunctionCall) and \
            expr.name.lower() in ("date_bin", "date_trunc"):
        for argx in expr.args:
            if isinstance(argx, Column) and table is not None and \
                    table.schema.contains(argx.name):
                src = table.schema.column_schema(argx.name).dtype
                if src.is_timestamp and \
                        src.time_unit == TimeUnit.MILLISECOND:
                    return src
    from ..sql.ast import Cast
    if isinstance(expr, Cast):
        # the projection carries the CAST target type, not whatever
        # dtype the value plane decayed to (NULL-bearing ints run as
        # float there)
        tn = expr.type_name.strip().lower()
        if tn in ("date", "timestamp", "datetime"):
            return dt.TIMESTAMP_MILLISECOND
        try:
            return parse_type_name(expr.type_name)
        except Exception:  # noqa: BLE001 — unknown alias: keep inference
            return None
    return None
