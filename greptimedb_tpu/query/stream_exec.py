"""Block-streamed cold scan: aggregate regions too large to cache in HBM.

The cached fast path (tpu_exec.SCAN_CACHE) materializes a region's merged
scan in host memory with device-resident mirrors — right for hot regions
that fit, impossible for regions larger than device (or host) memory.
This module streams instead:

1. The region's key domain is partitioned into contiguous slices sized
   by parquet row-group statistics (a row-budget per slice). The
   partition axis adapts to the file layout: short-window flush files
   slice on TIME (their row-group time stats are tight); compacted or
   long-window files slice on SERIES ID — the leading storage sort key,
   whose row-group stats are tight on every layout (_pick_slice_dim).
2. Each slice is read with row-group pruning (memtables + SSTs clipped to
   the slice range), then merged and MVCC-deduped *exactly*: a
   (series, ts) key lives in exactly one slice on either axis, so
   slice-local dedup — the same sort-based kernel the cached path uses —
   is globally exact, including overwrites and tombstones across SSTs.
3. Each slice reduces to a partial moment frame on the device (padded to
   shape buckets so XLA compiles once, not once per slice), and
   tpu_exec._finalize folds the partials — the same decomposable-moment
   algebra that already merges partials across regions and datanodes.
4. Host decode of slice i+1 overlaps device compute of slice i (a
   one-deep prefetch pipeline; parquet decode drops the GIL).

Reference behavior: src/storage/src/chunk.rs:35-218 (streamed merge
reader) and src/storage/src/sst/parquet.rs:217-330 (row-group readers);
SURVEY §7 hard part #3 (overlapped Parquet-decode + H2D streaming).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from ..common.time import TimestampRange
from ..ops.kernels import OP_PUT, merge_dedup_numpy, shape_bucket

#: stream (instead of caching) any region estimated above this many rows
_STREAM_THRESHOLD_ROWS = [64_000_000]
#: target rows per streamed slice (soft: slices track row-group edges)
_SLICE_ROWS = [16_000_000]
#: row-count shape bucket floor, so nearby slice sizes share one compile
_ROW_BUCKET_MIN = 1 << 20


def configure_streaming(threshold_rows: Optional[int] = None,
                        slice_rows: Optional[int] = None) -> None:
    """Tune the cold-scan streaming knobs (TOML [query] section)."""
    if threshold_rows is not None:
        _STREAM_THRESHOLD_ROWS[0] = int(threshold_rows)
    if slice_rows is not None:
        _SLICE_ROWS[0] = int(slice_rows)


def stream_threshold_rows() -> int:
    return _STREAM_THRESHOLD_ROWS[0]


def region_estimated_rows(region) -> int:
    """Upper-bound row estimate from memtable counters + SST metas."""
    vc = getattr(region, "version_control", None)
    if vc is None:
        return 0
    v = vc.current
    total = 0
    for mt in v.memtables.all_memtables():
        total += mt.num_rows
    for meta in v.ssts.all_files():
        total += meta.num_rows
    return total


def _plan_slices(stats: List[Tuple[int, int, int]], budget: int,
                 clip_lo: Optional[int], clip_hi: Optional[int]
                 ) -> List[Tuple[int, int]]:
    """Choose contiguous half-open time slices [t0, t1) covering every row.

    `stats` are (min_ts, max_ts_inclusive, rows) per storage chunk (parquet
    row group or memtable). Cuts land on chunk upper edges, accumulating
    until the row budget is reached — slices are exact partitions of the
    time domain regardless of cut quality; the stats only balance sizes.
    """
    clipped = []
    for lo, hi, rows in stats:
        if clip_lo is not None and hi < clip_lo:
            continue
        if clip_hi is not None and lo >= clip_hi:
            continue
        clipped.append((lo, hi, rows))
    if not clipped:
        return []
    tmin = min(lo for lo, _, _ in clipped)
    tmax = max(hi for _, hi, _ in clipped)
    if clip_lo is not None:
        tmin = max(tmin, clip_lo)
    if clip_hi is not None:
        tmax = min(tmax, clip_hi - 1)
    if tmin > tmax:
        return []
    total = sum(r for _, _, r in clipped)
    if total <= budget:
        return [(tmin, tmax + 1)]
    cuts: List[int] = []
    acc = 0
    for lo, hi, rows in sorted(clipped, key=lambda s: (s[1], s[0])):
        acc += rows
        if acc >= budget and hi < tmax:
            cuts.append(hi + 1)
            acc = 0
    bounds = [tmin] + sorted(set(cuts)) + [tmax + 1]
    return [(bounds[i], bounds[i + 1]) for i in range(len(bounds) - 1)
            if bounds[i] < bounds[i + 1]]


def _region_slice_stats(region, snap, unit
                        ) -> List[Tuple[int, int, int, int, int]]:
    """(min_ts, max_ts, min_sid, max_sid, rows) per chunk: SST row
    groups + memtables."""
    v = snap._version
    stats: List[Tuple[int, int, int, int, int]] = []
    for meta in v.ssts.all_files():
        rg = region.access_layer.row_group_stats(meta)
        if rg:
            stats.extend(rg)
        else:  # no stats: the whole file is one chunk
            lo, hi = meta.time_range
            stats.append((lo, hi, 0, 1 << 30, meta.num_rows))
    for mt in v.memtables.all_memtables():
        ms = mt.snapshot()
        if ms.num_rows:
            stats.append((int(ms.ts.min()), int(ms.ts.max()),
                          int(ms.series_ids.min()),
                          int(ms.series_ids.max()), ms.num_rows))
    return stats


def _pick_slice_dim(stats) -> str:
    """Choose the slicing dimension with tighter row-group spans.

    SSTs sort by (series, ts): flush files cover short time windows
    (time stats tight, series stats span everything), while compacted or
    long-window files cover each series' whole range (series stats
    tight, time stats useless). Mean span / domain span measures how
    well cuts on a dimension will prune row groups."""
    def ratio(lo_i: int, hi_i: int) -> float:
        los = [s[lo_i] for s in stats]
        his = [s[hi_i] for s in stats]
        domain = max(his) - min(los) + 1
        if domain <= 0:
            return 1.0
        spans = [h - l + 1 for l, h in zip(los, his)]
        return (sum(spans) / len(spans)) / domain

    return "series" if ratio(2, 3) < ratio(0, 1) else "time"


def _slice_dedup(data) -> Optional[np.ndarray]:
    """Kept-row indices for a slice — or None when EVERY row survives
    (append-only data, the common case), letting the caller skip the
    per-column fancy-index copies entirely.

    Skips the O(n log n) sort when the concatenated runs are already
    (sid, ts, seq)-sorted — true whenever a single SST covers the slice
    — which reduces dedup to a vectorized adjacency scan."""
    s, t, q = data.series_ids, data.ts, data.seq
    n = len(s)
    if n > 1:
        s_up = s[1:] > s[:-1]
        s_eq = s[1:] == s[:-1]
        t_up = t[1:] > t[:-1]
        t_eq = t[1:] == t[:-1]
        sorted_ok = bool(np.all(
            s_up | (s_eq & (t_up | (t_eq & (q[1:] >= q[:-1]))))))
        if sorted_ok:
            dup = s_eq & t_eq
            deletes = data.op_types != OP_PUT
            if not dup.any() and not deletes.any():
                return None                  # keep everything, zero copies
            nxt_same = np.concatenate([dup, [False]])
            keep = ~nxt_same & ~deletes
            return np.nonzero(keep)[0]
    return merge_dedup_numpy(s, t, q, data.op_types)


def _load_slice(snap, dim: str, lo: int, hi: int, unit, needed_fields,
                series_dict, row_bucket_min: int,
                time_range: Optional[TimestampRange]):
    """Read + merge + dedup one slice into a padded transient MergedScan.

    `dim` selects the partition axis: "time" slices [lo, hi) on the time
    index, "series" on __series_id (with the query's time filter still
    pruning files and row groups)."""
    from .tpu_exec import MergedScan

    if dim == "series":
        data = snap.scan(projection=needed_fields, series_range=(lo, hi),
                         time_range=time_range, synthetic_seq=True)
    else:
        data = snap.scan(projection=needed_fields,
                         time_range=TimestampRange(lo, hi, unit),
                         synthetic_seq=True)
    if data.num_rows == 0:
        return None
    kept = _slice_dedup(data)
    n = data.num_rows if kept is None else len(kept)
    if n == 0:
        return None

    # pad to a shape bucket so every slice shares one XLA compile; padded
    # rows repeat the last (sid, ts) — they extend the final run, stay
    # sorted, and are masked out via valid_rows. take + device-dtype cast
    # + pad fuse into ONE pass per column (each was a full copy).
    import jax
    x64 = jax.config.jax_enable_x64
    target = shape_bucket(n, minimum=row_bucket_min)

    def prepare(a, dtype=None, pad_fill=None):
        dtype = dtype or a.dtype
        if kept is None and target == n and a.dtype == dtype:
            return a
        out = np.empty(target, dtype)
        if kept is None:
            out[:n] = a
        elif a.dtype == dtype:
            np.take(a, kept, out=out[:n])
        else:
            out[:n] = a[kept]
        if target != n:
            out[n:] = pad_fill if pad_fill is not None else out[n - 1]
        return out

    sids = prepare(data.series_ids, np.int32)
    ts = prepare(data.ts)
    fields = {}
    for name, (d, vd) in data.fields.items():
        if d.dtype == object:
            d2 = d if kept is None else d[kept]
            if target != n:
                d2 = np.concatenate(
                    [d2, np.full(target - n, None, dtype=object)])
        else:
            want = np.float32 if d.dtype == np.float64 and not x64 \
                else d.dtype
            d2 = prepare(d, want)
        v2 = prepare(vd, np.bool_, pad_fill=False) \
            if vd is not None else None
        fields[name] = (d2, v2)
    base = int(ts[:n].min())
    scan = MergedScan(sids, ts, fields, series_dict, base)
    scan.valid_rows = n if target != n else None
    # start the H2D transfers here, on the prefetch thread: device_put is
    # asynchronous, so the copies stream while the next slice decodes and
    # the launch thread stays free for mask/run construction. Only dtypes
    # device_put maps 1:1 are staged — int64 fields keep device_field's
    # narrowing logic; anything else falls back to lazy upload at launch.
    try:
        rel = ts - base
        if not rel.size or int(rel.max()) < 2 ** 31:
            scan.device["__ts"] = jax.device_put(rel.astype(np.int32))
        for name, (d2, v2) in fields.items():
            if d2.dtype in (np.float32, np.bool_, np.int32) or \
                    (d2.dtype == np.float64 and x64):
                scan.device[f"f:{name}"] = jax.device_put(d2)
            if v2 is not None:
                scan.device[f"v:{name}"] = jax.device_put(v2)
        if target != n:
            pm = np.zeros(target, np.bool_)
            pm[:n] = True
            scan.device["__pad_mask"] = jax.device_put(pm)
    except Exception:  # noqa: BLE001 — staging is an optimization
        scan.device.clear()
    return scan


def stream_region_moment_frames(region, table, plan) -> List[pd.DataFrame]:
    """Partial moment frames for one region via slice streaming.

    Returns the same frame shape tpu_exec._execute_region produces, so
    tpu_exec._finalize folds slices exactly like regions.

    Pipelining: XLA dispatch is asynchronous, so each slice's reduction
    is *launched* and left in flight while the next slice decodes on the
    prefetch thread; device results are fetched in ONE round trip at the
    end (per-slice fetches would each pay the device-link latency, which
    dominates on tunneled chips). Only run-level context is kept per
    launched slice — full slice arrays are freed as the pipeline advances.
    """
    import jax

    from .tpu_exec import _collect_moment_frame, _launch_scan_kernel

    snap = region.snapshot()
    schema = snap.schema
    tc = schema.timestamp_column
    unit = tc.dtype.time_unit if tc is not None else None
    stats = _region_slice_stats(region, snap, unit)
    if not stats:
        return []
    dim = _pick_slice_dim(stats)
    if dim == "series":
        dstats = [(s[2], s[3], s[4]) for s in stats]
        clip_lo = clip_hi = None
        query_range = None
        if plan.time_lo is not None or plan.time_hi is not None:
            query_range = TimestampRange(plan.time_lo, plan.time_hi, unit)
    else:
        dstats = [(s[0], s[1], s[4]) for s in stats]
        clip_lo, clip_hi = plan.time_lo, plan.time_hi
        query_range = None
    slices = _plan_slices(dstats, _SLICE_ROWS[0], clip_lo, clip_hi)
    if not slices:
        return []
    needed = sorted({m.column for m in plan.moments if m.column is not None}
                    | {ff.column for ff in plan.field_filters})
    sd = region.series_dict

    launched = []
    # two-deep prefetch: decode slices i+1, i+2 while slice i launches
    # (decode is the cold-path bottleneck; two workers keep parquet
    # threads busy without unbounded slice residency)
    depth = 2
    with ThreadPoolExecutor(max_workers=depth,
                            thread_name_prefix="stream-scan") as pool:
        futs = [pool.submit(_load_slice, snap, dim, lo, hi, unit, needed,
                            sd, _ROW_BUCKET_MIN, query_range)
                for lo, hi in slices[:depth]]
        for i in range(len(slices)):
            scan = futs[i].result()
            if i + depth < len(slices):
                lo, hi = slices[i + depth]
                futs.append(pool.submit(_load_slice, snap, dim, lo, hi,
                                        unit, needed, sd, _ROW_BUCKET_MIN,
                                        query_range))
            futs[i] = None                   # free the slice as we go
            if scan is None:
                continue
            ln = _launch_scan_kernel(scan, schema, plan)
            if ln is not None:
                launched.append(ln)
            del scan
    if not launched:
        return []
    fetched = jax.device_get([(ln.counts, list(ln.results))
                              for ln in launched])
    frames: List[pd.DataFrame] = []
    for ln, (counts, res_np) in zip(launched, fetched):
        part = _collect_moment_frame(ln, plan, counts, res_np)
        if part is not None and len(part):
            frames.append(part)
    return frames
