"""Block-streamed cold scan: aggregate regions too large to cache in HBM.

The cached fast path (tpu_exec.SCAN_CACHE) materializes a region's merged
scan in host memory with device-resident mirrors — right for hot regions
that fit, impossible for regions larger than device (or host) memory.
This module streams instead:

1. The region's key domain is partitioned into contiguous slices sized
   by parquet row-group statistics (a row-budget per slice). The
   partition axis adapts to the file layout: short-window flush files
   slice on TIME (their row-group time stats are tight); compacted or
   long-window files slice on SERIES ID — the leading storage sort key,
   whose row-group stats are tight on every layout (_pick_slice_dim).
2. Each slice is read with row-group pruning (memtables + SSTs clipped to
   the slice range), then merged and MVCC-deduped *exactly*: a
   (series, ts) key lives in exactly one slice on either axis, so
   slice-local dedup — the same sort-based kernel the cached path uses —
   is globally exact, including overwrites and tombstones across SSTs.
3. Each slice reduces to a partial moment frame on the device (padded to
   shape buckets so XLA compiles once, not once per slice), and
   tpu_exec._finalize folds the partials — the same decomposable-moment
   algebra that already merges partials across regions and datanodes.
4. Host decode of slice i+1 overlaps device compute of slice i (a
   one-deep prefetch pipeline; parquet decode drops the GIL).

Reference behavior: src/storage/src/chunk.rs:35-218 (streamed merge
reader) and src/storage/src/sst/parquet.rs:217-330 (row-group readers);
SURVEY §7 hard part #3 (overlapped Parquet-decode + H2D streaming).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from ..common.failpoint import register as _fp_register
from ..common.time import TimestampRange
from ..ops.kernels import OP_PUT, merge_dedup_numpy, shape_bucket

# per-slice boundary of the streamed cold scan: delay(ms) makes a scan
# deterministically slow for the KILL-cancellation tests
_fp_register("stream_slice")

#: stream (instead of caching) any region estimated above this many rows
_STREAM_THRESHOLD_ROWS = [64_000_000]
#: target rows per streamed slice (soft: slices track row-group edges)
_SLICE_ROWS = [16_000_000]
#: row-count shape bucket floor, so nearby slice sizes share one compile
_ROW_BUCKET_MIN = 1 << 20
#: where a cold slice's one-pass partial reduction runs. "host": a
#: vectorized reduceat over the just-decoded columns — cold scans are
#: decode/link-bound, and a single streaming pass belongs where the bytes
#: already are (shipping n rows over the device link to produce nruns
#: partials is a losing trade at host↔device bandwidths; the resident
#: warm path keeps the TPU, where reuse amortizes the transfer).
#: "device": launch the moment kernel per slice (right when the link is
#: wide, e.g. co-located accelerators).
_COLD_REDUCE = ["host"]


def configure_streaming(threshold_rows: Optional[int] = None,
                        slice_rows: Optional[int] = None,
                        cold_reduce: Optional[str] = None) -> None:
    """Tune the cold-scan streaming knobs (TOML [query] section)."""
    if threshold_rows is not None:
        _STREAM_THRESHOLD_ROWS[0] = int(threshold_rows)
    if slice_rows is not None:
        _SLICE_ROWS[0] = int(slice_rows)
    if cold_reduce is not None:
        if cold_reduce not in ("host", "device"):
            raise ValueError(f"cold_reduce {cold_reduce!r}")
        _COLD_REDUCE[0] = cold_reduce


def stream_threshold_rows() -> int:
    return _STREAM_THRESHOLD_ROWS[0]


def region_estimated_rows(region) -> int:
    """Upper-bound row estimate from memtable counters + SST metas."""
    vc = getattr(region, "version_control", None)
    if vc is None:
        return 0
    v = vc.current
    total = 0
    for mt in v.memtables.all_memtables():
        total += mt.num_rows
    for meta in v.ssts.all_files():
        total += meta.num_rows
    return total


def region_estimated_bytes(region) -> int:
    """Estimated DECODED residency of a fully-cached scan: rows × the
    schema's in-memory row width (ts + sid + every field column and its
    validity). Parquet file sizes understate this badly — compression
    plus column pruning hide the real host+HBM footprint — and the
    streaming threshold exists to protect residency, so it must be
    measured in the same units as the scan-cache budget."""
    vc = getattr(region, "version_control", None)
    if vc is None:
        return 0
    schema = vc.current.schema
    width = 12                        # int64 ts + int32 sid
    for c in schema.field_columns():
        np_dtype = c.dtype.np_dtype
        width += (np.dtype(np_dtype).itemsize
                  if np_dtype is not None else 16) + 1
    return region_estimated_rows(region) * width


def region_time_span(region) -> int:
    """Inclusive width of a region's time domain in its native unit,
    from SST metas + memtable counters alone (no reads) — the bucket-
    count input of the cost-based scatter planner."""
    vc = getattr(region, "version_control", None)
    if vc is None:
        return 0
    lo = hi = None
    v = vc.current
    for meta in v.ssts.all_files():
        flo, fhi = meta.time_range
        lo = flo if lo is None else min(lo, flo)
        hi = fhi if hi is None else max(hi, fhi)
    for mt in v.memtables.all_memtables():
        ms = mt.snapshot()
        if ms.num_rows:
            lo = int(ms.ts.min()) if lo is None \
                else min(lo, int(ms.ts.min()))
            hi = int(ms.ts.max()) if hi is None \
                else max(hi, int(ms.ts.max()))
    return 0 if lo is None else int(hi - lo + 1)


def region_stat_entries(regions) -> tuple:
    """(per-region stat dicts, total_rows, total_bytes) for an iterable
    of Region objects — the ONE builder behind both the datanode
    heartbeat's DatanodeStat.region_stats and the standalone
    cluster_info row, so the two views of region heat cannot diverge.
    `series` (series-dict count) and `time_span` ride along so the
    frontend's cost-based scatter planner can estimate result
    cardinality for REMOTE datanodes from the heartbeat alone."""
    entries, total_rows, total_bytes = [], 0, 0
    for region in sorted(regions, key=lambda r: r.name):
        rows = int(region_estimated_rows(region))
        size = int(region_estimated_bytes(region))
        sd = getattr(region, "series_dict", None)
        total_rows += rows
        total_bytes += size
        entry = {"region": region.name, "rows": rows,
                 "size_bytes": size,
                 "series": int(getattr(sd, "num_series", 0) or 0),
                 "time_span": region_time_span(region)}
        # replication feed: followers beat their applied position,
        # leaders their acked frontier — meta derives per-replica lag
        # (region_peers) and picks the promotion winner from these
        vc = getattr(region, "version_control", None)
        committed = int(vc.committed_sequence) if vc is not None else 0
        if getattr(region, "standby", False):
            entry["standby"] = True
            entry["replicated_seq"] = committed
        else:
            entry["committed_seq"] = committed
        entries.append(entry)
    return entries, total_rows, total_bytes


def _plan_slices(stats: List[Tuple[int, int, int]], budget: int,
                 clip_lo: Optional[int], clip_hi: Optional[int]
                 ) -> List[Tuple[int, int]]:
    """Choose contiguous half-open time slices [t0, t1) covering every row.

    `stats` are (min_ts, max_ts_inclusive, rows) per storage chunk (parquet
    row group or memtable). Two kinds of cuts, both on chunk edges:

    - *clean breaks*: gaps where no chunk spans the boundary. A slice cut
      there covers whole sorted runs, so the reader takes the no-sort
      no-mask path — the dominant cold-scan cost is the host merge sort,
      and flush SSTs are time-disjoint, so most LSM layouts split fully
      into merge-free slices. Only taken once a slice has accumulated
      enough rows to amortize its kernel launch + padding.
    - *budget cuts*: inside an overlapping run of chunks, accumulate to
      the row budget (those slices still merge-sort, but stay bounded).

    Slices are exact partitions of the time domain regardless of cut
    quality; the stats only balance sizes.
    """
    clipped = []
    for lo, hi, rows in stats:
        if clip_lo is not None and hi < clip_lo:
            continue
        if clip_hi is not None and lo >= clip_hi:
            continue
        clipped.append((lo, hi, rows))
    if not clipped:
        return []
    tmin = min(lo for lo, _, _ in clipped)
    tmax = max(hi for _, hi, _ in clipped)
    if clip_lo is not None:
        tmin = max(tmin, clip_lo)
    if clip_hi is not None:
        tmax = min(tmax, clip_hi - 1)
    if tmin > tmax:
        return []
    # connected components of overlapping chunks: (lo, hi, rows, chunks)
    comps: List[list] = []
    for lo, hi, rows in sorted(clipped, key=lambda s: (s[0], s[1])):
        if comps and lo <= comps[-1][1]:
            c = comps[-1]
            c[1] = max(c[1], hi)
            c[2] += rows
            c[3].append((lo, hi, rows))
        else:
            comps.append([lo, hi, rows, [(lo, hi, rows)]])

    min_clean = max(_ROW_BUCKET_MIN, budget // 8)
    cuts: set = set()
    acc = 0
    prev_hi: Optional[int] = None
    for clo, chi, crows, chunks in comps:
        # close the running slice at the gap when it is big enough to
        # deserve its own launch, when adding the next component would
        # bust the row budget, or when a budget-busting component
        # follows (its internal cuts must not bleed into neighbors)
        if prev_hi is not None and acc and (acc >= min_clean
                                            or acc + crows > budget
                                            or crows > budget):
            cuts.add(prev_hi + 1)
            acc = 0
        if crows > budget:
            # oversized overlapping pile: budget cuts inside it (those
            # slices pay the merge sort, but stay bounded)
            inner = 0
            for lo, hi, rows in sorted(chunks, key=lambda s: (s[1], s[0])):
                inner += rows
                if inner >= budget and hi < chi:
                    cuts.add(hi + 1)
                    inner = 0
            acc = budget            # force a cut before whatever follows
        else:
            acc += crows
        prev_hi = chi
    bounds = [tmin] + sorted(c for c in cuts if tmin < c <= tmax) \
        + [tmax + 1]
    return [(bounds[i], bounds[i + 1]) for i in range(len(bounds) - 1)
            if bounds[i] < bounds[i + 1]]


def _region_slice_stats(region, snap, unit
                        ) -> List[Tuple[int, int, int, int, int]]:
    """(min_ts, max_ts, min_sid, max_sid, rows) per chunk: SST row
    groups + memtables."""
    v = snap._version
    stats: List[Tuple[int, int, int, int, int]] = []
    for meta in v.ssts.all_files():
        rg = region.access_layer.row_group_stats(meta)
        if rg:
            stats.extend(rg)
        else:  # no stats: the whole file is one chunk
            lo, hi = meta.time_range
            stats.append((lo, hi, 0, 1 << 30, meta.num_rows))
    for mt in v.memtables.all_memtables():
        ms = mt.snapshot()
        if ms.num_rows:
            stats.append((int(ms.ts.min()), int(ms.ts.max()),
                          int(ms.series_ids.min()),
                          int(ms.series_ids.max()), ms.num_rows))
    return stats


def _plan_jobs(stats: List[Tuple[int, int, int, int, int]], budget: int,
               time_lo: Optional[int], time_hi: Optional[int], unit
               ) -> List[Tuple[str, int, int, Optional[TimestampRange]]]:
    """Per-component hybrid slice plan: (dim, lo, hi, time_clip) jobs.

    Merge-freedom beats pruning tightness — the cold scan's dominant
    host cost is the (sid, ts) merge sort, which vanishes when a slice
    covers whole sorted runs. So:

    - chains of budget-sized time-disjoint components (in-order flushes
      and bulk loads) become TIME slices on their gap boundaries;
    - an oversized overlapping component (a big compacted file, or
      several files covering the same window) is sliced on SERIES id
      within its time range instead: SSTs sort by series first, so
      series row-group stats are tight there, and a series slice of a
      single file is itself one sorted run.
    """
    clipped = []
    for tlo, thi, slo, shi, rows in stats:
        if time_lo is not None and thi < time_lo:
            continue
        if time_hi is not None and tlo >= time_hi:
            continue
        clipped.append((tlo, thi, slo, shi, rows))
    if not clipped:
        return []
    # connected components over time: [lo, hi, rows, chunks]
    comps: List[list] = []
    for ch in sorted(clipped):
        if comps and ch[0] <= comps[-1][1]:
            c = comps[-1]
            c[1] = max(c[1], ch[1])
            c[2] += ch[4]
            c[3].append(ch)
        else:
            comps.append([ch[0], ch[1], ch[4], [ch]])

    def clamp(lo: int, end: int) -> Tuple[int, int]:
        if time_lo is not None:
            lo = max(lo, time_lo)
        if time_hi is not None:
            end = min(end, time_hi)
        return lo, end

    jobs: List[Tuple[str, int, int, Optional[TimestampRange]]] = []
    min_clean = max(_ROW_BUCKET_MIN, budget // 8)
    pend_lo: Optional[int] = None
    pend_rows = 0
    prev_hi: Optional[int] = None

    def flush_pending() -> None:
        nonlocal pend_lo, pend_rows
        if pend_lo is not None:
            lo, end = clamp(pend_lo, prev_hi + 1)
            if lo < end:
                jobs.append(("time", lo, end, None))
        pend_lo = None
        pend_rows = 0

    for clo, chi, crows, chunks in comps:
        if crows > budget:
            flush_pending()
            lo, end = clamp(clo, chi + 1)
            clip = TimestampRange(lo, end, unit)
            sstats = [(c[2], c[3], c[4]) for c in chunks]
            sslices = _plan_slices(sstats, budget, None, None)
            if len(sslices) > 1:
                for slo, shi in sslices:
                    jobs.append(("series", slo, shi, clip))
            else:
                # the series axis cannot subdivide (few series, or every
                # chunk spans the whole sid domain): fall back to time
                # budget cuts — those slices pay the merge sort but stay
                # bounded
                tstats = [(c[0], c[1], c[4]) for c in chunks]
                for tlo2, thi2 in _plan_slices(tstats, budget, lo, end):
                    jobs.append(("time", tlo2, thi2, None))
        else:
            if pend_lo is not None and (pend_rows >= min_clean
                                        or pend_rows + crows > budget):
                flush_pending()
            if pend_lo is None:
                pend_lo = clo
            pend_rows += crows
        prev_hi = chi
    flush_pending()
    return jobs


def _plan_needs_ts(plan) -> bool:
    """Whether the aggregate ever consults row times: time bucketing,
    time filtering, or a moment whose fold is keyed by time."""
    if plan.bucket is not None or plan.time_lo is not None \
            or plan.time_hi is not None:
        return True
    return any(getattr(m, "op", None) in ("min_ts", "max_ts",
                                          "first", "last")
               for m in plan.moments if m.column is not None)


def _slice_lean_proof(snap, dim: str, lo: int, hi: int, unit,
                      time_range: Optional[TimestampRange]
                      ) -> Tuple[bool, bool, list]:
    """(skip_dedup, fully_covered, files) for one slice, from file
    metadata alone.

    skip_dedup: no (series, ts) key in the slice can have two versions —
    every file is dup-free (num_dup_keys == 0) and delete-free, the
    files' key rectangles are pairwise disjoint, and no memtable rows
    exist. Merge dedup then keeps every row, so the per-row key-compare
    pass (and its ts dependency) can be skipped outright. Files from
    before the num_dup_keys upgrade report None and fail the proof.

    fully_covered: every candidate file's time range lies inside the
    slice's clip, so no per-row time mask can trigger — together with
    skip_dedup and a time-blind plan this lets the reader skip decoding
    the ts column entirely (the widest internal column).

    `files` is the slice's candidate file list the proof certified —
    the lean reader must consume exactly this list (re-deriving it
    could drift from what was proven)."""
    v = snap._version
    if any(mt.num_rows for mt in v.memtables.all_memtables()):
        return False, False, []
    if dim == "time":
        clip_lo, clip_hi = lo, hi
        files = v.ssts.files_in_range(TimestampRange(lo, hi, unit))
    else:
        clip_lo = time_range.start if time_range is not None else None
        clip_hi = time_range.end if time_range is not None else None
        files = [f for f in v.ssts.files_in_range(time_range)
                 if f.sid_range is None or
                 (f.sid_range[1] >= lo and f.sid_range[0] < hi)]
    covered = all(
        (clip_lo is None or f.time_range[0] >= clip_lo) and
        (clip_hi is None or f.time_range[1] < clip_hi)
        for f in files)
    for f in files:
        if f.num_dup_keys != 0 or f.num_deletes != 0:
            return False, covered, files
    if len(files) > 64:
        # the pairwise disjointness check is O(F^2); past this bound
        # just decline the proof (the general merge path is always
        # correct) rather than burn seconds of Python before any I/O
        return False, covered, files
    for i in range(len(files)):
        for j in range(i + 1, len(files)):
            if files[i].keys_overlap(files[j]):
                return False, covered, files
    return True, covered, files


class _LeanChunk:
    """Duck-typed ScanData stand-in for one parquet row group: numpy
    views over the arrow buffers (zero-copy for null-free numeric
    columns), just enough surface for _host_partial_frame. seq/op_types
    are 0-stride placeholders — the lean proof guarantees no consumer
    needs MVCC values (dup-free, delete-free slice)."""

    __slots__ = ("series_ids", "ts", "seq", "op_types", "fields")

    def __init__(self, series_ids, ts, fields):
        n = len(series_ids)
        self.series_ids = series_ids
        self.ts = ts
        self.seq = np.broadcast_to(np.int64(0), (n,))
        self.op_types = np.broadcast_to(np.int8(0), (n,))
        self.fields = fields


def _lean_chunk_frames(snap, access, files, dim: str, lo: int, hi: int,
                       needed_fields, plan, sd, need_ts: bool,
                       sid_keys: bool = False,
                       sid_set: Optional[np.ndarray] = None):
    """Decode→reduce fast path for a fully-covered, dedup-free slice:
    stream each SST's row groups as arrow record batches and reduce each
    batch straight into a partial moment frame over zero-copy column
    views. No ScanData assembly, no cross-run concatenation, no
    chunked→contiguous copies — on a two-metric scan those passes cost
    more than the parquet decode itself. Exactness is unchanged: every
    batch is (sid, ts)-sorted, partial frames fold by group key
    downstream (the same algebra that folds slices and regions), and the
    lean proof guarantees no key has competing versions to merge.

    `files` is the list _slice_lean_proof certified for this slice —
    the single source of truth for what belongs to it.

    Returns (frames, rows_read), or None when any precondition fails
    and the caller must take the general scan path."""
    import time as _time

    import pyarrow as pa
    import pyarrow.parquet as pq

    from ..common import exec_stats

    _t0 = _time.perf_counter()
    _rows_read = 0
    _bytes_read = 0
    _reduce_s = 0.0
    schema = snap._version.schema
    ts_name = schema.timestamp_column.name
    if dim == "series":
        # every file must be sid-contained too: row groups of a
        # straddling file would leak rows into the neighbor slice
        if any(f.sid_range is None or f.sid_range[0] < lo or
               f.sid_range[1] >= hi for f in files):
            return None
    sid_idxes = {}
    if sid_set is not None:
        # drop whole certified files (and then row groups) through the
        # index tier: a pruned file's rows would all be masked out by
        # the tag predicates anyway, so the lean proof still holds on
        # the subset
        from ..storage.index import prune_files
        files = prune_files(access.load_index, files, sid_set)[0]
        for meta in files:
            idx = access.load_index(meta)
            if idx is not None:
                sid_idxes[meta.file_name] = idx
    cols = list(needed_fields) + ["__series_id"]
    if need_ts:
        cols.append(ts_name)
    want_types = {}
    for name in needed_fields:
        cs = schema.column_schema(name)
        if cs.dtype.pa_type is None or cs.dtype.np_dtype is None:
            return None                      # non-numeric moment column
        want_types[name] = cs.dtype.pa_type
    frames = []
    for meta in files:
        key = access._key(meta.file_name)
        path = access.store.local_path(key)
        src = path if path is not None \
            else pa.BufferReader(access.store.read(key))
        pf = pq.ParquetFile(src)
        present = set(pf.schema_arrow.names)
        if any(c not in present for c in cols):
            return None                      # pre-ALTER file: general path
        sidx = sid_idxes.get(meta.file_name)
        # same alignment guard as read_sst: a sidecar whose group count
        # disagrees with the parquet layout (version skew) must degrade
        # to reading every group, never skip the wrong ones
        gk = sidx.row_groups_for(sid_set) \
            if sidx is not None and \
            len(sidx.rg_lo) == pf.metadata.num_row_groups else None
        for g in range(pf.metadata.num_row_groups):
            if gk is not None and not gk[g]:
                continue                     # no candidate sid in group
            # one row group at a time: the decode high-water mark stays
            # one group per prefetch worker, not the whole decoded file,
            # and each group reduces while the next one decodes
            table = pf.read_row_groups([g], columns=cols,
                                       use_threads=True)
            for batch in table.to_batches():
                nb = batch.num_rows
                if nb == 0:
                    continue
                _rows_read += nb
                _bytes_read += batch.nbytes
                data = _lean_batch(batch, schema, needed_fields,
                                   want_types, ts_name, need_ts, nb)
                if data is None:
                    return None
                _tr = _time.perf_counter()
                f = _host_partial_frame(data, None, plan, sd,
                                        sid_keys=sid_keys)
                _reduce_s += _time.perf_counter() - _tr
                if f is not None and len(f):
                    frames.append(f)
    # the lean reader bypasses read_sst, so it reports its own decode
    # stats (same stage names, so EXPLAIN ANALYZE sees one decode line)
    # stream_rows marks these decode rows as the STREAMED share (the
    # resident path's read_sst records plain decode rows too):
    # ExecStats.totals() uses it as the live rows-scanned floor while
    # stream_scan is still unpublished
    exec_stats.record("decode", rows=_rows_read, files=len(files),
                      bytes=_bytes_read, stream_rows=_rows_read,
                      elapsed_s=_time.perf_counter() - _t0 - _reduce_s)
    exec_stats.record("reduce", rows=_rows_read, elapsed_s=_reduce_s)
    return frames, _rows_read


def _lean_batch(batch, schema, needed_fields, want_types, ts_name: str,
                need_ts: bool, nb: int) -> Optional["_LeanChunk"]:
    """numpy views over one record batch; None when a column can't be
    viewed losslessly (unexpected type) and the slice must fall back."""
    import pyarrow as pa

    names = batch.schema.names
    idx = {nm: i for i, nm in enumerate(names)}
    sid_arr = batch.column(idx["__series_id"])
    sids = np.asarray(sid_arr)
    if need_ts:
        tcol = batch.column(idx[ts_name])
        if pa.types.is_timestamp(tcol.type):
            tcol = tcol.view(pa.int64())     # zero-copy reinterpret
        elif tcol.type != pa.int64():
            return None
        ts = np.asarray(tcol)
    else:
        ts = np.broadcast_to(np.int64(0), (nb,))
    fields = {}
    for name in needed_fields:
        col = batch.column(idx[name])
        if col.type != want_types[name]:
            return None
        if col.null_count:
            from ..datatypes import Vector
            vec = Vector.from_arrow(col)
            fields[name] = (vec.data, vec.validity)
        else:
            fields[name] = (np.asarray(col), None)
    return _LeanChunk(sids, ts, fields)


#: moment ops whose partials fold with a plain groupby sum/min/max —
#: first/last need their ts-companion argmin logic and stay label-keyed
_FOLDABLE_OPS = {"sum", "sum_sq", "count", "min", "max", "min_ts", "max_ts"}


def _sid_keyed(plan) -> bool:
    """Whether this region stream can key partials by series id and
    decode tag labels once after the fold, instead of decoding strings
    per batch and folding on object keys."""
    return bool(plan.tag_groups) and all(
        m.column is None or m.op in _FOLDABLE_OPS for m in plan.moments)


def _fold_sid_frames(frames: List[pd.DataFrame], plan, sd
                     ) -> List[pd.DataFrame]:
    """Intra-region fold of __sid-keyed partials (one groupby over dense
    ints — ~3x the speed of the object-string fold), then a single tag
    decode pass over the folded groups. Output frames carry the standard
    label columns, so the cross-region fold is unchanged."""
    from .planner import _group_slot

    df = pd.concat(frames, ignore_index=True) if len(frames) > 1 \
        else frames[0]
    keys = ["__sid"]
    if plan.bucket is not None:
        keys.append(_group_slot(plan.bucket.expr_key))
    aggs = {}
    for m in plan.moments:
        if m.column is None or m.op in ("sum", "sum_sq", "count"):
            aggs[m.slot] = "sum"
        elif m.op in ("min", "min_ts"):
            aggs[m.slot] = "min"
        else:
            aggs[m.slot] = "max"
    aggs["__rowcount"] = "sum"
    folded = df.groupby(keys, sort=False, as_index=False).agg(aggs)
    sids = folded["__sid"].to_numpy().astype(np.int32, copy=False)
    for tg in plan.tag_groups:
        folded[_group_slot(tg.name)] = sd.decode_tag_column(
            sids, tg.tag_index)
    return [folded.drop(columns=["__sid"])]


def _slice_dedup(data) -> Optional[np.ndarray]:
    """Kept-row indices for a slice — or None when EVERY row survives
    (append-only data, the common case), letting the caller skip the
    per-column fancy-index copies entirely.

    Skips the O(n log n) sort when the concatenated runs are already
    (sid, ts, seq)-sorted — true whenever a single SST covers the slice
    — which reduces dedup to a vectorized adjacency scan."""
    s, t, q = data.series_ids, data.ts, data.seq
    n = len(s)
    if n > 1:
        s_up = s[1:] > s[:-1]
        s_eq = s[1:] == s[:-1]
        t_up = t[1:] > t[:-1]
        t_eq = t[1:] == t[:-1]
        sorted_ok = bool(np.all(
            s_up | (s_eq & (t_up | (t_eq & (q[1:] >= q[:-1]))))))
        if sorted_ok:
            dup = s_eq & t_eq
            deletes = data.op_types != OP_PUT
            if not dup.any() and not deletes.any():
                return None                  # keep everything, zero copies
            nxt_same = np.concatenate([dup, [False]])
            keep = ~nxt_same & ~deletes
            return np.nonzero(keep)[0]
    return merge_dedup_numpy(s, t, q, data.op_types)


def _host_partial_frame(data, kept: Optional[np.ndarray], plan, sd,
                        sid_keys: bool = False
                        ) -> Optional[pd.DataFrame]:
    """One-pass vectorized host reduction of a sorted slice into the
    same partial moment frame shape `tpu_exec._collect_moment_frame`
    emits, so `_finalize` folds host and device partials identically.

    Everything is segment arithmetic over the (sid [, bucket]) run
    boundaries: `np.<ufunc>.reduceat` per moment, masks folded into the
    identity element. Runs are (sid, ts)-sorted, so first/last reduce to
    the min/max valid row index per run."""
    from .planner import _group_slot

    sids, ts = data.series_ids, data.ts
    fields = data.fields
    n = len(ts)
    if n == 0:
        return None

    # ---- base row mask (dedup + tag predicates + time/field filters) ----
    mask: Optional[np.ndarray] = None

    def and_mask(m: np.ndarray) -> None:
        nonlocal mask
        mask = m if mask is None else mask & m

    if kept is not None:
        if len(kept) > 1 and not bool(np.all(kept[1:] > kept[:-1])):
            # fallback merge-dedup: `kept` is in (sid, ts) SORT order, so
            # the arrays must be gathered before run detection — a keep
            # mask over the unsorted input would group nothing
            sids = sids[kept]
            ts = ts[kept]
            fields = {nm: (d[kept], vd[kept] if vd is not None else None)
                      for nm, (d, vd) in fields.items()}
            n = len(ts)
        else:
            km = np.zeros(n, dtype=bool)
            km[kept] = True
            and_mask(km)
    if plan.tag_predicates:
        from .expr import Evaluator
        S = sd.num_series
        tag_cols = {}
        for i, tname in enumerate(sd.tag_names):
            tag_cols[tname] = sd.decode_tag_column(
                np.arange(S, dtype=np.int32), i)
        sdf = pd.DataFrame(tag_cols)
        ev = Evaluator(sdf)
        smask = np.ones(S, dtype=bool)
        for p in plan.tag_predicates:
            m = ev.eval(p)
            m = m.fillna(False).astype(bool).to_numpy() \
                if isinstance(m, pd.Series) else np.full(S, bool(m))
            smask &= m
        if not smask.any():
            return None
        and_mask(smask[sids])
    if plan.time_lo is not None:
        and_mask(ts >= plan.time_lo)
    if plan.time_hi is not None:
        and_mask(ts < plan.time_hi)
    for ff in plan.field_filters:
        vals, valid = fields[ff.column]
        if vals.dtype == object:
            from ..errors import UnsupportedError
            raise UnsupportedError(f"filter on non-numeric {ff.column}")
        v = vals.astype(np.float64, copy=False)
        cmp = {"eq": v == ff.value, "ne": v != ff.value,
               "lt": v < ff.value, "le": v <= ff.value,
               "gt": v > ff.value, "ge": v >= ff.value}[ff.op]
        if valid is not None:
            cmp &= valid
        and_mask(cmp)
    if mask is not None and not mask.any():
        return None

    # ---- run boundaries over (sid [, bucket]) ----
    buckets = None
    if plan.bucket is not None:
        b = plan.bucket
        buckets = (ts - b.origin) // b.stride_ms
        flags = np.empty(n, dtype=bool)
        flags[0] = True
        np.not_equal(sids[1:], sids[:-1], out=flags[1:])
        flags[1:] |= buckets[1:] != buckets[:-1]
        starts = np.nonzero(flags)[0]
    elif plan.tag_groups:
        flags = np.empty(n, dtype=bool)
        flags[0] = True
        np.not_equal(sids[1:], sids[:-1], out=flags[1:])
        starts = np.nonzero(flags)[0]
    else:
        starts = np.zeros(1, dtype=np.int64)
    nruns = len(starts)

    if mask is None:
        counts = np.diff(starts, append=n).astype(np.int64)
    else:
        counts = np.add.reduceat(mask.astype(np.int64), starts)
    live = counts > 0
    if not live.any():
        return None

    f64max = np.finfo(np.float64).max
    i64max = np.iinfo(np.int64).max
    frame: Dict[str, np.ndarray] = {}
    if sid_keys:
        frame["__sid"] = sids[starts]
    else:
        for tg in plan.tag_groups:
            frame[_group_slot(tg.name)] = sd.decode_tag_column(
                sids[starts], tg.tag_index)
    if plan.bucket is not None:
        frame[_group_slot(plan.bucket.expr_key)] = \
            buckets[starts] * plan.bucket.stride_ms + plan.bucket.origin

    arange = None
    mcache: Dict[str, tuple] = {}
    for m in plan.moments:
        if m.column is None:             # plain row count
            frame[m.slot] = counts
            continue
        from .tpu_exec import SKETCH_MOMENT_OPS, moment_input, \
            sketch_run_column
        d, vd = moment_input(m, plan, fields, sids, ts, sd, cache=mcache)
        valid = vd if mask is None else (
            mask if vd is None else (vd & mask))
        if m.op in SKETCH_MOMENT_OPS:
            # per-run encoded sketch partials (distinct set / t-digest):
            # the bytes fold downstream through the codec exactly like
            # numeric moments fold through sums
            frame[m.slot] = sketch_run_column(m.op, d, valid, starts, n)
            continue
        if m.op in ("min_ts", "max_ts"):
            tsv = ts if valid is None else np.where(valid, ts, i64max
                                                    if m.op == "min_ts"
                                                    else -i64max)
            r = (np.minimum if m.op == "min_ts"
                 else np.maximum).reduceat(tsv, starts)
        elif m.op == "count":
            r = counts if valid is None or valid is mask else \
                np.add.reduceat(valid.astype(np.int64), starts)
        elif m.op in ("first", "last"):
            if arange is None:
                arange = np.arange(n, dtype=np.int64)
            if m.op == "first":
                idx = np.minimum.reduceat(
                    arange if valid is None
                    else np.where(valid, arange, n), starts)
                empty = idx >= n
            else:
                idx = np.maximum.reduceat(
                    arange if valid is None
                    else np.where(valid, arange, -1), starts)
                empty = idx < 0
            vals = d[np.clip(idx, 0, n - 1)].astype(np.float64, copy=False)
            if empty.any():
                vals = vals.copy()
                vals[empty] = np.nan
            r = vals
        else:
            dv = d.astype(np.float64, copy=False)
            if m.op == "sum":
                r = np.add.reduceat(
                    dv if valid is None else np.where(valid, dv, 0.0),
                    starts)
            elif m.op == "sum_sq":
                sq = dv * dv
                r = np.add.reduceat(
                    sq if valid is None else np.where(valid, sq, 0.0),
                    starts)
            elif m.op == "min":
                r = np.minimum.reduceat(
                    dv if valid is None else np.where(valid, dv, f64max),
                    starts)
            elif m.op == "max":
                r = np.maximum.reduceat(
                    dv if valid is None else np.where(valid, dv, -f64max),
                    starts)
            elif m.op == "reset_corr":
                # PromQL counter-reset correction: for each adjacent
                # VALID sample pair within a run where the later value
                # is smaller, the pre-reset value contributes
                # (ops/window.py: `where(pair_ok & (v < prev), prev, 0)`)
                if arange is None:
                    arange = np.arange(n, dtype=np.int64)
                runid = np.repeat(np.arange(nruns, dtype=np.int64),
                                  np.diff(starts, append=n))
                idx = arange if valid is None else np.nonzero(valid)[0]
                drop = np.zeros(n, dtype=np.float64)
                if len(idx) > 1:
                    prev_i, cur_i = idx[:-1], idx[1:]
                    hit = (runid[cur_i] == runid[prev_i]) & \
                        (dv[cur_i] < dv[prev_i])
                    drop[cur_i] = np.where(hit, dv[prev_i], 0.0)
                r = np.add.reduceat(drop, starts)
            else:  # pragma: no cover — planner only emits the ops above
                from ..errors import UnsupportedError
                raise UnsupportedError(f"host moment op {m.op!r}")
        frame[m.slot] = r
    frame["__rowcount"] = counts
    df = pd.DataFrame(frame)[live]
    return df if len(df) else None


def _load_slice(snap, dim: str, lo: int, hi: int, unit, needed_fields,
                series_dict, row_bucket_min: int,
                time_range: Optional[TimestampRange],
                plan=None, reduce: str = "device",
                sid_keys: bool = False,
                sid_set: Optional[np.ndarray] = None):
    """Read + merge + dedup one slice; reduce it on the host (returning
    partial moment frames) or prepare it for the device kernel
    (returning a padded transient MergedScan).

    Returns None for an empty slice, else a tagged
    ``(kind, payload, info)`` tuple — kind "frames" (lean chunk-frame
    path), "frame" (host-reduced general path) or "scan" (device
    MergedScan) — where `info` carries the per-slice facts the
    coordinator folds into ExecStats and Region.last_scan_profile
    (rows, lean_slices / merged_slices / dedup_skip_slices).

    `dim` selects the partition axis: "time" slices [lo, hi) on the time
    index, "series" on __series_id (with the query's time filter still
    pruning files and row groups).

    Before reading anything the slice is tested against its file
    metadata (_slice_lean_proof): when no key can have two versions the
    merge-dedup pass is skipped, and when additionally the plan never
    consults row times and every file sits fully inside the slice, the
    ts column is never decoded at all — on two-metric scans that cuts
    the decoded bytes by ~a quarter and the post-decode passes to the
    reduction itself."""
    from .tpu_exec import MergedScan

    skip_dedup = covered = False
    lean_files: list = []
    if reduce == "host" and plan is not None:
        skip_dedup, covered, lean_files = _slice_lean_proof(
            snap, dim, lo, hi, unit, time_range)
    need_ts = True
    if skip_dedup:
        need_ts = _plan_needs_ts(plan) or not covered
        if covered:
            lean = _lean_chunk_frames(
                snap, snap._region.access_layer, lean_files, dim, lo, hi,
                needed_fields, plan, series_dict, need_ts,
                sid_keys=sid_keys, sid_set=sid_set)
            if lean is not None:
                frames, rows_read = lean
                return ("frames", frames,
                        {"rows": rows_read, "lean_slices": 1,
                         "dedup_skip_slices": 1})
    if dim == "series":
        data = snap.scan(projection=needed_fields, series_range=(lo, hi),
                         time_range=time_range, sid_set=sid_set,
                         synthetic_seq=True,
                         need_ts=need_ts, need_mvcc=not skip_dedup)
    else:
        data = snap.scan(projection=needed_fields,
                         time_range=TimestampRange(lo, hi, unit),
                         sid_set=sid_set, synthetic_seq=True,
                         need_ts=need_ts, need_mvcc=not skip_dedup)
    if data.num_rows == 0:
        return None
    # the dedup-skip proof guarantees every row survives, but NOT that
    # the concatenated runs are globally (sid, ts)-sorted: two key-
    # disjoint files can share a boundary sid with non-monotonic time
    # across the concat. Decomposable moments are order-free; first/last
    # are POSITIONAL in _host_partial_frame, so they must still go
    # through _slice_dedup's sortedness check (which falls back to the
    # merge sort when the concat is out of order).
    positional = plan is not None and any(
        getattr(m, "op", None) in ("first", "last")
        for m in plan.moments if m.column is not None)
    kept = None if (skip_dedup and not positional) else _slice_dedup(data)
    info = {"rows": data.num_rows,
            "merged_slices": 0 if skip_dedup else 1,
            "dedup_skip_slices": int(skip_dedup)}
    if reduce == "host":
        return ("frame",
                _host_partial_frame(data, kept, plan, series_dict,
                                    sid_keys=sid_keys), info)
    n = data.num_rows if kept is None else len(kept)
    if n == 0:
        return None

    # pad to a shape bucket so every slice shares one XLA compile; padded
    # rows repeat the last (sid, ts) — they extend the final run, stay
    # sorted, and are masked out via valid_rows. take + device-dtype cast
    # + pad fuse into ONE pass per column (each was a full copy).
    import jax
    x64 = jax.config.jax_enable_x64
    target = shape_bucket(n, minimum=row_bucket_min)

    def prepare(a, dtype=None, pad_fill=None):
        dtype = dtype or a.dtype
        if kept is None and target == n and a.dtype == dtype:
            return a
        out = np.empty(target, dtype)
        if kept is None:
            out[:n] = a
        elif a.dtype == dtype:
            np.take(a, kept, out=out[:n])
        else:
            out[:n] = a[kept]
        if target != n:
            out[n:] = pad_fill if pad_fill is not None else out[n - 1]
        return out

    sids = prepare(data.series_ids, np.int32)
    ts = prepare(data.ts)
    fields = {}
    for name, (d, vd) in data.fields.items():
        if d.dtype == object:
            d2 = d if kept is None else d[kept]
            if target != n:
                d2 = np.concatenate(
                    [d2, np.full(target - n, None, dtype=object)])
        else:
            want = np.float32 if d.dtype == np.float64 and not x64 \
                else d.dtype
            d2 = prepare(d, want)
        v2 = prepare(vd, np.bool_, pad_fill=False) \
            if vd is not None else None
        fields[name] = (d2, v2)
    base = int(ts[:n].min())
    scan = MergedScan(sids, ts, fields, series_dict, base)
    scan.valid_rows = n if target != n else None
    # start the H2D transfers here, on the prefetch thread: device_put is
    # asynchronous, so the copies stream while the next slice decodes and
    # the launch thread stays free for mask/run construction. Only dtypes
    # device_put maps 1:1 are staged — int64 fields keep device_field's
    # narrowing logic; anything else falls back to lazy upload at launch.
    try:
        rel = ts - base
        if not rel.size or int(rel.max()) < 2 ** 31:
            scan.device["__ts"] = jax.device_put(rel.astype(np.int32))
        for name, (d2, v2) in fields.items():
            if d2.dtype in (np.float32, np.bool_, np.int32) or \
                    (d2.dtype == np.float64 and x64):
                scan.device[f"f:{name}"] = jax.device_put(d2)
            if v2 is not None:
                scan.device[f"v:{name}"] = jax.device_put(v2)
        if target != n:
            pm = np.zeros(target, np.bool_)
            pm[:n] = True
            scan.device["__pad_mask"] = jax.device_put(pm)
    except Exception:  # noqa: BLE001 — staging is an optimization; the
        # host arrays still serve the scan
        from ..common.telemetry import increment_counter
        increment_counter("stream_device_stage_errors")
        scan.device.clear()
    return ("scan", scan, info)


def stream_region_moment_frames(region, table, plan) -> List[pd.DataFrame]:
    """Partial moment frames for one region via slice streaming.

    Returns the same frame shape tpu_exec._execute_region produces, so
    tpu_exec._finalize folds slices exactly like regions.

    Pipelining: XLA dispatch is asynchronous, so each slice's reduction
    is *launched* and left in flight while the next slice decodes on the
    prefetch thread; device results are fetched in ONE round trip at the
    end (per-slice fetches would each pay the device-link latency, which
    dominates on tunneled chips). Only run-level context is kept per
    launched slice — full slice arrays are freed as the pipeline advances.

    Observability: publishes a stage breakdown to
    `region.last_scan_profile` (the scan twin of the ingest profiler)
    and mirrors the same numbers into the active ExecStats collector so
    EXPLAIN ANALYZE, the profile, and the tracing spans agree.
    """
    import time as _time

    import jax

    from ..common import exec_stats
    from ..common.telemetry import propagate, span
    from ..storage.region import ScanProfile
    from .tpu_exec import _collect_moment_frame, _launch_scan_kernel

    prof = ScanProfile(path="streamed")
    _t_start = _time.perf_counter()
    snap = region.snapshot()
    schema = snap.schema
    tc = schema.timestamp_column
    unit = tc.dtype.time_unit if tc is not None else None
    stats = _region_slice_stats(region, snap, unit)
    jobs = _plan_jobs(stats, _SLICE_ROWS[0], plan.time_lo, plan.time_hi,
                      unit) if stats else []
    prof.mark("slice_plan", _time.perf_counter() - _t_start)
    prof.bump("slices", len(jobs))
    exec_stats.record("slice_plan", elapsed_s=prof.stages["slice_plan"],
                      slices=len(jobs))
    if not jobs:
        prof.total_s = _time.perf_counter() - _t_start
        region.last_scan_profile = prof
        return []
    from .tpu_exec import plan_needs_host, plan_scan_columns
    needed = plan_scan_columns(plan, schema)
    sd = region.series_dict

    # point/IN tag conjuncts resolve to a candidate sid set so every
    # slice prunes SSTs through their index sidecars before decoding
    # (superset semantics: the per-slice reductions still apply the
    # full predicate set)
    sid_set = None
    if plan.tag_predicates and sd is not None and sd.tag_names:
        from ..storage.index import sst_index_enabled
        if sst_index_enabled():
            from ..mito.engine import sid_candidates_for_filters
            sid_set = sid_candidates_for_filters(sd, sd.tag_names,
                                                 plan.tag_predicates)
            if sid_set is not None and len(sid_set) == 0:
                # the point predicate matches no series of this region
                prof.total_s = _time.perf_counter() - _t_start
                region.last_scan_profile = prof
                return []

    mode = _COLD_REDUCE[0]
    if plan_needs_host(plan):
        # sketch / expression moments have no device kernel: every
        # slice reduces on the host (same partial-frame algebra)
        mode = "host"
    sid_keys = mode == "host" and _sid_keyed(plan)
    launched = []
    frames: List[pd.DataFrame] = []
    # two-deep prefetch: decode slices i+1, i+2 while slice i launches
    # (decode is the cold-path bottleneck; two workers keep parquet
    # threads busy without unbounded slice residency). propagate()
    # carries the trace context + ExecStats collector into the workers.
    depth = 2
    _t_stream = _time.perf_counter()
    load = propagate(_load_slice)
    from ..common.runtime import transient_executor
    from ..common import failpoint, process_list
    with span("stream_scan", region=region.name, slices=len(jobs),
              mode=mode), \
            transient_executor(depth, "stream-scan") as pool:
        futs = [pool.submit(load, snap, dim, lo, hi, unit, needed,
                            sd, _ROW_BUCKET_MIN, clip, plan, mode,
                            sid_keys, sid_set)
                for dim, lo, hi, clip in jobs[:depth]]
        try:
            for i in range(len(jobs)):
                # cooperative KILL at the slice boundary: prefetched
                # slices are cancelled in the finally, so a killed scan
                # releases its workers within one slice
                process_list.check_cancelled()
                failpoint.fail_point("stream_slice")
                res = futs[i].result()
                if i + depth < len(jobs):
                    dim, lo, hi, clip = jobs[i + depth]
                    futs.append(pool.submit(
                        load, snap, dim, lo, hi, unit, needed,
                        sd, _ROW_BUCKET_MIN, clip, plan, mode, sid_keys,
                        sid_set))
                futs[i] = None               # free the slice as we go
                if res is None:
                    prof.bump("empty_slices")
                    continue
                kind, payload, info = res
                prof.rows += info.get("rows", 0)
                for k in ("lean_slices", "merged_slices",
                          "dedup_skip_slices"):
                    if info.get(k):
                        prof.bump(k, info[k])
                if kind == "frames":
                    frames.extend(payload)
                    continue
                if kind == "frame":
                    if payload is not None and len(payload):
                        frames.append(payload)
                    continue
                prof.bump("device_slices")
                ln = _launch_scan_kernel(payload, schema, plan)
                if ln is not None:
                    launched.append(ln)
                del payload, res
        finally:
            # a raise (KILL, failed slice) must not leave prefetched
            # slices occupying the pool: unstarted futures cancel now,
            # the `with` shutdown then only waits for the ≤depth running
            for f in futs:
                if f is not None:
                    f.cancel()
    prof.mark("decode_reduce", _time.perf_counter() - _t_stream)
    _publish_stream_stats(prof)
    if sid_keys and frames:
        _t_fold = _time.perf_counter()
        frames = _fold_sid_frames(frames, plan, sd)
        prof.mark("fold", _time.perf_counter() - _t_fold)
        exec_stats.record("fold", elapsed_s=prof.stages["fold"])
    if not launched:
        prof.total_s = _time.perf_counter() - _t_start
        region.last_scan_profile = prof
        return frames
    # overlap the D2H copies: fetch every per-slice array concurrently —
    # a sequential device_get pays the (tunneled) device-link round-trip
    # latency once per array, which dominates for these small partials
    _t_fetch = _time.perf_counter()
    flat: List = []
    for ln in launched:
        flat.append(ln.counts)
        flat.extend(ln.results)
    from ..common.telemetry import increment_counter
    for arr in flat:
        if hasattr(arr, "copy_to_host_async"):
            try:
                arr.copy_to_host_async()
            except Exception:  # noqa: BLE001 — async staging is optional;
                # the blocking np.asarray below fetches regardless
                increment_counter("stream_async_fetch_errors")
                break
    from ..common.runtime import parallel_map
    flat_np = parallel_map(np.asarray, flat,
                           max_workers=min(8, len(flat)))
    fetched = []
    pos = 0
    for ln in launched:
        k = len(ln.results)
        fetched.append((flat_np[pos], flat_np[pos + 1:pos + 1 + k]))
        pos += 1 + k
    for ln, (counts, res_np) in zip(launched, fetched):
        part = _collect_moment_frame(ln, plan, counts, res_np)
        if part is not None and len(part):
            frames.append(part)
    prof.mark("device_fetch", _time.perf_counter() - _t_fetch)
    exec_stats.record("device_fetch", elapsed_s=prof.stages["device_fetch"])
    prof.total_s = _time.perf_counter() - _t_start
    region.last_scan_profile = prof
    return frames


def _publish_stream_stats(prof) -> None:
    """Mirror a streamed region's profile into the ExecStats collector
    (stream_scan row) and prometheus counters, so EXPLAIN ANALYZE,
    /metrics and Region.last_scan_profile tell one story."""
    from ..common import exec_stats
    from ..common.telemetry import increment_counter
    exec_stats.record(
        "stream_scan", rows=prof.rows,
        elapsed_s=prof.stages.get("decode_reduce", 0.0),
        **{k: v for k, v in prof.counters.items() if v})
    for k in ("lean_slices", "merged_slices", "dedup_skip_slices"):
        n = prof.counters.get(k, 0)
        if n:
            increment_counter(f"stream_{k}", n)
