"""Sketch partials for distributed aggregation (ISSUE 14).

The partial-state algebra of `tpu_exec` ships decomposable *moments*
(sum/count/min/max/...) so distributed GROUP BY never moves raw rows.
Two aggregate families break that algebra — ``count(DISTINCT x)`` and
percentiles — because their exact state is the whole value set. This
module supplies mergeable sketch partials for both, the reference shape
being DataFusion's ``approx_distinct`` (HyperLogLog) and
``approx_percentile_cont`` (t-digest) accumulators:

- :class:`DistinctSketch` — exact value set below a bounded size (the
  partial IS the deduplicated value set, so small-cardinality
  ``count(DISTINCT)`` stays exact end to end), degrading to a dense
  HyperLogLog past the bound (documented standard error
  ``1.04/sqrt(2^p)`` ≈ 0.8% at the default p=14). ``SET
  exact_distinct = 1`` refuses the sketch path entirely and forces the
  raw-row fallback.
- :class:`TDigest` — Dunning's merging t-digest with the k1
  (arcsin) scale function; rank error ≈ ``1/delta`` near the median
  and tighter in the tails (default delta=200 → well under 1% on p95).

Both are associative and commutative under :meth:`merge`, so datanodes
build per-group sketches, slices fold into regions, regions into the
statement — the exact same fold tree the numeric moments ride.

Wire codec: ``encode_sketch`` / ``decode_sketch`` frame every partial as
``magic + version + type + payload + crc32``. A corrupt or truncated
frame raises the typed :class:`~greptimedb_tpu.errors.SketchCodecError`
(never a wrong answer): the frontend counts
``greptime_sketch_degrade_total`` and retries the statement through the
raw-row path. The ``sketch_codec`` failpoint injects exactly that.
"""

from __future__ import annotations

import struct
import zlib
from typing import List, Optional, Union

import numpy as np

from ..common.failpoint import fail_point, register as _fp_register
from ..errors import InvalidArgumentsError, SketchCodecError
from ..utils import env_flag

_fp_register("sketch_codec")

# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

#: SET exact_distinct = 1 — refuse sketch partials for count(DISTINCT)
#: and take the raw-row path (exact at any cardinality, full wire cost)
_EXACT_DISTINCT = [env_flag("GREPTIME_EXACT_DISTINCT", False)]

#: per-group value-set bound below which count(DISTINCT) partials stay
#: an exact set; past it the partial degrades to HLL
EXACT_SET_LIMIT = 4096

#: SET approx_error_target — drives the HLL precision p
#: (1.04/sqrt(2^p) <= target) and the t-digest compression
#: (delta ~ 1/target); default 0.01
_ERROR_TARGET = [0.01]
_HLL_P = [14]
_TDIGEST_DELTA = [200.0]


def configure(*, exact_distinct: Optional[bool] = None,
              error_target: Optional[float] = None) -> None:
    """SET exact_distinct / approx_error_target."""
    if exact_distinct is not None:
        _EXACT_DISTINCT[0] = bool(exact_distinct)
    if error_target is not None:
        t = float(error_target)
        if not (0.001 <= t <= 0.25):
            raise InvalidArgumentsError(
                f"approx_error_target must be in [0.001, 0.25], got {t}")
        _ERROR_TARGET[0] = t
        # HLL standard error is 1.04/sqrt(m), m = 2^p
        p = int(np.ceil(2 * np.log2(1.04 / t)))
        _HLL_P[0] = min(16, max(6, p))
        _TDIGEST_DELTA[0] = min(1000.0, max(50.0, 2.0 / t))


def exact_distinct_forced() -> bool:
    return _EXACT_DISTINCT[0]


def error_target() -> float:
    return _ERROR_TARGET[0]


def hll_precision() -> int:
    return _HLL_P[0]


def tdigest_delta() -> float:
    return _TDIGEST_DELTA[0]


# ---------------------------------------------------------------------------
# hashing (process-stable: sketches merge across processes and restarts)
# ---------------------------------------------------------------------------

_SPLITMIX_C1 = np.uint64(0xBF58476D1CE4E5B9)
_SPLITMIX_C2 = np.uint64(0x94D049BB133111EB)
_SPLITMIX_GAMMA = np.uint64(0x9E3779B97F4A7C15)


def hash64(values: np.ndarray) -> np.ndarray:
    """Stable vectorized 64-bit hash. Numeric arrays hash their int64
    bit pattern through splitmix64; object arrays (strings) hash utf-8
    bytes through crc32 pairs folded into the same finalizer — never
    Python's seeded hash()."""
    a = np.asarray(values)
    if a.dtype == object or a.dtype.kind in "US":
        out = np.empty(len(a), dtype=np.uint64)
        for i, v in enumerate(a):
            b = str(v).encode("utf-8")
            out[i] = (zlib.crc32(b) << np.uint64(32)) | np.uint64(
                zlib.crc32(b, 0x9E3779B9))
        x = out
    else:
        if a.dtype.kind == "f":
            # canonicalize: -0.0 == 0.0 and all NaNs hash alike (callers
            # drop NaN-nulls before hashing, this is belt and braces)
            a = np.asarray(a, dtype=np.float64) + 0.0
            x = a.view(np.uint64).copy()
        else:
            x = a.astype(np.int64).view(np.uint64).copy()
    with np.errstate(over="ignore"):
        x = (x + _SPLITMIX_GAMMA)
        x ^= x >> np.uint64(30)
        x *= _SPLITMIX_C1
        x ^= x >> np.uint64(27)
        x *= _SPLITMIX_C2
        x ^= x >> np.uint64(31)
    return x


# ---------------------------------------------------------------------------
# HyperLogLog (dense registers)
# ---------------------------------------------------------------------------

class HyperLogLog:
    """Dense HLL over 64-bit hashes: 2^p uint8 registers; standard
    bias-corrected estimate with linear-counting small-range correction
    (the Flajolet et al. estimator DataFusion's approx_distinct uses)."""

    __slots__ = ("p", "registers")

    def __init__(self, p: Optional[int] = None,
                 registers: Optional[np.ndarray] = None):
        self.p = int(p if p is not None else _HLL_P[0])
        if not (4 <= self.p <= 18):
            raise InvalidArgumentsError(f"HLL precision {self.p}")
        m = 1 << self.p
        if registers is not None:
            if len(registers) != m:
                raise SketchCodecError(
                    f"HLL register count {len(registers)} != 2^{self.p}")
            self.registers = np.asarray(registers, dtype=np.uint8)
        else:
            self.registers = np.zeros(m, dtype=np.uint8)

    def add_hashes(self, h: np.ndarray) -> None:
        if len(h) == 0:
            return
        h = np.asarray(h, dtype=np.uint64)
        idx = (h >> np.uint64(64 - self.p)).astype(np.int64)
        # rank = leading-zero count of the remaining 64-p bits, + 1
        rest = (h << np.uint64(self.p)) | np.uint64((1 << self.p) - 1)
        rank = np.zeros(len(h), dtype=np.uint8)
        probe = np.uint64(1) << np.uint64(63)
        live = np.ones(len(h), dtype=bool)
        for r in range(1, 64 - self.p + 2):
            hit = live & ((rest & probe) != 0)
            rank[hit] = r
            live &= ~hit
            if not live.any():
                break
            probe >>= np.uint64(1)
        np.maximum.at(self.registers, idx, rank)

    def merge(self, other: "HyperLogLog") -> "HyperLogLog":
        if other.p != self.p:
            # precision changed mid-flight (SET approx_error_target):
            # fold the coarser way — rebuild at the smaller p by
            # folding register groups with max
            p = min(self.p, other.p)
            a, b = self._fold_to(p), other._fold_to(p)
            a.registers = np.maximum(a.registers, b.registers)
            return a
        self.registers = np.maximum(self.registers, other.registers)
        return self

    def _fold_to(self, p: int) -> "HyperLogLog":
        if p == self.p:
            out = HyperLogLog(p)
            out.registers = self.registers.copy()
            return out
        # max-fold is an upper-bound approximation of re-hashing; the
        # mid-statement precision change is a degenerate operator case
        m = 1 << p
        folded = self.registers.reshape(m, -1).max(axis=1)
        return HyperLogLog(p, folded)

    def estimate(self) -> float:
        m = float(len(self.registers))
        regs = self.registers.astype(np.float64)
        est = _hll_alpha(int(m)) * m * m / np.sum(np.power(2.0, -regs))
        if est <= 2.5 * m:
            zeros = int(np.count_nonzero(self.registers == 0))
            if zeros:
                return m * np.log(m / zeros)   # linear counting
        return float(est)

    def result(self) -> int:
        return int(round(self.estimate()))


def _hll_alpha(m: int) -> float:
    if m <= 16:
        return 0.673
    if m <= 32:
        return 0.697
    if m <= 64:
        return 0.709
    return 0.7213 / (1.0 + 1.079 / m)


# ---------------------------------------------------------------------------
# distinct sketch: exact value set below the bound, HLL past it
# ---------------------------------------------------------------------------

class DistinctSketch:
    """count(DISTINCT) partial. ``values`` is the exact deduplicated
    value set (numeric ndarray or list of strings) while it fits under
    ``EXACT_SET_LIMIT``; ``hll`` takes over past the bound. NULLs are
    excluded by the caller (SQL count distinct ignores them)."""

    __slots__ = ("values", "hll")

    def __init__(self, values=None, hll: Optional[HyperLogLog] = None):
        self.values = values
        self.hll = hll

    @property
    def exact(self) -> bool:
        return self.hll is None

    @classmethod
    def from_values(cls, values: np.ndarray) -> "DistinctSketch":
        a = np.asarray(values)
        if a.dtype == object or a.dtype.kind in "US":
            uniq = sorted({str(v) for v in a if v is not None})
            sk = cls(values=uniq)
        else:
            if a.dtype.kind == "f":
                a = a[~np.isnan(a)] + 0.0    # drop NaN, fold -0.0
            sk = cls(values=np.unique(a))
        if len(sk.values) > EXACT_SET_LIMIT:
            sk._degrade()
        return sk

    def _degrade(self) -> None:
        from ..common.telemetry import increment_counter
        hll = HyperLogLog()
        if isinstance(self.values, list):
            hll.add_hashes(hash64(np.asarray(self.values, dtype=object)))
        else:
            hll.add_hashes(hash64(self.values))
        self.values = None
        self.hll = hll
        increment_counter("distinct_exact_to_hll")

    def merge(self, other: "DistinctSketch") -> "DistinctSketch":
        if self.exact and other.exact:
            if isinstance(self.values, list) or isinstance(other.values,
                                                           list):
                a = self.values if isinstance(self.values, list) \
                    else [str(v) for v in self.values]
                b = other.values if isinstance(other.values, list) \
                    else [str(v) for v in other.values]
                self.values = sorted(set(a) | set(b))
            else:
                self.values = np.union1d(self.values, other.values)
            if len(self.values) > EXACT_SET_LIMIT:
                self._degrade()
            return self
        if self.exact:
            self._degrade()
        if other.exact:
            other = DistinctSketch(values=other.values)
            other._degrade()
        self.hll = self.hll.merge(other.hll)
        return self

    def result(self) -> int:
        if self.exact:
            return len(self.values)
        return self.hll.result()


# ---------------------------------------------------------------------------
# merging t-digest (Dunning), k1 / arcsin scale function
# ---------------------------------------------------------------------------

class TDigest:
    """Weighted centroids (mean-sorted) + an unmerged buffer; compress
    merges adjacent centroids while the k1 scale function's q-width
    budget holds, keeping centroid count O(delta) regardless of input
    size. merge() is buffer concatenation + compress, so digests fold
    across slices/regions/datanodes like any moment."""

    __slots__ = ("delta", "means", "weights", "_buf_means", "_buf_weights")

    def __init__(self, delta: Optional[float] = None,
                 means: Optional[np.ndarray] = None,
                 weights: Optional[np.ndarray] = None):
        self.delta = float(delta if delta is not None else _TDIGEST_DELTA[0])
        self.means = np.asarray(means, dtype=np.float64) \
            if means is not None else np.empty(0, np.float64)
        self.weights = np.asarray(weights, dtype=np.float64) \
            if weights is not None else np.empty(0, np.float64)
        self._buf_means: List[np.ndarray] = []
        self._buf_weights: List[np.ndarray] = []

    @classmethod
    def from_values(cls, values: np.ndarray) -> "TDigest":
        d = cls()
        d.add(values)
        d.compress()
        return d

    def add(self, values: np.ndarray) -> None:
        v = np.asarray(values, dtype=np.float64)
        v = v[~np.isnan(v)]
        if len(v):
            self._buf_means.append(v)
            self._buf_weights.append(np.ones(len(v), np.float64))

    def merge(self, other: "TDigest") -> "TDigest":
        if len(other.means):
            self._buf_means.append(other.means)
            self._buf_weights.append(other.weights)
        self._buf_means.extend(other._buf_means)
        self._buf_weights.extend(other._buf_weights)
        self.delta = max(self.delta, other.delta)
        self.compress()
        return self

    def _k(self, q: np.ndarray) -> np.ndarray:
        return (self.delta / (2 * np.pi)) * np.arcsin(
            np.clip(2 * q - 1, -1.0, 1.0))

    def compress(self) -> None:
        """Vectorized k-cell compression: sort points/centroids by
        mean, map each midpoint quantile through the k1 scale, and
        merge everything sharing a k-cell (floor(k)) with one reduceat
        pass — every cluster's k-width stays <= 1, the t-digest
        invariant, with no per-point Python loop."""
        if not self._buf_means and len(self.means) <= self.delta * 3:
            return
        means = np.concatenate([self.means] + self._buf_means) \
            if self._buf_means else self.means
        weights = np.concatenate([self.weights] + self._buf_weights) \
            if self._buf_weights else self.weights
        self._buf_means, self._buf_weights = [], []
        if len(means) == 0:
            return
        order = np.argsort(means, kind="stable")
        means, weights = means[order], weights[order]
        total = float(weights.sum())
        qmid = (np.cumsum(weights) - weights / 2.0) / total
        cell = np.floor(self._k(qmid)).astype(np.int64)
        starts_mask = np.empty(len(cell), dtype=bool)
        starts_mask[0] = True
        np.not_equal(cell[1:], cell[:-1], out=starts_mask[1:])
        starts = np.nonzero(starts_mask)[0]
        w = np.add.reduceat(weights, starts)
        m = np.add.reduceat(means * weights, starts) / w
        self.means = m
        self.weights = w

    @property
    def count(self) -> float:
        n = float(self.weights.sum()) if len(self.weights) else 0.0
        for w in self._buf_weights:
            n += float(w.sum())
        return n

    def quantile(self, q: float) -> Optional[float]:
        """Value at quantile q in [0, 100] (SQL percentile convention),
        interpolated through centroid midpoints."""
        self.compress()
        if not len(self.means):
            return None
        q = float(q) / 100.0
        if len(self.means) == 1:
            return float(self.means[0])
        total = float(self.weights.sum())
        target = q * total
        # cumulative weight at each centroid's MIDPOINT
        cum = np.cumsum(self.weights) - self.weights / 2.0
        if target <= cum[0]:
            return float(self.means[0])
        if target >= cum[-1]:
            return float(self.means[-1])
        i = int(np.searchsorted(cum, target) - 1)
        span = cum[i + 1] - cum[i]
        frac = (target - cum[i]) / span if span > 0 else 0.0
        return float(self.means[i] + frac * (self.means[i + 1] -
                                             self.means[i]))


# ---------------------------------------------------------------------------
# wire codec: magic + version + type + payload + crc32
# ---------------------------------------------------------------------------

_MAGIC = b"GSK"
_VERSION = 1
_T_DISTINCT_NUM = 1
_T_DISTINCT_STR = 2
_T_DISTINCT_HLL = 3
_T_TDIGEST = 4

Sketch = Union[DistinctSketch, TDigest]


def encode_sketch(sk: Sketch) -> bytes:
    """Versioned + crc32'd frame for one sketch partial."""
    if isinstance(sk, TDigest):
        sk.compress()
        payload = struct.pack("<dI", sk.delta, len(sk.means)) + \
            sk.means.astype("<f8").tobytes() + \
            sk.weights.astype("<f8").tobytes()
        body = _MAGIC + bytes([_VERSION, _T_TDIGEST]) + payload
    elif isinstance(sk, DistinctSketch):
        if not sk.exact:
            payload = bytes([sk.hll.p]) + sk.hll.registers.tobytes()
            body = _MAGIC + bytes([_VERSION, _T_DISTINCT_HLL]) + payload
        elif isinstance(sk.values, list):
            parts = [struct.pack("<I", len(sk.values))]
            for s in sk.values:
                b = s.encode("utf-8")
                parts.append(struct.pack("<I", len(b)))
                parts.append(b)
            body = _MAGIC + bytes([_VERSION, _T_DISTINCT_STR]) + \
                b"".join(parts)
        else:
            a = np.asarray(sk.values)
            tag = b"i" if a.dtype.kind in "iu" else b"f"
            arr = a.astype("<i8") if tag == b"i" else a.astype("<f8")
            payload = tag + struct.pack("<I", len(arr)) + arr.tobytes()
            body = _MAGIC + bytes([_VERSION, _T_DISTINCT_NUM]) + payload
    else:
        raise SketchCodecError(f"cannot encode {type(sk).__name__}")
    return body + struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF)


def decode_sketch(data: bytes) -> Sketch:
    """Decode one sketch frame; raises SketchCodecError on any corrupt,
    truncated or version-skewed frame — a bad partial must surface as a
    typed error (the statement retries raw), never a wrong answer."""
    try:
        fail_point("sketch_codec")
    except Exception as e:
        # the failpoint models a corrupt frame off the wire: it must
        # surface as the SAME typed error real corruption raises, so
        # the degrade path under test IS the production path
        raise SketchCodecError(f"injected sketch corruption: {e}") from e
    if not isinstance(data, (bytes, bytearray, memoryview)):
        raise SketchCodecError(
            f"sketch frame is {type(data).__name__}, not bytes")
    data = bytes(data)
    if len(data) < len(_MAGIC) + 2 + 4:
        raise SketchCodecError(f"truncated sketch frame ({len(data)}B)")
    body, crc_raw = data[:-4], data[-4:]
    (crc,) = struct.unpack("<I", crc_raw)
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise SketchCodecError("sketch frame crc mismatch")
    if body[:3] != _MAGIC:
        raise SketchCodecError("bad sketch magic")
    version, kind = body[3], body[4]
    if version != _VERSION:
        raise SketchCodecError(f"unsupported sketch codec version "
                               f"{version} (expected {_VERSION})")
    payload = body[5:]
    try:
        if kind == _T_TDIGEST:
            delta, n = struct.unpack_from("<dI", payload, 0)
            off = 12
            need = off + 16 * n
            if len(payload) < need:
                raise SketchCodecError("truncated t-digest payload")
            means = np.frombuffer(payload, "<f8", n, off)
            weights = np.frombuffer(payload, "<f8", n, off + 8 * n)
            return TDigest(delta, means.copy(), weights.copy())
        if kind == _T_DISTINCT_HLL:
            p = payload[0]
            regs = np.frombuffer(payload, np.uint8, offset=1)
            return DistinctSketch(hll=HyperLogLog(p, regs.copy()))
        if kind == _T_DISTINCT_NUM:
            tag = payload[:1]
            (n,) = struct.unpack_from("<I", payload, 1)
            if len(payload) < 5 + 8 * n:
                raise SketchCodecError("truncated distinct payload")
            dt = "<i8" if tag == b"i" else "<f8"
            vals = np.frombuffer(payload, dt, n, 5)
            return DistinctSketch(values=vals.copy())
        if kind == _T_DISTINCT_STR:
            (n,) = struct.unpack_from("<I", payload, 0)
            off = 4
            vals: List[str] = []
            for _ in range(n):
                (ln,) = struct.unpack_from("<I", payload, off)
                off += 4
                vals.append(payload[off:off + ln].decode("utf-8"))
                off += ln
            return DistinctSketch(values=vals)
    except SketchCodecError:
        raise
    except Exception as e:
        raise SketchCodecError(f"corrupt sketch payload: {e}") from e
    raise SketchCodecError(f"unknown sketch type {kind}")
