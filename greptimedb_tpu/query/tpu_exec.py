"""The TPU aggregate fast path.

Executes the canonical time-series shape — scan → filter → group by tags
and/or time bucket → aggregate — as one device kernel pass per region:

1. per-region merged scan (sorted by (series, ts), MVCC-deduped) from a
   version-keyed cache; arrays are device-resident across queries until the
   region version changes (the HBM-resident memtable design of SURVEY §7);
2. group ids are contiguous run ids over (series, bucket) — sorted by
   construction, so the scatter-free sorted-segment kernel applies;
3. the kernel computes decomposable *moments* (sum/sum_sq/count/min/max/
   first+ts/last+ts) per run; runs fold into final SQL groups on the host
   (tiny), which also merges partials across regions.

Anything outside this shape returns None and the engine falls back to the
CPU columnar executor — the same division of labor the reference has
between its pushed-down scans and DataFusion.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from ..errors import UnsupportedError
from ..ops.kernels import merge_dedup_numpy, shape_bucket, sorted_grouped_aggregate
from ..sql.ast import (
    Between, BinaryOp, Column, Expr, FunctionCall, InList, Interval, IsNull,
    Literal, Query, UnaryOp,
)
from ..common.failpoint import register as _fp_register
from .expr import Evaluator, expr_name
from .functions import SKETCH_AGGREGATES, TPU_AGGREGATES, parse_interval_ms
from .planner import Analysis, _group_slot

_fp_register("scan_cache_incremental")

_CMP_OPS = {"=": "eq", "!=": "ne", "<": "lt", "<=": "le", ">": "gt",
            ">=": "ge"}


# ---------------------------------------------------------------------------
# merged-scan cache (per region version)
# ---------------------------------------------------------------------------

@dataclass
class MergedScan:
    series_ids: np.ndarray            # int32, sorted
    ts: np.ndarray                    # int64 epoch (region units)
    fields: Dict[str, Tuple[np.ndarray, Optional[np.ndarray]]]
    series_dict: object
    ts_base: int                      # device ts = ts - ts_base (int32)
    seq: Optional[np.ndarray] = None  # per-row sequence (incremental merge)
    device: Dict[str, object] = field(default_factory=dict)
    #: rows beyond this index are shape-bucket padding (streamed slices
    #: pad to shared XLA shapes); None = every row is real
    valid_rows: Optional[int] = None

    @property
    def num_rows(self) -> int:
        return len(self.ts)

    def device_ts(self):
        import jax
        if "__ts" not in self.device:
            rel = self.ts - self.ts_base
            if rel.size and (rel.max() >= 2**31 or rel.min() < 0):
                raise UnsupportedError("region time span exceeds int32")
            self.device["__ts"] = jax.device_put(rel.astype(np.int32))
        return self.device["__ts"]

    def device_field(self, name: str):
        import jax
        key = f"f:{name}"
        if key not in self.device:
            vals, valid = self.fields[name]
            if vals.dtype == object:
                raise UnsupportedError(f"field {name} is not numeric")
            import jax as _jax
            v = vals
            x64 = _jax.config.jax_enable_x64
            if v.dtype == np.int64 and not x64:
                v = v.astype(np.float64) if abs(v).max(initial=0) >= 2**31 \
                    else v.astype(np.int32)
            if v.dtype == np.float64 and not x64:
                # TPU has no f64: the device mirrors are f32 (documented
                # precision tradeoff); with x64 on (CPU) keep full precision
                v = v.astype(np.float32)
            self.device[key] = jax.device_put(np.ascontiguousarray(v))
        return self.device[key]

    def device_valid(self, name: str):
        import jax
        key = f"v:{name}"
        if key not in self.device:
            _, valid = self.fields[name]
            if valid is None:
                return self.device_valid_all()
            self.device[key] = jax.device_put(valid)
        return self.device[key]

    def device_valid_all(self):
        import jax
        if "__all_valid" not in self.device:
            self.device["__all_valid"] = jax.device_put(
                np.ones(self.num_rows, dtype=bool))
        return self.device["__all_valid"]

    @property
    def nbytes(self) -> int:
        """Host + device residency of this scan (cache accounting)."""
        total = self.series_ids.nbytes + self.ts.nbytes
        if self.seq is not None:
            total += self.seq.nbytes
        for vals, valid in self.fields.values():
            total += getattr(vals, "nbytes", 8 * len(vals))
            if valid is not None:
                total += valid.nbytes
        for v in self.device.values():
            if isinstance(v, tuple):     # cached run-boundary context
                total += sum(getattr(x, "nbytes", 0) for x in v)
            else:
                total += getattr(v, "nbytes", 0)
        return total


@dataclass
class _CacheEntry:
    scan: MergedScan
    visible: int                      # sequences <= visible are merged in
    sst_names: frozenset              # SSTs whose content is merged in
    schema_version: int
    retraction_epoch: int


class _ScanCache:
    """Per-region merged-scan cache: byte-budget LRU + incremental
    maintenance.

    On a version bump the cache merges only the *delta* — memtable rows
    with sequences beyond the cached watermark plus SSTs that carry such
    rows — into the cached sorted arrays, instead of re-reading and
    re-sorting the whole region (VERDICT round-1 weakness 5: scan prep
    must be proportional to new data, not region size). Flushes and
    compactions whose files only contain already-covered sequences reuse
    the cache as-is; TTL retraction (region.retraction_epoch) and schema
    changes force a full rebuild.

    Residency is bounded by a byte budget across regions (host arrays +
    device mirrors): whole MergedScans evict LRU-first — never partially —
    so a server hosting many hot regions can't grow HBM without bound
    (VERDICT round-3 weakness 5). The newest entry always stays, even
    when it alone exceeds the budget (regions that large should be
    routed to the streaming path by region_moment_frames anyway)."""

    def __init__(self, capacity: int = 16,
                 budget_bytes: int = 4 << 30):
        self.capacity = capacity
        self.budget_bytes = budget_bytes
        from ..common.locks import TrackedLock
        from ..common.tracking import tracked_state
        self._lock = TrackedLock("query.scan_cache")
        self._entries: Dict[str, _CacheEntry] = tracked_state(
            {}, "query.scan_cache.entries")          # insertion = LRU order
        # per-thread outcome of the most recent get(): "hit" /
        # "incremental" / "full" — read by the resident scan profiler
        self._last = threading.local()

    def last_outcome(self) -> Optional[str]:
        return getattr(self._last, "outcome", None)

    def get(self, region) -> MergedScan:
        from ..common.telemetry import increment_counter
        snap = region.snapshot()
        v = snap._version
        visible = snap.visible_sequence
        sst_names = frozenset(f.file_name for f in v.ssts.all_files())
        epoch = getattr(region, "retraction_epoch", 0)
        with self._lock:
            entry = self._entries.get(region.uid)
            if entry is not None:                    # LRU touch
                self._entries.pop(region.uid)
                self._entries[region.uid] = entry
        if entry is not None and entry.schema_version == v.schema.version \
                and entry.retraction_epoch == epoch \
                and entry.visible <= visible:
            if entry.visible == visible and entry.sst_names == sst_names:
                self._last.outcome = "hit"
                increment_counter("scan_cache_hit")
                return entry.scan
            try:
                from ..common.failpoint import fail_point
                fail_point("scan_cache_incremental")
                scan = self._incremental(region, snap, v, entry, visible)
                self._last.outcome = "incremental"
                increment_counter("scan_cache_incremental")
            except Exception as e:  # noqa: BLE001 — degrade, don't fail
                # a corrupt/unusable cached scan must never fail the
                # query: drop the entry and rebuild cold from storage —
                # counted as a miss (that is what the reader pays), plus
                # the recovery marker for dashboards
                import logging
                logging.getLogger(__name__).warning(
                    "scan cache entry for region %s unusable (%s); "
                    "rebuilding cold", region.name, e)
                increment_counter("scan_cache_recovered")
                increment_counter("scan_cache_miss")
                with self._lock:
                    self._entries.pop(region.uid, None)
                self._last.outcome = "full"
                scan = self._full(region, snap)
        else:
            self._last.outcome = "full"
            increment_counter("scan_cache_miss")
            scan = self._full(region, snap)
        entry = _CacheEntry(scan, visible, sst_names, v.schema.version,
                            epoch)
        with self._lock:
            self._entries.pop(region.uid, None)
            self._entries[region.uid] = entry
            self._evict_locked()
        return scan

    def _evict_locked(self) -> None:
        """Drop LRU entries until count and byte budgets hold (whole
        scans only; the most recent entry is never evicted)."""
        while len(self._entries) > max(self.capacity, 1):
            self._entries.pop(next(iter(self._entries)))
        if self.budget_bytes <= 0:
            return
        total = {uid: e.scan.nbytes for uid, e in self._entries.items()}
        used = sum(total.values())
        for uid in list(self._entries):
            if used <= self.budget_bytes or len(self._entries) <= 1:
                break
            self._entries.pop(uid)
            used -= total[uid]

    def cached(self, region) -> bool:
        """Whether this region has a resident entry (any freshness):
        the indexed-point planner prefers a warm cache — incremental
        maintenance beats re-reading even one SST — and only routes
        around the cache when the region would be scanned cold."""
        with self._lock:
            return region.uid in self._entries

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(e.scan.nbytes for e in self._entries.values())

    def configure(self, *, budget_bytes: Optional[int] = None,
                  capacity: Optional[int] = None) -> None:
        with self._lock:
            if budget_bytes is not None:
                self.budget_bytes = int(budget_bytes)
            if capacity is not None:
                self.capacity = int(capacity)
            self._evict_locked()

    def _full(self, region, snap) -> MergedScan:
        data = snap.scan()
        if data.num_rows:
            kept = merge_dedup_numpy(data.series_ids, data.ts, data.seq,
                                     data.op_types)
            sids = data.series_ids[kept]
            ts = data.ts[kept]
            seq = data.seq[kept]
            fields = {n: (d[kept], vd[kept] if vd is not None else None)
                      for n, (d, vd) in data.fields.items()}
        else:
            sids, ts, seq = data.series_ids, data.ts, data.seq
            fields = data.fields
        base = int(ts.min()) if ts.size else 0
        return MergedScan(sids.astype(np.int32), ts, fields,
                          data.series_dict, base, seq=seq)

    def _incremental(self, region, snap, v, entry: _CacheEntry,
                     visible: int) -> MergedScan:
        from ..datatypes.vector import null_column
        schema = v.schema
        field_names = [c.name for c in schema.field_columns()]
        lo = entry.visible
        runs = []
        # memtable rows beyond the cached watermark
        for mt in v.memtables.all_memtables():
            ms = mt.snapshot()
            if ms.num_rows == 0:
                continue
            sel = (ms.seq > lo) & (ms.seq <= visible)
            if not sel.any():
                continue
            fields = {}
            for name in field_names:
                if name in ms.fields:
                    d, vd = ms.fields[name]
                    fields[name] = (d[sel],
                                    vd[sel] if vd is not None else None)
                else:
                    fields[name] = null_column(
                        schema.column_schema(name).dtype, int(sel.sum()))
            runs.append((ms.series_ids[sel], ms.ts[sel], ms.seq[sel],
                         ms.op_types[sel], fields))
        # SSTs not yet covered that carry rows beyond the watermark
        # (freshly flushed files whose max_sequence <= lo are already in
        # the cache via the memtable — skip reading them entirely)
        for meta in v.ssts.all_files():
            if meta.file_name in entry.sst_names or meta.max_sequence <= lo:
                continue
            sst = region.access_layer.read_sst(meta,
                                               projection=field_names)
            if sst.num_rows == 0:
                continue
            sel = (sst.seq > lo) & (sst.seq <= visible)
            if not sel.any():
                continue
            fields = {n: (d[sel], vd[sel] if vd is not None else None)
                      for n, (d, vd) in sst.fields.items()}
            runs.append((sst.series_ids[sel], sst.ts[sel], sst.seq[sel],
                         sst.op_types[sel], fields))

        cached = entry.scan
        if not runs:
            return cached
        # sort + dedup the delta alone (small), then splice it into the
        # already-sorted cached arrays with searchsorted + np.insert —
        # O(delta·log + n) memcpy, no sort over the region
        dsid = np.concatenate([r[0] for r in runs])
        dts = np.concatenate([r[1] for r in runs])
        dseq = np.concatenate([r[2] for r in runs])
        dop = np.concatenate([r[3] for r in runs])
        dorder = np.lexsort((dseq, dts, dsid))
        dsid, dts, dseq, dop = (a[dorder] for a in (dsid, dts, dseq, dop))
        # within-delta dedup: keep the newest version of each (sid, ts)
        nxt_same = np.concatenate([(dsid[1:] == dsid[:-1]) &
                                   (dts[1:] == dts[:-1]), [False]])
        dkeep0 = ~nxt_same
        dsel = dorder[dkeep0]
        dsid, dts, dseq, dop = (a[dkeep0] for a in (dsid, dts, dseq, dop))

        csid, cts = cached.series_ids, cached.ts
        n_cached = cached.num_rows
        # two-level searchsorted: sid bounds, then ts inside each sid run
        pos = np.empty(len(dsid), dtype=np.int64)
        for s in np.unique(dsid):
            m = dsid == s
            lo = int(np.searchsorted(csid, s, side="left"))
            hi = int(np.searchsorted(csid, s, side="right"))
            pos[m] = lo + np.searchsorted(cts[lo:hi], dts[m], side="left")
        # collisions: a delta key that already exists replaces (or deletes)
        # the cached row; all delta sequences are newer by construction
        collide = (pos < n_cached)
        if collide.any():
            pc = np.minimum(pos, n_cached - 1)
            collide &= (csid[pc] == dsid) & (cts[pc] == dts)
        ckeep = np.ones(n_cached, dtype=bool)
        ckeep[pos[collide]] = False
        dlive = dop == 0                      # delete tombstones vanish
        # adjust insert positions for dropped cached rows
        dropped_prefix = np.concatenate([[0], np.cumsum(~ckeep)])
        adj = pos - dropped_prefix[pos]

        ins = dlive
        sids = np.insert(csid[ckeep] if not ckeep.all() else csid,
                         adj[ins], dsid[ins]).astype(np.int32)
        ts = np.insert(cts[ckeep] if not ckeep.all() else cts,
                       adj[ins], dts[ins])
        cseq = cached.seq if cached.seq is not None \
            else np.zeros(n_cached, np.int64)
        seq = np.insert(cseq[ckeep] if not ckeep.all() else cseq,
                        adj[ins], dseq[ins])
        fields = {}
        for name in field_names:
            cd, cv = cached.fields[name]
            dd = np.concatenate([r[4][name][0] for r in runs])[dsel]
            dvs = [r[4][name][1] for r in runs]
            if cv is not None or any(x is not None for x in dvs):
                dv = np.concatenate([
                    x if x is not None else np.ones(len(r[4][name][0]),
                                                    dtype=bool)
                    for x, r in zip(dvs, runs)])[dsel]
                cvf = cv if cv is not None else np.ones(n_cached, bool)
                valid = np.insert(cvf[ckeep] if not ckeep.all() else cvf,
                                  adj[ins], dv[ins])
            else:
                valid = None
            data = np.insert(cd[ckeep] if not ckeep.all() else cd,
                             adj[ins], dd[ins])
            fields[name] = (data, valid)
        base = int(ts.min()) if ts.size else 0
        return MergedScan(sids, ts, fields, cached.series_dict, base,
                          seq=seq)


SCAN_CACHE = _ScanCache()


# ---------------------------------------------------------------------------
# concurrent scan fusion: single-flight over identical resident scans
# ---------------------------------------------------------------------------

#: SET scan_fusion toggles; single-slot swap (no lock needed for a read)
from ..utils import env_flag as _env_flag  # noqa: E402

_FUSION_ENABLED = [_env_flag("GREPTIME_SCAN_FUSION", True)]
#: bounded park for a follower on the leader's pass — a dead leader
#: degrades to a solo scan, never a hang
_FUSION_WAIT_TIMEOUT_S = 30.0


def configure_scan_fusion(*, enabled: Optional[bool] = None) -> None:
    if enabled is not None:
        _FUSION_ENABLED[0] = bool(enabled)


class _FlightEntry:
    """One in-flight region reduction shared by its cohort."""

    __slots__ = ("done", "frame", "failed")

    def __init__(self) -> None:
        self.done = threading.Event()
        self.frame: Optional[pd.DataFrame] = None
        self.failed = False


class _ScanFlightMap:
    """Single-flight map keyed on (region identity, visible data state,
    plan fingerprint): concurrent identical-shape small scans of the
    same region fuse into ONE shared pass — the leader decodes, the
    cohort adopts its moment frame. The data-state component of the key
    (committed sequence + retraction epoch, sampled at request start)
    keeps read-your-writes intact: a scan that begins after a write is
    acked can never fuse onto a pass that predates the write."""

    def __init__(self) -> None:
        from ..common.locks import TrackedLock
        from ..common.tracking import tracked_state
        self._lock = TrackedLock("query.scan_fusion")
        self._inflight: Dict[tuple, _FlightEntry] = tracked_state(
            {}, "query.scan_fusion.inflight")

    def execute(self, region, table, plan: "TpuPlan"):
        from ..common import exec_stats, process_list
        from ..common.telemetry import increment_counter
        if not _FUSION_ENABLED[0]:
            # check BEFORE fingerprinting: the opt-out must not pay the
            # plan serialization on every region of every scan
            return _execute_region(region, table, plan)
        key = self._key(region, plan)
        if key is None:
            return _execute_region(region, table, plan)
        with self._lock:
            entry = self._inflight.get(key)
            leader = entry is None
            if leader:
                entry = _FlightEntry()
                self._inflight[key] = entry
        if leader:
            try:
                entry.frame = _execute_region(region, table, plan)
            except BaseException:
                # cohort members fall back to their own solo scans: the
                # leader's failure may be leader-specific (a KILL on its
                # statement must not kill nine bystanders)
                entry.failed = True
                raise
            finally:
                entry.done.set()
                with self._lock:
                    self._inflight.pop(key, None)
            increment_counter("scan_fusion_leader")
            return entry.frame
        # follower: bounded park on the leader's shared pass
        import time as _time
        t0 = _time.perf_counter()
        deadline = _time.monotonic() + _FUSION_WAIT_TIMEOUT_S
        while not entry.done.wait(timeout=0.05):
            process_list.check_cancelled()    # killed mid-wait: bail out
            if _time.monotonic() > deadline:
                break
        if not entry.done.is_set() or entry.failed:
            return _execute_region(region, table, plan)
        increment_counter("scan_fusion_follower")
        # EXPLAIN ANALYZE surfaces the fusion: this statement's region
        # pass was adopted from a concurrent leader, not re-decoded
        exec_stats.record(
            "fused-follower",
            rows=0 if entry.frame is None else len(entry.frame),
            elapsed_s=_time.perf_counter() - t0, region=region.name)
        # hand back a copy: cohort members' downstream folds must never
        # share mutable frames (small scans — the copy is cheap)
        return None if entry.frame is None else entry.frame.copy()

    @staticmethod
    def _key(region, plan: "TpuPlan") -> Optional[tuple]:
        vc = getattr(region, "version_control", None)
        if vc is None:
            return None
        # fingerprint once per PLAN object, not once per region: a
        # multi-region scan serializes the identical plan only once
        fp = getattr(plan, "_fusion_fp", None)
        if fp is None:
            try:
                from .plan_codec import plan_to_dict
                import json
                fp = json.dumps(plan_to_dict(plan), sort_keys=True,
                                default=str)
            except Exception:  # noqa: BLE001 — unshippable: no fusion
                from ..common.telemetry import increment_counter
                increment_counter("scan_fusion_unfingerprintable")
                fp = False
            plan._fusion_fp = fp
        if fp is False:
            return None
        return (region.uid, vc.committed_sequence,
                getattr(region, "retraction_epoch", 0), fp)


SCAN_FLIGHTS = _ScanFlightMap()


# ---------------------------------------------------------------------------
# plan
# ---------------------------------------------------------------------------

@dataclass
class TagGroup:
    name: str                         # tag column name
    tag_index: int


@dataclass
class BucketGroup:
    stride_ms: int
    origin: int
    expr_key: str                     # expr_name of the bucket expression


@dataclass
class FieldFilter:
    column: str
    op: str                           # eq/ne/lt/le/gt/ge
    value: float


@dataclass
class Moment:
    op: str                           # kernel op
    column: Optional[str]             # field name; None = row count
    slot: str


#: moment ops whose per-run partial is an encoded sketch (bytes), not a
#: number — built on the host, merged by _finalize through the codec
SKETCH_MOMENT_OPS = frozenset({"distinct", "tdigest"})

#: numeric moment ops only the host reducer implements (no device
#: kernel): `reset_corr` is PromQL's counter-reset correction — the sum
#: of the pre-reset value over adjacent valid sample pairs within a run
#: where the later sample is smaller (ops/window.py rate kernel:
#: `where(pair_ok & (val < prev), prev, 0)`), so
#: increase = last - first + reset_corr folds like any other moment
HOST_ONLY_MOMENT_OPS = frozenset({"reset_corr"})


@dataclass
class TpuPlan:
    tag_groups: List[TagGroup]
    bucket: Optional[BucketGroup]
    moments: List[Moment]
    finals: List[Tuple[str, str, List[str]]]  # (slot, final op, moment slots)
    time_lo: Optional[int]
    time_hi: Optional[int]
    tag_predicates: List[Expr]
    field_filters: List[FieldFilter]
    #: arithmetic agg-arg expressions keyed by their moment "column"
    #: name (expr_name): `sum(a*b)` moments over a virtual column that
    #: each region evaluates from its stored fields before momenting
    field_exprs: Dict[str, Expr] = field(default_factory=dict)
    #: literal extras per final slot (approx_percentile's p)
    agg_params: Dict[str, tuple] = field(default_factory=dict)

    def describe(self) -> str:
        gs = [t.name for t in self.tag_groups]
        if self.bucket:
            gs.append(f"time_bucket({self.bucket.stride_ms}ms)")
        ops = [f"{op}" for _, op, _ in self.finals]
        return f"groups=[{', '.join(gs)}] aggs=[{', '.join(ops)}]"


def plan_needs_host(plan: "TpuPlan") -> bool:
    """Whether this plan's moments must reduce on the host: sketch
    partials (distinct/t-digest have no device kernel) and virtual
    expression columns both do. The partial-frame ALGEBRA is unchanged —
    host partials fold exactly like device partials."""
    return bool(plan.field_exprs) or \
        any(m.op in SKETCH_MOMENT_OPS or m.op in HOST_ONLY_MOMENT_OPS
            for m in plan.moments)


def plan_scan_columns(plan: "TpuPlan", schema) -> List[str]:
    """Base STORED columns a region scan must project for this plan:
    plain moment columns plus every field a virtual expression column
    references (tags ride the series ids, never the projection)."""
    tag_names = set(schema.tag_names())
    cols: set = set()
    for m in plan.moments:
        if m.column is None:
            continue
        if m.column in plan.field_exprs:
            cols |= _refs(plan.field_exprs[m.column])
        elif m.column not in tag_names:
            cols.add(m.column)
    cols |= {ff.column for ff in plan.field_filters}
    return sorted(cols)


def moment_input(m: Moment, plan: TpuPlan, fields: Dict, sids, ts, sd,
                 cache: Optional[dict] = None):
    """(values, validity) for one moment's input: a stored field, the
    time index, a tag column (decoded per row), or a registered
    arithmetic expression evaluated over the stored fields — the ONE
    resolution both host reducers share, so streamed, resident and
    indexed partials cannot disagree about what `sum(a*b)` means."""
    col = m.column
    if cache is not None and col in cache:
        return cache[col]
    if col in plan.field_exprs:
        base = {}
        for name in sorted(_refs(plan.field_exprs[col])):
            d, vd = fields[name]
            if d.dtype == object:
                raise UnsupportedError(
                    f"expression aggregate over non-numeric {name!r}")
            arr = d.astype(np.float64, copy=vd is not None)
            if vd is not None:
                arr[~vd] = np.nan        # pandas null convention, so the
            base[name] = arr             # expr semantics == the fallback
        ev = Evaluator(pd.DataFrame(base))
        v = ev.eval(plan.field_exprs[col])
        vals = v.to_numpy(dtype=np.float64) if isinstance(v, pd.Series) \
            else np.asarray(v, dtype=np.float64)
        if vals.ndim == 0:
            vals = np.full(len(ts), float(vals))
        valid = ~np.isnan(vals)
        out = (vals, None if valid.all() else valid)
    elif col in fields:
        out = fields[col]
    elif sd is not None and col in tuple(getattr(sd, "tag_names", ())):
        idx = tuple(sd.tag_names).index(col)
        out = (sd.decode_tag_column(np.asarray(sids, dtype=np.int32),
                                    idx), None)
    else:
        out = (ts, None)                 # the time index
    if cache is not None:
        cache[col] = out
    return out


def sketch_run_column(op: str, vals: np.ndarray,
                      valid: Optional[np.ndarray],
                      starts: np.ndarray, n: int) -> np.ndarray:
    """Encoded sketch partial per run: object column of codec frames,
    one per (sid [, bucket]) run — the sketch twin of a reduceat."""
    from .sketches import DistinctSketch, TDigest, encode_sketch
    ends = np.append(starts[1:], n)
    out = np.empty(len(starts), dtype=object)
    for i in range(len(starts)):
        seg = slice(int(starts[i]), int(ends[i]))
        v = vals[seg]
        if valid is not None:
            v = v[valid[seg]]
        if op == "distinct":
            sk = DistinctSketch.from_values(v)
        else:
            sk = TDigest.from_values(np.asarray(v, dtype=np.float64)) \
                if v.dtype != object else TDigest.from_values(
                    np.asarray(list(v), dtype=np.float64))
        out[i] = encode_sketch(sk)
    return out


def _conjuncts(e: Optional[Expr]) -> List[Expr]:
    if e is None:
        return []
    if isinstance(e, BinaryOp) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _refs(e: Expr) -> set:
    from .planner import _walk_columns
    out: set = set()
    _walk_columns(e, out)
    return out


def _literal_num(e: Expr):
    if isinstance(e, Literal) and isinstance(e.value, (int, float)) and \
            not isinstance(e.value, bool):
        return e.value
    if isinstance(e, UnaryOp) and e.op == "-":
        v = _literal_num(e.operand)
        return -v if v is not None else None
    return None


_ARITH_OPS = frozenset({"+", "-", "*", "/"})


def _is_expr_arg(e: Expr, field_names: set, schema) -> bool:
    """Arithmetic over numeric FIELD columns and numeric literals, with
    at least one operator — the agg-argument shapes each region can
    evaluate into a virtual moment column (`sum(a*b)`, `avg(a/b)`)."""
    if not isinstance(e, (BinaryOp, UnaryOp)):
        return False

    def ok(x: Expr) -> bool:
        if isinstance(x, Column):
            if x.name not in field_names:
                return False
            cs = schema.column_schema(x.name)
            return not (cs.dtype.is_string or cs.dtype.is_binary)
        if isinstance(x, Literal):
            return isinstance(x.value, (int, float)) and \
                not isinstance(x.value, bool)
        if isinstance(x, UnaryOp):
            return x.op == "-" and ok(x.operand)
        if isinstance(x, BinaryOp):
            return x.op in _ARITH_OPS and ok(x.left) and ok(x.right)
        return False

    return ok(e)


def standard_final(op: str, col: Optional[str], moment):
    """(final op, moment slots) for one standard aggregate through the
    `moment(op, column) -> slot` dedupe closure — the ONE op→moment
    mapping SQL planning (plan_for), PromQL lowering (promql/lowering)
    and flow compilation (flow/lowering) share, so no front end can
    teach the fold a private dialect. A count moment rides along with
    sum/min/max so empty groups finalize to NULL, not 0."""
    if op == "count":
        return "count", [moment("count", col)]
    if op in ("sum", "avg"):
        return op, [moment("sum", col), moment("count", col)]
    if op in ("min", "max"):
        return op, [moment(op, col), moment("count", col)]
    if op in ("stddev", "variance"):
        return op, [moment("sum", col), moment("sum_sq", col),
                    moment("count", col)]
    if op in ("first", "last"):
        mts = moment("min_ts" if op == "first" else "max_ts", col)
        return op, [moment(op, col), mts]
    return None


def plan_for(table, a: Analysis, query: Query) -> Optional[TpuPlan]:
    """Return a TpuPlan if (table, query) fits the fast-path shape."""
    if table is None or not a.is_aggregate or query.joins:
        return None
    if a.window_calls:
        # window slots evaluate on the post-aggregate frame in the
        # fallback engine (query/window.py); the device plan has no
        # WindowAggExec analogue yet
        return None
    if not hasattr(table, "regions"):
        return None  # only region-backed (mito) tables have the SoA path
    schema = table.schema
    tc = schema.timestamp_column
    tag_names = schema.tag_names()
    field_names = set(schema.field_names())

    # group exprs: tags and at most one time bucket
    tag_groups: List[TagGroup] = []
    bucket: Optional[BucketGroup] = None
    for g in a.group_exprs:
        if isinstance(g, Column) and g.name in tag_names:
            tag_groups.append(TagGroup(g.name, tag_names.index(g.name)))
            continue
        b = _match_bucket(g, tc.name if tc else None)
        if b is not None and bucket is None:
            bucket = b
            continue
        return None

    # aggregates → moments
    from .sketches import exact_distinct_forced
    is_pushdown = hasattr(table, "execute_tpu_plan")
    if is_pushdown and not _PARTIAL_PUSHDOWN[0]:
        # SET dist_partial_agg = 0: no pushdown PLAN at all, so EXPLAIN
        # (CpuAggregateExec) and execution (raw-row scatter + CPU
        # fallback) render the same decision
        return None
    moments: List[Moment] = []
    finals: List[Tuple[str, str, List[str]]] = []
    field_exprs: Dict[str, Expr] = {}
    agg_params: Dict[str, tuple] = {}
    seen: Dict[tuple, str] = {}

    def moment(op: str, column: Optional[str]) -> str:
        k = (op, column)
        if k in seen:
            return seen[k]
        slot = f"__m{len(moments)}"
        moments.append(Moment(op, column, slot))
        seen[k] = slot
        return slot

    for call in a.agg_calls:
        op = call.op
        if op not in TPU_AGGREGATES and op not in SKETCH_AGGREGATES:
            return None
        if call.distinct and (op != "count" or not is_pushdown or
                              exact_distinct_forced()):
            # distinct rides the sketch partial only where it pays — the
            # distributed pushdown (a standalone table keeps the exact
            # fallback), and never under SET exact_distinct = 1
            return None
        if call.arg is None:
            if op != "count" or call.distinct:
                return None
            finals.append((call.slot, "count", [moment("count", None)]))
            continue
        # distinct sketches take any value type (sets of strings are
        # sets); everything else needs numbers
        sketchy = call.distinct or op == "approx_distinct"
        if isinstance(call.arg, Column):
            col = call.arg.name
            if col == (tc.name if tc else None):
                pass                            # the time index
            elif col in field_names:
                cs = schema.column_schema(col)
                if (cs.dtype.is_string or cs.dtype.is_binary) and \
                        op != "count" and not sketchy:
                    return None
            elif col in tag_names and sketchy:
                pass          # distinct over a tag: decoded per series
            else:
                return None
        else:
            if not _is_expr_arg(call.arg, field_names, schema):
                return None
            col = expr_name(call.arg)
            field_exprs[col] = call.arg
        if call.distinct:                       # count(DISTINCT x)
            finals.append((call.slot, "count_distinct",
                           [moment("distinct", col)]))
            continue
        if op == "approx_distinct":
            finals.append((call.slot, "approx_distinct",
                           [moment("distinct", col)]))
            continue
        if op in ("approx_percentile", "median"):
            if op == "approx_percentile":
                if len(call.params) != 1 or \
                        not isinstance(call.params[0], (int, float)) or \
                        isinstance(call.params[0], bool) or \
                        not 0 <= float(call.params[0]) <= 100:
                    return None     # the fallback raises the typed error
                p = float(call.params[0])
            else:
                p = 50.0
            finals.append((call.slot, "approx_percentile",
                           [moment("tdigest", col)]))
            agg_params[call.slot] = (p,)
            continue
        std = standard_final(op, col, moment)
        if std is None:
            return None
        finals.append((call.slot, std[0], std[1]))

    # WHERE decomposition
    time_lo = time_hi = None
    tag_predicates: List[Expr] = []
    field_filters: List[FieldFilter] = []
    for c in _conjuncts(query.where):
        refs = _refs(c)
        if refs and refs <= set(tag_names):
            tag_predicates.append(c)
            continue
        if tc is not None and refs == {tc.name}:
            rng = _match_time_pred(c, tc.name)
            if rng is None:
                return None
            lo, hi = rng
            if lo is not None:
                time_lo = lo if time_lo is None else max(time_lo, lo)
            if hi is not None:
                time_hi = hi if time_hi is None else min(time_hi, hi)
            continue
        ff = _match_field_pred(c, field_names)
        if ff is None:
            return None
        field_filters.append(ff)

    return TpuPlan(tag_groups, bucket, moments, finals, time_lo, time_hi,
                   tag_predicates, field_filters, field_exprs, agg_params)


def _match_bucket(e: Expr, ts_name: Optional[str]) -> Optional[BucketGroup]:
    """date_bin(INTERVAL, ts [, origin]) / date_trunc('unit', ts)."""
    if ts_name is None or not isinstance(e, FunctionCall):
        return None
    if e.name == "date_bin" and len(e.args) >= 2:
        stride = None
        if isinstance(e.args[0], Interval):
            stride = parse_interval_ms(e.args[0].text)
        elif _literal_num(e.args[0]) is not None:
            stride = int(_literal_num(e.args[0]))
        if stride is None or stride <= 0:
            return None
        if not (isinstance(e.args[1], Column) and e.args[1].name == ts_name):
            return None
        origin = 0
        if len(e.args) >= 3:
            o = _literal_num(e.args[2])
            if o is None:
                return None
            origin = int(o)
        return BucketGroup(stride, origin, expr_name(e))
    if e.name == "date_trunc" and len(e.args) == 2:
        from .functions import _TRUNC_MS
        if not isinstance(e.args[0], Literal):
            return None
        unit = str(e.args[0].value).lower()
        if unit not in _TRUNC_MS:
            return None
        if not (isinstance(e.args[1], Column) and e.args[1].name == ts_name):
            return None
        from .functions import _WEEK_ORIGIN_MS
        origin = _WEEK_ORIGIN_MS if unit == "week" else 0
        return BucketGroup(_TRUNC_MS[unit], origin, expr_name(e))
    return None


def _match_time_pred(e: Expr, ts_name: str):
    import math as _math
    if isinstance(e, Between):
        lo, hi = _literal_num(e.low), _literal_num(e.high)
        if e.negated or lo is None or hi is None:
            return None
        # inclusive range: directional rounding for fractional bounds
        return _math.ceil(lo), _math.floor(hi) + 1
    if not isinstance(e, BinaryOp):
        return None
    op = e.op
    if isinstance(e.left, Column) and e.left.name == ts_name:
        v = _literal_num(e.right)
    elif isinstance(e.right, Column) and e.right.name == ts_name:
        v = _literal_num(e.left)
        op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
    else:
        return None
    if v is None:
        return None
    # timestamps are integral: round fractional bounds toward the predicate
    if op == "<":
        return None, _math.ceil(v)          # ts < 10.5 ≡ ts < 11
    if op == "<=":
        return None, _math.floor(v) + 1
    if op == ">":
        return _math.floor(v) + 1, None     # ts > 10.5 ≡ ts >= 11
    if op == ">=":
        return _math.ceil(v), None
    if op == "=":
        if v != int(v):
            return 0, 0                     # fractional equality: empty
        return int(v), int(v) + 1
    return None


def _match_field_pred(e: Expr, field_names: set) -> Optional[FieldFilter]:
    if not isinstance(e, BinaryOp) or e.op not in _CMP_OPS:
        return None
    if isinstance(e.left, Column) and e.left.name in field_names:
        v = _literal_num(e.right)
        if v is None:
            return None
        return FieldFilter(e.left.name, _CMP_OPS[e.op], float(v))
    if isinstance(e.right, Column) and e.right.name in field_names:
        v = _literal_num(e.left)
        if v is None:
            return None
        op = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}.get(
            _CMP_OPS[e.op], _CMP_OPS[e.op])
        return FieldFilter(e.right.name, op, float(v))
    return None


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------

#: Below this many estimated rows the CPU columnar path wins: device
#: round-trips dominate latency (BASELINE config 1: 281 ms device vs ~10 ms
#: CPU at 10k rows) and the host path keeps float64 precision for DOUBLE
#: columns, which the f32 device mirrors cannot. Cost-based dispatch playing
#: the role of DataFusion's physical-plan costing in the reference
#: (src/query/src/datafusion.rs).
TPU_DISPATCH_MIN_ROWS = 131072

#: assumed CPU columnar throughput for break-even estimation (pandas
#: groupby sustains ~8-25 Mrows/s on simple aggregates; be conservative)
_CPU_ROWS_PER_SEC = 15e6
#: fastest observed device-path query (seconds) — a lower bound on the
#: per-query fixed cost (dispatch chain + transfers + result fetch);
#: ~1-2ms on local PCIe, 100ms+ behind a tunneled chip
_observed_min_dt = [None]


def _dispatch_min_rows() -> int:
    """Latency-adaptive dispatch floor.

    The static floor (131072 rows) is right when a device query's fixed
    cost is ~1-2 ms (local PCIe). Behind a remote device link the same
    chain costs 100 ms+ — time the CPU path would spend on millions of
    rows — so the floor adapts to the fastest device-path query seen
    this process (a fixed-cost lower bound; warm compile caches make it
    representative after the first few queries)."""
    dt = _observed_min_dt[0]
    if dt is None:
        return TPU_DISPATCH_MIN_ROWS
    return max(TPU_DISPATCH_MIN_ROWS, int(dt * _CPU_ROWS_PER_SEC))


def _note_device_query_time(dt: float) -> None:
    # cap what one observation may contribute: a cold query includes
    # 10-40s of XLA compile, and an uncapped floor would route every
    # later mid-size query to the CPU path, so no device query would
    # ever run again to correct the estimate. The cap keeps tables
    # >7.5M rows on the device, whose warm queries then pull the
    # minimum down to the true fixed cost.
    dt = min(dt, 0.5)
    cur = _observed_min_dt[0]
    if cur is None or dt < cur:
        _observed_min_dt[0] = dt


def _estimated_table_rows(table) -> Optional[int]:
    """Cheap upper-bound row estimate from memtable counters + SST metas —
    no SST reads, no merged-scan build."""
    regions = getattr(table, "regions", None)
    if not regions:
        return None
    total = 0
    for region in regions.values():
        vc = getattr(region, "version_control", None)
        if vc is None:
            return None
        v = vc.current
        for mt in v.memtables.all_memtables():
            total += mt.num_rows
        for meta in v.ssts.all_files():
            total += meta.num_rows
    return total


def cached_table_frame(table) -> Optional[pd.DataFrame]:
    """Columnar pandas frame for the CPU fallback, memoized per region
    version on the merged-scan cache — the fallback otherwise re-reads
    and re-converts the whole table on every query (the role of
    DataFusion's MemTable caching for hot tables). Nulls follow the
    fallback's frame conventions: NaN for numerics, None for objects."""
    regions = getattr(table, "regions", None)
    if not regions:
        return None
    schema = table.schema
    ts_name = schema.timestamp_column.name \
        if schema.timestamp_column is not None else None
    frames = []
    for region in regions.values():
        scan = SCAN_CACHE.get(region)
        df = scan.device.get("__host_df")
        if df is None:
            cols = {}
            sd = scan.series_dict
            for i, tag in enumerate(sd.tag_names):
                cols[tag] = sd.decode_tag_column(scan.series_ids, i)
            if ts_name is not None:
                cols[ts_name] = scan.ts
            for name, (vals, valid) in scan.fields.items():
                if valid is None:
                    cols[name] = vals
                elif vals.dtype == object:
                    arr = vals.copy()
                    arr[~valid] = None
                    cols[name] = arr
                else:
                    arr = vals.astype(np.float64)
                    arr[~valid] = np.nan
                    cols[name] = arr
            # schema column order
            df = pd.DataFrame({n: cols[n] for n in schema.names()
                               if n in cols})
            scan.device["__host_df"] = df
        frames.append(df)
    if not frames:
        return pd.DataFrame()
    return frames[0] if len(frames) == 1 else \
        pd.concat(frames, ignore_index=True)


#: SET dist_partial_agg — kill switch for the distributed partial
#: pushdown: 0 routes aggregate statements over DistTables through the
#: raw-row scatter instead (the bench differential + ops escape hatch)
_PARTIAL_PUSHDOWN = [_env_flag("GREPTIME_DIST_PARTIAL_AGG", True)]


def configure_partial_pushdown(*, enabled: Optional[bool] = None) -> None:
    if enabled is not None:
        _PARTIAL_PUSHDOWN[0] = bool(enabled)


def try_execute(table, a: Analysis, query: Query) -> Optional[pd.DataFrame]:
    from ..common import exec_stats

    plan = plan_for(table, a, query)
    if plan is None:
        return None
    if not hasattr(table, "execute_tpu_plan"):
        # Distributed tables always push down (the fallback would pull raw
        # rows over the wire); local tables route small scans to the CPU
        # columnar path, which is faster and float64-exact.
        est = _estimated_table_rows(table)
        if est is not None and est < _dispatch_min_rows():
            exec_stats.set_dispatch(
                f"cpu-small-scan (est_rows={est} < "
                f"dispatch_floor={_dispatch_min_rows()})")
            return None
    # the ONE aggregate-node executor all three front ends share
    # (query/ir.py): scatter or local dispatch, then the moment fold
    from .ir import execute_agg_plan
    try:
        return execute_agg_plan(table, plan)
    except UnsupportedError:
        return None


#: finals whose result comes out of a sketch partial, not a numeric fold
_SKETCH_FINAL_OPS = frozenset({"count_distinct", "approx_distinct",
                               "approx_percentile"})


def _aggs_desc(plan: TpuPlan) -> str:
    """sketch-vs-exact per aggregate, for the finalize stage detail."""
    return ",".join(
        f"{op}:{'sketch' if op in _SKETCH_FINAL_OPS else 'exact'}"
        for _, op, _ in plan.finals)


def frames_nbytes(frames) -> int:
    """Byte size of partial moment frames — numeric columns by their
    array width, sketch columns by their encoded frame lengths. This is
    the number the wire pays (the IPC framing adds low single-digit %),
    so EXPLAIN ANALYZE's partial_bytes and the bench's wire-byte
    comparison measure the same thing for local and Flight datanodes."""
    total = 0
    for f in frames:
        for col in f.columns:
            s = f[col]
            if s.dtype == object:
                total += int(sum(
                    len(v) if isinstance(v, (bytes, bytearray, str))
                    else 8 for v in s))
            else:
                total += int(s.to_numpy().nbytes)
    return total


def dispatch_decision_for_pushdown(table, plan) -> str:
    """The ONE aggregate-pushdown dispatch string EXPLAIN (query/engine)
    and execution (try_execute) both print. DistTable exposes
    scatter_describe (regions pruned a/b, fan-out=k); other pushdown
    tables get the generic line."""
    describe = getattr(table, "scatter_describe", None)
    if describe is not None:
        try:
            return describe(plan)
        except Exception:  # noqa: BLE001 — describing must never fail a
            # query; fall through to the generic dispatch line
            from ..common.telemetry import increment_counter
            increment_counter("explain_describe_errors")
    return "aggregate-pushdown (datanodes reduce, frontend folds)"


def local_dispatch_decision(table, cold=None, regions=None, plan=None,
                            point_sids=None) -> str:
    """The resident / streamed / indexed-point / mixed decision string
    for a local region-backed table — the ONE source both EXPLAIN
    (query/engine.py) and execution (region_moment_frames → ExecStats)
    print, so the two views cannot drift. `cold` lets a caller that
    already evaluated region_streams_cold per region pass the answers
    in; `regions` the (possibly pruned) region list those answers
    correspond to; `plan` (or a pre-computed `point_sids` vector) routes
    point/IN tag queries through the SST secondary index."""
    from . import stream_exec
    if regions is None:
        regions = list(table.regions.values())
    if point_sids is None:
        point_sids = [region_point_sids(r, plan) for r in regions] \
            if plan is not None else [None] * len(regions)
    # sketch / expression moments reduce on the host wherever the rows
    # come from — the suffix keeps EXPLAIN honest about the kernel
    suffix = "; host-partial moments (sketch/expr)" \
        if plan is not None and plan_needs_host(plan) else ""
    n_idx = sum(1 for s in point_sids if s is not None)
    if regions and n_idx == len(regions):
        k = max((len(s) for s in point_sids if s is not None), default=0)
        return (f"indexed-point (sst index, {k} candidate series; "
                f"bloom/sid-summary file pruning{suffix})")
    if cold is None:
        cold = [region_streams_cold(r) for r in regions]
    n_stream = sum(1 for c, s in zip(cold, point_sids)
                   if c and s is None)
    if n_idx:
        return (f"mixed ({n_idx}/{len(regions)} regions indexed-point, "
                f"{n_stream} streamed-cold{suffix})")
    if n_stream == 0:
        return f"device-resident (scan cache{suffix})"
    if n_stream == len(regions):
        return (f"streamed-cold (est_rows={_estimated_table_rows(table)}, "
                f"stream_threshold_rows="
                f"{stream_exec.stream_threshold_rows()}{suffix})")
    return (f"mixed ({n_stream}/{len(regions)} regions "
            f"streamed-cold{suffix})")


def region_point_sids(region, plan) -> Optional[np.ndarray]:
    """Sorted candidate series ids for an indexed point/IN scan of this
    region, or None when the standard resident/streamed paths win.

    Eligible when the plan carries at least one point (`tag = lit`) or
    `IN` tag conjunct (resolved per region through its series dict —
    ROADMAP item 4's 'point and IN predicates prune files'), the sid
    set is selective, the index tier is enabled, and the region is not
    already resident in the scan cache (a warm cache beats any IO).
    The set is a SUPERSET: the host reduction re-applies every tag
    predicate exactly, so `!=`/range conjuncts riding along cannot
    drift answers."""
    from ..storage.index import sst_index_enabled
    if plan is None or not plan.tag_predicates or not sst_index_enabled():
        return None
    sd = getattr(region, "series_dict", None)
    if sd is None or not sd.tag_names:
        return None
    from ..mito.engine import sid_candidates_for_filters
    sids = sid_candidates_for_filters(sd, sd.tag_names,
                                      plan.tag_predicates)
    if sids is None:
        return None
    S = sd.num_series
    if S and len(sids) > max(64, S // 16):
        return None                       # not selective: scan normally
    if SCAN_CACHE.cached(region):
        return None
    return sids


def _indexed_point_frames(region, table, plan: "TpuPlan",
                          sids: np.ndarray) -> List[pd.DataFrame]:
    """Partial moment frames for one region via the SST secondary
    index: scan only the files/row groups that may hold the candidate
    series (RegionSnapshot.scan's sid_set tier), merge-dedup the
    surviving rows (exact MVCC), and reduce on the host with the same
    segment arithmetic the streamed path uses — so _finalize folds
    these partials like any others. Never touches the scan cache: a
    point query on a cold many-SST region must not pay (or pin) full
    residency for a handful of series."""
    import time as _time

    from ..common import exec_stats
    from ..common.time import TimestampRange
    from ..storage.region import ScanProfile
    from . import stream_exec

    prof = ScanProfile(path="indexed-point")
    _t0 = _time.perf_counter()
    snap = region.snapshot()
    schema = snap.schema
    tc = schema.timestamp_column
    trange = None
    if tc is not None and (plan.time_lo is not None or
                           plan.time_hi is not None):
        trange = TimestampRange(plan.time_lo, plan.time_hi,
                                tc.dtype.time_unit)
    needed = plan_scan_columns(plan, schema)
    data = snap.scan(projection=needed, time_range=trange, sid_set=sids)
    prof.rows = data.num_rows
    prof.bump("candidate_sids", len(sids))
    prof.mark("scan", _time.perf_counter() - _t0)
    frames: List[pd.DataFrame] = []
    if data.num_rows:
        _t1 = _time.perf_counter()
        kept = stream_exec._slice_dedup(data)
        frame = stream_exec._host_partial_frame(data, kept, plan,
                                                region.series_dict)
        prof.mark("reduce", _time.perf_counter() - _t1)
        exec_stats.record("reduce", rows=data.num_rows,
                          elapsed_s=prof.stages["reduce"])
        if frame is not None and len(frame):
            frames.append(frame)
    prof.total_s = _time.perf_counter() - _t0
    region.last_scan_profile = prof
    return frames


def region_streams_cold(region) -> bool:
    """Whether a region takes the streamed-cold path instead of the
    device-resident scan cache. Streams on either bound: row count, or
    estimated decoded bytes vs the scan-cache budget — a wide-schema
    region can bust residency long before the row threshold (the budget
    never evicts the newest entry, so admission is the only guard).
    Shared by execution (region_moment_frames) and EXPLAIN so the
    printed dispatch decision cannot drift from the real one."""
    from . import stream_exec
    return stream_exec.region_estimated_rows(region) > \
        stream_exec.stream_threshold_rows() or \
        (SCAN_CACHE.budget_bytes > 0 and
         stream_exec.region_estimated_bytes(region) >
         SCAN_CACHE.budget_bytes // 2)


def region_moment_frames(table, plan: TpuPlan,
                         regions: Optional[Sequence[int]] = None
                         ) -> List[pd.DataFrame]:
    """Per-region moment frames for a table's local regions (shared by the
    single-node fast path and the datanode side of aggregate pushdown).
    `regions` restricts to a subset of hosted region numbers — the
    frontend's surviving-region list after partition pruning, so a
    datanode does not scan its un-pruned siblings.

    Regions above the streaming threshold never enter the scan cache:
    their time domain is sliced and streamed through the device instead
    (query/stream_exec.py), bounding host+HBM residency by the slice
    budget rather than the region size."""
    from ..common import exec_stats
    from . import stream_exec
    if regions is None:
        regions = list(table.regions.values())
    else:
        want = set(regions)
        missing = want - set(table.regions)
        if missing:
            # a pruned aggregate naming regions this node no longer hosts
            # must not silently reduce a partial set — typed so the
            # DistTable refreshes its route and retries
            from ..errors import StaleRouteError
            raise StaleRouteError(
                f"region(s) {sorted(missing)} of table "
                f"{table.info.name} are not hosted here")
        regions = [r for rn, r in table.regions.items() if rn in want]
    if not regions:
        return []
    # indexed point/IN queries bypass both the cache and the slicer:
    # the SST index resolves the predicate to candidate series and the
    # scan opens only the files that may hold them
    point_sids = [region_point_sids(r, plan) for r in regions]
    cold = [False if s is not None else region_streams_cold(r)
            for r, s in zip(regions, point_sids)]
    exec_stats.set_dispatch(local_dispatch_decision(
        table, cold, regions, plan=plan, point_sids=point_sids))
    frames = []
    from ..common import process_list
    for region, streams, sids in zip(regions, cold, point_sids):
        process_list.check_cancelled()     # per-region batch boundary
        if sids is not None:
            frames.extend(_indexed_point_frames(region, table, plan,
                                                sids))
            continue
        if streams:
            frames.extend(stream_exec.stream_region_moment_frames(
                region, table, plan))
            continue
        # single-flight: identical concurrent scans of this region fuse
        # into one shared pass (followers adopt the leader's frame)
        part = SCAN_FLIGHTS.execute(region, table, plan)
        if part is not None and len(part):
            frames.append(part)
    return frames


def _execute_region(region, table, plan: TpuPlan) -> Optional[pd.DataFrame]:
    import time as _time

    from ..common import exec_stats
    from ..common.telemetry import span
    from ..storage.region import ScanProfile

    prof = ScanProfile(path="resident")
    _t0 = _time.perf_counter()
    with span("region_scan", region=region.name, path="resident"):
        scan = SCAN_CACHE.get(region)
        prep = _time.perf_counter() - _t0
        prof.mark("scan_prep", prep)
        outcome = SCAN_CACHE.last_outcome() or "full"
        # same outcome vocabulary as ExecStats (cache=...) and the
        # scan_cache_* prometheus counters: hit / incremental / full
        prof.bump(f"cache_{outcome}")
        prof.rows = scan.num_rows
        exec_stats.record("scan_prep", rows=scan.num_rows, elapsed_s=prep,
                          cache=outcome)
        if scan.num_rows == 0:
            prof.total_s = _time.perf_counter() - _t0
            region.last_scan_profile = prof
            return None
        _t1 = _time.perf_counter()
        out = _moment_frame_for_scan(scan, table.schema, plan)
        prof.mark("reduce", _time.perf_counter() - _t1)
        prof.total_s = _time.perf_counter() - _t0
        region.last_scan_profile = prof
        exec_stats.record("reduce", rows=scan.num_rows,
                          elapsed_s=prof.stages["reduce"])
    return out


@dataclass
class _Launched:
    """An in-flight device reduction: device handles + host fold context.

    XLA dispatch is asynchronous — the kernel call returns immediately
    with futures — so callers can launch many reductions (one per
    streamed slice), let host decode overlap device compute, and fetch
    every result in ONE device round trip (the tunnel-dominated rig cost;
    see _note_device_query_time)."""
    results: tuple                    # device arrays, one per moment
    counts: object                    # device int32 [nbucket]
    nruns: int
    run_sids: np.ndarray              # per-run series id [nruns] — only
    run_buckets: Optional[np.ndarray]  # run-level context is retained, so
    series_dict: object               # a streamed slice's full arrays are
    ts_base: int                      # freed while its reduction is in flight


def _moment_frame_for_scan(scan: MergedScan, schema,
                           plan: TpuPlan) -> Optional[pd.DataFrame]:
    if plan_needs_host(plan):
        # sketch / expression moments: reduce the resident merged scan
        # on the host with the same segment arithmetic the streamed
        # path uses — MergedScan rows are already sorted + MVCC-deduped,
        # so the partial frame folds like any other
        from .stream_exec import _host_partial_frame
        return _host_partial_frame(scan, None, plan, scan.series_dict)
    import jax
    launched = _launch_scan_kernel(scan, schema, plan)
    if launched is None:
        return None
    counts, res_np = jax.device_get((launched.counts,
                                     list(launched.results)))
    return _collect_moment_frame(launched, plan, counts, res_np)


def _launch_scan_kernel(scan: MergedScan, schema,
                        plan: TpuPlan) -> Optional[_Launched]:
    import jax

    n = scan.num_rows
    if n == 0:
        return None
    tag_names = schema.tag_names()

    # ---- host: run ids over (series [, bucket]) ----
    # cached per scan + bucket spec: dashboards repeat the same grouping
    # over a warm region, and the flags/cumsum/nonzero sweep is O(n) host
    # work per query otherwise
    sids = scan.series_ids
    if plan.bucket is not None:
        b = plan.bucket
        run_key = f"__runs:{b.stride_ms}:{b.origin}"
    elif plan.tag_groups:
        run_key = "__runs:series"
    else:
        run_key = "__runs:all"
    cached_runs = scan.device.get(run_key)
    if cached_runs is not None:
        rid, nruns, run_starts, buckets = cached_runs
    else:
        if plan.bucket is not None:
            b = plan.bucket
            buckets = ((scan.ts - b.origin) // b.stride_ms).astype(np.int64)
            flags = np.empty(n, dtype=bool)
            flags[0] = True
            np.not_equal(sids[1:], sids[:-1], out=flags[1:])
            flags[1:] |= buckets[1:] != buckets[:-1]
        else:
            buckets = None
            flags = np.empty(n, dtype=bool)
            flags[0] = True
            np.not_equal(sids[1:], sids[:-1], out=flags[1:])
            if not plan.tag_groups:
                flags[:] = False
                flags[0] = True
        rid = None          # lazy: only first/last reads per-row run ids
        run_starts = np.nonzero(flags)[0]
        nruns = len(run_starts)
        scan.device[run_key] = (rid, nruns, run_starts, buckets)
        # bound the per-scan run-context cache: each distinct bucket
        # spec stores O(n) host arrays, and dashboards sweeping many
        # strides over one hot region would otherwise grow host memory
        # past the scan-cache budget unchecked
        stale = [k for k in scan.device if k.startswith("__runs:")][:-4]
        for k in stale:
            scan.device.pop(k, None)

    # ---- host: per-series tag predicate → row mask ----
    base_mask = None
    if plan.tag_predicates:
        sd = scan.series_dict
        S = sd.num_series
        tag_cols = {}
        for i, tname in enumerate(tag_names):
            tag_cols[tname] = sd.decode_tag_column(
                np.arange(S, dtype=np.int32), i)
        sdf = pd.DataFrame(tag_cols)
        ev = Evaluator(sdf)
        smask = np.ones(S, dtype=bool)
        for p in plan.tag_predicates:
            m = ev.eval(p)
            m = m.fillna(False).astype(bool).to_numpy() \
                if isinstance(m, pd.Series) else np.full(S, bool(m))
            smask &= m
        if not smask.any():
            return None
        base_mask = smask[sids]

    # ---- row mask (host; cheap elementwise, skipped entirely for the
    # unfiltered case so unpadded/pre-staged scans touch no O(n) host
    # memory here) ----
    unfiltered = base_mask is None and plan.time_lo is None and \
        plan.time_hi is None and not plan.field_filters
    mask = None
    if not (unfiltered and (scan.valid_rows is None
                            or "__pad_mask" in scan.device)):
        mask = base_mask.copy() if base_mask is not None \
            else np.ones(n, dtype=bool)
        if scan.valid_rows is not None and scan.valid_rows < n:
            mask[scan.valid_rows:] = False   # shape-bucket padding rows
        if plan.time_lo is not None:
            mask &= scan.ts >= plan.time_lo
        if plan.time_hi is not None:
            mask &= scan.ts < plan.time_hi
        for ff in plan.field_filters:
            vals, valid = scan.fields[ff.column]
            if vals.dtype == object:
                raise UnsupportedError(
                    f"filter on non-numeric {ff.column}")
            v = vals.astype(np.float64)
            cmp = {"eq": v == ff.value, "ne": v != ff.value,
                   "lt": v < ff.value, "le": v <= ff.value,
                   "gt": v > ff.value, "ge": v >= ff.value}[ff.op]
            if valid is not None:
                cmp &= valid
            mask &= cmp
        if not mask.any():
            return None

    # ---- device kernel (module-level jit; compile cache shared across
    # queries with the same moment signature + shape bucket) ----
    d_ts = scan.device_ts()
    nbucket = shape_bucket(nruns, minimum=256)
    # unfiltered queries reuse the cached all-true device mask instead of
    # uploading n bool bytes per query (50 MB at 50M rows, per query);
    # padded streamed slices reuse the pre-staged padding mask
    if mask is None:
        d_mask = scan.device["__pad_mask"] \
            if scan.valid_rows is not None \
            else scan.device_valid_all()
    else:
        d_mask = jax.device_put(mask)

    values = []
    col_masks = []
    ops = []
    for m in plan.moments:
        if m.op in ("min_ts", "max_ts"):
            values.append(d_ts)
            col_masks.append(scan.device_valid(m.column))
            ops.append("min" if m.op == "min_ts" else "max")
        elif m.column is None:
            values.append(d_ts)   # dummy; count reads only the mask
            col_masks.append(scan.device_valid_all())
            ops.append("count")
        else:
            cs = schema.column_schema(m.column)
            if cs.dtype.is_string or cs.dtype.is_binary:
                values.append(d_ts)
            else:
                values.append(scan.device_field(m.column))
            col_masks.append(scan.device_valid(m.column))
            ops.append(m.op)

    # segment ends are free on the host (run boundaries are already
    # computed); shipping them skips the device binary search, the dominant
    # cost at high run cardinality
    run_ends = np.full(nbucket, n, dtype=np.int32)
    run_ends[:nruns - 1] = run_starts[1:]
    # with host ends the kernel reads gids for first/last (arg-extreme
    # tie-break) and for high-cardinality min/max (the shift-doubling
    # kernel's same-segment guard); for every other op ts stands in for
    # shape and both the O(n) rid cumsum and its upload are skipped
    from ..ops.kernels import _SEG_HIGH_CARD_THRESHOLD, seg_len_bucket
    high_card = nbucket > _SEG_HIGH_CARD_THRESHOLD
    needs_gids = any(op in ("first", "last") for op in ops) or \
        (high_card and any(op in ("min", "max") for op in ops))
    seg_len_k = None
    if needs_gids:
        if rid is None:
            starts_mark = np.zeros(n, dtype=np.int32)
            starts_mark[run_starts[1:]] = 1
            rid = np.cumsum(starts_mark, dtype=np.int32)
            scan.device[run_key] = (rid, nruns, run_starts, buckets)
        d_rid = jax.device_put(rid)
        # static ceil-log2 of the longest run, bucketized to even values
        # so nearby layouts share one compile
        lens = np.diff(run_starts, append=np.int64(n))
        seg_len_k = seg_len_bucket(int(lens.max()) if len(lens) else 1)
    else:
        d_rid = d_ts
    results, counts = sorted_grouped_aggregate(
        d_rid, d_mask, d_ts, tuple(values), tuple(col_masks),
        num_groups=nbucket, ops=tuple(ops), has_col_masks=True,
        ends=run_ends, seg_len_k=seg_len_k)
    return _Launched(tuple(results), counts, nruns, sids[run_starts],
                     buckets[run_starts] if buckets is not None else None,
                     scan.series_dict, scan.ts_base)


def _collect_moment_frame(launched: _Launched, plan: TpuPlan,
                          counts: np.ndarray,
                          res_np: List[np.ndarray]) -> Optional[pd.DataFrame]:
    nruns = launched.nruns
    counts = counts[:nruns]
    res_np = [r[:nruns] for r in res_np]

    # ---- host: fold runs into final groups ----
    live = counts > 0
    if not live.any():
        return None
    frame: Dict[str, Any] = {}
    run_sids = launched.run_sids
    sd = launched.series_dict
    for tg in plan.tag_groups:
        frame[_group_slot(tg.name)] = sd.decode_tag_column(
            run_sids, tg.tag_index)
    if plan.bucket is not None:
        frame[_group_slot(plan.bucket.expr_key)] = \
            launched.run_buckets * plan.bucket.stride_ms + \
            plan.bucket.origin
    for m, r in zip(plan.moments, res_np):
        if m.op in ("min_ts", "max_ts"):
            # device ts is region-relative (ts - ts_base, base differs per
            # region); rebase to absolute so cross-region first/last merge
            # in _finalize compares comparable timestamps
            r = r.astype(np.int64) + launched.ts_base
        frame[m.slot] = r
    frame["__rowcount"] = counts
    df = pd.DataFrame(frame)[live]
    return df


def _nan_if_none(v):
    return np.nan if v is None else v


def _merge_sketch_cells(cells) -> Optional[bytes]:
    """Fold encoded sketch partials (bytes) into ONE re-encoded partial.
    Decode errors raise SketchCodecError — try_execute degrades the
    statement to the raw-row path rather than answer wrong."""
    from .sketches import decode_sketch, encode_sketch
    merged = None
    for c in cells:
        if c is None or (isinstance(c, float) and np.isnan(c)):
            continue
        sk = decode_sketch(c)
        merged = sk if merged is None else merged.merge(sk)
    return None if merged is None else encode_sketch(merged)


def _finalize(df: pd.DataFrame, plan: TpuPlan) -> pd.DataFrame:
    key_cols = [_group_slot(t.name) for t in plan.tag_groups]
    if plan.bucket is not None:
        key_cols.append(_group_slot(plan.bucket.expr_key))

    moment_cols = {m.slot: m for m in plan.moments}

    def _ts_slot_for(m: Moment, kind: str) -> str:
        return next(s for s, mm in moment_cols.items()
                    if mm.op == kind and mm.column == m.column)

    def merge(group: pd.DataFrame) -> pd.Series:
        out = {}
        for slot, m in moment_cols.items():
            v = group[slot]
            if m.op in SKETCH_MOMENT_OPS:
                out[slot] = _merge_sketch_cells(v)
            elif m.op in ("sum", "sum_sq", "count"):
                out[slot] = v.sum()
            elif m.op in ("min", "min_ts"):
                out[slot] = v.min()
            elif m.op in ("max", "max_ts"):
                out[slot] = v.max()
            elif m.op in ("first", "last"):
                # partial with a valid value whose ts is extreme wins
                kind = "min_ts" if m.op == "first" else "max_ts"
                ts_slot = _ts_slot_for(m, kind)
                nn = group[group[slot].notna()]
                if not len(nn):
                    out[slot] = None
                elif m.op == "first":
                    out[slot] = nn.loc[nn[ts_slot].idxmin(), slot]
                else:
                    out[slot] = nn.loc[nn[ts_slot].idxmax(), slot]
            elif m.op == "reset_corr":
                # partials are time-disjoint slices of one series run:
                # total correction = per-slice corrections + each slice
                # boundary that itself crosses a counter reset
                # (first-of-next < last-of-prev contributes the prev)
                g = group.sort_values(_ts_slot_for(m, "min_ts"),
                                      kind="stable")
                prev = g[_ts_slot_for(m, "last")].shift()
                cur = g[_ts_slot_for(m, "first")]
                cross = (cur < prev) & cur.notna() & prev.notna()
                out[slot] = g[slot].sum() + \
                    prev.where(cross, 0.0).fillna(0.0).sum()
        return pd.Series(out)

    if key_cols:
        if df[key_cols + list(moment_cols)].duplicated(key_cols).any():
            # vectorized fold: one groupby.agg for the decomposable
            # moments (a per-group Python merge costs seconds at 10k+
            # groups — slice streaming produces one partial per group
            # per slice), plus a sort+first/last pass for ts-extremes
            gb = df.groupby(key_cols, dropna=False, sort=False)
            aggs = {}
            extremes = []
            sketches = []
            resets = []
            for slot, m in moment_cols.items():
                if m.op in SKETCH_MOMENT_OPS:
                    sketches.append(slot)
                elif m.op == "reset_corr":
                    resets.append((slot, m))
                elif m.op in ("sum", "sum_sq", "count"):
                    aggs[slot] = "sum"
                elif m.op in ("min", "min_ts"):
                    aggs[slot] = "min"
                elif m.op in ("max", "max_ts"):
                    aggs[slot] = "max"
                else:
                    extremes.append((slot, m))
            aggs["__rowcount"] = "sum"      # a plan of only sketch
            merged = gb.agg(aggs)           # moments still needs keys
            for slot, m in extremes:
                # groupby.first()/.last() take the first/last NON-NULL
                # value in frame order; sorting by the companion ts makes
                # that "valid partial with extreme ts" exactly
                kind = "min_ts" if m.op == "first" else "max_ts"
                ts_slot = _ts_slot_for(m, kind)
                srt = df.sort_values(ts_slot, kind="stable")
                gs = srt.groupby(key_cols, dropna=False, sort=False)[slot]
                merged[slot] = gs.first() if m.op == "first" else gs.last()
            for slot in sketches:
                # fold encoded partials per group through the codec
                # (bytes in, bytes out — pandas treats bytes as scalars)
                merged[slot] = gb[slot].agg(_merge_sketch_cells)
            for slot, m in resets:
                # per-group partials sorted by slice start: corrections
                # add, plus the prev-last where a slice boundary itself
                # crosses a reset (first-of-next < last-of-prev)
                srt = df.sort_values(_ts_slot_for(m, "min_ts"),
                                     kind="stable")
                gs = srt.groupby(key_cols, dropna=False, sort=False)
                prev = gs[_ts_slot_for(m, "last")].shift()
                cur = srt[_ts_slot_for(m, "first")]
                cross = (cur < prev) & cur.notna() & prev.notna()
                bonus = prev.where(cross, 0.0).fillna(0.0)
                merged[slot] = gs[slot].sum() + bonus.groupby(
                    [srt[k] for k in key_cols], dropna=False,
                    sort=False).sum()
            merged = merged.reset_index()
        else:
            merged = df
    else:
        merged = merge(df).to_frame().T

    # finalize ops from moments
    out = merged[key_cols].copy() if key_cols else pd.DataFrame(
        index=merged.index)
    for slot, op, mslots in plan.finals:
        if op in ("sum", "min", "max", "first", "last", "moment"):
            # "moment": raw merged-moment passthrough — PromQL's rate
            # finalization reads min_ts/max_ts/reset_corr directly
            out[slot] = merged[mslots[0]]
        elif op == "count":
            out[slot] = merged[mslots[0]].astype(np.int64)
        elif op in ("count_distinct", "approx_distinct"):
            from .sketches import decode_sketch
            out[slot] = merged[mslots[0]].map(
                lambda b: 0 if b is None
                else decode_sketch(b).result()).astype(np.int64)
        elif op == "approx_percentile":
            from .sketches import decode_sketch
            p = plan.agg_params.get(slot, (50.0,))[0]
            out[slot] = merged[mslots[0]].map(
                lambda b: np.nan if b is None
                else _nan_if_none(decode_sketch(b).quantile(p))
            ).astype(np.float64)
        elif op == "avg":
            s, c = merged[mslots[0]], merged[mslots[1]]
            out[slot] = np.where(c > 0, s / np.maximum(c, 1), np.nan)
        elif op in ("stddev", "variance"):
            s, sq, c = (merged[m] for m in mslots)
            cc = np.maximum(c, 1)
            # sample variance (ddof=1) to match DataFusion; <2 rows → NULL;
            # s/cc promotes to float BEFORE the square — s*s wraps int cols
            var = np.maximum(sq - (s / cc) * s, 0.0) / np.maximum(c - 1, 1)
            var = np.where(c >= 2, var, np.nan)
            out[slot] = np.sqrt(var) if op == "stddev" else var
    # null out empty-count aggregates (kernel yields NaN already for floats)
    for slot, op, mslots in plan.finals:
        if op in ("sum", "min", "max", "first", "last", "avg"):
            cnt = None
            for ms in mslots:
                if moment_cols[ms].op == "count":
                    cnt = merged[ms]
            if cnt is not None:
                out.loc[cnt == 0, slot] = np.nan
    return out.reset_index(drop=True)
