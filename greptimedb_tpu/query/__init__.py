"""Query engine: SQL statements → TPU kernels (hot path) or a CPU
columnar fallback.

Reference behavior: src/query — the `QueryEngine` trait + DataFusion
executor (src/query/src/datafusion.rs:61-232). Here DataFusion's role is
split per SURVEY.md §7: a Python analyzer lowers the parsed AST, and XLA is
the physical executor for the scan→filter→group-by→time-bucket reduce
pipeline (ops/kernels.py); everything the TPU shape doesn't cover runs on a
pandas/numpy columnar fallback, mirroring how the reference leans on
DataFusion for the long tail.
"""

from .output import Output
from .engine import QueryEngine

__all__ = ["Output", "QueryEngine"]
