"""One columnar plan IR: the single lowering target for every front end.

Reference behavior: src/query — the reference plans SQL *and* PromQL
into one DataFusion LogicalPlan, and src/common/substrait ships that
plan to datanodes. This build's equivalent is small and columnar:

- `TpuPlan` (query/tpu_exec.py) — the aggregate node: time range, tag
  predicates, group keys (tags + one time bucket), moment specs with
  sketch/expression extras. SQL (`plan_for`), PromQL
  (promql/lowering.py) and flows (flow/lowering.py) all lower into it,
  and `execute_agg_plan` below is the ONE executor: cost-based scatter
  through `DistTable.execute_tpu_plan`, or local region moment frames
  (device-resident / streamed-cold / indexed-point), folded by
  `_finalize`.
- `RawScan` (here) — the scan leaf for the non-lowerable row paths:
  a projected, filtered, time-bounded `scan_batches` that still rides
  region pruning and wire filter pushdown on distributed tables.

query/plan_codec.py is the wire codec for the aggregate node (the
router→worker boundary); it validates moment/final ops on decode so a
version-skewed datanode rejects a plan it cannot fold instead of
folding it wrong — the frontend then degrades to `RawScan`.

Lowering table (which shape becomes which node, and what it rides):

  front end  shape                          IR node   fast paths
  ---------  -----------------------------  --------  -----------------
  SQL        GROUP BY tags [+ date_bin]     TpuPlan   scatter + pruning
             agg(sum/avg/.../sketches)                + fusion + index
  SQL        everything else                RawScan   pruning + filter
                                                      pushdown
  PromQL     sum/avg/min/max/count by (...) TpuPlan   same as SQL
             over instant selectors and
             rate/increase/delta/*_over_time
             tumbling range windows
  PromQL     regex joins, subqueries, topk… RawScan   pruning + filter
                                                      pushdown
  flow       FlowSpec aggregates            TpuPlan   moment-frame folds
                                                      (+ device rollup)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from ..errors import SketchCodecError, UnsupportedError
from .tpu_exec import (
    BucketGroup,
    Moment,
    TagGroup,
    TpuPlan,
    _aggs_desc,
    _finalize,
    dispatch_decision_for_pushdown,
    frames_nbytes,
    region_moment_frames,
    standard_final,
)

__all__ = [
    "BucketGroup", "Moment", "RawScan", "TagGroup", "TpuPlan",
    "execute_agg_plan", "execute_raw_scan", "group_key_columns",
    "plan_from_specs",
]


def group_key_columns(plan: TpuPlan) -> List[str]:
    """The finalized frame's key column names, in key order."""
    from .planner import _group_slot
    cols = [_group_slot(t.name) for t in plan.tag_groups]
    if plan.bucket is not None:
        cols.append(_group_slot(plan.bucket.expr_key))
    return cols


# ---------------------------------------------------------------------------
# raw-scan leaf
# ---------------------------------------------------------------------------

@dataclass
class RawScan:
    """The row-path scan leaf: what a non-lowerable statement still
    pushes down — a projection, conjunctive filters and a half-open
    time range. `DistTable.scan_batches` prunes regions and ships the
    pushable filter subset over the wire; local tables serve it from
    their region scans."""

    projection: Optional[List[str]] = None
    time_range: Optional[Tuple[Optional[int], Optional[int]]] = None
    filters: List = field(default_factory=list)
    limit: Optional[int] = None

    def describe(self) -> str:
        proj = "*" if self.projection is None \
            else ", ".join(self.projection)
        parts = [f"project=[{proj}]"]
        if self.time_range is not None:
            parts.append(f"time=[{self.time_range[0]}, "
                         f"{self.time_range[1]})")
        if self.filters:
            parts.append(f"filters={len(self.filters)}")
        if self.limit is not None:
            parts.append(f"limit={self.limit}")
        return f"RawScan: {' '.join(parts)}"


def execute_raw_scan(table, scan: RawScan) -> list:
    """Run the scan leaf against any table shape (local mito table or
    DistTable — both speak the scan_batches protocol)."""
    return table.scan_batches(projection=scan.projection,
                              time_range=scan.time_range,
                              limit=scan.limit,
                              filters=scan.filters or None)


# ---------------------------------------------------------------------------
# building the aggregate node from explicit specs (non-SQL front ends)
# ---------------------------------------------------------------------------

def plan_from_specs(schema, aggs: Sequence[Tuple[str, str, Optional[str]]],
                    *, group_tags: Sequence[str] = (),
                    bucket: Optional[BucketGroup] = None,
                    time_lo: Optional[int] = None,
                    time_hi: Optional[int] = None,
                    tag_predicates: Sequence = (),
                    moment_specs: Sequence[Tuple[str, str, Optional[str]]]
                    = ()) -> TpuPlan:
    """Build a TpuPlan from explicit (dest, op, column) aggregate specs
    — the PromQL and flow front ends' entry into the IR (SQL goes
    through `plan_for`, which pattern-matches the AST onto the same
    `standard_final` mapping, so the three lowerings cannot drift).

    `aggs` ops use the standard vocabulary (sum/avg/min/max/count/
    first/last/stddev/variance); `moment_specs` requests raw merged
    moments (dest, moment op, column) finalized via passthrough — how
    PromQL's rate reads min_ts/max_ts/reset_corr at the frontend.
    Moments are deduped across both lists, so e.g. a rate plan's
    `first` aggregate and its `min_ts` moment share slots."""
    tag_names = schema.tag_names()
    for t in group_tags:
        if t not in tag_names:
            raise UnsupportedError(f"unknown group tag {t!r}")
    tag_groups = [TagGroup(t, tag_names.index(t)) for t in group_tags]

    moments: List[Moment] = []
    seen: Dict[tuple, str] = {}

    def moment(op: str, column: Optional[str]) -> str:
        k = (op, column)
        if k in seen:
            return seen[k]
        slot = f"__m{len(moments)}"
        moments.append(Moment(op, column, slot))
        seen[k] = slot
        return slot

    finals: List[Tuple[str, str, List[str]]] = []
    for dest, op, col in aggs:
        std = standard_final(op, col, moment)
        if std is None:
            raise UnsupportedError(
                f"aggregate {op!r} has no moment decomposition")
        finals.append((dest, std[0], std[1]))
    for dest, mop, col in moment_specs:
        finals.append((dest, "moment", [moment(mop, col)]))
    return TpuPlan(tag_groups, bucket, moments, finals, time_lo, time_hi,
                   list(tag_predicates), [], {}, {})


# ---------------------------------------------------------------------------
# the ONE aggregate-node executor
# ---------------------------------------------------------------------------

def execute_agg_plan(table, plan: TpuPlan) -> pd.DataFrame:
    """Execute the IR aggregate node and return the finalized frame
    (group key columns + final slots).

    Every fold in the system funnels here: SQL's `try_execute`, the
    PromQL lowering and flow folds. Distributed tables scatter the plan
    through their cost-based `_plan_scatter` (datanodes reduce, the
    frontend folds moment frames); local tables reduce their regions
    through the resident / streamed / indexed dispatch. Raises
    UnsupportedError when the statement should degrade to the raw-row
    path — cost-based dispatch chose raw-pull, a datanode rejected a
    version-skewed plan, or a sketch partial failed to decode — never
    a wrong answer."""
    from ..common import exec_stats
    from ..common.telemetry import span, timer

    if hasattr(table, "execute_tpu_plan"):
        # distributed: aggregate pushdown — datanodes reduce their
        # regions, the frontend folds moment frames (_finalize).
        # The table names its own scatter (pruning + fan-out) when it
        # can, so EXPLAIN and execution print the same decision.
        exec_stats.set_dispatch(dispatch_decision_for_pushdown(
            table, plan))
        with span("tpu_pushdown", table=table.name), \
                timer("tpu_pushdown"):
            frames = [f for f in table.execute_tpu_plan(plan)
                      if f is not None and len(f)]
    else:
        import time as _time

        from .tpu_exec import _note_device_query_time
        t0 = _time.perf_counter()
        with span("tpu_execute", table=table.name), \
                timer("tpu_execute"):
            frames = region_moment_frames(table, plan)
        _note_device_query_time(_time.perf_counter() - t0)
    if not frames:
        cols = group_key_columns(plan)
        if cols:
            return pd.DataFrame(columns=cols +
                                [slot for slot, _, _ in plan.finals])
        # global aggregate over zero rows still yields one row
        row = {slot: (0 if op in ("count", "count_distinct",
                                  "approx_distinct") else np.nan)
               for slot, op, _ in plan.finals}
        return pd.DataFrame([row])
    with exec_stats.stage("finalize", partial_frames=len(frames),
                          partial_bytes=frames_nbytes(frames),
                          aggs=_aggs_desc(plan)):
        merged = pd.concat(frames, ignore_index=True)
        try:
            out = _finalize(merged, plan)
        except SketchCodecError as e:
            # a corrupt/truncated sketch partial must NEVER become a
            # wrong answer: count the degrade and fall back to the
            # raw-row path (the caller re-runs this statement as a
            # plain scan + CPU aggregate)
            import logging

            from ..common.telemetry import increment_counter
            increment_counter("sketch_degrade")
            exec_stats.record("sketch_degrade", error=str(e)[:120])
            logging.getLogger(__name__).warning(
                "sketch partial failed to decode (%s); retrying %s via "
                "the raw-row path", e, table.name)
            raise UnsupportedError(
                f"sketch partial failed to decode: {e}") from e
    exec_stats.record("finalize", rows=len(out))
    return out
