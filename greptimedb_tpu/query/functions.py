"""Built-in SQL functions.

Reference behavior: src/common/function — scalar math/numpy functions
(pow, rate, clip, interp — scalars/{math,numpy}/), timestamp helpers
(to_unixtime), and accumulator aggregates (argmax, argmin, mean, diff,
percentile, polyval, scipy_stats_norm_{cdf,pdf} —
scalars/aggregate/). Plus the DataFusion builtins the reference inherits
(abs/ceil/floor/round/sqrt/log/exp/trig, date_bin/date_trunc, now).

Scalar functions operate on numpy arrays (broadcast over scalars);
aggregates map a 1-D array → scalar. The TPU path uses ops/kernels.py for
the hot aggregates; these host implementations are the fallback and the
oracle.
"""

from __future__ import annotations

import math
import time
from typing import Callable, Dict, Optional

import numpy as np

from ..errors import InvalidArgumentsError


# ---------------------------------------------------------------------------
# interval parsing (SQL INTERVAL literals + PromQL-style durations)
# ---------------------------------------------------------------------------

_UNIT_MS = {
    "ms": 1, "millisecond": 1, "milliseconds": 1,
    "s": 1000, "sec": 1000, "second": 1000, "seconds": 1000,
    "m": 60_000, "min": 60_000, "minute": 60_000, "minutes": 60_000,
    "h": 3_600_000, "hour": 3_600_000, "hours": 3_600_000,
    "d": 86_400_000, "day": 86_400_000, "days": 86_400_000,
    "w": 604_800_000, "week": 604_800_000, "weeks": 604_800_000,
    "y": 31_536_000_000, "year": 31_536_000_000, "years": 31_536_000_000,
}


def parse_interval_ms(text: str) -> int:
    """'1 minute' / '5m' / '1h30m' / '90' (seconds per PromQL bare) → ms."""
    s = text.strip().lower()
    if not s:
        raise InvalidArgumentsError("empty interval")
    total = 0.0
    num = ""
    unit = ""
    items = []
    for ch in s:
        if ch.isdigit() or ch == "." or (ch == "-" and not num and not items):
            if unit:
                items.append((num, unit))
                num, unit = "", ""
            num += ch
        elif ch == " ":
            continue
        else:
            unit += ch
    items.append((num, unit))
    for num, unit in items:
        if not num:
            raise InvalidArgumentsError(f"bad interval: {text!r}")
        if not unit:
            total += float(num) * 1000  # bare number = seconds
            continue
        unit = unit.strip()
        if unit not in _UNIT_MS:
            raise InvalidArgumentsError(f"unknown interval unit {unit!r}")
        total += float(num) * _UNIT_MS[unit]
    return int(total)


# ---------------------------------------------------------------------------
# scalar functions
# ---------------------------------------------------------------------------

def _rate(values, timestamps=None):
    """Per-second rate between consecutive points (reference:
    scalars/math/rate.rs): diff(v) / diff(ts_seconds); first element null."""
    v = np.asarray(values, dtype=np.float64)
    out = np.full(v.shape, np.nan)
    if timestamps is None:
        out[1:] = np.diff(v)
        return out
    t = np.asarray(timestamps, dtype=np.float64) / 1000.0
    dt = np.diff(t)
    with np.errstate(divide="ignore", invalid="ignore"):
        out[1:] = np.diff(v) / np.where(dt == 0, np.nan, dt)
    return out


def _date_bin(interval_ms, ts, origin=0):
    t = np.asarray(ts, dtype=np.int64)
    step = int(interval_ms)
    return ((t - origin) // step) * step + origin


_TRUNC_MS = {"second": 1000, "minute": 60_000, "hour": 3_600_000,
             "day": 86_400_000, "week": 604_800_000}
# weeks are Monday-aligned (epoch 1970-01-01 is a Thursday; first epoch
# Monday is 1970-01-05), matching DataFusion date_trunc
_WEEK_ORIGIN_MS = 4 * 86_400_000


def _date_trunc(unit, ts):
    u = str(unit).lower()
    if u in _TRUNC_MS:
        step = _TRUNC_MS[u]
        t = np.asarray(ts, dtype=np.int64)
        if u == "week":
            return ((t - _WEEK_ORIGIN_MS) // step) * step + _WEEK_ORIGIN_MS
        return (t // step) * step
    # month/year need calendar math
    import pandas as pd
    s = pd.to_datetime(np.asarray(ts, dtype=np.int64), unit="ms", utc=True)
    if u == "month":
        out = s.to_period("M").to_timestamp(tz="UTC")
    elif u == "year":
        out = s.to_period("Y").to_timestamp(tz="UTC")
    else:
        raise InvalidArgumentsError(f"unsupported date_trunc unit {unit!r}")
    return (out.asi8 // 1_000_000).astype(np.int64)


def _to_unixtime(v):
    a = np.asarray(v)
    if a.dtype.kind in "iuf":
        return a.astype(np.int64)
    import pandas as pd
    return (pd.to_datetime(a, utc=True).asi8 // 1_000_000_000).astype(np.int64)


def _clip(v, lo, hi):
    return np.clip(np.asarray(v, dtype=np.float64), lo, hi)


def _interp(x, xp, fp):
    return np.interp(np.asarray(x, np.float64), np.asarray(xp, np.float64),
                     np.asarray(fp, np.float64))


SCALAR_FUNCTIONS: Dict[str, Callable] = {
    "abs": np.abs, "ceil": np.ceil, "floor": np.floor,
    "round": lambda v, d=0: np.round(np.asarray(v, np.float64), int(d)),
    "sqrt": np.sqrt, "exp": np.exp, "ln": np.log, "log": np.log10,
    "log2": np.log2, "log10": np.log10, "sin": np.sin, "cos": np.cos,
    "tan": np.tan, "asin": np.arcsin, "acos": np.arccos, "atan": np.arctan,
    "atan2": np.arctan2, "signum": np.sign, "sign": np.sign,
    "power": np.power, "pow": np.power, "mod": np.mod,
    "clip": _clip, "interp": _interp, "rate": _rate,
    "to_unixtime": _to_unixtime,
    "date_bin": _date_bin, "date_trunc": _date_trunc,
    "length": lambda v: np.asarray([len(x) if x is not None else None
                                    for x in np.asarray(v, object)], object),
    "lower": lambda v: np.asarray([x.lower() if isinstance(x, str) else x
                                   for x in np.asarray(v, object)], object),
    "upper": lambda v: np.asarray([x.upper() if isinstance(x, str) else x
                                   for x in np.asarray(v, object)], object),
    "concat": lambda *vs: np.asarray(
        ["".join(str(x) for x in row) for row in zip(
            *[np.asarray(v, object) for v in vs])], object),
    "coalesce": lambda *vs: _coalesce(*vs),
}


def _coalesce(*vs):
    arrs = [np.asarray(v, object) for v in vs]
    out = arrs[0].copy()
    for a in arrs[1:]:
        sel = np.array([x is None or (isinstance(x, float) and math.isnan(x))
                        for x in out])
        out[sel] = a[sel]
    return out


# zero-arg / context functions, evaluated per query
def now_ms() -> int:
    return int(time.time() * 1000)


# ---------------------------------------------------------------------------
# aggregate functions (host/fallback implementations = the oracle)
# ---------------------------------------------------------------------------

def _valid(a):
    a = np.asarray(a)
    if a.dtype.kind == "f":
        return a[~np.isnan(a)]
    if a.dtype == object:
        return np.asarray([x for x in a if x is not None])
    return a


def _agg_percentile(a, p):
    v = _valid(a)
    return float(np.percentile(v.astype(np.float64), p)) if v.size else None


def _agg_argmax(a):
    v = np.asarray(a, dtype=np.float64)
    if not v.size or np.all(np.isnan(v)):
        return None
    return int(np.nanargmax(v))


def _agg_argmin(a):
    v = np.asarray(a, dtype=np.float64)
    if not v.size or np.all(np.isnan(v)):
        return None
    return int(np.nanargmin(v))


def _agg_diff(a):
    """Aggregate diff: returns the list of consecutive differences
    (reference: scalars/aggregate/diff.rs outputs a vector)."""
    v = _valid(a).astype(np.float64)
    return np.diff(v).tolist() if v.size > 1 else []


def _agg_polyval(a, x):
    v = _valid(a).astype(np.float64)
    return float(np.polyval(v, x)) if v.size else None


def _norm_cdf(x):
    return 0.5 * (1.0 + math.erf(x / math.sqrt(2.0)))


def _agg_norm_cdf(a, x=0.0):
    v = _valid(a).astype(np.float64)
    if not v.size:
        return None
    mu, sigma = float(v.mean()), float(v.std())
    if sigma == 0:
        return 0.5
    return _norm_cdf((x - mu) / sigma)


def _agg_norm_pdf(a, x=0.0):
    v = _valid(a).astype(np.float64)
    if not v.size:
        return None
    mu, sigma = float(v.mean()), float(v.std())
    if sigma == 0:
        return None
    z = (x - mu) / sigma
    return math.exp(-0.5 * z * z) / (sigma * math.sqrt(2 * math.pi))


def _agg_approx_distinct(a):
    """Sketch-backed distinct count — the standalone twin of the
    distributed HLL pushdown (query/sketches.py): exact below the
    bounded set size, HLL past it, so both engines answer within the
    same documented bound."""
    from .sketches import DistinctSketch
    v = _valid(a)
    if not v.size:
        return 0
    return DistinctSketch.from_values(v).result()


def _agg_approx_percentile(a, p=None):
    if p is None:
        raise InvalidArgumentsError(
            "approx_percentile(x, p) needs a percentile argument")
    p = float(p)
    if not (0.0 <= p <= 100.0):
        raise InvalidArgumentsError(
            f"approx_percentile: p must be in [0, 100], got {p}")
    from .sketches import TDigest
    v = _valid(a)
    if not v.size:
        return None
    return TDigest.from_values(v.astype(np.float64)).quantile(p)


def _agg_median(a):
    """t-digest median (documented approximation, same bound as
    approx_percentile(x, 50)); use percentile(x, 50) for the exact
    sort-based answer."""
    return _agg_approx_percentile(a, 50.0)


AGGREGATE_FUNCTIONS: Dict[str, Callable] = {
    "count": lambda a: int(_valid(a).size),
    "sum": lambda a: (lambda v: float(v.astype(np.float64).sum())
                      if v.size else None)(_valid(a)),
    "avg": lambda a: (lambda v: float(v.astype(np.float64).mean())
                      if v.size else None)(_valid(a)),
    "mean": lambda a: AGGREGATE_FUNCTIONS["avg"](a),
    "min": lambda a: (lambda v: v.min() if v.size else None)(_valid(a)),
    "max": lambda a: (lambda v: v.max() if v.size else None)(_valid(a)),
    # sample (ddof=1) to match DataFusion and the window path; <2 rows → NULL
    "stddev": lambda a: (lambda v: float(v.astype(np.float64).std(ddof=1))
                         if v.size >= 2 else None)(_valid(a)),
    "variance": lambda a: (lambda v: float(v.astype(np.float64).var(ddof=1))
                           if v.size >= 2 else None)(_valid(a)),
    "argmax": _agg_argmax,
    "argmin": _agg_argmin,
    "percentile": _agg_percentile,
    "approx_distinct": _agg_approx_distinct,
    "approx_percentile": _agg_approx_percentile,
    "median": _agg_median,
    "diff": _agg_diff,
    "polyval": _agg_polyval,
    "scipy_stats_norm_cdf": _agg_norm_cdf,
    "scipy_stats_norm_pdf": _agg_norm_pdf,
}

# aggregates the TPU sorted kernel executes natively (ops/kernels.py AGG_OPS)
TPU_AGGREGATES = {"count", "sum", "avg", "min", "max", "stddev", "variance",
                  "first", "last"}

# aggregates served by sketch partials in the partial-pushdown algebra
# (query/sketches.py): datanodes build per-group sketches, the frontend
# merges — plus count(DISTINCT x), which rides the same distinct sketch
SKETCH_AGGREGATES = {"approx_distinct", "approx_percentile", "median"}


# ---------------------------------------------------------------------------
# user-defined functions (coprocessors registered by the script engine;
# reference: src/script/src/python/engine.rs:44-80 registers each compiled
# coprocessor as a UDF in the query engine)
# ---------------------------------------------------------------------------

UDF_REGISTRY: Dict[str, Callable] = {}


def register_udf(name: str, fn: Callable) -> None:
    UDF_REGISTRY[name.lower()] = fn


def unregister_udf(name: str) -> None:
    UDF_REGISTRY.pop(name.lower(), None)
