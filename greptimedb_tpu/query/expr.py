"""Scalar SQL expression evaluation over columnar data (numpy/pandas).

This is the CPU fallback's evaluator and the filter/projection evaluator
shared with the TPU path's host-side pieces. Columns live in a pandas
DataFrame (nulls as NaN/None); expressions produce pandas Series (or python
scalars for constant folds).
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional

import numpy as np
import pandas as pd

from ..datatypes.data_type import parse_type_name
from ..errors import ColumnNotFoundError, PlanError, UnsupportedError
from ..sql.ast import (
    Between, BinaryOp, Case, Cast, Column, Expr, FunctionCall, InList,
    Interval, IsNull, Literal, Placeholder, Star, Subquery, UnaryOp,
)
from .functions import SCALAR_FUNCTIONS, now_ms, parse_interval_ms


def like_to_regex(pattern: str) -> str:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


def expr_name(e: Expr) -> str:
    """Display/column name for an unaliased projection (DataFusion-style)."""
    if isinstance(e, Column):
        return e.name
    if isinstance(e, Star):
        return "*"
    if isinstance(e, FunctionCall):
        inner = ", ".join(expr_name(a) for a in e.args)
        if e.distinct:
            inner = "DISTINCT " + inner
        base = f"{e.name}({inner})"
        if e.over is not None:
            # distinct OVER specs are distinct expressions: the window
            # rewriter dedups by this name, and projections of two
            # windows of the same function must not collide
            return f"{base} OVER ({e.over})"
        return base
    if isinstance(e, Literal):
        return str(e)
    if isinstance(e, BinaryOp):
        return f"{expr_name(e.left)} {e.op.upper()} {expr_name(e.right)}"
    if isinstance(e, UnaryOp):
        return f"{e.op.upper()} {expr_name(e.operand)}" if e.op == "not" \
            else f"{e.op}{expr_name(e.operand)}"
    if isinstance(e, Cast):
        return f"CAST({expr_name(e.expr)} AS {e.type_name})"
    if isinstance(e, IsNull):
        return f"{expr_name(e.expr)} IS {'NOT ' if e.negated else ''}NULL"
    return type(e).__name__.lower()


class Evaluator:
    def __init__(self, df: pd.DataFrame, params: Optional[Dict[int, Any]] = None):
        self.df = df
        self.params = params or {}
        self._now = now_ms()

    def series(self, value) -> pd.Series:
        """Broadcast a scalar result to a column aligned with the frame's
        index (the frame may be a WHERE-filtered view with gaps)."""
        if isinstance(value, pd.Series):
            return value
        return pd.Series([value] * len(self.df), index=self.df.index)

    def eval(self, e: Expr):
        if isinstance(e, Literal):
            return e.value
        if isinstance(e, Column):
            key = e.name
            if e.table and f"{e.table}.{e.name}" in self.df.columns:
                # joined frames carry alias-qualified columns
                return self.df[f"{e.table}.{e.name}"]
            if key not in self.df.columns:
                # case-insensitive fallback (MySQL compat)
                lowered = {c.lower(): c for c in self.df.columns}
                if key.lower() in lowered:
                    key = lowered[key.lower()]
                else:
                    raise ColumnNotFoundError(f"column {e.name!r} not found")
            return self.df[key]
        if isinstance(e, Interval):
            return parse_interval_ms(e.text)
        if isinstance(e, Placeholder):
            if e.index not in self.params:
                raise PlanError(f"unbound placeholder ?{e.index}")
            return self.params[e.index]
        if isinstance(e, UnaryOp):
            v = self.eval(e.operand)
            if e.op == "not":
                return self._negate(self._as_bool(v))
            if e.op == "-":
                return -self._num(v)
            return v
        if isinstance(e, BinaryOp):
            return self._binary(e)
        if isinstance(e, Between):
            v = self._num_or_raw(self.eval(e.expr))
            lo = self.eval(e.low)
            hi = self.eval(e.high)
            out = (v >= lo) & (v <= hi)
            return self._negate(self._as_bool(out)) if e.negated else out
        if isinstance(e, InList):
            if any(isinstance(i, Subquery) for i in e.items):
                raise UnsupportedError("IN (subquery) is not supported yet")
            v = self.eval(e.expr)
            items = [self.eval(i) for i in e.items]
            s = v if isinstance(v, pd.Series) else self.series(v)
            out = s.isin(items)
            return ~out if e.negated else out
        if isinstance(e, IsNull):
            v = self.eval(e.expr)
            s = v if isinstance(v, pd.Series) else self.series(v)
            out = s.isna()
            return ~out if e.negated else out
        if isinstance(e, Cast):
            return self._cast(self.eval(e.expr), e.type_name)
        if isinstance(e, Case):
            return self._case(e)
        if isinstance(e, FunctionCall):
            return self._call(e)
        if isinstance(e, Star):
            raise PlanError("'*' is only valid as a projection or in count(*)")
        if isinstance(e, Subquery):
            raise UnsupportedError("scalar subqueries are not supported yet")
        raise UnsupportedError(f"cannot evaluate {type(e).__name__}")

    # ---- helpers ----
    def _as_bool(self, v):
        if isinstance(v, pd.Series):
            return v.fillna(False).astype(bool)
        return bool(v)

    @staticmethod
    def _negate(b):
        """Boolean NOT that is safe for scalars: ~True is -2 (truthy!),
        so Python bools must use `not`, Series use `~`."""
        return ~b if isinstance(b, pd.Series) else (not b)

    def _num(self, v):
        return v

    def _num_or_raw(self, v):
        return v

    def _binary(self, e: BinaryOp):
        op = e.op
        if op in ("and", "or"):
            l = self._as_bool(self.eval(e.left))
            r = self._as_bool(self.eval(e.right))
            return (l & r) if op == "and" else (l | r)
        l = self.eval(e.left)
        r = self.eval(e.right)
        if op in ("like", "ilike", "regexp"):
            if not isinstance(r, str):
                raise PlanError(f"{op.upper()} pattern must be a string")
            pattern = like_to_regex(r) if op in ("like", "ilike") else r
            flags = re.IGNORECASE if op == "ilike" else 0
            s = l if isinstance(l, pd.Series) else self.series(l)
            return s.astype("string").str.match(pattern, flags=flags,
                                                na=False).astype(bool)
        if op == "||":
            ls = l if isinstance(l, pd.Series) else self.series(l)
            return ls.astype("string") + pd.Series(r).astype("string")[0] \
                if not isinstance(r, pd.Series) \
                else ls.astype("string") + r.astype("string")
        try:
            if op == "=":
                return l == r
            if op == "!=":
                return l != r
            if op == "<":
                return l < r
            if op == "<=":
                return l <= r
            if op == ">":
                return l > r
            if op == ">=":
                return l >= r
            if op == "+":
                return l + r
            if op == "-":
                return l - r
            if op == "*":
                return l * r
            if op == "/":
                return self._div(l, r)
            if op == "%":
                return l % r
        except TypeError as err:
            raise PlanError(f"type error in {op!r}: {err}") from err
        raise UnsupportedError(f"operator {op!r}")

    def _div(self, l, r):
        with np.errstate(divide="ignore", invalid="ignore"):
            lv = l.astype(np.float64) if isinstance(l, pd.Series) else float(l)
            rv = r.astype(np.float64) if isinstance(r, pd.Series) else float(r)
            return lv / rv

    def _cast(self, v, type_name: str):
        """SQL CAST semantics: NULL in → NULL out for every target type
        (pandas astype would either raise on NaN→int or coerce NaN→True
        for bool), and invalid literals surface as taxonomy errors."""
        from ..errors import InvalidArgumentsError
        tn = type_name.strip().lower()
        try:
            if tn in ("date", "timestamp", "datetime"):
                if isinstance(v, pd.Series):
                    dtv = pd.to_datetime(v, utc=True)
                    return dtv.map(
                        lambda x: None if pd.isna(x)
                        else int(x.value // 1_000_000))
                return int(pd.Timestamp(v, tz="UTC").value // 1_000_000)
            dtype = parse_type_name(type_name)
            if isinstance(v, pd.Series):
                if dtype.is_string:
                    return v.astype("string")
                kind = np.dtype(dtype.np_dtype).kind \
                    if dtype.np_dtype is not None else "O"
                if kind in "iu" and v.dtype.kind in "fO":
                    # float→int CAST rounds (Postgres semantics), and the
                    # same way whether or not the column holds NULLs
                    num = pd.to_numeric(v)
                    if num.isna().any():
                        return num.map(
                            lambda x: None if pd.isna(x)
                            else int(round(float(x))))
                    return np.rint(num.to_numpy(np.float64)) \
                        .astype(dtype.np_dtype)
                if kind == "b" and v.isna().any():
                    return v.map(lambda x: None if pd.isna(x)
                                 else bool(x))
                return v.astype(dtype.np_dtype)
            return dtype.cast_value(v) if v is not None else None
        except (ValueError, TypeError, OverflowError) as err:
            raise InvalidArgumentsError(
                f"cannot cast value to {type_name}: {err}") from None

    def _case(self, e: Case):
        idx = self.df.index
        result = pd.Series([None] * len(idx), dtype=object, index=idx)
        decided = pd.Series([False] * len(idx), index=idx)
        for cond, value in e.whens:
            if e.operand is not None:
                c = self.eval(BinaryOp("=", e.operand, cond)) \
                    if not isinstance(cond, Expr) else \
                    self._as_bool(self.series(self.eval(e.operand))
                                  == self.series(self.eval(cond)))
            else:
                c = self._as_bool(self.series(self.eval(cond)))
            c = self.series(c).fillna(False).astype(bool)
            take = c & ~decided
            v = self.series(self.eval(value))
            result[take] = v[take]
            decided |= take
        if e.else_ is not None:
            v = self.series(self.eval(e.else_))
            result[~decided] = v[~decided]
        return result.infer_objects()

    def _call(self, e: FunctionCall):
        name = e.name
        if name == "now" or name == "current_timestamp":
            return self._now
        if name in SCALAR_FUNCTIONS:
            args = [self.eval(a) for a in e.args]
            np_args = [a.to_numpy() if isinstance(a, pd.Series) else a
                       for a in args]
            out = SCALAR_FUNCTIONS[name](*np_args)
            if isinstance(out, np.ndarray) and len(self.df):
                return pd.Series(out, index=self.df.index)
            return out
        from .functions import UDF_REGISTRY
        if name in UDF_REGISTRY:
            args = [self.eval(a) for a in e.args]
            np_args = [a.to_numpy() if isinstance(a, pd.Series) else a
                       for a in args]
            out = UDF_REGISTRY[name](*np_args)
            if isinstance(out, np.ndarray) and len(self.df) and \
                    len(out) == len(self.df):
                return pd.Series(out, index=self.df.index)
            return out
        raise UnsupportedError(f"unknown function {name!r}")
