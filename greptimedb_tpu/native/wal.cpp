// Native WAL: segmented append log with group commit.
//
// Reference behavior: src/log-store/src/raft_engine/log_store.rs — the
// reference delegates WAL throughput to raft-engine (a native Rust log
// with batched fsync). This is the C++ twin for the TPU build's host
// runtime: many writer threads append under one mutex; a single
// group-commit thread turns N concurrent durability requests into one
// fdatasync (the classic group commit), with epoch tickets so writers
// wait only for *their* sync.
//
// On-disk format is IDENTICAL to the Python Wal (storage/wal.py):
//   segments named {first_seq:020}.wal, records
//   [len u32][crc32 u32][seq u64][schema_version u32][payload]
// so either implementation can replay the other's log.

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// zlib-compatible CRC32 (slice-by-1 table; matches Python zlib.crc32)
uint32_t crc_table[256];
std::once_flag crc_once;

void init_crc() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
}

uint32_t crc32(const uint8_t* data, size_t len) {
  std::call_once(crc_once, init_crc);
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    c = crc_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Wal {
  std::string dir;
  uint64_t segment_bytes;
  uint32_t group_interval_us;

  std::mutex mu;                 // guards fd/size/dirty/epoch bookkeeping
  int fd = -1;
  std::string fd_path;
  uint64_t fd_size = 0;

  // group commit state
  std::condition_variable cv;
  uint64_t requested_epoch = 0;  // bumped per append needing durability
  uint64_t synced_epoch = 0;
  bool dirty = false;
  bool stop = false;
  std::thread syncer;

  ~Wal() {
    {
      std::lock_guard<std::mutex> g(mu);
      stop = true;
    }
    cv.notify_all();
    if (syncer.joinable()) syncer.join();
    if (fd >= 0) {
      ::fdatasync(fd);
      ::close(fd);
    }
  }
};

std::string segment_name(uint64_t first_seq) {
  char buf[32];
  std::snprintf(buf, sizeof(buf), "%020llu.wal",
                (unsigned long long)first_seq);
  return std::string(buf);
}

int open_segment(Wal* w, uint64_t first_seq) {
  if (w->fd >= 0) {
    ::fdatasync(w->fd);
    ::close(w->fd);
    w->fd = -1;
  }
  std::string path = w->dir + "/" + segment_name(first_seq);
  int fd = ::open(path.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (fd < 0) return -errno;
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    int e = errno;
    ::close(fd);
    return -e;
  }
  w->fd = fd;
  w->fd_path = path;
  w->fd_size = (uint64_t)st.st_size;
  return 0;
}

// resume onto the highest existing segment (append continues there)
int resume(Wal* w) {
  DIR* d = ::opendir(w->dir.c_str());
  if (d == nullptr) return -errno;
  uint64_t best = 0;
  bool found = false;
  struct dirent* ent;
  while ((ent = ::readdir(d)) != nullptr) {
    std::string fn(ent->d_name);
    if (fn.size() == 24 && fn.substr(20) == ".wal") {
      uint64_t v = std::strtoull(fn.substr(0, 20).c_str(), nullptr, 10);
      if (!found || v > best) best = v;
      found = true;
    }
  }
  ::closedir(d);
  if (found) return open_segment(w, best);
  return 0;  // first append opens a segment
}

void sync_loop(Wal* w) {
  std::unique_lock<std::mutex> lk(w->mu);
  while (!w->stop) {
    w->cv.wait_for(lk, std::chrono::microseconds(w->group_interval_us),
                   [w] { return w->stop || w->dirty; });
    if (w->stop) break;
    if (!w->dirty) continue;
    uint64_t target = w->requested_epoch;
    int fd = w->fd;
    w->dirty = false;
    lk.unlock();
    if (fd >= 0) ::fdatasync(fd);   // ONE sync covers every waiter <= target
    lk.lock();
    if (w->synced_epoch < target) w->synced_epoch = target;
    w->cv.notify_all();
  }
}

}  // namespace

extern "C" {

void* wal_open(const char* dir, uint64_t segment_bytes,
               uint32_t group_interval_us) {
  ::mkdir(dir, 0755);  // best-effort; parents made by caller
  Wal* w = new Wal();
  w->dir = dir;
  w->segment_bytes = segment_bytes ? segment_bytes : (64ull << 20);
  w->group_interval_us = group_interval_us ? group_interval_us : 1000;
  if (resume(w) < 0) {
    delete w;
    return nullptr;
  }
  w->syncer = std::thread(sync_loop, w);
  return w;
}

// Appends one record; returns the durability ticket (epoch) to pass to
// wal_wait, or a negative errno.
int64_t wal_append(void* h, uint64_t seq, uint32_t schema_version,
                   const uint8_t* data, uint32_t len) {
  Wal* w = (Wal*)h;
  uint8_t hdr[20];
  uint32_t crc = crc32(data, len);
  std::memcpy(hdr + 0, &len, 4);
  std::memcpy(hdr + 4, &crc, 4);
  std::memcpy(hdr + 8, &seq, 8);
  std::memcpy(hdr + 16, &schema_version, 4);

  std::lock_guard<std::mutex> g(w->mu);
  if (w->fd < 0 || w->fd_size >= w->segment_bytes) {
    int rc = open_segment(w, seq);
    if (rc < 0) return rc;
  }
  // one buffer, one write syscall: records stay atomic wrt other
  // appenders (O_APPEND)
  std::vector<uint8_t> rec(20 + len);
  std::memcpy(rec.data(), hdr, 20);
  if (len) std::memcpy(rec.data() + 20, data, len);
  ssize_t n = ::write(w->fd, rec.data(), rec.size());
  if (n != (ssize_t)rec.size()) return n < 0 ? -errno : -EIO;
  w->fd_size += rec.size();
  w->dirty = true;
  uint64_t ticket = ++w->requested_epoch;
  w->cv.notify_all();
  return (int64_t)ticket;
}

// Block until the given ticket (or everything, ticket==0 → current) is
// durable. Returns 0, or -ETIMEDOUT after timeout_ms (0 = forever).
int wal_wait(void* h, int64_t ticket, uint32_t timeout_ms) {
  Wal* w = (Wal*)h;
  std::unique_lock<std::mutex> lk(w->mu);
  uint64_t target = ticket > 0 ? (uint64_t)ticket : w->requested_epoch;
  auto pred = [w, target] { return w->synced_epoch >= target; };
  if (timeout_ms == 0) {
    w->cv.wait(lk, pred);
    return 0;
  }
  if (!w->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred))
    return -ETIMEDOUT;
  return 0;
}

int wal_sync(void* h) {
  Wal* w = (Wal*)h;
  std::unique_lock<std::mutex> lk(w->mu);
  uint64_t target = w->requested_epoch;
  if (w->synced_epoch >= target && !w->dirty) return 0;
  int fd = w->fd;
  w->dirty = false;
  lk.unlock();
  if (fd >= 0 && ::fdatasync(fd) != 0) return -errno;
  lk.lock();
  if (w->synced_epoch < target) w->synced_epoch = target;
  w->cv.notify_all();
  return 0;
}

// Delete whole segments entirely <= seq (same rule as the Python Wal:
// a segment is deletable when the NEXT segment starts at <= seq+1 and it
// is not the active segment).
int wal_obsolete(void* h, uint64_t seq) {
  Wal* w = (Wal*)h;
  std::vector<uint64_t> firsts;
  {
    DIR* d = ::opendir(w->dir.c_str());
    if (d == nullptr) return -errno;
    struct dirent* ent;
    while ((ent = ::readdir(d)) != nullptr) {
      std::string fn(ent->d_name);
      if (fn.size() == 24 && fn.substr(20) == ".wal")
        firsts.push_back(
            std::strtoull(fn.substr(0, 20).c_str(), nullptr, 10));
    }
    ::closedir(d);
  }
  std::sort(firsts.begin(), firsts.end());
  std::lock_guard<std::mutex> g(w->mu);
  for (size_t i = 0; i + 1 < firsts.size(); i++) {
    if (firsts[i + 1] <= seq + 1) {
      std::string path = w->dir + "/" + segment_name(firsts[i]);
      if (path == w->fd_path) continue;
      ::unlink(path.c_str());
    }
  }
  return 0;
}

void wal_close(void* h) { delete (Wal*)h; }

}  // extern "C"
