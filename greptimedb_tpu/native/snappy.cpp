// Snappy block-format codec (compress + decompress).
//
// Prometheus remote read/write bodies are snappy-compressed protobuf
// (reference: src/servers/src/prometheus.rs:286-373, via the snappy
// crate). The image ships no snappy library, so this implements the
// block format natively: greedy 4-byte hash matching on the comppress
// side (the classic snappy scheme), full tag support on the decompress
// side. Bound via ctypes (storage/native_snappy.py) with the pure-
// Python codec as fallback.

#include <cstdint>
#include <cstring>

namespace {

inline uint32_t load32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint32_t hash32(uint32_t v) {
  return (v * 0x1e35a7bdu) >> 18;   // 14-bit table
}

constexpr int kHashBits = 14;
constexpr int kHashSize = 1 << kHashBits;

size_t write_varint(uint8_t* dst, uint64_t n) {
  size_t i = 0;
  while (n >= 0x80) {
    dst[i++] = (uint8_t)(n | 0x80);
    n >>= 7;
  }
  dst[i++] = (uint8_t)n;
  return i;
}

size_t emit_literal(uint8_t* dst, const uint8_t* src, size_t len) {
  size_t i = 0;
  size_t n = len - 1;
  if (n < 60) {
    dst[i++] = (uint8_t)(n << 2);
  } else if (n < (1u << 8)) {
    dst[i++] = 60 << 2;
    dst[i++] = (uint8_t)n;
  } else if (n < (1u << 16)) {
    dst[i++] = 61 << 2;
    dst[i++] = (uint8_t)n;
    dst[i++] = (uint8_t)(n >> 8);
  } else if (n < (1u << 24)) {
    dst[i++] = 62 << 2;
    dst[i++] = (uint8_t)n;
    dst[i++] = (uint8_t)(n >> 8);
    dst[i++] = (uint8_t)(n >> 16);
  } else {
    dst[i++] = 63 << 2;
    dst[i++] = (uint8_t)n;
    dst[i++] = (uint8_t)(n >> 8);
    dst[i++] = (uint8_t)(n >> 16);
    dst[i++] = (uint8_t)(n >> 24);
  }
  std::memcpy(dst + i, src, len);
  return i + len;
}

size_t emit_copy(uint8_t* dst, size_t offset, size_t len) {
  size_t i = 0;
  // prefer copy-1 (4..11 len, offset < 2048)
  while (len > 0) {
    if (len >= 4 && len <= 11 && offset < 2048) {
      dst[i++] = (uint8_t)(1 | ((len - 4) << 2) | ((offset >> 8) << 5));
      dst[i++] = (uint8_t)offset;
      return i;
    }
    size_t chunk = len > 64 ? 64 : len;
    if (chunk < 4 && len > 64) chunk = 60;  // keep remainder >= 4
    if (len - chunk != 0 && len - chunk < 4) chunk = len - 4;
    dst[i++] = (uint8_t)(2 | ((chunk - 1) << 2));
    dst[i++] = (uint8_t)offset;
    dst[i++] = (uint8_t)(offset >> 8);
    len -= chunk;
  }
  return i;
}

}  // namespace

extern "C" {

// Worst-case output size for n input bytes (snappy's MaxCompressedLength).
uint64_t snappy_max_compressed(uint64_t n) { return 32 + n + n / 6; }

// Returns compressed size, or 0 on error. dst must have
// snappy_max_compressed(n) bytes.
uint64_t snappy_compress(const uint8_t* src, uint64_t n, uint8_t* dst) {
  size_t d = write_varint(dst, n);
  if (n == 0) return d;

  uint16_t table[kHashSize];
  std::memset(table, 0, sizeof(table));
  // table stores pos+1 within the current 64KB-ish window; reset per block
  const size_t kBlock = 1 << 16;

  size_t ip = 0;
  while (ip < n) {
    size_t block_end = ip + kBlock < n ? ip + kBlock : n;
    size_t base = ip;
    std::memset(table, 0, sizeof(table));
    size_t lit_start = ip;
    while (ip + 4 <= block_end) {
      uint32_t h = hash32(load32(src + ip));
      size_t cand = base + table[h];     // 1-based within block
      table[h] = (uint16_t)(ip - base + 1);
      if (table[h] == 0) {               // overflowed uint16: skip
        ip++;
        continue;
      }
      if (cand > base && cand - 1 < ip &&
          load32(src + (cand - 1)) == load32(src + ip) &&
          ip - (cand - 1) < 65536) {
        size_t match_pos = cand - 1;
        // flush pending literal
        if (ip > lit_start)
          d += emit_literal(dst + d, src + lit_start, ip - lit_start);
        // extend the match
        size_t len = 4;
        while (ip + len < block_end &&
               src[match_pos + len] == src[ip + len] && len < 0xFFFF)
          len++;
        d += emit_copy(dst + d, ip - match_pos, len);
        ip += len;
        lit_start = ip;
      } else {
        ip++;
      }
    }
    // trailing literal of this block
    if (block_end > lit_start) {
      d += emit_literal(dst + d, src + lit_start, block_end - lit_start);
    }
    ip = block_end;
  }
  return d;
}

// Returns decompressed size, or 0 on error (call snappy_uncompressed_length
// first to size dst).
uint64_t snappy_uncompressed_length(const uint8_t* src, uint64_t n) {
  uint64_t result = 0;
  int shift = 0;
  for (uint64_t i = 0; i < n && i < 10; i++) {
    result |= (uint64_t)(src[i] & 0x7F) << shift;
    if (!(src[i] & 0x80)) return result;
    shift += 7;
  }
  return 0;
}

int64_t snappy_uncompress(const uint8_t* src, uint64_t n, uint8_t* dst,
                          uint64_t dst_cap) {
  // skip varint
  uint64_t pos = 0;
  while (pos < n && (src[pos] & 0x80)) pos++;
  if (pos >= n) return -1;
  pos++;

  uint64_t d = 0;
  while (pos < n) {
    uint8_t tag = src[pos];
    int elem = tag & 3;
    if (elem == 0) {                        // literal
      uint64_t len = (tag >> 2) + 1;
      pos++;
      if (len > 60) {
        uint64_t extra = len - 60;
        if (pos + extra > n) return -1;
        len = 0;
        for (uint64_t j = 0; j < extra; j++)
          len |= (uint64_t)src[pos + j] << (8 * j);
        len += 1;
        pos += extra;
      }
      if (pos + len > n || d + len > dst_cap) return -1;
      std::memcpy(dst + d, src + pos, len);
      pos += len;
      d += len;
    } else {
      uint64_t len, offset;
      if (elem == 1) {
        if (pos + 2 > n) return -1;
        len = ((tag >> 2) & 0x7) + 4;
        offset = ((uint64_t)(tag >> 5) << 8) | src[pos + 1];
        pos += 2;
      } else if (elem == 2) {
        if (pos + 3 > n) return -1;
        len = (tag >> 2) + 1;
        offset = (uint64_t)src[pos + 1] | ((uint64_t)src[pos + 2] << 8);
        pos += 3;
      } else {
        if (pos + 5 > n) return -1;
        len = (tag >> 2) + 1;
        offset = (uint64_t)src[pos + 1] | ((uint64_t)src[pos + 2] << 8) |
                 ((uint64_t)src[pos + 3] << 16) |
                 ((uint64_t)src[pos + 4] << 24);
        pos += 5;
      }
      if (offset == 0 || offset > d || d + len > dst_cap) return -1;
      // byte-by-byte: overlapping copies are part of the format
      for (uint64_t j = 0; j < len; j++) {
        dst[d] = dst[d - offset];
        d++;
      }
    }
  }
  return (int64_t)d;
}

}  // extern "C"
