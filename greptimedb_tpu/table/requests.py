"""Table engine request types.

Reference behavior: src/table/src/requests.rs — Create/Open/Alter/Drop/
Insert/Delete request structs handed to a `TableEngine`.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from ..datatypes.schema import ColumnSchema, Schema
from .. import DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME


@dataclass
class CreateTableRequest:
    table_name: str
    schema: Schema
    catalog_name: str = DEFAULT_CATALOG_NAME
    schema_name: str = DEFAULT_SCHEMA_NAME
    desc: Optional[str] = None
    primary_key_indices: List[int] = field(default_factory=list)
    create_if_not_exists: bool = False
    region_numbers: List[int] = field(default_factory=lambda: [0])
    table_options: Dict[str, Any] = field(default_factory=dict)
    partitions: Optional[object] = None      # sql.ast.Partitions
    table_id: Optional[int] = None           # pre-allocated (distributed)
    # distributed: this datanode materializes only these regions (the
    # full region set stays in table metadata for routing/splitting)
    assigned_region_numbers: Optional[List[int]] = None


@dataclass
class OpenTableRequest:
    table_name: str
    catalog_name: str = DEFAULT_CATALOG_NAME
    schema_name: str = DEFAULT_SCHEMA_NAME
    table_id: Optional[int] = None
    region_numbers: Optional[List[int]] = None


class AlterKind(enum.Enum):
    ADD_COLUMNS = "add_columns"
    DROP_COLUMNS = "drop_columns"
    RENAME_TABLE = "rename_table"


@dataclass
class AddColumnRequest:
    column_schema: ColumnSchema
    is_key: bool = False
    location: Optional[str] = None           # FIRST / AFTER <col>


@dataclass
class AlterTableRequest:
    table_name: str
    kind: AlterKind
    catalog_name: str = DEFAULT_CATALOG_NAME
    schema_name: str = DEFAULT_SCHEMA_NAME
    add_columns: List[AddColumnRequest] = field(default_factory=list)
    drop_columns: List[str] = field(default_factory=list)
    new_table_name: Optional[str] = None


@dataclass
class DropTableRequest:
    table_name: str
    catalog_name: str = DEFAULT_CATALOG_NAME
    schema_name: str = DEFAULT_SCHEMA_NAME


@dataclass
class InsertRequest:
    table_name: str
    columns: Dict[str, Sequence]
    catalog_name: str = DEFAULT_CATALOG_NAME
    schema_name: str = DEFAULT_SCHEMA_NAME


@dataclass
class DeleteRequest:
    table_name: str
    key_columns: Dict[str, Sequence]
    catalog_name: str = DEFAULT_CATALOG_NAME
    schema_name: str = DEFAULT_SCHEMA_NAME
