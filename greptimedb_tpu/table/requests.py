"""Table engine request types.

Reference behavior: src/table/src/requests.rs — Create/Open/Alter/Drop/
Insert/Delete request structs handed to a `TableEngine`.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from ..datatypes.schema import ColumnSchema, Schema
from .. import DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME


@dataclass
class CreateTableRequest:
    table_name: str
    schema: Schema
    catalog_name: str = DEFAULT_CATALOG_NAME
    schema_name: str = DEFAULT_SCHEMA_NAME
    desc: Optional[str] = None
    primary_key_indices: List[int] = field(default_factory=list)
    create_if_not_exists: bool = False
    region_numbers: List[int] = field(default_factory=lambda: [0])
    table_options: Dict[str, Any] = field(default_factory=dict)
    partitions: Optional[object] = None      # sql.ast.Partitions
    table_id: Optional[int] = None           # pre-allocated (distributed)
    # distributed: this datanode materializes only these regions (the
    # full region set stays in table metadata for routing/splitting)
    assigned_region_numbers: Optional[List[int]] = None


@dataclass
class OpenTableRequest:
    table_name: str
    catalog_name: str = DEFAULT_CATALOG_NAME
    schema_name: str = DEFAULT_SCHEMA_NAME
    table_id: Optional[int] = None
    region_numbers: Optional[List[int]] = None


class AlterKind(enum.Enum):
    ADD_COLUMNS = "add_columns"
    DROP_COLUMNS = "drop_columns"
    RENAME_TABLE = "rename_table"


@dataclass
class AddColumnRequest:
    column_schema: ColumnSchema
    is_key: bool = False
    location: Optional[str] = None           # FIRST / AFTER <col>


@dataclass
class AlterTableRequest:
    table_name: str
    kind: AlterKind
    catalog_name: str = DEFAULT_CATALOG_NAME
    schema_name: str = DEFAULT_SCHEMA_NAME
    add_columns: List[AddColumnRequest] = field(default_factory=list)
    drop_columns: List[str] = field(default_factory=list)
    new_table_name: Optional[str] = None


@dataclass
class DropTableRequest:
    table_name: str
    catalog_name: str = DEFAULT_CATALOG_NAME
    schema_name: str = DEFAULT_SCHEMA_NAME


@dataclass
class InsertRequest:
    table_name: str
    columns: Dict[str, Sequence]
    catalog_name: str = DEFAULT_CATALOG_NAME
    schema_name: str = DEFAULT_SCHEMA_NAME


@dataclass
class DeleteRequest:
    table_name: str
    key_columns: Dict[str, Sequence]
    catalog_name: str = DEFAULT_CATALOG_NAME
    schema_name: str = DEFAULT_SCHEMA_NAME


def create_request_to_dict(req: CreateTableRequest) -> dict:
    """JSON-safe codec shared by the Flight DDL plane and the durable
    procedure store (both ship CreateTableRequest across a boundary)."""
    parts = None
    if req.partitions is not None:
        parts = {"columns": list(req.partitions.columns),
                 "entries": [{"name": e.name, "values": list(e.values)}
                             for e in req.partitions.entries],
                 "kind": getattr(req.partitions, "kind", "range"),
                 "num_partitions": getattr(req.partitions,
                                           "num_partitions", None)}
    return {
        "table_name": req.table_name,
        "schema": req.schema.to_dict(),
        "catalog_name": req.catalog_name,
        "schema_name": req.schema_name,
        "desc": req.desc,
        "primary_key_indices": list(req.primary_key_indices),
        "create_if_not_exists": req.create_if_not_exists,
        "region_numbers": list(req.region_numbers),
        "table_options": dict(req.table_options),
        "partitions": parts,
        "table_id": req.table_id,
        "assigned_region_numbers": req.assigned_region_numbers,
    }


def create_request_from_dict(d: dict) -> CreateTableRequest:
    from ..sql.ast import PartitionEntry, Partitions
    parts = None
    if d.get("partitions") is not None:
        p = d["partitions"]
        parts = Partitions(
            columns=list(p["columns"]),
            entries=[PartitionEntry(e["name"], list(e["values"]))
                     for e in p["entries"]],
            kind=p.get("kind", "range"),
            num_partitions=p.get("num_partitions"))
    return CreateTableRequest(
        table_name=d["table_name"],
        schema=Schema.from_dict(d["schema"]),
        catalog_name=d["catalog_name"],
        schema_name=d["schema_name"],
        desc=d.get("desc"),
        primary_key_indices=list(d["primary_key_indices"]),
        create_if_not_exists=d["create_if_not_exists"],
        region_numbers=list(d["region_numbers"]),
        table_options=dict(d["table_options"]),
        partitions=parts,
        table_id=d.get("table_id"),
        assigned_region_numbers=d.get("assigned_region_numbers"),
    )


def alter_request_to_dict(r: AlterTableRequest) -> dict:
    return {"table_name": r.table_name, "kind": r.kind.value,
            "catalog_name": r.catalog_name, "schema_name": r.schema_name,
            "drop_columns": list(r.drop_columns),
            "new_table_name": r.new_table_name,
            "add_columns": [
                {"column": a.column_schema.to_dict(), "is_key": a.is_key,
                 "location": a.location} for a in r.add_columns]}


def alter_request_from_dict(d: dict) -> AlterTableRequest:
    return AlterTableRequest(
        d["table_name"], AlterKind(d["kind"]),
        catalog_name=d["catalog_name"], schema_name=d["schema_name"],
        add_columns=[AddColumnRequest(
            ColumnSchema.from_dict(a["column"]), a["is_key"],
            a["location"]) for a in d["add_columns"]],
        drop_columns=list(d["drop_columns"]),
        new_table_name=d["new_table_name"])
