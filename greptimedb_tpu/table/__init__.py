"""Table abstraction layer.

Reference behavior: src/table — the `Table` trait
(src/table/src/table.rs:36-122: schema/scan/insert/delete/alter/flush),
`TableEngine` (src/table/src/engine.rs:64), `TableInfo`/`TableMeta`
(src/table/src/metadata.rs), and the `NumbersTable` test fixture
(src/table/src/table/numbers.rs).
"""

from .metadata import TableIdent, TableInfo, TableMeta, TableType
from .requests import (
    AddColumnRequest,
    AlterKind,
    AlterTableRequest,
    CreateTableRequest,
    DeleteRequest,
    DropTableRequest,
    InsertRequest,
    OpenTableRequest,
)
from .table import Table, TableEngine
from .numbers import NumbersTable

__all__ = [
    "Table", "TableEngine", "TableIdent", "TableInfo", "TableMeta",
    "TableType", "CreateTableRequest", "OpenTableRequest",
    "AlterTableRequest", "AlterKind", "AddColumnRequest", "DropTableRequest",
    "InsertRequest", "DeleteRequest", "NumbersTable",
]
