"""Table metadata: TableMeta / TableInfo / idents.

Reference behavior: src/table/src/metadata.rs:801 — `TableMeta` carries the
schema + primary key indices + engine + region numbers + options;
`TableInfo` adds identity (id, version), names and table type.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..datatypes.schema import Schema
from .. import DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME, MITO_ENGINE


class TableType(enum.Enum):
    BASE = "base"
    VIEW = "view"
    TEMPORARY = "temporary"


@dataclass
class TableIdent:
    table_id: int
    version: int = 0


@dataclass
class TableMeta:
    schema: Schema
    primary_key_indices: List[int] = field(default_factory=list)
    engine: str = MITO_ENGINE
    region_numbers: List[int] = field(default_factory=lambda: [0])
    next_column_id: int = 0
    options: Dict[str, object] = field(default_factory=dict)
    created_on_ms: int = field(default_factory=lambda: int(time.time() * 1000))
    partition_rule: Optional[dict] = None   # serialized partition rule

    @property
    def primary_key_names(self) -> List[str]:
        names = self.schema.names()
        return [names[i] for i in self.primary_key_indices]

    def value_indices(self) -> List[int]:
        pk = set(self.primary_key_indices)
        ts = None
        tc = self.schema.timestamp_column
        if tc is not None:
            ts = self.schema.column_index(tc.name)
        return [i for i in range(len(self.schema))
                if i not in pk and i != ts]

    def to_dict(self) -> dict:
        return {
            "schema": self.schema.to_dict(),
            "primary_key_indices": self.primary_key_indices,
            "engine": self.engine,
            "region_numbers": self.region_numbers,
            "next_column_id": self.next_column_id,
            "options": self.options,
            "created_on_ms": self.created_on_ms,
            "partition_rule": self.partition_rule,
        }

    @staticmethod
    def from_dict(d: dict) -> "TableMeta":
        return TableMeta(
            schema=Schema.from_dict(d["schema"]),
            primary_key_indices=list(d.get("primary_key_indices", [])),
            engine=d.get("engine", MITO_ENGINE),
            region_numbers=list(d.get("region_numbers", [0])),
            next_column_id=d.get("next_column_id", 0),
            options=dict(d.get("options", {})),
            created_on_ms=d.get("created_on_ms", 0),
            partition_rule=d.get("partition_rule"),
        )


@dataclass
class TableInfo:
    ident: TableIdent
    name: str
    meta: TableMeta
    catalog_name: str = DEFAULT_CATALOG_NAME
    schema_name: str = DEFAULT_SCHEMA_NAME
    desc: Optional[str] = None
    table_type: TableType = TableType.BASE

    @property
    def full_name(self) -> str:
        return f"{self.catalog_name}.{self.schema_name}.{self.name}"

    def to_dict(self) -> dict:
        return {
            "table_id": self.ident.table_id,
            "version": self.ident.version,
            "name": self.name,
            "catalog_name": self.catalog_name,
            "schema_name": self.schema_name,
            "desc": self.desc,
            "table_type": self.table_type.value,
            "meta": self.meta.to_dict(),
        }

    @staticmethod
    def from_dict(d: dict) -> "TableInfo":
        return TableInfo(
            ident=TableIdent(d["table_id"], d.get("version", 0)),
            name=d["name"],
            catalog_name=d.get("catalog_name", DEFAULT_CATALOG_NAME),
            schema_name=d.get("schema_name", DEFAULT_SCHEMA_NAME),
            desc=d.get("desc"),
            table_type=TableType(d.get("table_type", "base")),
            meta=TableMeta.from_dict(d["meta"]),
        )
