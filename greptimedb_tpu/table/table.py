"""The Table and TableEngine interfaces.

Reference behavior: src/table/src/table.rs:36-122 (`Table`:
schema/scan/insert/delete/alter/flush/close) and src/table/src/engine.rs:64
(`TableEngine`: create/open/alter/drop/exists). Scans come in two shapes:

- `scan_batches` — generic RecordBatch output every table supports (the
  DataFusion TableProvider analog; CPU/protocol paths consume it);
- `scan_raw` — the TPU fast path: per-region SoA arrays + series dictionary
  that the query engine feeds straight to the device kernels. Only the mito
  engine implements it; callers must fall back to `scan_batches` when it
  returns None.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..common.time import TimestampRange
from ..datatypes.record_batch import RecordBatch
from ..datatypes.schema import Schema
from ..errors import UnsupportedError
from .metadata import TableInfo
from .requests import AlterTableRequest


class Table:
    def __init__(self, info: TableInfo):
        self._info = info

    @property
    def info(self) -> TableInfo:
        return self._info

    @property
    def schema(self) -> Schema:
        return self._info.meta.schema

    @property
    def name(self) -> str:
        return self._info.name

    def scan_batches(self, projection: Optional[Sequence[str]] = None,
                     time_range: Optional[TimestampRange] = None,
                     limit: Optional[int] = None) -> List[RecordBatch]:
        raise NotImplementedError

    def scan_raw(self, projection: Optional[Sequence[str]] = None,
                 time_range: Optional[TimestampRange] = None):
        """TPU fast path: list of per-region storage ScanData, or None if
        this table has no SoA representation."""
        return None

    def insert(self, columns: Dict[str, Sequence]) -> int:
        raise UnsupportedError(f"table {self.name} does not support insert")

    def delete(self, key_columns: Dict[str, Sequence]) -> int:
        raise UnsupportedError(f"table {self.name} does not support delete")

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class TableEngine:
    name: str = "base"

    def create_table(self, request) -> Table:
        raise NotImplementedError

    def open_table(self, request) -> Optional[Table]:
        raise NotImplementedError

    def alter_table(self, request: AlterTableRequest) -> Table:
        raise NotImplementedError

    def drop_table(self, request) -> bool:
        raise NotImplementedError

    def truncate_table(self, catalog: str, schema: str, name: str) -> bool:
        raise NotImplementedError

    def table_exists(self, catalog: str, schema: str, name: str) -> bool:
        raise NotImplementedError

    def get_table(self, catalog: str, schema: str, name: str) -> Optional[Table]:
        raise NotImplementedError

    def close(self) -> None:
        pass
