"""NumbersTable test fixture: a read-only table of 0..99.

Reference behavior: src/table/src/table/numbers.rs:177 — used across the
reference's query tests (`SELECT * FROM numbers`).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..datatypes import data_type as dt
from ..datatypes.record_batch import RecordBatch
from ..datatypes.schema import ColumnSchema, Schema, SemanticType
from .metadata import TableIdent, TableInfo, TableMeta, TableType
from .table import Table

NUMBERS_TABLE_ID = 2


class NumbersTable(Table):
    def __init__(self, count: int = 100):
        schema = Schema([ColumnSchema("number", dt.UINT32, nullable=False,
                                      semantic_type=SemanticType.FIELD)])
        info = TableInfo(
            ident=TableIdent(NUMBERS_TABLE_ID),
            name="numbers",
            meta=TableMeta(schema=schema, engine="test"),
            table_type=TableType.TEMPORARY,
        )
        super().__init__(info)
        self._count = count

    def scan_batches(self, projection: Optional[Sequence[str]] = None,
                     time_range=None, limit: Optional[int] = None
                     ) -> List[RecordBatch]:
        n = self._count if limit is None else min(self._count, limit)
        schema = self.schema if projection is None \
            else self.schema.project(projection)
        if projection is not None and "number" not in projection:
            return [RecordBatch.empty(schema)]
        return [RecordBatch.from_pydict(
            schema, {"number": np.arange(n, dtype=np.uint32)})]
