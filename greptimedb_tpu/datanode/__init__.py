"""Datanode: the node role that hosts storage regions + a query engine.

Reference behavior: src/datanode/src/instance.rs:106-236 — wires object
store, WAL, storage engine, table engines, catalog, and query engine.
"""

from .instance import DatanodeInstance, DatanodeOptions

__all__ = ["DatanodeInstance", "DatanodeOptions"]
