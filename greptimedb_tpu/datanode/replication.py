"""Continuous WAL-tail replication: the leader datanode's ship loop.

Read replicas (ISSUE 19) bootstrap through the balancer's op-doc
snapshot+tail codec (meta/balancer.py `replica_add`), then stay caught
up through this shipper: every committed write nudges it via the
region's `on_commit` hook, and a background thread reads the new WAL
records (`Region.wal_entries_since` — safe on a live region) and pushes
them to each follower's `repl_apply`. Acks NEVER wait on followers: the
hook only sets a dirty bit under a condition variable.

Delivery is at-least-once with self-healing gaps: a ship round only
advances the per-region cursor when every follower applied it, and a
follower that observes a sequence gap (or a leader flush that obsoleted
the segments it missed) reopens its standby region from the shared
manifest (`MitoEngine.refresh_standby`), which always covers anything
the WAL no longer holds — the WAL never deletes a segment above the
flushed sequence.

Follower targets arrive via `repl_set_followers` mailbox messages (the
balancer wires them after the route commit, and failover re-wires them
after a promotion); the target list itself is durable in the meta route
doc, so this in-memory state is reconstructible.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from ..common import failpoint as _fp
from ..errors import RegionNotFoundError

logger = logging.getLogger(__name__)

_fp.register("repl_ship")

#: records per ship round — bounds one round's memory/wire cost; the
#: drain loop keeps going while a region stays behind
SHIP_BATCH_RECORDS = 4096


def _follower_id(follower: dict):
    """Peer docs spell the node id either way: the meta route's
    Peer.to_dict uses "id", mailbox bodies may carry "node_id"."""
    nid = follower.get("node_id", follower.get("id"))
    return int(nid) if nid is not None else None


class ReplicaShipper:
    """Per-datanode background shipper for all leader regions that have
    followers attached."""

    def __init__(self, datanode) -> None:
        self.datanode = datanode
        self._cond = threading.Condition()
        #: region_name -> {"catalog","schema","table","region_number",
        #:   "followers":[{"node_id","addr"}], "last_shipped": int}
        self._targets: Dict[str, dict] = {}
        self._dirty: set = set()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        #: (node_id, addr) -> client (Flight conns are reusable; the
        #: in-process registry resolves per call and is not cached here)
        self._clients: Dict[tuple, object] = {}

    # ---- wiring (repl_set_followers mailbox step) ----
    def set_followers(self, catalog: str, schema: str, table: str,
                      region_number: int, region_name: str,
                      followers: List[dict]) -> int:
        """Replace the follower set for one region; an empty set detaches
        it (and clears the region's on_commit hook)."""
        try:
            region = self.datanode.storage.get_region(region_name)
        except RegionNotFoundError:
            region = None
        with self._cond:
            if not followers:
                self._targets.pop(region_name, None)
                self._dirty.discard(region_name)
            else:
                prev = self._targets.get(region_name)
                # start the cursor at the flushed sequence: everything
                # below it is durable in shared SSTs (a freshly attached
                # follower adopted that state), everything above ships —
                # followers skip already-applied records idempotently
                last = prev["last_shipped"] if prev is not None else (
                    int(region.version_control.current.flushed_sequence)
                    if region is not None else 0)
                self._targets[region_name] = {
                    "catalog": catalog, "schema": schema, "table": table,
                    "region_number": int(region_number),
                    "followers": list(followers), "last_shipped": last}
                self._dirty.add(region_name)
                self._cond.notify()
        if region is not None:
            region.on_commit = self.notify if followers else None
        if followers:
            self._ensure_thread()
        logger.info("replica shipper: region %s now has %d follower(s)",
                    region_name, len(followers))
        return len(followers)

    def targets(self) -> Dict[str, dict]:
        with self._cond:
            return {k: dict(v) for k, v in self._targets.items()}

    # ---- leader write hook (Region.on_commit) ----
    def notify(self, region) -> None:
        with self._cond:
            if region.name in self._targets:
                self._dirty.add(region.name)
                self._cond.notify()

    # ---- the ship loop ----
    def _ensure_thread(self) -> None:
        with self._cond:
            if self._thread is not None and self._thread.is_alive():
                return
            from ..common.runtime import new_thread
            self._stop = False
            self._thread = new_thread(
                self._run, daemon=True,
                name=f"repl-ship-dn{self.datanode.opts.node_id}",
                propagate_context=False)
            self._thread.start()

    def _run(self) -> None:
        while True:
            with self._cond:
                if not self._dirty and not self._stop:
                    # the timeout doubles as the retry tick: a region a
                    # failed round left behind re-ships without waiting
                    # for the next write
                    self._cond.wait(timeout=0.5)
                if self._stop:
                    return
                names = set(self._dirty)
                self._dirty.clear()
                names.update(self._lagging_locked())
            for name in sorted(names):
                try:
                    self.ship_region(name)
                except Exception:  # noqa: BLE001 — one region's ship
                    logger.exception(      # failure must not kill the loop
                        "replica ship for region %s failed", name)

    def _lagging_locked(self) -> List[str]:
        """Regions whose cursor trails their committed sequence (failed
        or truncated earlier rounds). Caller holds the condition."""
        out = []
        for name, t in self._targets.items():
            try:
                region = self.datanode.storage.get_region(name)
            except RegionNotFoundError:
                continue
            if t["last_shipped"] < region.version_control.committed_sequence:
                out.append(name)
        return out

    def ship_region(self, region_name: str) -> Optional[dict]:
        """One ship round for one region: read the WAL delta past the
        cursor and push it to every follower. Public so tests and the
        acceptance harness can drain synchronously. Returns the round's
        summary, or None when the region has no followers / is gone."""
        from ..common.telemetry import increment_counter
        with self._cond:
            target = self._targets.get(region_name)
        if target is None:
            return None
        try:
            region = self.datanode.storage.get_region(region_name)
        except RegionNotFoundError:
            with self._cond:
                self._targets.pop(region_name, None)
            return None
        last = target["last_shipped"]
        flushed = int(region.version_control.current.flushed_sequence)
        entries = region.wal_entries_since(
            last, max_records=SHIP_BATCH_RECORDS)
        if not entries and flushed <= last and \
                region.version_control.committed_sequence <= last:
            return {"shipped": 0, "followers_ok": len(target["followers"])}
        # crash/err HERE (torture): the cursor has not advanced, so the
        # round re-ships after restart — followers dedup by sequence
        _fp.fail_point("repl_ship")
        ok = 0
        for follower in target["followers"]:
            try:
                client = self._client_for(follower)
                if client is None:
                    raise RegionNotFoundError(
                        f"follower dn{_follower_id(follower)} "
                        f"unreachable (no address, not in-process)")
                client.repl_apply(
                    target["catalog"], target["schema"], target["table"],
                    target["region_number"], entries,
                    leader_flushed=flushed)
                ok += 1
            except Exception as e:  # noqa: BLE001 — a lagging/briefly-dead
                # follower self-heals by manifest refresh on a later round
                increment_counter("repl_ship_errors")
                logger.warning(
                    "replica ship %s -> dn%s failed (%s: %s); follower "
                    "will gap-refresh", region_name,
                    _follower_id(follower), type(e).__name__, e)
        advanced = False
        if ok == len(target["followers"]):
            # advance only on full success: a partial round re-ships to
            # everyone (idempotent) instead of leaving one follower with
            # a gap the WAL may later obsolete
            new_last = int(entries[-1]["seq"]) if entries \
                else max(last, flushed)
            with self._cond:
                cur = self._targets.get(region_name)
                if cur is not None and cur["last_shipped"] < new_last:
                    cur["last_shipped"] = new_last
                    advanced = True
                if cur is not None and entries and \
                        len(entries) >= SHIP_BATCH_RECORDS:
                    self._dirty.add(region_name)   # more behind: keep going
                    self._cond.notify()
        if entries and ok:
            increment_counter("repl_records_shipped", len(entries))
        return {"shipped": len(entries), "followers_ok": ok,
                "advanced": advanced}

    def drain(self, region_name: str, rounds: int = 64) -> None:
        """Ship until the region's cursor catches its committed sequence
        (tests / acceptance; production relies on the loop)."""
        for _ in range(rounds):
            with self._cond:
                target = self._targets.get(region_name)
            if target is None:
                return
            try:
                region = self.datanode.storage.get_region(region_name)
            except RegionNotFoundError:
                return
            if target["last_shipped"] >= \
                    region.version_control.committed_sequence:
                return
            self.ship_region(region_name)

    def _client_for(self, follower: dict):
        """Resolve a follower to a datanode client: a live in-process
        instance first (single-process clusters), then Arrow Flight by
        the peer's advertised address."""
        node_id = _follower_id(follower)
        from .instance import live_datanode
        inst = live_datanode(node_id)
        if inst is not None:
            return inst
        addr = follower.get("addr") or ""
        if not addr:
            return None
        key = (node_id, addr)
        client = self._clients.get(key)
        if client is None:
            from ..client.flight import FlightDatanodeClient
            location = addr if "://" in addr else f"grpc://{addr}"
            client = FlightDatanodeClient(location, int(node_id))
            self._clients[key] = client
        return client

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
        for client in self._clients.values():
            try:
                client.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                logger.debug("replica shipper: client close failed",
                             exc_info=True)
        self._clients.clear()
