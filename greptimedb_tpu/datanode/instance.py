"""Datanode instance: storage + table engines + catalog + query engine.

Reference behavior: src/datanode/src/instance.rs — `Instance::new_with`
builds object store → log store → storage engine → mito engine → catalog →
query engine; `start_instance` replays the catalog (which replays region
WALs via table open).

Elastic-region worker side: meta's balancer (meta/balancer.py) drives
multi-step region operations through mailbox messages riding heartbeat
responses; each handler here performs one idempotent step (flush
snapshot, fence + WAL-tail read, adopt + tail replay, release, split
copy/apply) and reports back through ``balancer_ack`` on the meta
client, so a re-delivered message after a crash resumes the operation
instead of corrupting it.
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..catalog import LocalCatalogManager
from ..common import failpoint as _fp
from ..mito import MitoEngine
from ..query import QueryEngine
from ..storage.engine import EngineConfig, StorageEngine
from ..storage.object_store import FsObjectStore, ObjectStore
from ..table import NumbersTable
from .. import DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME

logger = logging.getLogger(__name__)

_fp.register("balancer_snapshot_upload")
_fp.register("repl_apply")
_fp.register("repl_promote")
_fp.register("repl_bootstrap")

#: live in-process datanodes by node id (latest wins) — the replica
#: shipper resolves same-process followers here instead of dialing a
#: Flight socket (single-process clusters: tests, embedded topologies)
_live_lock = threading.Lock()
_live_datanodes: Dict[int, "DatanodeInstance"] = {}


def live_datanode(node_id) -> Optional["DatanodeInstance"]:
    if node_id is None:
        return None
    with _live_lock:
        return _live_datanodes.get(int(node_id))


@dataclass
class DatanodeOptions:
    data_home: str = "./greptimedb_data"
    node_id: int = 0
    flush_size_bytes: int = 64 * 1024 * 1024
    wal_sync_on_write: bool = False
    disable_wal: bool = False
    register_numbers_table: bool = True   # test fixture, like the reference
    #: continuous-flow background fold cadence; the free-running task is
    #: never started under pytest (tests drive FlowManager.tick()
    #: cooperatively — tier-1 safety), and 0 disables it everywhere
    flow_tick_interval_s: float = 10.0
    #: self-monitoring scrape cadence (metrics + region heat →
    #: greptime_private system tables); same pytest/0 rules as the flow
    #: tick. 30s keeps the history fine-grained enough for the region
    #: split/migrate decisions ROADMAP item 1 needs without measurable
    #: ingest overhead (<3%, see bench.py self_monitoring_overhead)
    self_monitor_interval_s: float = 30.0


class DatanodeInstance:
    def __init__(self, opts: DatanodeOptions,
                 store: Optional[ObjectStore] = None):
        self.opts = opts
        config = EngineConfig(
            data_home=opts.data_home,
            # node-scoped WAL home: datanodes that share one data_home
            # (shared object store deployments) must never share WAL
            # dirs or region fence markers — both are per-owner state
            wal_home=os.path.join(opts.data_home, "nodes",
                                  str(opts.node_id), "wal")
            if opts.node_id else None,
            flush_size_bytes=opts.flush_size_bytes,
            wal_sync_on_write=opts.wal_sync_on_write,
            disable_wal=opts.disable_wal)
        self.storage = StorageEngine(config, store=store)
        self.store = self.storage.store
        # node-scoped control state: on a shared object store each
        # datanode keeps its own registry/manifests/catalog doc while
        # region data stays globally addressed (failover moves regions)
        prefix = f"nodes/{opts.node_id}/" if opts.node_id else ""
        self.state_prefix = prefix
        self.mito = MitoEngine(self.storage, state_prefix=prefix)
        from ..file_table import ImmutableFileTableEngine
        self.file_engine = ImmutableFileTableEngine(self.store, state_prefix=prefix)
        self.engines = {self.mito.name: self.mito,
                        self.file_engine.name: self.file_engine}
        self.catalog = LocalCatalogManager(self.store, self.engines,
                                           state_prefix=prefix)
        self.query_engine = QueryEngine(self.catalog)
        # durable DDL (reference: procedure manager + loader registration,
        # src/datanode/src/instance.rs:210-236)
        from ..mito.procedure import register_loaders
        from ..procedure import ProcedureManager
        self.procedure_manager = ProcedureManager(self.store, state_prefix=prefix)
        register_loaders(self.procedure_manager, self.mito, self.catalog)
        # continuous rollup flows: specs + watermarks persist next to the
        # mito manifests; the query engine gets the manager for the
        # transparent rollup rewrite
        from ..flow import FlowManager, ObjectStoreFlowStore
        self.flow_manager = FlowManager(
            self.catalog, ObjectStoreFlowStore(self.store, prefix),
            create_sink_fn=self._create_flow_sink)
        self.query_engine.flow_manager = self.flow_manager
        # information_schema gauges read flow watermarks off the catalog
        self.catalog.flow_manager = self.flow_manager
        self._started = False
        self._heartbeat_task = None
        #: meta client for datanode→meta control RPCs (balancer step
        #: acks); start_heartbeat wires it, tests may attach directly
        self._meta_client = None
        # continuous WAL-tail replication to read replicas (ISSUE 19):
        # repl_set_followers mailbox steps wire regions in, the region
        # on_commit hook nudges the ship thread
        from .replication import ReplicaShipper
        self.replication = ReplicaShipper(self)
        with _live_lock:
            _live_datanodes[int(opts.node_id)] = self

    def _create_flow_sink(self, spec, schema, pk_indices):
        from ..table.requests import CreateTableRequest
        table = self.mito.create_table(CreateTableRequest(
            spec.sink, schema, catalog_name=spec.catalog,
            schema_name=spec.schema, primary_key_indices=pk_indices,
            create_if_not_exists=True))
        if self.catalog.table(spec.catalog, spec.schema, spec.sink) is None:
            self.catalog.register_table(spec.catalog, spec.schema,
                                        spec.sink, table)
        return table

    def start(self) -> None:
        """Catalog replay → table open → region WAL replay → resume
        in-flight procedures → reload flow specs + watermarks."""
        self.catalog.start()
        self.procedure_manager.recover()
        self.flow_manager.recover()
        if self.opts.flow_tick_interval_s > 0 and \
                "PYTEST_CURRENT_TEST" not in os.environ:
            self.flow_manager.start_background(
                self.opts.flow_tick_interval_s)
        if self.opts.register_numbers_table and \
                self.catalog.table(DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME,
                                   "numbers") is None:
            self.catalog.register_table(
                DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME, "numbers",
                NumbersTable())
        self._started = True

    def attach_meta(self, meta_client) -> None:
        """Wire the meta client used for balancer step acks (heartbeat
        startup calls this; cooperative tests call it directly)."""
        self._meta_client = meta_client

    def start_heartbeat(self, meta_client, interval_s: float = 5.0,
                        stats_every: int = 4) -> None:
        """Report liveness + region stats to the meta service (reference:
        src/datanode/src/heartbeat.rs:27-141; stats feed the load-based
        selector and the phi failure detector). Liveness beats every
        `interval_s`; the per-region stat walk (O(regions × files) over
        memtable/SST metadata) and its linearly-growing payload ride only
        every `stats_every`-th beat — meta's ingest-rate derivation
        divides row deltas by the actual elapsed time between stat
        beats, so the lower cadence doesn't distort the rate."""
        from ..common.telemetry import root_span
        from ..meta import DatanodeStat
        from ..storage.scheduler import RepeatedTask
        self.attach_meta(meta_client)
        counter = [0]

        def beat():
            # per-region rows/size travel with stat-bearing heartbeats:
            # meta keeps them (DatanodeStat.region_stats) as the
            # region-heat signal behind information_schema.cluster_info
            # and the ingest-rate column; the heartbeat span carries a
            # trace id over the meta RPC (wire propagation) so the hop
            # is attributable
            regions = self.storage.list_regions()
            if counter[0] % max(1, stats_every) == 0:
                from ..query.stream_exec import region_stat_entries
                region_stats, total_rows, total_bytes = \
                    region_stat_entries(regions.values())
                stat = DatanodeStat(region_count=len(regions),
                                    approximate_rows=total_rows,
                                    approximate_bytes=total_bytes,
                                    region_stats=region_stats)
            else:
                # light beat: region_count is a len() — the load_based
                # selector reads it fresh every beat; the O(regions ×
                # files) per-region walk waits for the next full beat
                stat = DatanodeStat(region_count=len(regions),
                                    full=False)
            counter[0] += 1
            # root_span: each beat is its own (sampled) trace — the
            # loop thread has no ambient context to inherit anyway
            with root_span("heartbeat", node=self.opts.node_id):
                resp = meta_client.heartbeat(self.opts.node_id, stat)
            for msg in resp.mailbox:
                self._handle_mailbox(msg)

        beat()                         # immediate first beat (registration)
        self._heartbeat_task = RepeatedTask(
            interval_s, beat, name=f"heartbeat-dn{self.opts.node_id}")
        self._heartbeat_task.start()

    def _handle_mailbox(self, msg: dict) -> None:
        """Meta→datanode control messages riding heartbeat responses."""
        kind = msg.get("type")
        if kind == "flush_table":
            t = self.catalog.table(msg["catalog"], msg["schema"],
                                   msg["table"])
            if t is not None:
                t.flush()
        elif kind == "open_regions":
            # failover: adopt a dead peer's regions (data on the shared
            # object store; schema shipped in the message)
            if msg.get("table_info") is None:
                logger.error(
                    "open_regions for %s without table info; skipping",
                    msg.get("table"))
                return
            table = self.mito.adopt_regions(msg["table_info"],
                                            msg["region_numbers"])
            if self.catalog.table(msg["catalog"], msg["schema"],
                                  msg["table"]) is None:
                self.catalog.register_table(
                    msg["catalog"], msg["schema"], msg["table"], table)
        elif kind is not None and (kind.startswith("balancer_") or
                                   kind.startswith("repl_")):
            self._handle_balancer_msg(msg)

    # ---- elastic-region steps (meta/balancer.py's worker side) ----
    def _handle_balancer_msg(self, msg: dict) -> None:
        """Run one balancer step and ack the result to meta. SimulatedCrash
        (a BaseException) propagates — the torture harness, like a real
        SIGKILL, must see the step die before its ack."""
        op_id, step = msg.get("op_id"), msg.get("type")
        from ..common import background_jobs
        try:
            with background_jobs.job(
                    "balancer_step", table=msg.get("table"),
                    region=str(msg.get("region")), op_id=op_id,
                    step=step):
                payload = self._balancer_step(msg)
            ok, error = True, None
        except Exception as e:  # noqa: BLE001 — relayed to the balancer,
            # which rolls the operation back or retries the step
            logger.exception("balancer step %s of op %s failed",
                             step, op_id)
            ok, error, payload = False, f"{type(e).__name__}: {e}", {}
        if op_id is None:
            # fire-and-forget control message (failover promotion /
            # follower re-wiring): no op doc is waiting on an ack
            return
        if self._meta_client is None:
            logger.error("balancer step %s of op %s has no meta client "
                         "to ack through", step, op_id)
            return
        try:
            self._meta_client.balancer_ack(
                self.opts.node_id, op_id, step, ok, error, payload or {})
        except Exception:  # noqa: BLE001 — the balancer re-mails the
            logger.exception(          # step after its ack timeout
                "balancer ack for op %s step %s failed", op_id, step)

    def _balancer_step(self, msg: dict) -> dict:
        kind = msg["type"]
        cat, sch, tbl = msg["catalog"], msg["schema"], msg["table"]
        if kind == "balancer_snapshot":
            # migrate step 1: make the region's full state durable on the
            # shared object store (ingest continues meanwhile)
            _fp.fail_point("balancer_snapshot_upload")
            _, region = self.mito._hosted(cat, sch, tbl, msg["region"])
            region.flush()
            return {"flushed_seq":
                    int(region.version_control.current.flushed_sequence)}
        if kind == "balancer_fence":
            # migrate step 2: stop the world for THIS region only, then
            # read the final WAL tail for the target to replay
            _, region = self.mito._hosted(cat, sch, tbl, msg["region"])
            region.fence()
            return {"wal_tail": region.wal_tail()}
        if kind == "balancer_open":
            # migrate step 3 (target side): last-flushed shared state +
            # shipped WAL tail = everything the source ever acked
            table = self.mito.adopt_region_with_tail(
                msg["table_info"], msg["region"], msg.get("wal_tail"))
            if self.catalog.table(cat, sch, tbl) is None:
                self.catalog.register_table(cat, sch, tbl, table)
            return {"replayed": len(msg.get("wal_tail") or [])}
        if kind == "balancer_release":
            gone = self.mito.release_region(cat, sch, tbl, msg["region"])
            if gone:
                self.catalog.deregister_table(cat, sch, tbl)
            return {"table_gone": gone}
        if kind == "balancer_unfence":
            table = self.catalog.table(cat, sch, tbl)
            region = (getattr(table, "regions", None) or {}).get(
                msg["region"])
            if region is not None and region.fenced:
                region.unfence()
            return {}
        if kind == "balancer_split_prepare":
            if msg.get("at_value") is None:
                # probe-only round: the balancer pins the value in the
                # op doc BEFORE any copy, so a re-delivered prepare
                # cannot re-probe a moved median and copy rows across a
                # different boundary (cross-child duplicates)
                value = self.mito.probe_split_value(
                    cat, sch, tbl, msg["region"])
                return {"split_value": value, "probed": True}
            _fp.fail_point("balancer_snapshot_upload")
            seq, copied = self.mito.prepare_split(
                cat, sch, tbl, msg["region"], list(msg["children"]),
                msg["at_value"])
            return {"split_value": msg["at_value"], "snapshot_seq": seq,
                    "copied": copied}
        if kind == "balancer_split_catchup":
            copied = self.mito.split_catchup(
                cat, sch, tbl, msg["region"], list(msg["children"]),
                msg["at_value"], int(msg["snapshot_seq"]))
            return {"copied": copied}
        if kind == "balancer_split_apply":
            self.mito.apply_split(cat, sch, tbl, msg["region"],
                                  list(msg["children"]), msg["rule"])
            return {}
        if kind == "balancer_split_abort":
            self.mito.abort_split(cat, sch, tbl, msg["region"],
                                  list(msg["children"]))
            return {}
        if kind == "repl_bootstrap":
            # replica-add step 2 (leader side): the WAL delta past the
            # snapshot's flushed sequence, WITHOUT fencing — ingest
            # continues; the continuous shipper covers records committed
            # after this read (followers dedup by sequence)
            _fp.fail_point("repl_bootstrap")
            _, region = self.mito._hosted(cat, sch, tbl, msg["region"])
            flushed = int(region.version_control.current.flushed_sequence)
            return {"wal_tail": region.wal_entries_since(flushed),
                    "flushed_seq": flushed}
        if kind == "repl_attach":
            # replica-add step 3 (follower side): adopt the last-flushed
            # shared state as a durable standby + replay the bootstrap
            # tail at its original sequences
            table = self.mito.adopt_standby(
                msg["table_info"], msg["region"], msg.get("wal_tail"))
            if self.catalog.table(cat, sch, tbl) is None:
                self.catalog.register_table(cat, sch, tbl, table)
            return {"replayed": len(msg.get("wal_tail") or [])}
        if kind == "repl_set_followers":
            # leader side, post-commit (and after failover promotions):
            # (re)wire the continuous shipper's follower set
            _, region = self.mito._hosted(cat, sch, tbl, msg["region"])
            n = self.replication.set_followers(
                cat, sch, tbl, msg["region"], region.name,
                list(msg.get("followers") or []))
            return {"followers": n}
        if kind == "repl_drop":
            # follower side: detach the standby (replica removed, or a
            # pre-commit replica-add rollback)
            gone = self.mito.release_region(cat, sch, tbl, msg["region"])
            if gone:
                self.catalog.deregister_table(cat, sch, tbl)
            return {"table_gone": gone}
        if kind == "repl_promote":
            # failover promotion (fire-and-forget from failover_check):
            # fence the dead leader's WAL dir, refresh from the shared
            # manifest, salvage + replay its surviving WAL records, then
            # take over as leader — zero acked rows lost
            _fp.fail_point("repl_promote")
            _, region = self.mito._hosted(cat, sch, tbl, msg["region"])
            if not getattr(region, "standby", False):
                # re-delivered promotion (meta retries the fire-and-
                # forget mail until a heartbeat confirms): already leader
                return {"salvaged": 0, "replayed": 0, "committed_seq":
                        int(region.version_control.committed_sequence)}
            old_id = msg.get("old_leader")
            old_dir = self._wal_dir_of(old_id, region.name) \
                if old_id is not None else None
            return self.mito.promote_standby(cat, sch, tbl, msg["region"],
                                             old_dir)
        from ..errors import UnsupportedError
        raise UnsupportedError(f"unknown balancer step {kind!r}")

    def _wal_dir_of(self, node_id: int, region_name: str) -> str:
        """Another datanode's WAL dir for a region, on the SHARED
        data_home (mirrors EngineConfig.wal_home scoping) — promotion
        salvages a dead leader's acked-but-unflushed records from it."""
        if node_id:
            return os.path.join(self.opts.data_home, "nodes",
                                str(node_id), "wal", region_name)
        return os.path.join(self.opts.data_home, "wal", region_name)

    # ---- replica apply (follower side of the continuous ship path;
    # reached in-process via the shipper or over the repl_apply Flight
    # action) ----
    def repl_apply(self, catalog: str, schema: str, table: str,
                   region_number: int, entries: list,
                   leader_flushed: int = 0) -> dict:
        _fp.fail_point("repl_apply")
        _, region = self.mito._hosted(catalog, schema, table,
                                      region_number)
        if not region.standby:
            # already promoted (or never a standby): a late ship from a
            # deposed leader — ignore it; the WAL-dir fence keeps that
            # leader from acking anything new
            return {"replayed": 0, "standby": False, "committed_seq":
                    int(region.version_control.committed_sequence)}
        vc = region.version_control
        gap = bool(entries) and \
            int(entries[0]["seq"]) > vc.committed_sequence + 1
        if gap or int(leader_flushed or 0) > \
                vc.current.flushed_sequence:
            # the leader flushed past this replica's manifest view (or
            # shipped records skipped ahead): reopen from the CURRENT
            # shared manifest — it always covers the gap, and the reopen
            # bounds the standby's memtable to the leader's unflushed
            # window
            region = self.mito.refresh_standby(catalog, schema, table,
                                               region_number)
        replayed = region.ingest_wal_tail(entries) if entries else 0
        return {"replayed": replayed, "standby": True, "committed_seq":
                int(region.version_control.committed_sequence)}

    def shutdown(self) -> None:
        self.replication.stop()
        self.flow_manager.stop()
        if self._heartbeat_task is not None:
            self._heartbeat_task.stop()
        for engine in self.engines.values():
            engine.close()
        self.storage.close()
        with _live_lock:
            if _live_datanodes.get(int(self.opts.node_id)) is self:
                del _live_datanodes[int(self.opts.node_id)]
