"""Datanode instance: storage + table engines + catalog + query engine.

Reference behavior: src/datanode/src/instance.rs — `Instance::new_with`
builds object store → log store → storage engine → mito engine → catalog →
query engine; `start_instance` replays the catalog (which replays region
WALs via table open).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..catalog import LocalCatalogManager
from ..mito import MitoEngine
from ..query import QueryEngine
from ..storage.engine import EngineConfig, StorageEngine
from ..storage.object_store import FsObjectStore, ObjectStore
from ..table import NumbersTable
from .. import DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME


@dataclass
class DatanodeOptions:
    data_home: str = "./greptimedb_data"
    node_id: int = 0
    flush_size_bytes: int = 64 * 1024 * 1024
    wal_sync_on_write: bool = False
    disable_wal: bool = False
    register_numbers_table: bool = True   # test fixture, like the reference


class DatanodeInstance:
    def __init__(self, opts: DatanodeOptions,
                 store: Optional[ObjectStore] = None):
        self.opts = opts
        config = EngineConfig(
            data_home=opts.data_home,
            flush_size_bytes=opts.flush_size_bytes,
            wal_sync_on_write=opts.wal_sync_on_write,
            disable_wal=opts.disable_wal)
        self.storage = StorageEngine(config, store=store)
        self.store = self.storage.store
        self.mito = MitoEngine(self.storage)
        self.engines = {self.mito.name: self.mito}
        self.catalog = LocalCatalogManager(self.store, self.engines)
        self.query_engine = QueryEngine(self.catalog)
        self._started = False

    def start(self) -> None:
        """Catalog replay → table open → region WAL replay."""
        self.catalog.start()
        if self.opts.register_numbers_table and \
                self.catalog.table(DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME,
                                   "numbers") is None:
            self.catalog.register_table(
                DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME, "numbers",
                NumbersTable())
        self._started = True

    def shutdown(self) -> None:
        for engine in self.engines.values():
            engine.close()
        self.storage.close()
