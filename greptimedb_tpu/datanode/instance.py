"""Datanode instance: storage + table engines + catalog + query engine.

Reference behavior: src/datanode/src/instance.rs — `Instance::new_with`
builds object store → log store → storage engine → mito engine → catalog →
query engine; `start_instance` replays the catalog (which replays region
WALs via table open).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..catalog import LocalCatalogManager
from ..mito import MitoEngine
from ..query import QueryEngine
from ..storage.engine import EngineConfig, StorageEngine
from ..storage.object_store import FsObjectStore, ObjectStore
from ..table import NumbersTable
from .. import DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME


@dataclass
class DatanodeOptions:
    data_home: str = "./greptimedb_data"
    node_id: int = 0
    flush_size_bytes: int = 64 * 1024 * 1024
    wal_sync_on_write: bool = False
    disable_wal: bool = False
    register_numbers_table: bool = True   # test fixture, like the reference


class DatanodeInstance:
    def __init__(self, opts: DatanodeOptions,
                 store: Optional[ObjectStore] = None):
        self.opts = opts
        config = EngineConfig(
            data_home=opts.data_home,
            flush_size_bytes=opts.flush_size_bytes,
            wal_sync_on_write=opts.wal_sync_on_write,
            disable_wal=opts.disable_wal)
        self.storage = StorageEngine(config, store=store)
        self.store = self.storage.store
        # node-scoped control state: on a shared object store each
        # datanode keeps its own registry/manifests/catalog doc while
        # region data stays globally addressed (failover moves regions)
        prefix = f"nodes/{opts.node_id}/" if opts.node_id else ""
        self.state_prefix = prefix
        self.mito = MitoEngine(self.storage, state_prefix=prefix)
        from ..file_table import ImmutableFileTableEngine
        self.file_engine = ImmutableFileTableEngine(self.store, state_prefix=prefix)
        self.engines = {self.mito.name: self.mito,
                        self.file_engine.name: self.file_engine}
        self.catalog = LocalCatalogManager(self.store, self.engines,
                                           state_prefix=prefix)
        self.query_engine = QueryEngine(self.catalog)
        # durable DDL (reference: procedure manager + loader registration,
        # src/datanode/src/instance.rs:210-236)
        from ..mito.procedure import register_loaders
        from ..procedure import ProcedureManager
        self.procedure_manager = ProcedureManager(self.store, state_prefix=prefix)
        register_loaders(self.procedure_manager, self.mito, self.catalog)
        self._started = False
        self._heartbeat_task = None

    def start(self) -> None:
        """Catalog replay → table open → region WAL replay → resume
        in-flight procedures."""
        self.catalog.start()
        self.procedure_manager.recover()
        if self.opts.register_numbers_table and \
                self.catalog.table(DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME,
                                   "numbers") is None:
            self.catalog.register_table(
                DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME, "numbers",
                NumbersTable())
        self._started = True

    def start_heartbeat(self, meta_client, interval_s: float = 5.0) -> None:
        """Report liveness + region stats to the meta service (reference:
        src/datanode/src/heartbeat.rs:27-141; stats feed the load-based
        selector and the phi failure detector)."""
        from ..meta import DatanodeStat
        from ..storage.scheduler import RepeatedTask

        def beat():
            regions = self.storage.list_regions()
            stat = DatanodeStat(region_count=len(regions))
            resp = meta_client.heartbeat(self.opts.node_id, stat)
            for msg in resp.mailbox:
                self._handle_mailbox(msg)

        beat()                         # immediate first beat (registration)
        self._heartbeat_task = RepeatedTask(
            interval_s, beat, name=f"heartbeat-dn{self.opts.node_id}")
        self._heartbeat_task.start()

    def _handle_mailbox(self, msg: dict) -> None:
        """Meta→datanode control messages riding heartbeat responses."""
        if msg.get("type") == "flush_table":
            t = self.catalog.table(msg["catalog"], msg["schema"],
                                   msg["table"])
            if t is not None:
                t.flush()
        elif msg.get("type") == "open_regions":
            # failover: adopt a dead peer's regions (data on the shared
            # object store; schema shipped in the message)
            if msg.get("table_info") is None:
                import logging
                logging.getLogger(__name__).error(
                    "open_regions for %s without table info; skipping",
                    msg.get("table"))
                return
            table = self.mito.adopt_regions(msg["table_info"],
                                            msg["region_numbers"])
            if self.catalog.table(msg["catalog"], msg["schema"],
                                  msg["table"]) is None:
                self.catalog.register_table(
                    msg["catalog"], msg["schema"], msg["table"], table)

    def shutdown(self) -> None:
        if self._heartbeat_task is not None:
            self._heartbeat_task.stop()
        for engine in self.engines.values():
            engine.close()
        self.storage.close()
