"""Catalog: resolve catalog.schema.table → Table.

Reference behavior: src/catalog — `CatalogManager/CatalogProvider/
SchemaProvider` traits (src/catalog/src/lib.rs:45-110),
`MemoryCatalogManager` (src/catalog/src/local/memory.rs) and
`LocalCatalogManager` persisting registrations so restart re-opens tables
(src/catalog/src/local/manager.rs).
"""

from .manager import CatalogManager, MemoryCatalogManager, LocalCatalogManager

__all__ = ["CatalogManager", "MemoryCatalogManager", "LocalCatalogManager"]
