"""Catalog managers.

`MemoryCatalogManager` holds catalogs → schemas → tables in maps.
`LocalCatalogManager` layers persistence on top: databases and table
registrations are durable (a JSON doc on the object store mirrors the
reference's system catalog table, src/catalog/src/system.rs:50), and
`start()` re-opens every registered table through its engine — the analog
of the reference's catalog-table replay on boot
(src/catalog/src/local/manager.rs:640).
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional

from .. import DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME
from ..errors import (
    DatabaseAlreadyExistsError,
    DatabaseNotFoundError,
    TableAlreadyExistsError,
    TableNotFoundError,
)
from ..table.table import Table, TableEngine
from ..table.requests import OpenTableRequest

SYSTEM_CATALOG_KEY = "catalog/system.json"


class CatalogManager:
    def catalog_names(self) -> List[str]:
        raise NotImplementedError

    def schema_names(self, catalog: str) -> List[str]:
        raise NotImplementedError

    def table_names(self, catalog: str, schema: str) -> List[str]:
        raise NotImplementedError

    def table(self, catalog: str, schema: str, name: str) -> Optional[Table]:
        raise NotImplementedError

    def register_table(self, catalog: str, schema: str, name: str,
                       table: Table) -> None:
        raise NotImplementedError

    def deregister_table(self, catalog: str, schema: str, name: str) -> None:
        raise NotImplementedError

    def register_schema(self, catalog: str, schema: str) -> None:
        raise NotImplementedError

    def deregister_schema(self, catalog: str, schema: str) -> None:
        raise NotImplementedError

    def schema_exists(self, catalog: str, schema: str) -> bool:
        return schema in self.schema_names(catalog)

    def table_exists(self, catalog: str, schema: str, name: str) -> bool:
        return self.table(catalog, schema, name) is not None


class MemoryCatalogManager(CatalogManager):
    """In-memory catalogs (reference: src/catalog/src/local/memory.rs:592)."""

    def __init__(self):
        from ..common.locks import TrackedRLock
        from ..common.tracking import tracked_state
        self._lock = TrackedRLock("catalog.manager")
        self._catalogs: Dict[str, Dict[str, Dict[str, Table]]] = \
            tracked_state({
                DEFAULT_CATALOG_NAME: {DEFAULT_SCHEMA_NAME: {}},
            }, "catalog.manager.catalogs")

    def catalog_names(self) -> List[str]:
        with self._lock:
            return sorted(self._catalogs)

    def schema_names(self, catalog: str) -> List[str]:
        with self._lock:
            if catalog not in self._catalogs:
                raise DatabaseNotFoundError(f"catalog {catalog!r} not found")
            return sorted(self._catalogs[catalog])

    def table_names(self, catalog: str, schema: str) -> List[str]:
        with self._lock:
            schemas = self._catalogs.get(catalog)
            if schemas is None or schema not in schemas:
                raise DatabaseNotFoundError(
                    f"schema {catalog}.{schema} not found")
            return sorted(schemas[schema])

    def table(self, catalog: str, schema: str, name: str) -> Optional[Table]:
        with self._lock:
            return self._catalogs.get(catalog, {}).get(schema, {}).get(name)

    def register_catalog(self, catalog: str) -> None:
        with self._lock:
            self._catalogs.setdefault(catalog, {})

    def register_schema(self, catalog: str, schema: str) -> None:
        with self._lock:
            schemas = self._catalogs.setdefault(catalog, {})
            if schema in schemas:
                raise DatabaseAlreadyExistsError(
                    f"schema {catalog}.{schema} already exists")
            schemas[schema] = {}

    def deregister_schema(self, catalog: str, schema: str) -> None:
        with self._lock:
            schemas = self._catalogs.get(catalog)
            if schemas is None or schema not in schemas:
                raise DatabaseNotFoundError(
                    f"schema {catalog}.{schema} not found")
            if schemas[schema]:
                from ..errors import InvalidArgumentsError
                raise InvalidArgumentsError(
                    f"schema {catalog}.{schema} is not empty")
            del schemas[schema]

    def register_table(self, catalog: str, schema: str, name: str,
                       table: Table) -> None:
        with self._lock:
            schemas = self._catalogs.setdefault(catalog, {})
            tables = schemas.setdefault(schema, {})
            if name in tables:
                raise TableAlreadyExistsError(
                    f"table {catalog}.{schema}.{name} already exists")
            tables[name] = table

    def deregister_table(self, catalog: str, schema: str, name: str) -> None:
        with self._lock:
            tables = self._catalogs.get(catalog, {}).get(schema)
            if tables is None or name not in tables:
                raise TableNotFoundError(
                    f"table {catalog}.{schema}.{name} not found")
            del tables[name]

    def rename_table(self, catalog: str, schema: str, name: str,
                     new_name: str) -> None:
        with self._lock:
            tables = self._catalogs.get(catalog, {}).get(schema)
            if tables is None or name not in tables:
                raise TableNotFoundError(
                    f"table {catalog}.{schema}.{name} not found")
            if new_name in tables:
                raise TableAlreadyExistsError(
                    f"table {catalog}.{schema}.{new_name} already exists")
            tables[new_name] = tables.pop(name)


class LocalCatalogManager(MemoryCatalogManager):
    """Durable catalog over an object store + table engines.

    Registrations are written to `catalog/system.json`; `start()` replays
    it, re-opening tables via their engine (engines recover schema/data from
    their own manifests).
    """

    def __init__(self, store, engines: Dict[str, TableEngine],
                 state_prefix: str = ""):
        super().__init__()
        self.store = store
        self.engines = engines
        self._doc_key = state_prefix + SYSTEM_CATALOG_KEY
        self._started = False
        # registrations whose engine was unavailable at start(); preserved
        # verbatim in the system doc so a config fix can recover them
        self._orphans: List[dict] = []

    # ---- persistence ----
    def _load_doc(self) -> dict:
        if self.store.exists(self._doc_key):
            return json.loads(self.store.read(self._doc_key))
        return {"schemas": [[DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME]],
                "tables": []}

    def _save_doc(self) -> None:
        with self._lock:
            schemas = [[c, s] for c in self._catalogs
                       for s in self._catalogs[c]]
            tables = [{"catalog": c, "schema": s, "name": n,
                       "engine": t.info.meta.engine}
                      for c in self._catalogs
                      for s in self._catalogs[c]
                      for n, t in self._catalogs[c][s].items()
                      if t.info.meta.engine in self.engines]
        self.store.write(self._doc_key, json.dumps(
            {"schemas": schemas,
             "tables": tables + list(self._orphans)}).encode())

    def start(self) -> None:
        """Replay the system catalog: register schemas, re-open tables."""
        doc = self._load_doc()
        with self._lock:
            for c, s in doc["schemas"]:
                self._catalogs.setdefault(c, {}).setdefault(s, {})
        import logging
        for ent in doc["tables"]:
            engine = self.engines.get(ent["engine"])
            table = None
            if engine is not None:
                table = engine.open_table(OpenTableRequest(
                    ent["name"], ent["catalog"], ent["schema"]))
            if table is None:
                logging.getLogger(__name__).warning(
                    "catalog: cannot open %s.%s.%s (engine %r); keeping "
                    "its registration", ent["catalog"], ent["schema"],
                    ent["name"], ent["engine"])
                self._orphans.append(ent)
                continue
            with self._lock:
                self._catalogs[ent["catalog"]][ent["schema"]][
                    ent["name"]] = table
        self._started = True

    # ---- durable mutations ----
    def register_schema(self, catalog: str, schema: str) -> None:
        super().register_schema(catalog, schema)
        self._save_doc()

    def deregister_schema(self, catalog: str, schema: str) -> None:
        super().deregister_schema(catalog, schema)
        self._save_doc()

    def register_table(self, catalog: str, schema: str, name: str,
                       table: Table) -> None:
        super().register_table(catalog, schema, name, table)
        self._save_doc()

    def deregister_table(self, catalog: str, schema: str, name: str) -> None:
        super().deregister_table(catalog, schema, name)
        self._save_doc()

    def rename_table(self, catalog: str, schema: str, name: str,
                     new_name: str) -> None:
        super().rename_table(catalog, schema, name, new_name)
        self._save_doc()
