"""information_schema virtual tables.

Reference behavior: the reference serves `information_schema` through
the catalog's schema provider (exercised by
tests/cases/standalone/common/system/information_schema.sql). Virtual
tables are materialized from live catalog state at scan time:

- information_schema.tables  — one row per registered table
- information_schema.columns — one row per column of every table
- information_schema.runtime_metrics — every sample the prometheus
  registry would export on /metrics (same counters, same values), plus
  live engine gauges (region/memtable/SST state, scan-cache residency,
  object-store read-cache hit ratio) — so metrics are queryable over
  SQL exactly like the /metrics endpoint.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..datatypes import data_type as dt
from ..datatypes.record_batch import RecordBatch
from ..datatypes.schema import ColumnSchema, Schema
from ..table.metadata import TableIdent, TableInfo, TableMeta, TableType
from ..table.table import Table

INFORMATION_SCHEMA_NAME = "information_schema"

_TABLES_SCHEMA = Schema([
    ColumnSchema("table_catalog", dt.STRING),
    ColumnSchema("table_schema", dt.STRING),
    ColumnSchema("table_name", dt.STRING),
    ColumnSchema("table_type", dt.STRING),
    ColumnSchema("table_id", dt.INT64),
    ColumnSchema("engine", dt.STRING),
])

_COLUMNS_SCHEMA = Schema([
    ColumnSchema("table_catalog", dt.STRING),
    ColumnSchema("table_schema", dt.STRING),
    ColumnSchema("table_name", dt.STRING),
    ColumnSchema("column_name", dt.STRING),
    ColumnSchema("data_type", dt.STRING),
    ColumnSchema("semantic_type", dt.STRING),
    ColumnSchema("is_nullable", dt.STRING),
])

_RUNTIME_METRICS_SCHEMA = Schema([
    ColumnSchema("metric_name", dt.STRING),
    ColumnSchema("labels", dt.STRING),
    ColumnSchema("value", dt.FLOAT64),
    ColumnSchema("kind", dt.STRING),
])

_FAILPOINTS_SCHEMA = Schema([
    ColumnSchema("name", dt.STRING),
    ColumnSchema("action", dt.STRING, nullable=True),
    ColumnSchema("hits", dt.INT64),
    ColumnSchema("fires", dt.INT64),
])

_CLUSTER_INFO_SCHEMA = Schema([
    ColumnSchema("peer_id", dt.INT64),
    ColumnSchema("peer_type", dt.STRING),
    ColumnSchema("peer_addr", dt.STRING),
    ColumnSchema("lease_state", dt.STRING),
    ColumnSchema("last_seen_ms", dt.INT64, nullable=True),
    ColumnSchema("region_count", dt.INT64),
    ColumnSchema("approximate_rows", dt.INT64),
    ColumnSchema("ingest_rate_rps", dt.FLOAT64),
    ColumnSchema("region_stats", dt.STRING),
])

_REGION_PEERS_SCHEMA = Schema([
    ColumnSchema("table_name", dt.STRING),
    ColumnSchema("region_number", dt.INT64),
    ColumnSchema("peer_id", dt.INT64),
    ColumnSchema("peer_addr", dt.STRING),
    ColumnSchema("is_leader", dt.STRING),
    ColumnSchema("status", dt.STRING),
    # read replicas (PR 19): the leader row's replicated_seq is its
    # committed sequence; a follower row's is its applied position, and
    # lag_ms bounds its staleness (0 = caught up, NULL = no beat yet)
    ColumnSchema("replicated_seq", dt.INT64, nullable=True),
    ColumnSchema("lag_ms", dt.INT64, nullable=True),
    ColumnSchema("route_version", dt.INT64),
    ColumnSchema("operation", dt.STRING, nullable=True),
    ColumnSchema("op_id", dt.STRING, nullable=True),
])

_PROCESSES_SCHEMA = Schema([
    ColumnSchema("id", dt.INT64),
    ColumnSchema("node", dt.STRING),
    ColumnSchema("catalog", dt.STRING),
    ColumnSchema("schema", dt.STRING),
    ColumnSchema("query", dt.STRING),
    ColumnSchema("protocol", dt.STRING),
    ColumnSchema("state", dt.STRING),
    ColumnSchema("trace_id", dt.STRING),
    ColumnSchema("elapsed_ms", dt.FLOAT64),
    ColumnSchema("rows_scanned", dt.INT64),
    ColumnSchema("bytes_read", dt.INT64),
    ColumnSchema("rpcs", dt.INT64),
    ColumnSchema("partial_bytes", dt.INT64),
])

_SELF_MONITOR_SCHEMA = Schema([
    ColumnSchema("node", dt.STRING),
    ColumnSchema("ticks", dt.INT64),
    ColumnSchema("metric_rows", dt.INT64),
    ColumnSchema("heat_rows", dt.INT64),
    ColumnSchema("rows_written", dt.INT64),
    ColumnSchema("retention_deleted", dt.INT64),
    ColumnSchema("retention_ms", dt.INT64),
    ColumnSchema("last_tick_ms", dt.FLOAT64),
    ColumnSchema("last_error", dt.STRING, nullable=True),
])

_TRACE_SPANS_SCHEMA = Schema([
    ColumnSchema("trace_id", dt.STRING),
    ColumnSchema("span_id", dt.STRING),
    ColumnSchema("parent_span_id", dt.STRING, nullable=True),
    ColumnSchema("node", dt.STRING),
    ColumnSchema("service", dt.STRING),
    ColumnSchema("span_name", dt.STRING),
    ColumnSchema("ts", dt.INT64),
    ColumnSchema("duration_ms", dt.FLOAT64),
    ColumnSchema("status", dt.STRING),
    ColumnSchema("attrs", dt.STRING, nullable=True),
])

_PROFILE_SAMPLES_SCHEMA = Schema([
    ColumnSchema("node", dt.STRING),
    ColumnSchema("kind", dt.STRING),
    ColumnSchema("id", dt.STRING),
    ColumnSchema("trace_id", dt.STRING),
    ColumnSchema("stack_id", dt.STRING),
    ColumnSchema("ts", dt.INT64),
    ColumnSchema("stack", dt.STRING),
    ColumnSchema("count", dt.INT64),
])

_BACKGROUND_JOBS_SCHEMA = Schema([
    ColumnSchema("job_id", dt.INT64),
    ColumnSchema("kind", dt.STRING),
    ColumnSchema("table_name", dt.STRING, nullable=True),
    ColumnSchema("region", dt.STRING, nullable=True),
    ColumnSchema("node", dt.STRING),
    ColumnSchema("state", dt.STRING),
    ColumnSchema("trace_id", dt.STRING),
    ColumnSchema("start_ms", dt.INT64),
    ColumnSchema("duration_ms", dt.FLOAT64, nullable=True),
    ColumnSchema("error", dt.STRING, nullable=True),
    ColumnSchema("detail", dt.STRING, nullable=True),
])

_FLOWS_SCHEMA = Schema([
    ColumnSchema("flow_name", dt.STRING),
    ColumnSchema("source_table", dt.STRING),
    ColumnSchema("sink_table", dt.STRING),
    ColumnSchema("stride_ms", dt.INT64),
    ColumnSchema("aggs", dt.STRING),
    ColumnSchema("watermark", dt.INT64, nullable=True),
    ColumnSchema("folds", dt.INT64),
    ColumnSchema("rows_folded", dt.INT64),
    ColumnSchema("buckets_written", dt.INT64),
])


def _engine_gauges(catalog_manager, catalog_name: str):
    """Live engine state as gauge samples: per-region storage facts plus
    process-wide cache gauges. These exist even before any metric has
    been observed, so `SELECT ... WHERE metric_name = 'greptime_...'`
    over a fresh server is deterministic (the sqlness golden relies on
    that)."""
    rows = []          # (name, labels, value, kind)
    region_count = 0
    for schema_name in catalog_manager.schema_names(catalog_name):
        for tname in catalog_manager.table_names(catalog_name,
                                                 schema_name):
            t = catalog_manager.table(catalog_name, schema_name, tname)
            regions = getattr(t, "regions", None)
            if not regions:
                continue
            for rnum, region in sorted(regions.items()):
                region_count += 1
                vc = getattr(region, "version_control", None)
                if vc is None:
                    continue
                v = vc.current
                labels = (f'{{region="{rnum}", schema="{schema_name}", '
                          f'table="{tname}"}}')
                mt_rows = sum(m.num_rows
                              for m in v.memtables.all_memtables())
                files = list(v.ssts.all_files())
                rows.append(("greptime_region_memtable_rows", labels,
                             float(mt_rows), "gauge"))
                rows.append(("greptime_region_sst_files", labels,
                             float(len(files)), "gauge"))
                rows.append(("greptime_region_sst_rows", labels,
                             float(sum(f.num_rows for f in files)),
                             "gauge"))
    rows.append(("greptime_region_count", "", float(region_count),
                 "gauge"))
    # flow fold state: watermark timestamp + lifetime counters per flow
    # (the flow_* prometheus counters cover rates; these are the gauges)
    fm = getattr(catalog_manager, "flow_manager", None)
    if fm is not None:
        for spec in fm.flows(catalog_name):
            labels = f'{{flow="{spec.name}", source="{spec.source}"}}'
            wm = spec.watermark_ts()
            if wm is not None:
                rows.append(("greptime_flow_watermark_ts", labels,
                             float(wm), "gauge"))
            rows.append(("greptime_flow_rows_folded", labels,
                         float(spec.stats.get("rows_folded", 0)),
                         "gauge"))
            rows.append(("greptime_flow_buckets_written", labels,
                         float(spec.stats.get("buckets_written", 0)),
                         "gauge"))
    from ..query.tpu_exec import SCAN_CACHE
    rows.append(("greptime_scan_cache_resident_bytes", "",
                 float(SCAN_CACHE.resident_bytes()), "gauge"))
    store = getattr(catalog_manager, "store", None)
    hit_ratio = getattr(store, "hit_ratio", None)
    if callable(hit_ratio):
        rows.append(("greptime_read_cache_hit_ratio", "",
                     float(hit_ratio()), "gauge"))
    return rows


def _collect_families():
    """One walk of the default Prometheus registry, shared by the raw
    sample rows and the pXX summaries (the registry grows with statement
    kinds × protocols × routes — don't materialize it twice per query).
    Delegates to the telemetry helper so this view, /metrics and the
    self-monitoring scraper read the SAME walk and label formatting —
    greptime_private.node_metrics can never diverge from
    runtime_metrics."""
    from ..common.telemetry import collect_families
    return collect_families()


def _prometheus_samples(families=None):
    """Every sample the /metrics endpoint would render, via the same
    default registry prometheus_client.generate_latest reads."""
    from ..common.telemetry import registry_snapshot
    return registry_snapshot(families)


def _latency_summary_rows(families=None):
    """p50/p95/p99 gauge rows interpolated from every histogram in the
    registry (telemetry.latency_summaries) — the summarized view of the
    log-bucketed latency distributions next to their raw samples."""
    from ..common.telemetry import latency_summaries
    return [(name, labels, float(value), "summary")
            for name, labels, value in latency_summaries(
                families=families)]


def _cluster_nodes(catalog_manager, catalog_name: str):
    """cluster_info rows: from the meta service when this frontend is
    clustered (DistInstance pins `meta_client` on its catalog), else a
    single synthesized row for the standalone process so the view exists
    on every topology."""
    meta = getattr(catalog_manager, "meta_client", None)
    if meta is not None and hasattr(meta, "cluster_info"):
        try:
            # advisory() bounds a failover client to one quick pass over
            # the replicas: the health view must degrade immediately
            # when meta is down, not stall behind the write-path's
            # multi-round retry budget
            if hasattr(meta, "advisory"):
                meta = meta.advisory()
            return meta.cluster_info()
        except Exception:  # noqa: BLE001 — health view over a flaky
            import logging                 # meta must degrade, not 500
            logging.getLogger(__name__).exception(
                "cluster_info: meta unreachable")
            return []
    import json as _json
    import time as _time
    from ..query.stream_exec import region_stat_entries
    regions = []
    for schema_name in catalog_manager.schema_names(catalog_name):
        for tname in catalog_manager.table_names(catalog_name,
                                                 schema_name):
            t = catalog_manager.table(catalog_name, schema_name, tname)
            regions.extend((getattr(t, "regions", None) or {}).values())
    region_stats, total_rows, _ = region_stat_entries(regions)
    return [{
        "peer_id": 0, "peer_type": "standalone", "peer_addr": "",
        "lease_state": "alive", "last_seen_ms": int(_time.time() * 1000),
        "region_count": len(region_stats),
        "approximate_rows": total_rows, "ingest_rate_rps": 0.0,
        "region_stats": _json.dumps(region_stats,
                                    separators=(",", ":")),
    }]


def _region_peer_rows(catalog_manager, catalog_name: str):
    """region_peers rows: placement + lease state + in-flight balancer
    operation per (table, region). Meta-backed on a clustered frontend
    (same advisory degradation as cluster_info); synthesized from local
    regions standalone so the view exists on every topology."""
    meta = getattr(catalog_manager, "meta_client", None)
    if meta is not None and hasattr(meta, "region_peers"):
        try:
            if hasattr(meta, "advisory"):
                meta = meta.advisory()
            return meta.region_peers()
        except Exception:  # noqa: BLE001 — health view over a flaky
            import logging                 # meta must degrade, not 500
            logging.getLogger(__name__).exception(
                "region_peers: meta unreachable")
            return []
    rows = []
    for schema_name in catalog_manager.schema_names(catalog_name):
        for tname in catalog_manager.table_names(catalog_name,
                                                 schema_name):
            t = catalog_manager.table(catalog_name, schema_name, tname)
            regions = getattr(t, "regions", None)
            if not regions:
                continue
            for rn in sorted(regions):
                vc = getattr(regions[rn], "version_control", None)
                rows.append({
                    "table_name":
                        f"{catalog_name}.{schema_name}.{tname}",
                    "region_number": rn, "peer_id": 0, "peer_addr": "",
                    "is_leader": "Yes", "status": "ALIVE",
                    "replicated_seq": int(vc.committed_sequence)
                    if vc is not None else None,
                    "lag_ms": 0,
                    "route_version": 0, "operation": None,
                    "op_id": None,
                })
    return rows


class _VirtualTable(Table):
    """Read-only table whose rows come from a builder at scan time."""

    def __init__(self, name: str, schema: Schema, builder):
        info = TableInfo(
            ident=TableIdent(3),
            name=name,
            meta=TableMeta(schema=schema, engine="system"),
            schema_name=INFORMATION_SCHEMA_NAME,
            table_type=TableType.TEMPORARY)
        super().__init__(info)
        self._builder = builder

    def scan_batches(self, projection: Optional[Sequence[str]] = None,
                     time_range=None, limit: Optional[int] = None
                     ) -> List[RecordBatch]:
        data = self._builder()
        if limit is not None:
            data = {k: v[:limit] for k, v in data.items()}
        batch = RecordBatch.from_pydict(self.schema, data)
        if projection is not None:
            batch = batch.project(list(projection))
        return [batch]


def information_schema_table(catalog_manager, catalog_name: str,
                             table_name: str) -> Optional[Table]:
    """Resolve `information_schema.<table>` against live catalog state."""
    name = table_name.lower()
    if name == "tables":
        def build_tables():
            rows = {k: [] for k in _TABLES_SCHEMA.names()}
            for schema_name in catalog_manager.schema_names(catalog_name):
                for tname in catalog_manager.table_names(catalog_name,
                                                         schema_name):
                    t = catalog_manager.table(catalog_name, schema_name,
                                              tname)
                    if t is None:
                        continue
                    rows["table_catalog"].append(catalog_name)
                    rows["table_schema"].append(schema_name)
                    rows["table_name"].append(tname)
                    rows["table_type"].append(
                        getattr(t.info.table_type, "value", "BASE TABLE"))
                    rows["table_id"].append(t.info.ident.table_id)
                    rows["engine"].append(t.info.meta.engine)
            return rows
        return _VirtualTable("tables", _TABLES_SCHEMA, build_tables)
    if name == "columns":
        def build_columns():
            rows = {k: [] for k in _COLUMNS_SCHEMA.names()}
            for schema_name in catalog_manager.schema_names(catalog_name):
                for tname in catalog_manager.table_names(catalog_name,
                                                         schema_name):
                    t = catalog_manager.table(catalog_name, schema_name,
                                              tname)
                    if t is None:
                        continue
                    for cs in t.schema.column_schemas:
                        rows["table_catalog"].append(catalog_name)
                        rows["table_schema"].append(schema_name)
                        rows["table_name"].append(tname)
                        rows["column_name"].append(cs.name)
                        rows["data_type"].append(cs.dtype.name)
                        rows["semantic_type"].append(
                            cs.semantic_type.value
                            if hasattr(cs.semantic_type, "value")
                            else str(cs.semantic_type))
                        rows["is_nullable"].append(
                            "YES" if cs.nullable else "NO")
            return rows
        return _VirtualTable("columns", _COLUMNS_SCHEMA, build_columns)
    if name == "flows":
        def build_flows():
            rows = {k: [] for k in _FLOWS_SCHEMA.names()}
            fm = getattr(catalog_manager, "flow_manager", None)
            for spec in (fm.flows(catalog_name) if fm is not None else []):
                rows["flow_name"].append(spec.name)
                rows["source_table"].append(spec.source)
                rows["sink_table"].append(spec.sink)
                rows["stride_ms"].append(spec.stride_ms)
                rows["aggs"].append(", ".join(a.describe()
                                              for a in spec.aggs))
                rows["watermark"].append(spec.watermark_ts())
                rows["folds"].append(spec.stats.get("folds", 0))
                rows["rows_folded"].append(
                    spec.stats.get("rows_folded", 0))
                rows["buckets_written"].append(
                    spec.stats.get("buckets_written", 0))
            return rows
        return _VirtualTable("flows", _FLOWS_SCHEMA, build_flows)
    if name == "failpoints":
        def build_failpoints():
            from ..common import failpoint
            points = failpoint.list_points()
            return {
                "name": [p["name"] for p in points],
                "action": [p["action"] for p in points],
                "hits": [p["hits"] for p in points],
                "fires": [p["fires"] for p in points],
            }
        return _VirtualTable("failpoints", _FAILPOINTS_SCHEMA,
                             build_failpoints)
    if name == "cluster_info":
        def build_cluster_info():
            rows = {k: [] for k in _CLUSTER_INFO_SCHEMA.names()}
            for node in _cluster_nodes(catalog_manager, catalog_name):
                for k in rows:
                    rows[k].append(node.get(k))
            return rows
        return _VirtualTable("cluster_info", _CLUSTER_INFO_SCHEMA,
                             build_cluster_info)
    if name == "region_peers":
        def build_region_peers():
            rows = {k: [] for k in _REGION_PEERS_SCHEMA.names()}
            for peer in _region_peer_rows(catalog_manager, catalog_name):
                for k in rows:
                    rows[k].append(peer.get(k))
            return rows
        return _VirtualTable("region_peers", _REGION_PEERS_SCHEMA,
                             build_region_peers)
    if name == "processes":
        def build_processes():
            from ..common import process_list
            rows = {k: [] for k in _PROCESSES_SCHEMA.names()}
            for r in process_list.REGISTRY.rows():
                for k in rows:
                    rows[k].append(r.get(k))
            return rows
        return _VirtualTable("processes", _PROCESSES_SCHEMA,
                             build_processes)
    if name == "self_monitor":
        def build_self_monitor():
            rows = {k: [] for k in _SELF_MONITOR_SCHEMA.names()}
            mon = getattr(catalog_manager, "self_monitor", None)
            if mon is not None:
                for k, v in mon.row().items():
                    rows[k].append(v)
            return rows
        return _VirtualTable("self_monitor", _SELF_MONITOR_SCHEMA,
                             build_self_monitor)
    if name == "trace_spans":
        def build_trace_spans():
            # a SQL view over the DURABLE store: ping the datanodes
            # (the ordinary RPC piggyback releases freshly-verdicted
            # buffered spans — same sequence as ADMIN SHOW TRACE) and
            # flush the sink first, so "the query just finished" reads
            # see their spans cluster-wide, then serve the
            # greptime_private.trace_spans rows
            from ..common import trace_store
            sink = trace_store.sink()
            clients = getattr(catalog_manager, "dist_clients", None)
            for client in (dict(clients).values() if clients else ()):
                ping = getattr(client, "ping", None)
                if ping is None:
                    continue
                try:
                    ping()
                except Exception as e:  # noqa: BLE001 — degrade to
                    import logging      # what the store already holds
                    logging.getLogger(__name__).debug(
                        "trace_spans: span-sync ping failed: %s", e)
            if sink is not None:
                sink.flush()
            rows = {k: [] for k in _TRACE_SPANS_SCHEMA.names()}
            table = catalog_manager.table(
                catalog_name, trace_store.PRIVATE_SCHEMA,
                trace_store.TRACE_SPANS_TABLE)
            if table is None:
                return rows
            for b in table.scan_batches():
                d = b.to_pydict()
                n = len(d.get("trace_id", []))
                for k in rows:
                    col = d.get(k)
                    rows[k].extend(col if col is not None
                                   else [None] * n)
            return rows
        return _VirtualTable("trace_spans", _TRACE_SPANS_SCHEMA,
                             build_trace_spans)
    if name == "profile_samples":
        def build_profile_samples():
            # SQL view over the continuous profiler's durable table:
            # drain every reachable datanode's pending aggregate (the
            # same Flight `profile` action ADMIN SHOW PROFILE uses) and
            # flush the local sampler first, so a just-finished query's
            # stacks are visible cluster-wide, then serve the
            # greptime_private.profile_samples rows
            from ..common import profiler
            s = profiler.sampler()
            if s is not None:
                clients = getattr(catalog_manager, "dist_clients", None)
                for client in (dict(clients).values() if clients
                               else ()):
                    fetch = getattr(client, "profile", None)
                    if fetch is None:
                        continue
                    try:
                        s.absorb_rows(fetch(drain=True))
                    except Exception:  # noqa: BLE001 — a dead peer
                        import logging  # degrades, never 500s the view
                        logging.getLogger(__name__).debug(
                            "profile_samples: peer drain failed",
                            exc_info=True)
                s.flush()
            rows = {k: [] for k in _PROFILE_SAMPLES_SCHEMA.names()}
            table = catalog_manager.table(
                catalog_name, profiler.PRIVATE_SCHEMA,
                profiler.PROFILE_SAMPLES_TABLE)
            if table is None:
                return rows
            for b in table.scan_batches():
                d = b.to_pydict()
                n = len(d.get("stack_id", []))
                for k in rows:
                    col = d.get(k)
                    rows[k].extend(col if col is not None
                                   else [None] * n)
            return rows
        return _VirtualTable("profile_samples", _PROFILE_SAMPLES_SCHEMA,
                             build_profile_samples)
    if name == "background_jobs":
        def build_background_jobs():
            from ..common import background_jobs
            # local registry first, then every reachable datanode's (a
            # dist frontend pins `dist_clients`); dedup by
            # (node, job_id) — an in-process cluster shares one
            # process-wide registry, so the fan-out re-reads it
            merged = {}
            for r in background_jobs.rows():
                merged[(r.get("node"), r.get("job_id"))] = r
            clients = getattr(catalog_manager, "dist_clients", None)
            peers = list(dict(clients).values()) if clients else []
            # the metasrv runs the balancer: its op-step jobs live in
            # ITS registry (advisory() bounds a failover client to one
            # quick pass, the cluster_info precedent)
            meta = getattr(catalog_manager, "meta_client", None)
            if meta is not None and hasattr(meta, "background_jobs"):
                peers.append(meta.advisory() if hasattr(meta, "advisory")
                             else meta)
            for client in peers:
                fetch = getattr(client, "background_jobs", None)
                if fetch is None:
                    continue
                try:
                    for r in fetch():
                        merged.setdefault(
                            (r.get("node"), r.get("job_id")), r)
                except Exception:  # noqa: BLE001 — a dead peer
                    import logging      # degrades, never 500s the view
                    logging.getLogger(__name__).debug(
                        "background_jobs: peer unreachable",
                        exc_info=True)
            ordered = sorted(
                merged.values(),
                key=lambda r: (r.get("state") != "running",
                               str(r.get("node")),
                               -(r.get("job_id") or 0)))
            rows = {k: [] for k in _BACKGROUND_JOBS_SCHEMA.names()}
            for r in ordered:
                for k in rows:
                    rows[k].append(r.get(k))
            return rows
        return _VirtualTable("background_jobs", _BACKGROUND_JOBS_SCHEMA,
                             build_background_jobs)
    if name == "runtime_metrics":
        def build_metrics():
            families = _collect_families()
            samples = _prometheus_samples(families) + \
                _engine_gauges(catalog_manager, catalog_name) + \
                _latency_summary_rows(families)
            samples.sort(key=lambda r: (r[0], r[1]))
            return {
                "metric_name": [r[0] for r in samples],
                "labels": [r[1] for r in samples],
                "value": [r[2] for r in samples],
                "kind": [r[3] for r in samples],
            }
        return _VirtualTable("runtime_metrics", _RUNTIME_METRICS_SCHEMA,
                             build_metrics)
    return None
