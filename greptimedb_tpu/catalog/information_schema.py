"""information_schema virtual tables.

Reference behavior: the reference serves `information_schema` through
the catalog's schema provider (exercised by
tests/cases/standalone/common/system/information_schema.sql). Virtual
tables are materialized from live catalog state at scan time:

- information_schema.tables  — one row per registered table
- information_schema.columns — one row per column of every table
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..datatypes import data_type as dt
from ..datatypes.record_batch import RecordBatch
from ..datatypes.schema import ColumnSchema, Schema
from ..table.metadata import TableIdent, TableInfo, TableMeta, TableType
from ..table.table import Table

INFORMATION_SCHEMA_NAME = "information_schema"

_TABLES_SCHEMA = Schema([
    ColumnSchema("table_catalog", dt.STRING),
    ColumnSchema("table_schema", dt.STRING),
    ColumnSchema("table_name", dt.STRING),
    ColumnSchema("table_type", dt.STRING),
    ColumnSchema("table_id", dt.INT64),
    ColumnSchema("engine", dt.STRING),
])

_COLUMNS_SCHEMA = Schema([
    ColumnSchema("table_catalog", dt.STRING),
    ColumnSchema("table_schema", dt.STRING),
    ColumnSchema("table_name", dt.STRING),
    ColumnSchema("column_name", dt.STRING),
    ColumnSchema("data_type", dt.STRING),
    ColumnSchema("semantic_type", dt.STRING),
    ColumnSchema("is_nullable", dt.STRING),
])


class _VirtualTable(Table):
    """Read-only table whose rows come from a builder at scan time."""

    def __init__(self, name: str, schema: Schema, builder):
        info = TableInfo(
            ident=TableIdent(3),
            name=name,
            meta=TableMeta(schema=schema, engine="system"),
            schema_name=INFORMATION_SCHEMA_NAME,
            table_type=TableType.TEMPORARY)
        super().__init__(info)
        self._builder = builder

    def scan_batches(self, projection: Optional[Sequence[str]] = None,
                     time_range=None, limit: Optional[int] = None
                     ) -> List[RecordBatch]:
        data = self._builder()
        if limit is not None:
            data = {k: v[:limit] for k, v in data.items()}
        batch = RecordBatch.from_pydict(self.schema, data)
        if projection is not None:
            batch = batch.project(list(projection))
        return [batch]


def information_schema_table(catalog_manager, catalog_name: str,
                             table_name: str) -> Optional[Table]:
    """Resolve `information_schema.<table>` against live catalog state."""
    name = table_name.lower()
    if name == "tables":
        def build_tables():
            rows = {k: [] for k in _TABLES_SCHEMA.names()}
            for schema_name in catalog_manager.schema_names(catalog_name):
                for tname in catalog_manager.table_names(catalog_name,
                                                         schema_name):
                    t = catalog_manager.table(catalog_name, schema_name,
                                              tname)
                    if t is None:
                        continue
                    rows["table_catalog"].append(catalog_name)
                    rows["table_schema"].append(schema_name)
                    rows["table_name"].append(tname)
                    rows["table_type"].append(
                        getattr(t.info.table_type, "value", "BASE TABLE"))
                    rows["table_id"].append(t.info.ident.table_id)
                    rows["engine"].append(t.info.meta.engine)
            return rows
        return _VirtualTable("tables", _TABLES_SCHEMA, build_tables)
    if name == "columns":
        def build_columns():
            rows = {k: [] for k in _COLUMNS_SCHEMA.names()}
            for schema_name in catalog_manager.schema_names(catalog_name):
                for tname in catalog_manager.table_names(catalog_name,
                                                         schema_name):
                    t = catalog_manager.table(catalog_name, schema_name,
                                              tname)
                    if t is None:
                        continue
                    for cs in t.schema.column_schemas:
                        rows["table_catalog"].append(catalog_name)
                        rows["table_schema"].append(schema_name)
                        rows["table_name"].append(tname)
                        rows["column_name"].append(cs.name)
                        rows["data_type"].append(cs.dtype.name)
                        rows["semantic_type"].append(
                            cs.semantic_type.value
                            if hasattr(cs.semantic_type, "value")
                            else str(cs.semantic_type))
                        rows["is_nullable"].append(
                            "YES" if cs.nullable else "NO")
            return rows
        return _VirtualTable("columns", _COLUMNS_SCHEMA, build_columns)
    return None
