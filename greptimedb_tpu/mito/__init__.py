"""Mito: the default table engine, mapping tables onto storage regions.

Reference behavior: src/mito — `MitoEngine` creates one storage region per
table partition (src/mito/src/engine.rs:84-260), persists a table manifest
next to the data (src/mito/src/manifest.rs), and `MitoTable` implements the
Table trait by fanning scans over regions
(src/mito/src/table.rs:140-213).
"""

from .engine import MitoEngine, MitoTable

__all__ = ["MitoEngine", "MitoTable"]
