"""Procedure-backed DDL for the mito engine.

Reference behavior: src/mito/src/engine/procedure/{create,alter,drop}.rs
(+ src/table-procedure gluing catalog and engine): CREATE/ALTER/DROP run
as durable procedures whose steps persist, so a crash between "engine
applied" and "catalog registered" resumes to a consistent end state
instead of leaving a half-created table.

Steps (mirroring CreateMitoTable's state machine, create.rs:60-260):
  create: engine_create → register_catalog → done
  drop:   engine_drop → deregister_catalog → done
  alter:  engine_alter → update_catalog → done
Every step is idempotent: the engine's manifest-first create/open and the
catalog register/deregister calls tolerate replay.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..procedure import Procedure, Status
from ..table.requests import (
    AddColumnRequest, AlterKind, AlterTableRequest, CreateTableRequest,
    DropTableRequest, alter_request_from_dict, alter_request_to_dict,
    create_request_from_dict, create_request_to_dict)
from ..datatypes.schema import ColumnSchema


class CreateTableProcedure(Procedure):
    type_name = "mito.CreateTable"

    def __init__(self, request: CreateTableRequest, engine, catalog,
                 state: str = "engine_create"):
        self.request = request
        self.engine = engine
        self.catalog = catalog
        self.state = state

    def lock_key(self) -> Optional[str]:
        r = self.request
        return f"{r.catalog_name}.{r.schema_name}.{r.table_name}"

    def execute(self, ctx) -> Status:
        if self.state == "engine_create":
            # resume-safe: an already-created table is re-opened via its
            # manifest rather than failed (engine create is idempotent
            # under create_if_not_exists)
            req = self.request
            if not req.create_if_not_exists:
                import dataclasses
                req = dataclasses.replace(req, create_if_not_exists=True)
            self._table = self.engine.create_table(req)
            self.state = "register_catalog"
            return Status.executing()
        if self.state == "register_catalog":
            r = self.request
            if not hasattr(self, "_table"):
                self._table = self.engine.create_table(
                    _with_if_not_exists(self.request))
            if self.catalog.table(r.catalog_name, r.schema_name,
                                  r.table_name) is None:
                self.catalog.register_table(
                    r.catalog_name, r.schema_name, r.table_name,
                    self._table)
            return Status.done()
        raise ValueError(f"unknown state {self.state!r}")

    def dump(self) -> dict:
        return {"state": self.state,
                "request": create_request_to_dict(self.request)}

    @staticmethod
    def loader(engine, catalog):
        def load(data: dict) -> "CreateTableProcedure":
            return CreateTableProcedure(
                create_request_from_dict(data["request"]), engine, catalog,
                state=data["state"])
        return load


def _with_if_not_exists(req: CreateTableRequest) -> CreateTableRequest:
    import dataclasses
    return req if req.create_if_not_exists else \
        dataclasses.replace(req, create_if_not_exists=True)


class DropTableProcedure(Procedure):
    type_name = "mito.DropTable"

    def __init__(self, request: DropTableRequest, engine, catalog,
                 state: str = "engine_drop"):
        self.request = request
        self.engine = engine
        self.catalog = catalog
        self.state = state

    def lock_key(self) -> Optional[str]:
        r = self.request
        return f"{r.catalog_name}.{r.schema_name}.{r.table_name}"

    def execute(self, ctx) -> Status:
        r = self.request
        if self.state == "engine_drop":
            self.engine.drop_table(r)     # returns False if already gone
            self.state = "deregister_catalog"
            return Status.executing()
        if self.state == "deregister_catalog":
            self.catalog.deregister_table(r.catalog_name, r.schema_name,
                                          r.table_name)
            return Status.done()
        raise ValueError(f"unknown state {self.state!r}")

    def dump(self) -> dict:
        r = self.request
        return {"state": self.state,
                "request": {"table_name": r.table_name,
                            "catalog_name": r.catalog_name,
                            "schema_name": r.schema_name}}

    @staticmethod
    def loader(engine, catalog):
        def load(data: dict) -> "DropTableProcedure":
            d = data["request"]
            return DropTableProcedure(
                DropTableRequest(d["table_name"], d["catalog_name"],
                                 d["schema_name"]),
                engine, catalog, state=data["state"])
        return load


class AlterTableProcedure(Procedure):
    type_name = "mito.AlterTable"

    def __init__(self, request: AlterTableRequest, engine, catalog,
                 state: str = "engine_alter"):
        self.request = request
        self.engine = engine
        self.catalog = catalog
        self.state = state

    def lock_key(self) -> Optional[str]:
        r = self.request
        return f"{r.catalog_name}.{r.schema_name}.{r.table_name}"

    def execute(self, ctx) -> Status:
        r = self.request
        if self.state == "engine_alter":
            from ..errors import ColumnExistsError
            try:
                self.engine.alter_table(r)
            except ColumnExistsError:
                # replayed add-column after a crash between apply+commit
                pass
            self.state = "update_catalog"
            return Status.executing()
        if self.state == "update_catalog":
            if r.kind == AlterKind.RENAME_TABLE and \
                    self.catalog.table(r.catalog_name, r.schema_name,
                                       r.table_name) is not None:
                self.catalog.rename_table(r.catalog_name, r.schema_name,
                                          r.table_name, r.new_table_name)
            return Status.done()
        raise ValueError(f"unknown state {self.state!r}")

    def dump(self) -> dict:
        return {"state": self.state,
                "request": alter_request_to_dict(self.request)}

    @staticmethod
    def loader(engine, catalog):
        def load(data: dict) -> "AlterTableProcedure":
            return AlterTableProcedure(
                alter_request_from_dict(data["request"]), engine, catalog,
                state=data["state"])
        return load


def register_loaders(manager, engine, catalog) -> None:
    """Bind DDL procedure loaders to a datanode's engine+catalog
    (reference: procedure loader registration,
    src/datanode/src/instance.rs:210-236)."""
    manager.register_loader(CreateTableProcedure.type_name,
                            CreateTableProcedure.loader(engine, catalog))
    manager.register_loader(DropTableProcedure.type_name,
                            DropTableProcedure.loader(engine, catalog))
    manager.register_loader(AlterTableProcedure.type_name,
                            AlterTableProcedure.loader(engine, catalog))
