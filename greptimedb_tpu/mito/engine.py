"""MitoEngine + MitoTable.

Layout on the object store (mirrors the reference's `table_dir`/
`region_name` scheme, src/table/src/engine.rs):

    mito/engine.json                       — next_table_id + table registry
    mito/{catalog}/{schema}/{table_id}/manifest.json — TableInfo
    region data under region name "{table_id}_{region_number:010d}"

DDL ordering follows the reference's manifest-first create
(src/mito/src/engine/procedure/create.rs): persist the table manifest, then
create regions, then register — recovery re-opens from the manifest.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import MITO_ENGINE
from ..common.time import TimestampRange
from ..datatypes.record_batch import RecordBatch
from ..datatypes.schema import ColumnSchema, Schema, SemanticType
from ..errors import (
    ColumnExistsError,
    ColumnNotFoundError,
    InvalidArgumentsError,
    RegionNotFoundError,
    TableAlreadyExistsError,
    TableNotFoundError,
)
from ..partition import rule_from_partitions, split_rows
from ..partition.rule import (
    MAXVALUE,
    HashPartitionRule,
    PartitionRule,
    RangeColumnsPartitionRule,
    RangePartitionRule,
)
from ..storage.engine import StorageEngine
from ..storage.region import Region
from ..storage.write_batch import WriteBatch
from ..table.metadata import TableIdent, TableInfo, TableMeta
from ..table.requests import (
    AlterKind,
    AlterTableRequest,
    CreateTableRequest,
    DropTableRequest,
    OpenTableRequest,
)
from ..table.table import Table, TableEngine

logger = logging.getLogger(__name__)

MIN_USER_TABLE_ID = 1024


def region_opts_from_table_options(options: Dict) -> Optional[Dict]:
    """Map CREATE TABLE WITH(...) options onto region knobs
    (ttl='7d', compaction_time_window='1h')."""
    from ..common.time import parse_duration_ms
    opts = {}
    ttl = options.get("ttl")
    if ttl:
        opts["ttl_ms"] = parse_duration_ms(str(ttl))
    cw = options.get("compaction_time_window")
    if cw:
        opts["compaction_time_window_ms"] = parse_duration_ms(str(cw))
    return opts or None


def region_name(table_id: int, region_number: int) -> str:
    return f"{table_id}_{region_number:010d}"


def region_rows_columns(region, seq_gt: Optional[int] = None):
    """One region's merged live rows as an ingest-shaped column dict
    (tags decoded, None for NULL fields), optionally restricted to rows
    committed AFTER `seq_gt` — the split copy's source view. Returns
    (columns, snapshot_visible_sequence)."""
    snap = region.snapshot()
    visible = snap.visible_sequence
    data = snap.read_merged()
    if data.num_rows == 0:
        return {}, visible
    if seq_gt is not None and data.seq is not None:
        keep = data.seq > seq_gt
        if not keep.any():
            return {}, visible
        import dataclasses
        data = dataclasses.replace(
            data,
            series_ids=data.series_ids[keep], ts=data.ts[keep],
            seq=data.seq[keep],
            op_types=data.op_types[keep]
            if data.op_types is not None else None,
            fields={n: (d[keep], vd[keep] if vd is not None else None)
                    for n, (d, vd) in data.fields.items()})
    sd = data.series_dict
    cols: Dict[str, object] = {}
    for i, tag in enumerate(sd.tag_names):
        cols[tag] = sd.decode_tag_column(data.series_ids, i)
    tc = region.schema.timestamp_column
    if tc is not None:
        cols[tc.name] = data.ts
    for name, (vals, valid) in data.fields.items():
        if valid is None or bool(valid.all()):
            cols[name] = vals
        else:
            arr = np.empty(len(vals), dtype=object)
            arr[:] = vals
            arr[~valid] = None
            cols[name] = list(arr)
    return cols, visible


def _median_split_value(values):
    """The region's median partition-column value, adjusted to be
    STRICTLY above the minimum so both children are non-empty; None when
    the region has no value spread (all rows share one value)."""
    vals = sorted(v for v in values if v is not None)
    if not vals or vals[0] == vals[-1]:
        return None
    v = vals[len(vals) // 2]
    if v == vals[0]:
        nxt = [x for x in vals if x > vals[0]]
        if not nxt:
            return None
        v = nxt[0]
    if hasattr(v, "item") and not isinstance(v, (str, bytes)):
        v = v.item()               # numpy scalar → JSON-encodable builtin
    return v


def _serialize_rule(rule: Optional[PartitionRule]) -> Optional[dict]:
    if rule is None:
        return None

    def enc(v):
        return {"maxvalue": True} if v is MAXVALUE else v

    if isinstance(rule, RangePartitionRule):
        return {"kind": "range", "column": rule.column,
                "bounds": [enc(b) for b in rule.bounds],
                "regions": rule.regions}
    if isinstance(rule, RangeColumnsPartitionRule):
        return {"kind": "range_columns", "columns": rule.columns,
                "bounds": [[enc(v) for v in b] for b in rule.bounds],
                "regions": rule.regions}
    if isinstance(rule, HashPartitionRule):
        return {"kind": "hash", "columns": rule.columns,
                "regions": rule.regions}
    raise InvalidArgumentsError(f"unserializable rule {type(rule)}")


def _deserialize_rule(d: Optional[dict]) -> Optional[PartitionRule]:
    if d is None:
        return None

    def dec(v):
        return MAXVALUE if isinstance(v, dict) and v.get("maxvalue") else v

    if d["kind"] == "hash":
        return HashPartitionRule(list(d["columns"]), list(d["regions"]))
    if d["kind"] == "range":
        return RangePartitionRule(d["column"], [dec(b) for b in d["bounds"]],
                                  list(d["regions"]))
    return RangeColumnsPartitionRule(
        list(d["columns"]), [tuple(dec(v) for v in b) for b in d["bounds"]],
        list(d["regions"]))


#: comparison shapes a datanode can apply exactly on its tag columns —
#: the frontend only pushes `limit` over the wire when EVERY conjunct is
#: pushable by this definition, so both sides must share it
_PUSHABLE_OPS = {"=", "!=", "<", "<=", ">", ">="}


def pushable_tag_filter(e, tag_names) -> bool:
    """True iff `e` is a tag-vs-literal predicate the scan path can apply
    exactly (shared by DistTable's wire encoder and the datanode)."""
    from ..sql.ast import BinaryOp, Column, InList, Literal
    tags = set(tag_names)
    if isinstance(e, BinaryOp) and e.op in _PUSHABLE_OPS:
        for col, lit in ((e.left, e.right), (e.right, e.left)):
            if isinstance(col, Column) and col.name in tags and \
                    isinstance(lit, Literal) and lit.value is not None:
                return True
        return False
    if isinstance(e, InList) and isinstance(e.expr, Column) and \
            e.expr.name in tags and e.items:
        return all(isinstance(i, Literal) and i.value is not None
                   for i in e.items)
    return False


def sid_candidates_for_filters(series_dict, tag_names,
                               filters) -> Optional[np.ndarray]:
    """Sorted candidate series-id set from the point (`tag = literal`)
    and non-negated `tag IN (...)` conjuncts of `filters`, resolved
    through the series dictionary — the sid sets the per-SST secondary
    index (storage/index.py) prunes files and row groups with.

    Returns None when no such conjunct exists (nothing selective to
    prune on: `!=`, ranges and regex-shaped predicates are deliberately
    EXCLUDED — their sid sets are near-total, so consulting blooms would
    cost without shedding). The result is a SUPERSET guarantee, not a
    filter: every row matching ALL conjuncts has a sid in the set, so
    callers still apply the full predicate downstream and answers cannot
    drift. An equality on a never-seen value resolves to the empty set —
    exact, and it prunes every file."""
    from ..sql.ast import BinaryOp, Column, InList, Literal
    tags = set(tag_names)
    cand: Optional[np.ndarray] = None
    for e in filters:
        col = None
        vals = None
        if isinstance(e, BinaryOp) and e.op == "=":
            for c, lit in ((e.left, e.right), (e.right, e.left)):
                if isinstance(c, Column) and c.name in tags and \
                        isinstance(lit, Literal) and lit.value is not None:
                    col, vals = c.name, [lit.value]
                    break
        elif isinstance(e, InList) and not e.negated and \
                isinstance(e.expr, Column) and e.expr.name in tags and \
                e.items and all(isinstance(i, Literal) and
                                i.value is not None for i in e.items):
            col, vals = e.expr.name, [i.value for i in e.items]
        if col is None:
            continue
        sids = series_dict.sids_for_tag_values(tag_names.index(col), vals)
        cand = sids if cand is None else \
            np.intersect1d(cand, sids, assume_unique=True)
        if cand is not None and len(cand) == 0:
            break                       # provably empty: nothing matches
    return cand


def _tag_series_keep(series_dict, tag_names, filters) -> np.ndarray:
    """Per-series keep mask for pushable tag filters: predicates evaluate
    once per SERIES (via the dictionary), not once per row, then broadcast
    through series_ids. NULL tags compare UNKNOWN → dropped, matching the
    engine's `mask.fillna(False)` WHERE semantics."""
    import operator
    from ..sql.ast import BinaryOp, Column, InList, Literal
    ops = {"=": operator.eq, "!=": operator.ne, "<": operator.lt,
           "<=": operator.le, ">": operator.gt, ">=": operator.ge}
    flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
    S = series_dict.num_series
    keep = np.ones(S, dtype=bool)
    ids = np.arange(S, dtype=np.int32)
    cache: Dict[str, list] = {}

    def col_values(name: str):
        if name not in cache:
            cache[name] = series_dict.decode_tag_column(
                ids, tag_names.index(name))
        return cache[name]

    for e in filters:
        if isinstance(e, BinaryOp):
            op = e.op
            if isinstance(e.left, Column) and isinstance(e.right, Literal):
                col, lit = e.left, e.right
            else:
                col, lit = e.right, e.left
                op = flip.get(op, op)
            vals = col_values(col.name)
            fn = ops[op]
            m = np.zeros(S, dtype=bool)
            for i, v in enumerate(vals):
                if v is None:
                    continue
                try:
                    m[i] = bool(fn(v, lit.value))
                except TypeError:
                    m[i] = False
            keep &= m
        elif isinstance(e, InList):
            items = {i.value for i in e.items}
            vals = col_values(e.expr.name)
            m = np.fromiter(
                ((v is not None) and ((v in items) != e.negated)
                 for v in vals), dtype=bool, count=S)
            keep &= m
    return keep


class MitoTable(Table):
    def __init__(self, info: TableInfo, regions: Dict[int, Region],
                 rule: Optional[PartitionRule] = None):
        super().__init__(info)
        self.regions = regions
        self.partition_rule = rule

    # ---- writes ----
    def insert(self, columns: Dict[str, Sequence]) -> int:
        if not columns:
            return 0
        num_rows = len(next(iter(columns.values())))
        for name, vals in columns.items():
            if len(vals) != num_rows:
                raise InvalidArgumentsError(
                    f"ragged insert column {name!r}")
        splits = split_rows(self.partition_rule, columns, num_rows) \
            if self.partition_rule is not None \
            else {min(self.regions): None}
        written = 0
        for rnum, idx in splits.items():
            region = self.regions.get(rnum)
            if region is None:
                raise RegionNotFoundError(
                    f"rows target region {rnum}, which this node does not "
                    f"host for table {self.info.name} (distributed writes "
                    f"must go through the frontend router)")
            if idx is None:
                part = columns
            else:
                part = {k: [v[i] for i in idx] for k, v in columns.items()}
            wb = WriteBatch(region.schema)
            wb.put(part)
            region.write(wb)
            written += num_rows if idx is None else len(idx)
        return written

    def bulk_load(self, columns: Dict[str, Sequence]) -> int:
        """WAL-less bulk ingestion straight to SSTs (COPY FROM / loader
        path): same routing as insert, ~10x the throughput of the
        WAL+memtable write path (Region.bulk_ingest)."""
        if not columns:
            return 0
        from ..common.telemetry import span
        num_rows = len(next(iter(columns.values())))
        with span("bulk_load", table=self.info.name, rows=num_rows):
            return self._bulk_load_inner(columns, num_rows)

    def _bulk_load_inner(self, columns: Dict[str, Sequence],
                         num_rows: int) -> int:
        for name, vals in columns.items():
            if len(vals) != num_rows:
                raise InvalidArgumentsError(
                    f"ragged bulk_load column {name!r}")
        splits = split_rows(self.partition_rule, columns, num_rows) \
            if self.partition_rule is not None \
            else {min(self.regions): None}
        written = 0
        for rnum, idx in splits.items():
            region = self.regions.get(rnum)
            if region is None:
                raise RegionNotFoundError(
                    f"rows target region {rnum}, which this node does not "
                    f"host for table {self.info.name}")
            # lists stay lists under the split (an object-ndarray round
            # trip would feed None-bearing numerics to astype, which
            # rejects None) — typed ndarrays keep the raw fast path
            part = columns if idx is None else \
                {k: v[idx] if isinstance(v, np.ndarray)
                 else [v[i] for i in idx]
                 for k, v in columns.items()}
            written += region.bulk_ingest(part)
        return written

    def delete(self, key_columns: Dict[str, Sequence]) -> int:
        if not key_columns:
            return 0
        num_rows = len(next(iter(key_columns.values())))
        splits = split_rows(self.partition_rule, key_columns, num_rows) \
            if self.partition_rule is not None \
            else {min(self.regions): None}
        deleted = 0
        for rnum, idx in splits.items():
            region = self.regions.get(rnum)
            if region is None:
                raise RegionNotFoundError(
                    f"rows target region {rnum}, which this node does not "
                    f"host for table {self.info.name}")
            part = key_columns if idx is None else \
                {k: [v[i] for i in idx] for k, v in key_columns.items()}
            wb = WriteBatch(region.schema)
            wb.delete(part)
            region.write(wb)
            deleted += num_rows if idx is None else len(idx)
        return deleted

    def write_region(self, region_number: int,
                     columns: Dict[str, Sequence],
                     op: str = "put") -> int:
        """Distributed write path: rows pre-split by the frontend land on
        one specific region (reference: datanode handles per-region
        inserts, src/datanode/src/instance/grpc.rs:124-160)."""
        region = self.regions.get(region_number)
        if region is None:
            # typed so the DistTable refreshes its route and retries —
            # the region moved (migrate) or was refined away (split)
            from ..errors import StaleRouteError
            raise StaleRouteError(
                f"region {region_number} of table {self.info.name} is "
                f"not hosted here (it may have moved)")
        if op == "bulk":
            # WAL-less direct-to-SST load (frontend bulk routing)
            return region.bulk_ingest(columns)
        wb = WriteBatch(region.schema)
        if op == "put":
            wb.put(columns)
        else:
            wb.delete(columns)
        region.write(wb)
        return len(next(iter(columns.values()))) if columns else 0

    # ---- reads ----
    def scan_raw(self, projection: Optional[Sequence[str]] = None,
                 time_range: Optional[TimestampRange] = None):
        return [r.snapshot().scan(projection=projection,
                                  time_range=time_range)
                for r in self.regions.values()]

    def scan_batches(self, projection: Optional[Sequence[str]] = None,
                     time_range: Optional[TimestampRange] = None,
                     limit: Optional[int] = None,
                     filters: Optional[Sequence] = None,
                     regions: Optional[Sequence[int]] = None
                     ) -> List[RecordBatch]:
        """`filters`: pushable tag predicates applied region-side so a
        pruned distributed scan stops shipping dead rows; `regions`:
        restrict to this subset of hosted region numbers (the frontend's
        surviving-region list — without it a datanode would scan its
        un-pruned sibling regions too)."""
        out: List[RecordBatch] = []
        remaining = limit
        schema = self.schema if projection is None \
            else self.schema.project(self._scan_columns(projection))
        tag_names = self.schema.tag_names()
        usable = [f for f in (filters or ())
                  if pushable_tag_filter(f, tag_names)]
        if regions is not None:
            missing = set(regions) - set(self.regions)
            if missing:
                # silently skipping would return PARTIAL results for a
                # frontend whose route predates a migrate/split; typed so
                # it refreshes and retries instead
                from ..errors import StaleRouteError
                raise StaleRouteError(
                    f"region(s) {sorted(missing)} of table "
                    f"{self.info.name} are not hosted here")
        hosted = self.regions if regions is None else \
            {rn: r for rn, r in self.regions.items() if rn in set(regions)}
        from ..storage.index import sst_index_enabled
        for region in hosted.values():
            # point/IN conjuncts resolve to sid sets per REGION (series
            # dictionaries are region-local) so the scan prunes whole
            # SSTs through their index sidecars — this is the datanode
            # side of the wire-pushed tag filters too
            sid_set = None
            if usable and sst_index_enabled():
                sid_set = sid_candidates_for_filters(
                    region.series_dict, tag_names, usable)
            data = region.snapshot().read_merged(
                projection=projection, time_range=time_range,
                sid_set=sid_set)
            if usable and data.num_rows:
                keep = _tag_series_keep(data.series_dict, tag_names,
                                        usable)
                if not keep.all():
                    import dataclasses
                    sel = keep[data.series_ids]
                    data = dataclasses.replace(
                        data,
                        series_ids=data.series_ids[sel],
                        ts=data.ts[sel],
                        seq=data.seq[sel] if data.seq is not None else None,
                        op_types=data.op_types[sel]
                        if data.op_types is not None else None,
                        fields={n: (d[sel],
                                    vd[sel] if vd is not None else None)
                                for n, (d, vd) in data.fields.items()})
            rb = self._scan_data_to_batch(data, schema)
            if remaining is not None:
                rb = rb.slice(0, min(remaining, rb.num_rows))
                remaining -= rb.num_rows
            out.append(rb)
            if remaining is not None and remaining <= 0:
                break
        return out

    def _scan_columns(self, projection: Sequence[str]) -> List[str]:
        return [c.name for c in self.schema.column_schemas
                if c.name in projection]

    def _scan_data_to_batch(self, data, schema: Schema) -> RecordBatch:
        """SoA scan arrays → RecordBatch with zero per-value Python: the
        scan already holds numpy columns + validity bitmaps, so vectors
        wrap them directly (small-query latency is conversion-bound)."""
        from ..datatypes.vector import Vector
        import numpy as np
        sd = data.series_dict
        vectors = []
        for c in schema.column_schemas:
            if c.is_tag:
                tag_idx = self.schema.tag_names().index(c.name)
                decoded = sd.decode_tag_column(data.series_ids, tag_idx)
                arr = np.empty(len(decoded), dtype=object)
                arr[:] = decoded
                vectors.append(Vector(c.dtype, arr))
            elif c.is_time_index:
                vectors.append(Vector.from_numpy(data.ts, c.dtype))
            elif c.name in data.fields:
                vals, valid = data.fields[c.name]
                if vals.dtype == object:
                    vectors.append(Vector(c.dtype, vals, valid))
                else:
                    vectors.append(Vector.from_numpy(vals, c.dtype,
                                                     validity=valid))
            else:
                vectors.append(Vector.nulls(data.num_rows, c.dtype))
        return RecordBatch(schema, vectors)

    def flush(self) -> None:
        for region in self.regions.values():
            region.flush()

    def close(self) -> None:
        for region in self.regions.values():
            region.close()


class MitoEngine(TableEngine):
    name = MITO_ENGINE

    def __init__(self, storage: StorageEngine, state_prefix: str = ""):
        # control state (registry/manifests) is node-scoped when several
        # datanodes share one object store (failover deployments); region
        # DATA stays globally addressed so regions can move between nodes
        self.state_prefix = state_prefix
        self.storage = storage
        self.store = storage.store
        from ..common.locks import TrackedLock
        from ..common.tracking import tracked_state
        self._tables: Dict[tuple, MitoTable] = tracked_state(
            {}, "mito.engine.tables")
        self._lock = TrackedLock("mito.engine")
        self._registry = self._load_registry()
        #: split-in-flight child regions, keyed (catalog, schema, table):
        #: hosted on disk but invisible to reads until apply_split swaps
        #: them into the table's served region set
        self._pending_splits: Dict[tuple, Dict[int, Region]] = \
            tracked_state({}, "mito.engine.pending_splits")

    # ---- engine registry (next id + table dirs) ----
    def _registry_key(self) -> str:
        return f"{self.state_prefix}mito/engine.json"

    def _load_registry(self) -> dict:
        if self.store.exists(self._registry_key()):
            return json.loads(self.store.read(self._registry_key()))
        return {"next_table_id": MIN_USER_TABLE_ID, "tables": {}}

    def _save_registry(self) -> None:
        self.store.write(self._registry_key(),
                         json.dumps(self._registry).encode())

    def _manifest_key(self, catalog: str, schema: str, table_id: int) -> str:
        return (f"{self.state_prefix}mito/{catalog}/{schema}/"
                f"{table_id}/manifest.json")

    # ---- DDL ----
    def create_table(self, request: CreateTableRequest) -> MitoTable:
        key = (request.catalog_name, request.schema_name, request.table_name)
        full = ".".join(key)
        with self._lock:
            existing = self._tables.get(key)
            if existing is None and full in self._registry["tables"]:
                existing = self._open_locked(OpenTableRequest(
                    request.table_name, request.catalog_name,
                    request.schema_name))
            if existing is not None:
                if request.create_if_not_exists:
                    return existing
                raise TableAlreadyExistsError(f"table {full} already exists")
            if request.table_id is not None:
                table_id = request.table_id
                self._registry["next_table_id"] = max(
                    self._registry["next_table_id"], table_id + 1)
            else:
                table_id = self._registry["next_table_id"]
                self._registry["next_table_id"] = table_id + 1

            rule = None
            region_numbers = list(request.region_numbers)
            if request.partitions is not None:
                rule = rule_from_partitions(request.partitions)
                region_numbers = rule.region_numbers()
            elif len(region_numbers) > 1:
                raise InvalidArgumentsError(
                    "multi-region table requires a partition rule")
            if request.assigned_region_numbers is not None:
                # distributed: this datanode materializes (and records in
                # its local manifest) only its assigned regions; the full
                # set lives in the frontend's table route
                bad = set(request.assigned_region_numbers) - \
                    set(region_numbers)
                if bad:
                    raise InvalidArgumentsError(
                        f"assigned regions {sorted(bad)} not in the "
                        f"table's region set {region_numbers}")
                region_numbers = list(request.assigned_region_numbers)
            schema = request.schema
            meta = TableMeta(
                schema=schema,
                primary_key_indices=list(request.primary_key_indices),
                engine=self.name,
                region_numbers=region_numbers,
                next_column_id=len(schema),
                options=dict(request.table_options),
                partition_rule=_serialize_rule(rule),
            )
            info = TableInfo(ident=TableIdent(table_id),
                             name=request.table_name, meta=meta,
                             catalog_name=request.catalog_name,
                             schema_name=request.schema_name,
                             desc=request.desc)
            # manifest first (create recovers from it), then regions
            self.store.write(
                self._manifest_key(*key[:2], table_id),
                json.dumps(info.to_dict()).encode())
            ropts = region_opts_from_table_options(meta.options)
            regions = {rn: self.storage.create_region(
                region_name(table_id, rn), schema, opts=ropts)
                for rn in region_numbers}
            table = MitoTable(info, regions, rule)
            self._tables[key] = table
            self._registry["tables"][full] = table_id
            self._save_registry()
            return table

    def open_table(self, request: OpenTableRequest) -> Optional[MitoTable]:
        with self._lock:
            return self._open_locked(request)

    def _open_locked(self, request: OpenTableRequest) -> Optional[MitoTable]:
        key = (request.catalog_name, request.schema_name, request.table_name)
        if key in self._tables:
            return self._tables[key]
        full = ".".join(key)
        table_id = self._registry["tables"].get(full)
        if table_id is None:
            return None
        raw = self.store.read(self._manifest_key(*key[:2], table_id))
        info = TableInfo.from_dict(json.loads(raw))
        rule = _deserialize_rule(info.meta.partition_rule)
        regions = {}
        ropts = region_opts_from_table_options(info.meta.options)
        for rn in info.meta.region_numbers:
            region = self.storage.open_region(region_name(table_id, rn),
                                              info.meta.schema, opts=ropts)
            if region is None:
                region = self.storage.create_region(
                    region_name(table_id, rn), info.meta.schema, opts=ropts)
            regions[rn] = region
        table = MitoTable(info, regions, rule)
        self._tables[key] = table
        return table

    def adopt_regions(self, info_doc: dict, region_numbers) -> MitoTable:
        """Failover: open the given regions of a table this node may have
        never seen — schema arrives via the meta-stored TableGlobalValue
        (the reference leaves the failover *action* TODO,
        failure_handler/runner.rs:132). Region manifests + SSTs live on
        the shared object store at their last-flushed state; the dead
        node's unflushed WAL tail is lost by design (RFC
        2023-03-08-region-fault-tolerance). Fencing writes from a
        partitioned-but-alive old owner is future lease work."""
        import dataclasses
        info = TableInfo.from_dict(info_doc)
        key = (info.catalog_name, info.schema_name, info.name)
        full = ".".join(key)
        with self._lock:
            table = self._tables.get(key)
            schema = info.meta.schema
            tid = info.ident.table_id
            ropts = region_opts_from_table_options(info.meta.options)
            opened = {}
            for rn in region_numbers:
                # no orphan sweep on adoption: fencing a partitioned-but-
                # alive old owner is future lease work, and sweeping here
                # could delete the old owner's mid-flush output right
                # before its manifest commit references it
                adopt_opts = {**(ropts or {}), "sweep_orphans": False}
                region = self.storage.open_region(
                    region_name(tid, rn), schema, opts=adopt_opts)
                if region is None:
                    region = self.storage.create_region(
                        region_name(tid, rn), schema, opts=adopt_opts)
                opened[rn] = region
            if table is None:
                rule = _deserialize_rule(info.meta.partition_rule)
                meta = dataclasses.replace(
                    info.meta, region_numbers=sorted(region_numbers))
                local_info = dataclasses.replace(info, meta=meta)
                table = MitoTable(local_info, opened, rule)
                self._tables[key] = table
                self._registry["tables"][full] = tid
                self._registry["next_table_id"] = max(
                    self._registry["next_table_id"], tid + 1)
                self._save_registry()
            else:
                table.regions.update(opened)
                table.info.meta.region_numbers = sorted(
                    set(table.info.meta.region_numbers)
                    | set(region_numbers))
            self.store.write(
                self._manifest_key(*key[:2], tid),
                json.dumps(table.info.to_dict()).encode())
            return table

    # ---- elastic region operations (meta/balancer.py drives these via
    # datanode mailbox handlers; each is idempotent so a re-delivered
    # message after a crash resumes instead of corrupting) ----

    def _hosted(self, catalog: str, schema: str, name: str,
                region_number: int):
        """(table, region) or typed errors the balancer handlers relay."""
        table = self.open_table(OpenTableRequest(name, catalog, schema))
        if table is None:
            raise TableNotFoundError(
                f"table {catalog}.{schema}.{name} not on this datanode")
        region = table.regions.get(region_number)
        if region is None:
            from ..errors import StaleRouteError
            raise StaleRouteError(
                f"region {region_number} of table {name} is not hosted "
                f"here")
        return table, region

    def adopt_region_with_tail(self, info_doc: dict, region_number: int,
                               wal_tail: Optional[List[dict]]) -> MitoTable:
        """Migration target side: open the region at its last-flushed
        state from the shared object store, then replay the shipped WAL
        tail at its original sequences (idempotent — replayed records at
        or below the committed sequence are skipped)."""
        table = self.adopt_regions(info_doc, [region_number])
        if wal_tail:
            table.regions[region_number].ingest_wal_tail(wal_tail)
        return table

    def adopt_standby(self, info_doc: dict, region_number: int,
                      wal_tail: Optional[List[dict]]) -> MitoTable:
        """Replica-attach target side: open the region at its
        last-flushed shared state, durably mark it a standby (fenced for
        writes, read-serving, never flushing — the shared manifest
        belongs to the leader), then replay the bootstrap WAL tail at
        its original sequences. Idempotent: a re-delivered attach finds
        the standby already marked and the tail already applied."""
        table = self.adopt_regions(info_doc, [region_number])
        region = table.regions[region_number]
        region.make_standby()
        if wal_tail:
            region.ingest_wal_tail(wal_tail)
        return table

    def refresh_standby(self, catalog: str, schema: str, name: str,
                        region_number: int) -> Region:
        """Close + reopen a standby from the CURRENT shared manifest —
        the catch-up path when shipped records skipped ahead of the
        replica (the shipper was down past a leader flush that obsoleted
        the segments it would have shipped, or a WAL-less bulk ingest
        landed) and the bounded-memory path (the reopen drops memtable
        rows the leader has since flushed). Local WAL records the fresh
        manifest already covers are trimmed."""
        table, region = self._hosted(catalog, schema, name, region_number)
        ropts = region_opts_from_table_options(table.info.meta.options)
        reopened = self.storage.reopen_region(
            region.name, table.info.meta.schema,
            opts={**(ropts or {}), "sweep_orphans": False})
        if reopened is None:
            from ..errors import StaleRouteError
            raise StaleRouteError(
                f"standby region {region.name} vanished from shared "
                f"storage during refresh")
        reopened.wal.obsolete(
            reopened.version_control.current.flushed_sequence)
        table.regions[region_number] = reopened
        return reopened

    def promote_standby(self, catalog: str, schema: str, name: str,
                        region_number: int,
                        old_wal_dir: Optional[str]) -> dict:
        """Failover promotion: fence the dead leader's WAL dir (a
        resurrected old owner must reopen fenced, never dual-own),
        refresh from the current shared manifest, salvage and replay
        every surviving WAL record the old leader acked but never
        flushed or shipped, then unfence into the leader role. Zero
        acked loss: an acked row was fsynced in the old WAL, so it is
        either in a flushed SST (the refresh covers it) or in a
        surviving WAL segment (the salvage covers it — the WAL only ever
        deletes segments at or below the flushed sequence)."""
        from ..storage.region import fence_wal_dir, salvage_wal_entries
        self._hosted(catalog, schema, name, region_number)
        if old_wal_dir:
            try:
                fence_wal_dir(old_wal_dir)
            except OSError:
                logger.exception("promotion: could not fence old leader "
                                 "wal dir %s", old_wal_dir)
        region = self.refresh_standby(catalog, schema, name, region_number)
        salvaged = replayed = 0
        if old_wal_dir:
            try:
                entries = salvage_wal_entries(
                    old_wal_dir,
                    region.version_control.committed_sequence)
                salvaged = len(entries)
                replayed = region.ingest_wal_tail(entries)
            except Exception:  # noqa: BLE001 — degrade, don't block the
                logger.exception(          # takeover of a healthy replica
                    "promotion: WAL salvage from %s failed; region %s "
                    "serves from its last shipped/flushed state",
                    old_wal_dir, region.name)
        region.unfence()
        logger.warning(
            "region %s PROMOTED to leader (salvaged=%d replayed=%d "
            "committed_seq=%d)", region.name, salvaged, replayed,
            region.version_control.committed_sequence)
        return {"salvaged": salvaged, "replayed": replayed,
                "committed_seq":
                    int(region.version_control.committed_sequence)}

    def release_region(self, catalog: str, schema: str, name: str,
                       region_number: int) -> bool:
        """Migration source side, post-route-commit: forget the region
        locally WITHOUT touching its shared data (the new owner serves
        it). When the last hosted region leaves, the table itself is
        forgotten on this node. Returns True when the table is now gone
        from this node entirely (caller deregisters it from the
        catalog)."""
        key = (catalog, schema, name)
        full = ".".join(key)
        with self._lock:
            table = self._open_locked(OpenTableRequest(name, catalog,
                                                       schema))
            if table is None:
                return True
            region = table.regions.pop(region_number, None)
            table.info.meta.region_numbers = sorted(table.regions)
            tid = table.info.ident.table_id
            if table.regions:
                self.store.write(
                    self._manifest_key(catalog, schema, tid),
                    json.dumps(table.info.to_dict()).encode())
                gone = False
            else:
                self._tables.pop(key, None)
                self._registry["tables"].pop(full, None)
                self._save_registry()
                # node-scoped manifest only — the region data and its own
                # region manifest stay put for the new owner
                self.store.delete(self._manifest_key(catalog, schema, tid))
                gone = True
        if region is not None:
            self.storage.release_region(region.name)
        return gone

    def probe_split_value(self, catalog: str, schema: str, name: str,
                          region_number: int):
        """The region's observed median partition-column value — its own
        balancer round-trip so the value is PINNED in the op doc before
        any row copies: a re-delivered prepare after a lost ack must
        copy across the SAME boundary (a re-probe under ingest could
        move the median and leave the first run's copies in the wrong
        child — duplicate rows after commit)."""
        table, region = self._hosted(catalog, schema, name, region_number)
        rule = table.partition_rule
        if rule is None:
            raise InvalidArgumentsError(
                f"table {name} has no partition rule; single-region "
                f"tables cannot split")
        cols, _ = region_rows_columns(region)
        pcol = rule.partition_columns()[0]
        value = _median_split_value(cols.get(pcol, []))
        if value is None:
            raise InvalidArgumentsError(
                f"region {region_number} of {name} has no splittable "
                f"value spread on {pcol!r}")
        return value

    def prepare_split(self, catalog: str, schema: str, name: str,
                      region_number: int, children: List[int],
                      at_value):
        """Split phase 1 (unfenced): create the child regions as PENDING
        (hosted on disk, invisible to reads until apply) and bulk-copy
        the parent's snapshot rows into them per the refined rule.
        `at_value` is mandatory — probed values go through
        probe_split_value first so re-deliveries are idempotent.
        Returns (snapshot_seq, copied_rows)."""
        from ..partition.rule import refine_range_rule
        table, region = self._hosted(catalog, schema, name, region_number)
        rule = table.partition_rule
        if rule is None:
            raise InvalidArgumentsError(
                f"table {name} has no partition rule; single-region "
                f"tables cannot split")
        if at_value is None:
            raise InvalidArgumentsError(
                "prepare_split needs a pinned split value")
        cols, visible = region_rows_columns(region)
        refined = refine_range_rule(rule, region_number, at_value,
                                    children)
        kids = self._open_pending_children(table, children)
        copied = self._copy_split_rows(refined, children, kids, cols)
        return int(visible), copied

    def split_catchup(self, catalog: str, schema: str, name: str,
                      region_number: int, children: List[int], at_value,
                      seq_gt: int) -> int:
        """Split phase 2: fence the parent (no more writes), then copy
        the delta — rows committed after the phase-1 snapshot — into the
        children. After this the children hold everything."""
        from ..partition.rule import refine_range_rule
        table, region = self._hosted(catalog, schema, name, region_number)
        region.fence()
        refined = refine_range_rule(table.partition_rule, region_number,
                                    at_value, children)
        kids = self._open_pending_children(table, children)
        cols, _ = region_rows_columns(region, seq_gt=seq_gt)
        return self._copy_split_rows(refined, children, kids, cols)

    def apply_split(self, catalog: str, schema: str, name: str,
                    region_number: int, children: List[int],
                    rule_doc: dict) -> None:
        """Split commit, datanode side: atomically swap the parent for
        its children in the served region set, adopt the refined rule,
        persist the manifest, then drop the parent's storage (its rows
        were fully copied). Idempotent: a re-delivered apply after a
        crash re-persists the same state."""
        key = (catalog, schema, name)
        with self._lock:
            table = self._open_locked(OpenTableRequest(name, catalog,
                                                       schema))
            if table is None:
                raise TableNotFoundError(
                    f"table {catalog}.{schema}.{name} not on this "
                    f"datanode")
            tid = table.info.ident.table_id
            pend = self._pending_splits.get(key, {})
            for rn in children:
                child = pend.pop(rn, None)
                if child is None and rn not in table.regions:
                    ropts = region_opts_from_table_options(
                        table.info.meta.options)
                    child = self.storage.open_region(
                        region_name(tid, rn), table.info.meta.schema,
                        opts=ropts)
                if child is not None:
                    table.regions[rn] = child
            parent = table.regions.pop(region_number, None)
            table.partition_rule = _deserialize_rule(rule_doc)
            table.info.meta.partition_rule = dict(rule_doc)
            table.info.meta.region_numbers = sorted(table.regions)
            self.store.write(
                self._manifest_key(catalog, schema, tid),
                json.dumps(table.info.to_dict()).encode())
        pname = region_name(tid, region_number)
        if parent is not None:
            self.storage.drop_region(pname)
        else:
            # re-delivered apply after a crash between the manifest write
            # and the drop: sweep any leftover parent files directly
            self._purge_region_dir(pname)

    def abort_split(self, catalog: str, schema: str, name: str,
                    region_number: int, children: List[int]) -> None:
        """Roll a failed split back: unfence the parent and drop the
        pending children (their copied rows are disposable)."""
        key = (catalog, schema, name)
        with self._lock:
            table = self._open_locked(OpenTableRequest(name, catalog,
                                                       schema))
            pend = self._pending_splits.get(key, {})
            kids = [pend.pop(rn, None) for rn in children]
            tid = table.info.ident.table_id if table is not None else None
        if table is None:
            return
        region = table.regions.get(region_number)
        if region is not None and region.fenced:
            region.unfence()
        for rn, child in zip(children, kids):
            if child is not None:
                self.storage.drop_region(child.name)
            elif tid is not None:
                self._purge_region_dir(region_name(tid, rn))

    def _open_pending_children(self, table: MitoTable,
                               children: List[int]) -> Dict[int, Region]:
        """Open-or-create the child regions OUTSIDE table.regions: reads
        must not see them until the route/rule commit swaps them in."""
        key = (table.info.catalog_name, table.info.schema_name,
               table.info.name)
        with self._lock:
            pend = self._pending_splits.setdefault(key, {})
            tid = table.info.ident.table_id
            ropts = region_opts_from_table_options(table.info.meta.options)
            for rn in children:
                if rn in pend:
                    continue
                rname = region_name(tid, rn)
                region = self.storage.open_region(
                    rname, table.info.meta.schema, opts=ropts)
                if region is None:
                    region = self.storage.create_region(
                        rname, table.info.meta.schema, opts=ropts)
                pend[rn] = region
            return dict(pend)

    @staticmethod
    def _copy_split_rows(refined_rule, children: List[int],
                         kids: Dict[int, Region],
                         cols: Dict[str, list]) -> int:
        if not cols:
            return 0
        n = len(next(iter(cols.values())))
        if n == 0:
            return 0
        copied = 0
        child_set = set(children)
        for rn, idx in split_rows(refined_rule, cols, n).items():
            if rn not in child_set:
                continue               # rows of untouched sibling regions
            part = cols if idx is None else \
                {k: v[idx] if isinstance(v, np.ndarray)
                 else [v[i] for i in idx] for k, v in cols.items()}
            copied += kids[rn].bulk_ingest(part)
        return copied

    def _purge_region_dir(self, rname: str) -> None:
        """Best-effort sweep of a region dir no manifest references
        (a crash between the split's manifest commit and the parent drop
        leaves files nothing will ever revisit)."""
        import logging
        import os
        import shutil
        for key in self.store.list(rname):
            try:
                self.store.delete(key)
            except Exception:  # noqa: BLE001 — purge is best-effort;
                logging.getLogger(__name__).warning(
                    "split cleanup could not delete %s (will re-sweep "
                    "on the next apply delivery)", key)
        shutil.rmtree(os.path.join(self.storage.wal_home, rname),
                      ignore_errors=True)

    def alter_table(self, request: AlterTableRequest) -> MitoTable:
        key = (request.catalog_name, request.schema_name, request.table_name)
        with self._lock:
            table = self._open_locked(
                OpenTableRequest(request.table_name, request.catalog_name,
                                 request.schema_name))
            if table is None:
                raise TableNotFoundError(f"table {'.'.join(key)} not found")
            info = table.info
            schema = info.meta.schema
            if request.kind == AlterKind.RENAME_TABLE:
                new_key = key[:2] + (request.new_table_name,)
                full, new_full = ".".join(key), ".".join(new_key)
                if new_full in self._registry["tables"]:
                    raise TableAlreadyExistsError(
                        f"table {new_full} already exists")
                info.name = request.new_table_name
                self._registry["tables"][new_full] = \
                    self._registry["tables"].pop(full)
                del self._tables[key]
                self._tables[new_key] = table
            elif request.kind == AlterKind.ADD_COLUMNS:
                cols = list(schema.column_schemas)
                names = {c.name for c in cols}
                for add in request.add_columns:
                    cs = add.column_schema
                    if cs.name in names:
                        raise ColumnExistsError(
                            f"column {cs.name!r} already exists")
                    if cs.semantic_type != SemanticType.FIELD:
                        # the region series dictionary is immutable (same as
                        # the reference v0.2): new tags/time-index columns
                        # would corrupt existing series encodings
                        raise InvalidArgumentsError(
                            f"only FIELD columns can be added, not "
                            f"{cs.semantic_type.name}")
                    if not cs.nullable and cs.default is None:
                        raise InvalidArgumentsError(
                            f"new column {cs.name!r} must be nullable or "
                            f"have a default")
                    if add.location is None or add.location == "":
                        cols.append(cs)
                    elif add.location == "FIRST":
                        cols.insert(0, cs)
                    else:  # AFTER <col>
                        after = add.location.split(" ", 1)[1]
                        idx = next((i for i, c in enumerate(cols)
                                    if c.name == after), None)
                        if idx is None:
                            raise ColumnNotFoundError(
                                f"column {after!r} not found")
                        cols.insert(idx + 1, cs)
                    names.add(cs.name)
                new_schema = Schema(cols, version=schema.version + 1)
                for region in table.regions.values():
                    region.alter(new_schema)
                info.meta.schema = new_schema
                info.meta.next_column_id = len(cols)
                info.meta.primary_key_indices = [
                    i for i, c in enumerate(cols)
                    if c.semantic_type == SemanticType.TAG]
                info.ident.version += 1
            elif request.kind == AlterKind.DROP_COLUMNS:
                cols = list(schema.column_schemas)
                for name in request.drop_columns:
                    idx = next((i for i, c in enumerate(cols)
                                if c.name == name), None)
                    if idx is None:
                        raise ColumnNotFoundError(f"column {name!r} not found")
                    c = cols[idx]
                    if c.is_time_index or c.is_tag:
                        raise InvalidArgumentsError(
                            f"cannot drop key column {name!r}")
                    cols.pop(idx)
                new_schema = Schema(cols, version=schema.version + 1)
                for region in table.regions.values():
                    region.alter(new_schema)
                info.meta.schema = new_schema
                info.meta.primary_key_indices = [
                    i for i, c in enumerate(cols)
                    if c.semantic_type == SemanticType.TAG]
                info.ident.version += 1
            self.store.write(
                self._manifest_key(info.catalog_name, info.schema_name,
                                   info.ident.table_id),
                json.dumps(info.to_dict()).encode())
            self._save_registry()
            return table

    def drop_table(self, request: DropTableRequest) -> bool:
        key = (request.catalog_name, request.schema_name, request.table_name)
        with self._lock:
            table = self._open_locked(
                OpenTableRequest(request.table_name, request.catalog_name,
                                 request.schema_name))
            if table is None:
                return False
            for rn in table.info.meta.region_numbers:
                self.storage.drop_region(
                    region_name(table.info.ident.table_id, rn))
            self.store.delete(self._manifest_key(
                *key[:2], table.info.ident.table_id))
            self._registry["tables"].pop(".".join(key), None)
            self._tables.pop(key, None)
            self._save_registry()
            return True

    def truncate_table(self, catalog: str, schema: str, name: str) -> bool:
        """Drop + recreate regions, keeping table identity and schema."""
        key = (catalog, schema, name)
        with self._lock:
            table = self._open_locked(OpenTableRequest(name, catalog, schema))
            if table is None:
                return False
            info = table.info
            ropts = region_opts_from_table_options(info.meta.options)
            for rn in list(table.regions):
                rname = region_name(info.ident.table_id, rn)
                self.storage.drop_region(rname)
                table.regions[rn] = self.storage.create_region(
                    rname, info.meta.schema, opts=ropts)
            return True

    def table_exists(self, catalog: str, schema: str, name: str) -> bool:
        with self._lock:
            return ".".join((catalog, schema, name)) in self._registry["tables"]

    def get_table(self, catalog: str, schema: str, name: str
                  ) -> Optional[MitoTable]:
        return self.open_table(OpenTableRequest(name, catalog, schema))

    def table_ids(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._registry["tables"])

    def close(self) -> None:
        with self._lock:
            for table in self._tables.values():
                table.close()
            self._tables.clear()
