"""file-table-engine: immutable external-file tables.

Reference behavior: src/file-table-engine — `ImmutableFileTableEngine`
serves read-only tables whose data lives in CSV/JSON/Parquet files on the
object store (engine/immutable.rs:449); the format/location come from
table options (table/format.rs), a small table manifest persists the
metadata (manifest.rs), and inserts are rejected.

    CREATE EXTERNAL TABLE logs (ts TIMESTAMP TIME INDEX, msg STRING)
      WITH (location='data/logs.parquet', format='parquet');
"""

from .engine import ImmutableFileTable, ImmutableFileTableEngine

__all__ = ["ImmutableFileTable", "ImmutableFileTableEngine"]
