"""Immutable file table engine implementation.

Reference mapping: engine create/open/drop with per-table JSON manifest
(src/file-table-engine/src/engine/immutable.rs:100-310,
manifest.rs), format readers (src/file-table-engine/src/table/format.rs;
CSV/JSON/Parquet via common-datasource). Schema comes from the CREATE
statement or, when no columns are declared, is inferred from the file.
"""

from __future__ import annotations

import io
import json
import threading
from typing import Dict, List, Optional, Sequence

import pyarrow as pa
import pyarrow.csv as pa_csv
import pyarrow.json as pa_json
import pyarrow.parquet as pq

from ..datatypes.record_batch import RecordBatch
from ..datatypes.schema import Schema
from ..errors import (
    InvalidArgumentsError, TableAlreadyExistsError, UnsupportedError)
from ..table.metadata import TableIdent, TableInfo, TableMeta
from ..table.table import Table, TableEngine

ENGINE_NAME = "file"
MANIFEST_DIR = "file_tables"


class ImmutableFileTable(Table):
    def __init__(self, info: TableInfo, store, location: str, fmt: str):
        super().__init__(info)
        self.store = store
        self.location = location
        self.format = fmt

    def _read_arrow(self) -> pa.Table:
        from ..common.datasource import file_codec
        data = self.store.read(self.location)
        codec = file_codec(self.location,
                           self.info.meta.options.get("compression")
                           if self.info.meta.options else None)
        if codec is not None and self.format != "parquet":
            data = pa.CompressedInputStream(
                pa.BufferReader(data), codec).read()
        if self.format == "parquet":
            return pq.read_table(io.BytesIO(data))
        if self.format == "csv":
            return pa_csv.read_csv(io.BytesIO(data))
        if self.format == "json":
            return pa_json.read_json(io.BytesIO(data))
        raise UnsupportedError(f"external table format {self.format!r}")

    def scan_batches(self, projection: Optional[Sequence[str]] = None,
                     time_range=None, limit: Optional[int] = None
                     ) -> List[RecordBatch]:
        at = self._read_arrow()
        schema = self.schema
        # align file columns to the declared schema (by name); missing
        # declared columns surface as an error, extra file columns drop
        names = list(schema.names()) if len(schema) else at.schema.names
        cols = []
        for n in names:
            if n not in at.schema.names:
                raise InvalidArgumentsError(
                    f"external file lacks column {n!r}")
            cols.append(at.column(n))
        at = pa.table(dict(zip(names, cols)))
        if len(schema):
            at = at.cast(schema.to_arrow())
        if projection is not None:
            at = at.select(list(projection))
        if limit is not None:
            at = at.slice(0, limit)
        batch_schema = Schema.from_arrow(at.schema) if not len(schema) \
            else (schema if projection is None
                  else schema.project(list(projection)))
        out = []
        for rb in at.combine_chunks().to_batches():
            out.append(RecordBatch.from_arrow(rb, batch_schema))
        if not out:
            out.append(RecordBatch.empty(batch_schema))
        return out


class ImmutableFileTableEngine(TableEngine):
    name = ENGINE_NAME

    def __init__(self, store, state_prefix: str = ""):
        self.store = store
        self._prefix = state_prefix
        self._tables: Dict[tuple, ImmutableFileTable] = {}
        self._lock = threading.Lock()
        self._next_id = 2_000_000          # distinct id space from mito

    def _manifest_key(self, catalog: str, schema: str, name: str) -> str:
        return f"{self._prefix}{MANIFEST_DIR}/{catalog}/{schema}/{name}.json"

    # ---- TableEngine ----
    def create_table(self, request) -> Table:
        opts = {k.lower(): v for k, v in request.table_options.items()}
        location = opts.get("location")
        if not location:
            raise InvalidArgumentsError(
                "external table needs WITH (location='...')")
        fmt = str(opts.get("format", _infer_format(location))).lower()
        key = (request.catalog_name, request.schema_name,
               request.table_name)
        with self._lock:
            if key in self._tables:
                if request.create_if_not_exists:
                    return self._tables[key]
                raise TableAlreadyExistsError(
                    f"external table {request.table_name!r} exists")
            table_id = request.table_id or self._next_id
            self._next_id = max(self._next_id + 1, table_id + 1)

        schema = request.schema
        if not len(schema):
            # schema inference from the file itself
            probe = ImmutableFileTable(
                TableInfo(TableIdent(table_id), request.table_name,
                          TableMeta(schema=schema, engine=self.name),
                          request.catalog_name, request.schema_name),
                self.store, location, fmt)
            arrow = probe._read_arrow()
            schema = Schema.from_arrow(arrow.schema)

        info = TableInfo(
            ident=TableIdent(table_id), name=request.table_name,
            meta=TableMeta(schema=schema,
                           primary_key_indices=list(
                               request.primary_key_indices),
                           engine=self.name,
                           region_numbers=[],
                           next_column_id=len(schema),
                           options={"location": location, "format": fmt,
                                    **({"compression": opts["compression"]}
                                       if "compression" in opts else {})}),
            catalog_name=request.catalog_name,
            schema_name=request.schema_name)
        self.store.write(self._manifest_key(*key),
                         json.dumps(info.to_dict()).encode())
        table = ImmutableFileTable(info, self.store, location, fmt)
        with self._lock:
            self._tables[key] = table
        return table

    def open_table(self, request) -> Optional[Table]:
        key = (request.catalog_name, request.schema_name,
               request.table_name)
        with self._lock:
            if key in self._tables:
                return self._tables[key]
        mkey = self._manifest_key(*key)
        if not self.store.exists(mkey):
            return None
        info = TableInfo.from_dict(json.loads(self.store.read(mkey)))
        table = ImmutableFileTable(
            info, self.store, info.meta.options["location"],
            info.meta.options["format"])
        with self._lock:
            self._tables[key] = table
        return table

    def alter_table(self, request) -> Table:
        raise UnsupportedError("external file tables are immutable")

    def drop_table(self, request) -> bool:
        key = (request.catalog_name, request.schema_name,
               request.table_name)
        with self._lock:
            existed = self._tables.pop(key, None) is not None
        mkey = self._manifest_key(*key)
        on_disk = self.store.exists(mkey)
        self.store.delete(mkey)            # data file is NOT ours to drop
        return existed or on_disk

    def truncate_table(self, catalog, schema, name) -> bool:
        raise UnsupportedError("external file tables are immutable")

    def table_exists(self, catalog, schema, name) -> bool:
        with self._lock:
            if (catalog, schema, name) in self._tables:
                return True
        return self.store.exists(self._manifest_key(catalog, schema, name))

    def get_table(self, catalog, schema, name) -> Optional[Table]:
        with self._lock:
            return self._tables.get((catalog, schema, name))


def _infer_format(location: str) -> str:
    base = location
    for cext in (".gz", ".gzip", ".zst", ".zstd"):
        if base.lower().endswith(cext):
            base = base[:-len(cext)]
            break
    for ext, fmt in ((".parquet", "parquet"), (".csv", "csv"),
                     (".json", "json"), (".ndjson", "json")):
        if base.endswith(ext):
            return fmt
    raise InvalidArgumentsError(
        f"cannot infer format from {location!r}; pass WITH (format=...)")
