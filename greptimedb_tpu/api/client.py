"""greptime-proto SDK twin: the reference `Database` client's wire flow.

Reference behavior: src/client/src/database.rs — `Database::sql` /
`Database::insert` wrap a GreptimeRequest protobuf in an Arrow Flight
ticket and call do_get; results arrive as a FlightData stream (schema +
record batches, or FlightMetadata{affected_rows} in app_metadata).
This client emits byte-identical tickets, so it doubles as the interop
test harness for any server speaking the greptime-proto plane.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import pyarrow as pa
import pyarrow.flight as flight

from .v1 import (
    Column, ColumnDataType, ColumnDef, CreateTableExpr, DdlRequest,
    GreptimeRequest, InsertRequest, QueryRequest, SemanticType,
    decode_flight_metadata_affected_rows, encode_greptime_request)


def _infer_datatype(values: Sequence) -> int:
    for v in values:
        if v is None:
            continue
        if isinstance(v, bool):
            return ColumnDataType.BOOLEAN
        if isinstance(v, int):
            return ColumnDataType.INT64
        if isinstance(v, float):
            return ColumnDataType.FLOAT64
        if isinstance(v, bytes):
            return ColumnDataType.BINARY
        return ColumnDataType.STRING
    return ColumnDataType.FLOAT64


class GreptimeDatabase:
    """Protobuf-plane client (reference `Database`)."""

    def __init__(self, address: str, *, catalog: str = "greptime",
                 schema: str = "public"):
        self.address = address
        self.conn = flight.FlightClient(address)
        self.catalog = catalog
        self.schema = schema

    def close(self) -> None:
        self.conn.close()

    def _do_get(self, req: GreptimeRequest):
        req.catalog = self.catalog
        req.schema = self.schema
        ticket = flight.Ticket(encode_greptime_request(req))
        return self.conn.do_get(ticket)

    def sql(self, sql: str):
        """Run SQL; returns (pyarrow.Table, affected_rows or None)."""
        reader = self._do_get(GreptimeRequest(query=QueryRequest(sql=sql)))
        batches: List[pa.RecordBatch] = []
        affected: Optional[int] = None
        schema = reader.schema
        while True:
            try:
                chunk = reader.read_chunk()
            except StopIteration:
                break
            if chunk.app_metadata is not None:
                got = decode_flight_metadata_affected_rows(
                    chunk.app_metadata.to_pybytes())
                if got is not None:
                    affected = got
            if chunk.data is not None:
                batches.append(chunk.data)
        table = pa.Table.from_batches(batches, schema=schema) \
            if batches else None
        if (schema.metadata or {}).get(b"gdb.kind") == b"affected_rows":
            if affected is None and table is not None:
                affected = int(table.column(0)[0].as_py())
            table = None
        return table, affected

    def create(self, table_name: str,
               columns: Sequence[Tuple[str, int]], *,
               time_index: str, primary_keys: Sequence[str] = (),
               if_not_exists: bool = True) -> None:
        """DDL over the proto plane (reference Database::create).
        columns: (name, ColumnDataType) pairs."""
        expr = CreateTableExpr(
            table_name=table_name,
            column_defs=[ColumnDef(n, dt, is_nullable=(n != time_index))
                         for n, dt in columns],
            time_index=time_index, primary_keys=list(primary_keys),
            create_if_not_exists=if_not_exists)
        reader = self._do_get(GreptimeRequest(
            ddl=DdlRequest(create_table=expr)))
        reader.read_all()

    def drop_table(self, table_name: str) -> None:
        reader = self._do_get(GreptimeRequest(ddl=DdlRequest(
            drop_table=(self.catalog, self.schema, table_name))))
        reader.read_all()

    def insert(self, table_name: str, columns: Dict[str, Sequence], *,
               tag_columns: Sequence[str] = (),
               timestamp_column: str = "ts",
               datatypes: Optional[Dict[str, int]] = None) -> int:
        """Columnar insert (reference Database::insert). Returns the
        affected-row count reported by the server."""
        row_count = len(next(iter(columns.values()))) if columns else 0
        cols = []
        for name, values in columns.items():
            dt = (datatypes or {}).get(name)
            if dt is None:
                if name == timestamp_column:
                    dt = ColumnDataType.TIMESTAMP_MILLISECOND
                else:
                    dt = _infer_datatype(values)
            sem = SemanticType.FIELD
            if name in tag_columns:
                sem = SemanticType.TAG
            elif name == timestamp_column:
                sem = SemanticType.TIMESTAMP
            cols.append(Column.from_rows(name, values, dt, sem))
        req = GreptimeRequest(insert=InsertRequest(
            table_name=table_name, columns=cols, row_count=row_count))
        reader = self._do_get(req)
        affected = 0
        while True:
            try:
                chunk = reader.read_chunk()
            except StopIteration:
                break
            if chunk.app_metadata is not None:
                got = decode_flight_metadata_affected_rows(
                    chunk.app_metadata.to_pybytes())
                if got is not None:
                    affected = got
        return affected
