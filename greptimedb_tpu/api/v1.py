"""greptime-proto v1 wire codec (hand-rolled protowire, no protoc).

Field numbers mirror greptime-proto v1 at the revision GreptimeDB
v0.2.0 pins (e8abf824, src/api/Cargo.toml:13):

  GreptimeRequest { RequestHeader header = 1;
                    oneof request { InsertRequest insert = 2;
                                    QueryRequest query = 3;
                                    DdlRequest ddl = 4;
                                    DeleteRequest delete = 5; } }
  RequestHeader   { string catalog = 1; string schema = 2;
                    AuthHeader authorization = 3; string dbname = 4; }
  QueryRequest    { oneof query { string sql = 1; bytes logical_plan = 2;
                                  PromRangeQuery prom_range_query = 3; } }
  InsertRequest   { string table_name = 1; repeated Column columns = 3;
                    uint32 row_count = 4; uint32 region_number = 5; }
  Column          { string column_name = 1; SemanticType semantic_type = 2;
                    Values values = 3; bytes null_mask = 4;
                    ColumnDataType datatype = 5; }
  GreptimeResponse{ ResponseHeader header = 1;
                    oneof response { AffectedRows affected_rows = 2; } }
  FlightMetadata  { AffectedRows affected_rows = 1; }
  AffectedRows    { uint32 value = 1; }

`Column.values` packs only the non-null entries per type-specific
repeated field (Values fields 1-19); `null_mask` is an LSB-first bitmap
over all row_count rows. The deserialized forms here are plain
dataclasses sized to what the servers need: inserts and SQL queries (the
paths reference SDKs use for data); DDL/delete tickets decode to typed
stubs so the server can reject them with a clear error.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.protowire import (
    field_bytes, field_varint, iter_fields, write_varint)


class SemanticType:
    TAG = 0
    FIELD = 1
    TIMESTAMP = 2


class ColumnDataType:
    BOOLEAN = 0
    INT8 = 1
    INT16 = 2
    INT32 = 3
    INT64 = 4
    UINT8 = 5
    UINT16 = 6
    UINT32 = 7
    UINT64 = 8
    FLOAT32 = 9
    FLOAT64 = 10
    BINARY = 11
    STRING = 12
    DATE = 13
    DATETIME = 14
    TIMESTAMP_SECOND = 15
    TIMESTAMP_MILLISECOND = 16
    TIMESTAMP_MICROSECOND = 17
    TIMESTAMP_NANOSECOND = 18


#: Values message: field number per datatype, wire kind.
#: kinds: v = packed varint, f32/f64 = packed fixed, len = length-delim
_VALUES_FIELD: Dict[int, Tuple[int, str]] = {
    ColumnDataType.INT8: (1, "v"),
    ColumnDataType.INT16: (2, "v"),
    ColumnDataType.INT32: (3, "v"),
    ColumnDataType.INT64: (4, "v"),
    ColumnDataType.UINT8: (5, "v"),
    ColumnDataType.UINT16: (6, "v"),
    ColumnDataType.UINT32: (7, "v"),
    ColumnDataType.UINT64: (8, "v"),
    ColumnDataType.FLOAT32: (9, "f32"),
    ColumnDataType.FLOAT64: (10, "f64"),
    ColumnDataType.BOOLEAN: (11, "v"),
    ColumnDataType.BINARY: (12, "len"),
    ColumnDataType.STRING: (13, "len"),
    ColumnDataType.DATE: (14, "v"),
    ColumnDataType.DATETIME: (15, "v"),
    ColumnDataType.TIMESTAMP_SECOND: (16, "v"),
    ColumnDataType.TIMESTAMP_MILLISECOND: (17, "v"),
    ColumnDataType.TIMESTAMP_MICROSECOND: (18, "v"),
    ColumnDataType.TIMESTAMP_NANOSECOND: (19, "v"),
}
_FIELD_TO_DTYPE = {fnum: dt for dt, (fnum, _) in _VALUES_FIELD.items()}

_SIGNED = {ColumnDataType.INT8, ColumnDataType.INT16, ColumnDataType.INT32,
           ColumnDataType.INT64, ColumnDataType.DATE,
           ColumnDataType.DATETIME, ColumnDataType.TIMESTAMP_SECOND,
           ColumnDataType.TIMESTAMP_MILLISECOND,
           ColumnDataType.TIMESTAMP_MICROSECOND,
           ColumnDataType.TIMESTAMP_NANOSECOND}


@dataclass
class Column:
    column_name: str
    semantic_type: int = SemanticType.FIELD
    datatype: int = ColumnDataType.FLOAT64
    values: List = field(default_factory=list)   # non-null entries only
    null_mask: bytes = b""                       # LSB-first, 1 = null

    def rows(self, row_count: int) -> List:
        """Expand to row_count entries with None at masked positions."""
        out: List = []
        it = iter(self.values)
        for i in range(row_count):
            if self.null_mask and (i // 8) < len(self.null_mask) and \
                    (self.null_mask[i // 8] >> (i % 8)) & 1:
                out.append(None)
            else:
                out.append(next(it, None))
        return out

    @staticmethod
    def from_rows(name: str, rows: Sequence, datatype: int,
                  semantic_type: int = SemanticType.FIELD) -> "Column":
        mask = bytearray((len(rows) + 7) // 8)
        vals = []
        any_null = False
        for i, v in enumerate(rows):
            if v is None:
                mask[i // 8] |= 1 << (i % 8)
                any_null = True
            else:
                vals.append(v)
        return Column(name, semantic_type, datatype, vals,
                      bytes(mask) if any_null else b"")


@dataclass
class InsertRequest:
    table_name: str
    columns: List[Column] = field(default_factory=list)
    row_count: int = 0
    region_number: int = 0


@dataclass
class QueryRequest:
    sql: Optional[str] = None


@dataclass
class ColumnDef:
    """greptime-proto ColumnDef { string name = 1;
    ColumnDataType datatype = 2; bool is_nullable = 3;
    bytes default_constraint = 4; }"""
    name: str
    datatype: int
    is_nullable: bool = True


@dataclass
class CreateTableExpr:
    """CreateTableExpr { catalog_name = 1; schema_name = 2;
    table_name = 3; desc = 4; repeated ColumnDef column_defs = 5;
    string time_index = 6; repeated string primary_keys = 7;
    bool create_if_not_exists = 8; map table_options = 9;
    TableId table_id = 10; repeated uint32 region_ids = 11;
    string engine = 12; }"""
    table_name: str
    column_defs: List[ColumnDef] = field(default_factory=list)
    time_index: str = ""
    primary_keys: List[str] = field(default_factory=list)
    create_if_not_exists: bool = False
    catalog_name: str = ""
    schema_name: str = ""


@dataclass
class DdlRequest:
    """DdlRequest oneof: create_database = 1; create_table = 2;
    alter = 3; drop_table = 4; flush_table = 5."""
    create_table: Optional[CreateTableExpr] = None
    drop_table: Optional[Tuple[str, str, str]] = None   # catalog,schema,table
    create_database: Optional[str] = None
    other: Optional[str] = None


@dataclass
class GreptimeRequest:
    catalog: str = ""
    schema: str = ""
    dbname: str = ""
    insert: Optional[InsertRequest] = None
    query: Optional[QueryRequest] = None
    ddl: Optional[DdlRequest] = None
    other: Optional[str] = None      # "delete" (decoded as a stub)


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------

def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _encode_values(datatype: int, values: Sequence) -> bytes:
    fnum, kind = _VALUES_FIELD[datatype]
    if kind == "len":
        out = b"".join(
            field_bytes(fnum, v.encode() if isinstance(v, str) else bytes(v))
            for v in values)
        return out
    if kind in ("f32", "f64"):
        fmt = "<f" if kind == "f32" else "<d"
        packed = b"".join(struct.pack(fmt, float(v)) for v in values)
        return field_bytes(fnum, packed) if values else b""
    # packed varints (proto3 default for repeated scalars)
    buf = bytearray()
    for v in values:
        if datatype == ColumnDataType.BOOLEAN:
            buf += write_varint(1 if v else 0)
        elif datatype in _SIGNED:
            # proto int64/int32: negative values ride as 10-byte varints
            buf += write_varint(int(v) & 0xFFFFFFFFFFFFFFFF)
        else:
            buf += write_varint(int(v))
    return field_bytes(fnum, bytes(buf)) if values else b""


def encode_column(c: Column) -> bytes:
    out = field_bytes(1, c.column_name.encode())
    if c.semantic_type:
        out += field_varint(2, c.semantic_type)
    vals = _encode_values(c.datatype, c.values)
    if vals:
        out += field_bytes(3, vals)
    if c.null_mask:
        out += field_bytes(4, c.null_mask)
    if c.datatype:
        out += field_varint(5, c.datatype)
    return out


def encode_insert(req: InsertRequest) -> bytes:
    out = field_bytes(1, req.table_name.encode())
    for c in req.columns:
        out += field_bytes(3, encode_column(c))
    out += field_varint(4, req.row_count)
    if req.region_number:
        out += field_varint(5, req.region_number)
    return out


def encode_column_def(cd: ColumnDef) -> bytes:
    out = field_bytes(1, cd.name.encode())
    if cd.datatype:
        out += field_varint(2, cd.datatype)
    if cd.is_nullable:
        out += field_varint(3, 1)
    return out


def encode_create_table(ct: CreateTableExpr) -> bytes:
    out = b""
    if ct.catalog_name:
        out += field_bytes(1, ct.catalog_name.encode())
    if ct.schema_name:
        out += field_bytes(2, ct.schema_name.encode())
    out += field_bytes(3, ct.table_name.encode())
    for cd in ct.column_defs:
        out += field_bytes(5, encode_column_def(cd))
    if ct.time_index:
        out += field_bytes(6, ct.time_index.encode())
    for pk in ct.primary_keys:
        out += field_bytes(7, pk.encode())
    if ct.create_if_not_exists:
        out += field_varint(8, 1)
    return out


def encode_ddl(ddl: DdlRequest) -> bytes:
    if ddl.create_table is not None:
        return field_bytes(2, encode_create_table(ddl.create_table))
    if ddl.drop_table is not None:
        cat, sch, tbl = ddl.drop_table
        body = b""
        if cat:
            body += field_bytes(1, cat.encode())
        if sch:
            body += field_bytes(2, sch.encode())
        body += field_bytes(3, tbl.encode())
        return field_bytes(4, body)
    if ddl.create_database is not None:
        return field_bytes(1, field_bytes(1, ddl.create_database.encode()))
    raise ValueError("empty DdlRequest")


def encode_greptime_request(req: GreptimeRequest) -> bytes:
    header = b""
    if req.catalog:
        header += field_bytes(1, req.catalog.encode())
    if req.schema:
        header += field_bytes(2, req.schema.encode())
    if req.dbname:
        header += field_bytes(4, req.dbname.encode())
    out = field_bytes(1, header) if header else b""
    if req.insert is not None:
        out += field_bytes(2, encode_insert(req.insert))
    elif req.query is not None and req.query.sql is not None:
        out += field_bytes(3, field_bytes(1, req.query.sql.encode()))
    elif req.ddl is not None:
        out += field_bytes(4, encode_ddl(req.ddl))
    return out


def encode_affected_rows_metadata(n: int) -> bytes:
    """FlightMetadata { AffectedRows affected_rows = 1; } — rides in
    FlightData.app_metadata (reference flight.rs:84-90)."""
    return field_bytes(1, field_varint(1, n))


def encode_greptime_response(n: int) -> bytes:
    """GreptimeResponse with affected_rows (the handle() RPC reply)."""
    header = field_bytes(1, field_varint(1, 0))   # status_code OK
    return field_bytes(1, header) + field_bytes(2, field_varint(1, n))


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------

def _u64_to_i64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _decode_values(data: bytes) -> Dict[int, List]:
    """Values message → {datatype: [non-null entries]}."""
    out: Dict[int, List] = {}
    for fnum, wire, payload in iter_fields(memoryview(data)):
        dt = _FIELD_TO_DTYPE.get(fnum)
        if dt is None:
            continue
        _, kind = _VALUES_FIELD[dt]
        dest = out.setdefault(dt, [])
        if kind == "len":
            raw = bytes(payload)
            dest.append(raw.decode() if dt == ColumnDataType.STRING
                        else raw)
        elif kind in ("f32", "f64"):
            fmt, width = ("<f", 4) if kind == "f32" else ("<d", 8)
            if wire == 5 or wire == 1:     # non-packed single value
                dest.append(struct.unpack(fmt, bytes(payload))[0])
            else:                          # packed
                raw = bytes(payload)
                dest.extend(struct.unpack(fmt, raw[i:i + width])[0]
                            for i in range(0, len(raw), width))
        else:
            if wire == 0:                  # non-packed varint
                vals = [payload]
            else:                          # packed varints
                vals = _iter_varints(bytes(payload))
            for v in vals:
                if dt == ColumnDataType.BOOLEAN:
                    dest.append(bool(v))
                elif dt in _SIGNED:
                    dest.append(_u64_to_i64(v))
                else:
                    dest.append(v)
    return out


def _iter_varints(data: bytes) -> List[int]:
    from ..utils.protowire import read_varint
    out, pos, mv = [], 0, memoryview(data)
    while pos < len(data):
        v, pos = read_varint(mv, pos)
        out.append(v)
    return out


def decode_column(data: bytes) -> Column:
    name, sem, dtype, mask = "", 0, ColumnDataType.FLOAT64, b""
    values_raw = b""
    for fnum, wire, payload in iter_fields(memoryview(data)):
        if fnum == 1:
            name = bytes(payload).decode()
        elif fnum == 2:
            sem = payload
        elif fnum == 3:
            values_raw = bytes(payload)
        elif fnum == 4:
            mask = bytes(payload)
        elif fnum == 5:
            dtype = payload
    vals_by_type = _decode_values(values_raw) if values_raw else {}
    values = vals_by_type.get(dtype)
    if values is None and vals_by_type:
        # tolerate a datatype/values-field mismatch: take what was sent
        dtype, values = next(iter(vals_by_type.items()))
    return Column(name, sem, dtype, values or [], mask)


def decode_insert(data: bytes) -> InsertRequest:
    req = InsertRequest(table_name="")
    for fnum, wire, payload in iter_fields(memoryview(data)):
        if fnum == 1:
            req.table_name = bytes(payload).decode()
        elif fnum == 3:
            req.columns.append(decode_column(bytes(payload)))
        elif fnum == 4:
            req.row_count = payload
        elif fnum == 5:
            req.region_number = payload
    return req


def decode_column_def(data: bytes) -> ColumnDef:
    cd = ColumnDef(name="", datatype=ColumnDataType.FLOAT64,
                   is_nullable=False)
    for fnum, _, payload in iter_fields(memoryview(data)):
        if fnum == 1:
            cd.name = bytes(payload).decode()
        elif fnum == 2:
            cd.datatype = payload
        elif fnum == 3:
            cd.is_nullable = bool(payload)
    return cd


def decode_create_table(data: bytes) -> CreateTableExpr:
    ct = CreateTableExpr(table_name="")
    for fnum, _, payload in iter_fields(memoryview(data)):
        if fnum == 1:
            ct.catalog_name = bytes(payload).decode()
        elif fnum == 2:
            ct.schema_name = bytes(payload).decode()
        elif fnum == 3:
            ct.table_name = bytes(payload).decode()
        elif fnum == 5:
            ct.column_defs.append(decode_column_def(bytes(payload)))
        elif fnum == 6:
            ct.time_index = bytes(payload).decode()
        elif fnum == 7:
            ct.primary_keys.append(bytes(payload).decode())
        elif fnum == 8:
            ct.create_if_not_exists = bool(payload)
    return ct


def decode_ddl(data: bytes) -> DdlRequest:
    ddl = DdlRequest()
    for fnum, _, payload in iter_fields(memoryview(data)):
        if fnum == 1:
            for df, _, dp in iter_fields(memoryview(bytes(payload))):
                if df == 1:
                    ddl.create_database = bytes(dp).decode()
        elif fnum == 2:
            ddl.create_table = decode_create_table(bytes(payload))
        elif fnum == 4:
            cat = sch = tbl = ""
            for df, _, dp in iter_fields(memoryview(bytes(payload))):
                if df == 1:
                    cat = bytes(dp).decode()
                elif df == 2:
                    sch = bytes(dp).decode()
                elif df == 3:
                    tbl = bytes(dp).decode()
            ddl.drop_table = (cat, sch, tbl)
        elif fnum == 3:
            ddl.other = "alter"
        elif fnum == 5:
            ddl.other = "flush_table"
    return ddl


def decode_greptime_request(data: bytes) -> GreptimeRequest:
    req = GreptimeRequest()
    for fnum, wire, payload in iter_fields(memoryview(data)):
        if fnum == 1:
            for hf, _, hp in iter_fields(memoryview(bytes(payload))):
                if hf == 1:
                    req.catalog = bytes(hp).decode()
                elif hf == 2:
                    req.schema = bytes(hp).decode()
                elif hf == 4:
                    req.dbname = bytes(hp).decode()
        elif fnum == 2:
            req.insert = decode_insert(bytes(payload))
        elif fnum == 3:
            for qf, _, qp in iter_fields(memoryview(bytes(payload))):
                if qf == 1:
                    req.query = QueryRequest(sql=bytes(qp).decode())
        elif fnum == 4:
            req.ddl = decode_ddl(bytes(payload))
        elif fnum == 5:
            req.other = "delete"
    return req


#: ColumnDataType → SQL type name (the DDL translation the server runs)
SQL_TYPE_NAMES = {
    ColumnDataType.BOOLEAN: "BOOLEAN",
    ColumnDataType.INT8: "TINYINT",
    ColumnDataType.INT16: "SMALLINT",
    ColumnDataType.INT32: "INT",
    ColumnDataType.INT64: "BIGINT",
    ColumnDataType.UINT8: "TINYINT UNSIGNED",
    ColumnDataType.UINT16: "SMALLINT UNSIGNED",
    ColumnDataType.UINT32: "INT UNSIGNED",
    ColumnDataType.UINT64: "BIGINT UNSIGNED",
    ColumnDataType.FLOAT32: "FLOAT",
    ColumnDataType.FLOAT64: "DOUBLE",
    ColumnDataType.BINARY: "BLOB",
    ColumnDataType.STRING: "STRING",
    ColumnDataType.DATE: "DATE",
    ColumnDataType.DATETIME: "DATETIME",
    ColumnDataType.TIMESTAMP_SECOND: "TIMESTAMP(0)",
    ColumnDataType.TIMESTAMP_MILLISECOND: "TIMESTAMP(3)",
    ColumnDataType.TIMESTAMP_MICROSECOND: "TIMESTAMP(6)",
    ColumnDataType.TIMESTAMP_NANOSECOND: "TIMESTAMP(9)",
}


def create_table_to_sql(ct: CreateTableExpr) -> str:
    """CreateTableExpr → CREATE TABLE statement (the server-side DDL
    translation; reference grpc handlers build table requests directly,
    src/common/grpc-expr/src/)."""
    cols = []
    for cd in ct.column_defs:
        ty = SQL_TYPE_NAMES.get(cd.datatype, "DOUBLE")
        null = "" if cd.is_nullable or cd.name == ct.time_index \
            else " NOT NULL"
        entry = f'"{cd.name}" {ty}{null}'
        if cd.name == ct.time_index:
            entry += " TIME INDEX"
        cols.append(entry)
    if ct.primary_keys:
        keys = ", ".join(f'"{k}"' for k in ct.primary_keys)
        cols.append(f"PRIMARY KEY({keys})")
    ine = "IF NOT EXISTS " if ct.create_if_not_exists else ""
    return f'CREATE TABLE {ine}"{ct.table_name}" ({", ".join(cols)})'


def decode_flight_metadata_affected_rows(data: bytes) -> Optional[int]:
    for fnum, _, payload in iter_fields(memoryview(data)):
        if fnum == 1:
            for af, _, ap in iter_fields(memoryview(bytes(payload))):
                if af == 1:
                    return int(ap)
            return 0
    return None
