"""greptime-proto interop plane (api crate twin).

Reference behavior: src/api/ re-exports the generated `greptime_proto`
v1 types (src/api/src/v1.rs); reference SDKs serialize a
`GreptimeRequest` protobuf into an Arrow Flight ticket
(src/client/src/database.rs:209-231) and the server decodes it in
do_get (src/servers/src/grpc/flight.rs:87-96). This package speaks that
wire format with a hand-rolled protowire codec (no protoc runtime), so
clients built against greptime-proto v1 can connect.
"""

from .v1 import (  # noqa: F401
    Column, ColumnDataType, GreptimeRequest, InsertRequest, QueryRequest,
    SemanticType, decode_greptime_request, encode_affected_rows_metadata,
    encode_greptime_request,
)
