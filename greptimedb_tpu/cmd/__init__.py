"""Process entry: the `greptime` CLI.

Reference behavior: src/cmd/src/bin/greptime.rs — subcommands
standalone|datanode|frontend|metasrv with layered TOML + flag options.
"""

from .main import main

__all__ = ["main"]
