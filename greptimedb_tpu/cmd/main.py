"""greptime CLI: option loading (TOML + flags) and server lifecycle.

Reference behavior: src/cmd — `greptime standalone start -c config.toml
--http-addr ...`; flags override file options (src/cmd/src/options.rs).
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class StandaloneOptions:
    data_home: str = "./greptimedb_data"
    http_addr: str = "127.0.0.1:4000"
    mysql_addr: str = "127.0.0.1:4002"
    postgres_addr: str = "127.0.0.1:4003"
    grpc_addr: str = "127.0.0.1:4001"
    #: OpenTSDB telnet `put` listener; empty/None = disabled (reference
    #: serves it on 4242, src/servers/src/opentsdb.rs:60)
    opentsdb_addr: Optional[str] = None
    user_provider: Optional[str] = None
    enable_mysql: bool = True
    enable_postgres: bool = True
    enable_grpc: bool = True
    log_level: str = "info"
    #: [storage] table from the TOML: type=File|S3, bucket, endpoint,
    #: cache_path... (reference: ObjectStoreConfig, datanode.rs:126-204)
    storage: dict = field(default_factory=dict)
    #: [tls] table: mode=disable|prefer|require + cert/key paths
    #: (reference: TlsOption, servers/src/tls.rs)
    tls: dict = field(default_factory=dict)
    #: [query] table: stream_threshold_rows / stream_slice_rows (cold-scan
    #: streaming), cold_reduce ("host"/"device" partial reduction),
    #: scan_cache_budget_mb (device scan cache bound)
    query: dict = field(default_factory=dict)
    log_dir: Optional[str] = None
    #: [logging] otlp_endpoint: OTLP/HTTP collector base URL (spans are
    #: exported to {endpoint}/v1/traces when set)
    otlp_endpoint: Optional[str] = None


def load_options(args) -> StandaloneOptions:
    opts = StandaloneOptions()
    if getattr(args, "config_file", None):
        import tomllib
        with open(args.config_file, "rb") as f:
            doc = tomllib.load(f)
        opts.storage = doc.get("storage", {})
        opts.data_home = opts.storage.get("data_home", opts.data_home)
        http = doc.get("http", {})
        opts.http_addr = http.get("addr", opts.http_addr)
        mysql = doc.get("mysql", {})
        opts.mysql_addr = mysql.get("addr", opts.mysql_addr)
        opts.enable_mysql = mysql.get("enable", True)
        pg = doc.get("postgres", {})
        opts.postgres_addr = pg.get("addr", opts.postgres_addr)
        opts.enable_postgres = pg.get("enable", True)
        grpc = doc.get("grpc", {})
        opts.grpc_addr = grpc.get("addr", opts.grpc_addr)
        opts.enable_grpc = grpc.get("enable", True)
        tsdb = doc.get("opentsdb", {})
        if tsdb.get("enable", False):
            opts.opentsdb_addr = tsdb.get("addr", "127.0.0.1:4242")
        logging_doc = doc.get("logging", {})
        opts.log_level = logging_doc.get("level", opts.log_level)
        opts.log_dir = logging_doc.get("dir", opts.log_dir)
        opts.otlp_endpoint = logging_doc.get("otlp_endpoint",
                                             opts.otlp_endpoint)
        opts.tls = doc.get("tls", {})
        opts.query = doc.get("query", {})
    for name in ("data_home", "http_addr", "mysql_addr", "postgres_addr",
                 "grpc_addr", "opentsdb_addr", "user_provider"):
        v = getattr(args, name, None)
        if v is not None:
            setattr(opts, name, v)
    return opts


def build_servers(opts: StandaloneOptions):
    """Compose standalone frontend + protocol servers (not yet started)."""
    from ..datanode import DatanodeInstance, DatanodeOptions
    from ..frontend import FrontendInstance
    from ..servers.auth import NoopUserProvider, StaticUserProvider
    from ..servers.http import HttpServer

    if opts.query:
        from ..query.stream_exec import configure_streaming
        configure_streaming(
            threshold_rows=opts.query.get("stream_threshold_rows"),
            slice_rows=opts.query.get("stream_slice_rows"),
            cold_reduce=opts.query.get("cold_reduce"))
        budget_mb = opts.query.get("scan_cache_budget_mb")
        if budget_mb is not None:
            from ..query.tpu_exec import SCAN_CACHE
            SCAN_CACHE.configure(budget_bytes=int(budget_mb) << 20)
    store = None
    if opts.storage and str(opts.storage.get("type", "File")) != "File":
        from ..storage.object_store import build_object_store
        store = build_object_store(opts.storage, opts.data_home)
    dn = DatanodeInstance(DatanodeOptions(data_home=opts.data_home),
                          store=store)
    fe = FrontendInstance(dn)
    fe.start()
    provider = NoopUserProvider()
    if opts.user_provider:
        provider = StaticUserProvider.from_option(opts.user_provider)
    def split_addr(addr):
        host, _, port = addr.partition(":")
        return host or "127.0.0.1", int(port or 0)

    ssl_context = None
    if opts.tls:
        from ..servers.tls import TlsOption
        ssl_context = TlsOption.from_config(opts.tls).setup()
    servers = [HttpServer(fe, provider, opts.http_addr,
                          ssl_context=ssl_context)]
    if opts.enable_mysql:
        from ..servers.mysql import MysqlServer
        host, port = split_addr(opts.mysql_addr)
        servers.append(MysqlServer(fe, host=host, port=port,
                                   user_provider=provider,
                                   ssl_context=ssl_context))
    if opts.enable_postgres:
        from ..servers.postgres import PostgresServer
        host, port = split_addr(opts.postgres_addr)
        servers.append(PostgresServer(fe, host=host, port=port,
                                      user_provider=provider,
                                      ssl_context=ssl_context))
    if opts.enable_grpc:
        from ..servers.grpc import GrpcServer
        servers.append(GrpcServer(fe, provider, opts.grpc_addr))
    if opts.opentsdb_addr:
        from ..servers.opentsdb import OpentsdbServer
        host, port = split_addr(opts.opentsdb_addr)
        servers.append(OpentsdbServer(fe, host=host, port=port))
    return fe, servers


def standalone_start(args) -> None:
    opts = load_options(args)
    from ..common.jax_cache import enable_compile_cache
    from ..common.telemetry import (configure_otlp, init_logging,
                                    install_panic_hook)
    init_logging(opts.log_level, opts.log_dir)
    if opts.otlp_endpoint:
        configure_otlp(opts.otlp_endpoint, service_name="greptimedb")
    install_panic_hook()
    enable_compile_cache(opts.data_home)
    fe, servers = build_servers(opts)
    for s in servers:
        s.start()
        logging.info("started %s on %s:%s", type(s).__name__,
                     getattr(s, "host", "?"), getattr(s, "port", "?"))
    stop = threading.Event()

    def on_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    logging.info("greptimedb_tpu standalone ready (data_home=%s)",
                 opts.data_home)
    stop.wait()
    for s in servers:
        s.shutdown()
    fe.shutdown()


def _block_until_signal(on_shutdown) -> None:
    stop = threading.Event()

    def on_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    stop.wait()
    on_shutdown()


def _meta_client(addr_arg: str):
    """--metasrv-addr accepts a comma-separated replica list; the
    failover client walks it until a leader answers."""
    from ..meta.flight import FailoverFlightMetaClient
    addrs = [a.strip() for a in addr_arg.split(",") if a.strip()]
    return FailoverFlightMetaClient([f"grpc://{a}" for a in addrs])


def metasrv_start(args) -> None:
    """Run the metadata server role (reference: greptime metasrv start;
    etcd is replaced by a file-backed KV snapshot)."""
    from ..common.telemetry import init_logging
    from ..meta import MetaSrv
    from ..meta.flight import FlightMetaServer
    from ..meta.kv import FileKv, MemKv

    init_logging(args.log_level or "info")
    from ..common import background_jobs, trace_store
    background_jobs.configure_node("metasrv")
    # buffer-role sink: balancer-op traces root HERE and verdict
    # locally (always retained — the balancer tail rule); retained
    # spans ride home on the next meta RPC's response and the caller
    # writes them into greptime_private.trace_spans
    trace_store.install(trace_store.TraceSink(
        node_label="metasrv", service="metasrv", role="buffer"))
    raft_node = None
    if args.peers:
        # replicated meta: --peers is the FULL replica set (including
        # this node) and must be IDENTICAL on every node — raft ids come
        # from its sorted order, so a divergent list (extra/missing
        # entry, different host spelling) would misattribute votes.
        # Routes survive a metasrv loss (reference: etcd cluster,
        # store/etcd.rs:762); transports ride the same Flight plane.
        from ..meta.replication import (
            FlightTransport, RaftNode, ReplicatedKv)
        peers = sorted({a.strip() for a in args.peers.split(",")
                        if a.strip()})
        if args.bind_addr not in peers:
            raise SystemExit(
                f"--peers must list every replica including this node's "
                f"--bind-addr {args.bind_addr!r} verbatim; got {peers}")
        peer_addrs = dict(enumerate(peers, start=1))
        my_id = next(i for i, a in peer_addrs.items()
                     if a == args.bind_addr)
        raft_node = RaftNode(
            my_id, list(peer_addrs),
            store_path=f"{args.store}.raft" if args.store else None)
        for pid, addr in peer_addrs.items():
            if pid != my_id:
                raft_node.transports[pid] = FlightTransport(
                    f"grpc://{addr}")
        kv = ReplicatedKv(raft_node)
    else:
        kv = FileKv(args.store) if args.store else MemKv()
    srv = MetaSrv(kv, datanode_lease_secs=args.datanode_lease_secs)
    server = FlightMetaServer(srv, f"grpc://{args.bind_addr}",
                              raft_node=raft_node)
    server.serve_in_background()
    if raft_node is not None:
        raft_node.start()
    # leader election: with several metasrv replicas over one KV, only
    # the lease holder mutates routes (reference: election/etcd.rs).
    # Under raft the consensus leader IS the lease holder.
    if raft_node is not None:
        class _RaftElection:
            def start(self):
                pass

            def stop(self):
                pass

            @property
            def is_leader(self):
                return raft_node.is_leader
        election = _RaftElection()
    else:
        from ..meta.lock import Election
        election = Election(kv, f"metasrv-{args.bind_addr}")
    election.start()

    # region failover runner (reference: FailureDetectRunner on the
    # leader; the action itself is this build's upgrade over v0.2) plus
    # the elastic-region balancer control loop (split/migrate/rebalance
    # state machines resume from the __balancer/ KV keys on restart)
    from ..common.runtime import RepeatedTask
    srv.balancer.is_leader_fn = lambda: election.is_leader

    def failover_tick():
        if not election.is_leader:
            return
        moves = srv.failover_check()
        for m in moves:
            logging.warning("failover: region %s of %s moved %d -> %d",
                            m["region"], m["table"], m["from"], m["to"])
        srv.balancer.tick()

    runner = RepeatedTask(args.failover_interval, failover_tick,
                          name="failover-runner")
    runner.start()
    logging.info("metasrv ready on %s (leader=%s)", server.address,
                 election.is_leader)

    def shutdown():
        runner.stop()
        election.stop()
        if raft_node is not None:
            raft_node.stop()
        server.shutdown()

    _block_until_signal(shutdown)


def datanode_start(args) -> None:
    """Run a region-hosting worker: Flight data plane + meta heartbeats
    (reference: greptime datanode start)."""
    from ..common.jax_cache import enable_compile_cache
    from ..common.telemetry import init_logging
    from ..datanode import DatanodeInstance, DatanodeOptions
    from ..meta import Peer
    from ..meta.flight import FlightMetaClient
    from ..servers.flight import FlightDatanodeServer

    init_logging(args.log_level or "info")
    enable_compile_cache(args.data_home or "./greptimedb_data")
    # buffer-role trace sink: this process cannot decide tail-sampling
    # verdicts (it sees only its fragments of each trace) and cannot
    # write trace_spans — it buffers spans keyed by trace_id until the
    # frontend's verdict piggybacks on a later RPC, then ships released
    # spans home on that RPC's response (TTL evicts the unclaimed)
    from ..common import background_jobs, profiler, trace_store
    label = f"dn{args.node_id}"
    background_jobs.configure_node(label)
    trace_store.install(trace_store.TraceSink(
        node_label=label, service="datanode", role="buffer"))
    # writer-less sampler: this process cannot write profile_samples;
    # its folded stacks drain over the Flight `profile` action to the
    # asking frontend, which absorbs and writes them
    profiler.install(profiler.Profiler(node_label=label))
    dn = DatanodeInstance(DatanodeOptions(
        data_home=args.data_home or "./greptimedb_data",
        node_id=args.node_id, register_numbers_table=False,
        wal_sync_on_write=bool(getattr(args, "wal_sync_on_write",
                                       False))))
    dn.start()
    server = FlightDatanodeServer(dn, f"grpc://{args.rpc_addr}")
    server.serve_in_background()
    meta = _meta_client(args.metasrv_addr)
    meta.register(Peer(args.node_id, server.address))
    dn.start_heartbeat(meta, interval_s=args.heartbeat_interval)
    logging.info("datanode %d ready on %s (meta %s)", args.node_id,
                 server.address, args.metasrv_addr)

    def shutdown():
        server.shutdown()
        dn.shutdown()
        meta.close()

    _block_until_signal(shutdown)


def frontend_start(args) -> None:
    """Run the stateless router role: SQL over HTTP/MySQL/Postgres/Flight
    against datanodes resolved through the meta service (reference:
    greptime frontend start)."""
    from ..common.telemetry import init_logging
    from ..frontend.distributed import DistInstance
    from ..meta.flight import FlightMetaClient, PeerClientRegistry
    from ..servers.flight import FlightFrontendServer
    from ..servers.http import HttpServer
    from ..servers.auth import NoopUserProvider

    init_logging(args.log_level or "info")
    meta = _meta_client(args.metasrv_addr)
    clients = PeerClientRegistry(meta)
    fe = DistInstance(meta, clients)
    # self-monitoring scrape loop: frontend registry + cluster-wide
    # region heat (meta heartbeats) → greptime_private tables
    from ..common.runtime import env_int
    monitor_interval = env_int("GREPTIME_SELF_MONITOR_INTERVAL_S", 30)
    if monitor_interval > 0:
        fe.self_monitor.start_background(monitor_interval)
    servers = [HttpServer(fe, NoopUserProvider(), args.http_addr)]
    if args.mysql_addr:
        from ..servers.mysql import MysqlServer
        host, _, port = args.mysql_addr.partition(":")
        servers.append(MysqlServer(fe, host=host or "127.0.0.1",
                                   port=int(port or 0)))
    if args.postgres_addr:
        from ..servers.postgres import PostgresServer
        host, _, port = args.postgres_addr.partition(":")
        servers.append(PostgresServer(fe, host=host or "127.0.0.1",
                                      port=int(port or 0)))
    if args.grpc_addr:
        servers.append(FlightFrontendServer(fe,
                                            f"grpc://{args.grpc_addr}"))
    for s in servers:
        s.serve_in_background() if hasattr(s, "serve_in_background")             else s.start()
    logging.info("frontend ready (http %s, meta %s)", args.http_addr,
                 args.metasrv_addr)

    def shutdown():
        fe.self_monitor.stop()
        for s in servers:
            s.shutdown()
        meta.close()

    _block_until_signal(shutdown)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="greptime", description="greptimedb_tpu CLI")
    sub = parser.add_subparsers(dest="subcommand", required=True)

    standalone = sub.add_parser("standalone")
    ssub = standalone.add_subparsers(dest="action", required=True)
    start = ssub.add_parser("start")
    start.add_argument("-c", "--config-file")
    start.add_argument("--data-home")
    start.add_argument("--http-addr")
    start.add_argument("--mysql-addr")
    start.add_argument("--postgres-addr")
    start.add_argument("--grpc-addr")
    start.add_argument("--opentsdb-addr")
    start.add_argument("--user-provider")
    start.set_defaults(func=standalone_start)

    metasrv = sub.add_parser("metasrv")
    msub = metasrv.add_subparsers(dest="action", required=True)
    mstart = msub.add_parser("start")
    mstart.add_argument("--bind-addr", default="127.0.0.1:3002")
    mstart.add_argument("--store", help="path for the file-backed KV")
    mstart.add_argument("--peers", help="comma-separated bind addrs of "
                        "the full metasrv replica set (enables the "
                        "replicated raft store)")
    mstart.add_argument("--failover-interval", type=float, default=10.0)
    mstart.add_argument("--datanode-lease-secs", type=float, default=15.0)
    mstart.add_argument("--log-level")
    mstart.set_defaults(func=metasrv_start)

    datanode = sub.add_parser("datanode")
    dsub = datanode.add_subparsers(dest="action", required=True)
    dstart = dsub.add_parser("start")
    dstart.add_argument("--node-id", type=int, required=True)
    dstart.add_argument("--rpc-addr", default="127.0.0.1:0")
    dstart.add_argument("--metasrv-addr", default="127.0.0.1:3002")
    dstart.add_argument("--data-home")
    dstart.add_argument("--heartbeat-interval", type=float, default=5.0)
    dstart.add_argument("--wal-sync-on-write", action="store_true",
                        help="fsync the WAL before acking each write "
                             "(the replication acceptance drives run "
                             "with this on)")
    dstart.add_argument("--log-level")
    dstart.set_defaults(func=datanode_start)

    frontend = sub.add_parser("frontend")
    fsub = frontend.add_subparsers(dest="action", required=True)
    fstart = fsub.add_parser("start")
    fstart.add_argument("--metasrv-addr", default="127.0.0.1:3002")
    fstart.add_argument("--http-addr", default="127.0.0.1:4000")
    fstart.add_argument("--mysql-addr")
    fstart.add_argument("--postgres-addr")
    fstart.add_argument("--grpc-addr")
    fstart.add_argument("--log-level")
    fstart.set_defaults(func=frontend_start)

    cli = sub.add_parser("cli")
    csub = cli.add_subparsers(dest="action", required=True)
    attach = csub.add_parser("attach")
    attach.add_argument("--grpc-addr", default="127.0.0.1:4001")
    attach.set_defaults(func=_cli_attach)

    args = parser.parse_args(argv)
    args.func(args)
    return 0


def _cli_attach(args) -> None:
    """Interactive SQL REPL over the Flight/gRPC client."""
    from ..client.flight import Database
    from ..datatypes.record_batch import pretty_print
    addr = args.grpc_addr
    if "://" not in addr:
        addr = f"grpc://{addr}"
    db = Database(addr)
    print("greptimedb_tpu REPL — end statements with ';', \\q to quit")
    buf = []
    while True:
        try:
            line = input("> " if not buf else "… ")
        except EOFError:
            break
        if line.strip() in ("\\q", "exit", "quit"):
            break
        buf.append(line)
        if line.rstrip().endswith(";"):
            sql = "\n".join(buf)
            buf = []
            try:
                out = db.sql(sql)
                if isinstance(out, int):
                    print(f"Affected Rows: {out}")
                else:
                    print(pretty_print(out))
            except Exception as e:  # noqa: BLE001
                print(f"error: {e}")


if __name__ == "__main__":
    sys.exit(main())
