"""greptlint: AST-based static analyzer for this repo's invariants.

Usage::

    python -m greptimedb_tpu.devtools.greptlint greptimedb_tpu/

Exit codes: 0 clean (after suppressions + baseline), 1 findings,
2 unusable input (unparseable file / bad flags). See rules.py for the
rule catalog and README "Static analysis & invariants" for the workflow.
"""

from __future__ import annotations

from .core import (Finding, ModuleInfo, ProjectContext, apply_baseline,
                   build_context, collect_files, load_baseline, run_files,
                   save_baseline)
from .rules import ALL_RULES, Rule

__all__ = ["Finding", "ModuleInfo", "ProjectContext", "Rule", "ALL_RULES",
           "collect_files", "build_context", "run_files", "load_baseline",
           "save_baseline", "apply_baseline", "lint_paths"]


def lint_paths(paths, baseline_path=None, rules=None):
    """Library entry point: returns (fresh_findings, all_findings, errors).

    ``fresh_findings`` has the baseline applied (what should fail a
    build); ``all_findings`` is pre-baseline (what --write-baseline
    records)."""
    import os
    rules = ALL_RULES if rules is None else rules
    files = collect_files(paths)
    root = os.path.commonpath([p for p, _ in files]) if files else "."
    ctx = build_context(files, root)
    findings, errors = run_files(files, rules, ctx)
    fresh = findings
    if baseline_path is not None:
        fresh = apply_baseline(findings, load_baseline(baseline_path))
    return fresh, findings, errors
