"""Seeded GL14 violation: a front-end helper reaching into storage
regions directly instead of lowering onto the plan IR (selftest/ is in
the rule's scope so this fixture can live here instead of inside
promql/ or flow/)."""


def series_count(table):
    return sum(r.series_dict.num_series for r in table.regions.values())
