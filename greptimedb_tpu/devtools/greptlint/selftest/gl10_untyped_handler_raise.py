"""Seeded GL10 violation: a Flight handler reaches (two calls deep) a
`raise` of an exception class outside the errors.* taxonomy — the wire
would carry status UNKNOWN/500 instead of a real code. The handler
touches remote_context so GL07 stays quiet: this fixture seeds exactly
one finding."""


class NotWireMapped(Exception):
    """Deliberately NOT a GreptimeError subclass."""


class FixtureFlightServer:
    def do_get(self, context, ticket):
        with remote_context(None):  # noqa: F821 — parsed, never run
            return _load(ticket)


def _load(ticket):
    return _decode(ticket)


def _decode(ticket):
    raise NotWireMapped("untyped error escaping the RPC boundary")
