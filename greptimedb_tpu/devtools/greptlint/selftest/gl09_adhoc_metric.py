"""Seeded GL09 violation: an ad-hoc prometheus metric object. It lives
outside the common/telemetry helpers, so the self-monitoring scraper,
/metrics and information_schema.runtime_metrics all miss or mis-handle
it (no shared registry walk, no suppress_metrics recursion guard, no
name-collision sanitizer)."""

from prometheus_client import Counter

_MY_COUNTER = Counter("my_private_requests_total", "bespoke counter")


def record_request():
    _MY_COUNTER.inc()
