"""Seeded GL04 violation: a fail_point() site naming a point nobody
registered — at runtime it only WARNs once and never fires."""

from greptimedb_tpu.common import failpoint as _fp


def flush_with_typo():
    _fp.fail_point("flush_memtabel_typo_never_registered")
