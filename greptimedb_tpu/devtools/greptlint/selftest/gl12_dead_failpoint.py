"""Seeded GL12 violation: a registered failpoint whose only evaluation
site lives in a function no non-test code calls — arming the point in a
torture experiment would silently never fire."""

register("gl12_dead_failpoint")  # noqa: F821 — parsed, never run


def _never_called():
    fail_point("gl12_dead_failpoint")  # noqa: F821
