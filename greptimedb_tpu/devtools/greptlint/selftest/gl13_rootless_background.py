"""Seeded GL13 violation: a background loop registered on
RepeatedTask whose callback never opens a root span or a
background_jobs.job() — the work rides no trace, so the durable trace
store can never retain it and information_schema.background_jobs never
shows it running."""


class _RootlessLoop:
    def start(self):
        self._task = RepeatedTask(  # noqa: F821 — parsed, never run
            5.0, self._gl13_sweep_loop, name="rootless")
        self._task.start()

    def _gl13_sweep_loop(self):
        sweep_everything()  # noqa: F821 — stand-in for real work
