"""Seeded GL01 violation: except Exception that does nothing at all."""


def load_optional_state(path):
    state = {}
    try:
        with open(path) as f:
            state = eval(f.read())  # noqa: S307 — fixture only, never run
    except Exception:
        pass
    return state
