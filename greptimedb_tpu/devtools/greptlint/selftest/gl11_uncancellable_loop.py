"""Seeded GL11 violation: a per-file I/O loop reachable from statement
execution (`do_query` is a root) that never passes through
check_cancelled() — a KILL could not interrupt it at a batch boundary.
The failpoint name is registered here so GL04 stays quiet; the site's
enclosing function has a caller so GL12 stays quiet too."""

register("objstore_read")  # noqa: F821 — parsed, never run


def do_query(sst_files):
    out = []
    for f in sst_files:            # the uncancellable batch loop
        out.append(_read_one(f))
    return out


def _read_one(f):
    fail_point("objstore_read")  # noqa: F821 — blocking-I/O site
    return f
