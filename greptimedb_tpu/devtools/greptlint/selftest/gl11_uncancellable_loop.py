"""Seeded GL11 violation: a cohort-wait loop (the WAL group-commit /
ingest-coalescer shape) that parks on an event with neither a bounded
timeout nor a cancellation point — a dead leader wedges every follower
forever and KILL cannot interrupt the park. The interprocedural
I/O-loop form of GL11 is seeded by
tests/test_greptlint.py::test_gl11_fires_without_check_and_clears_with_it."""


def follow_cohort(batch):
    while not batch.done.is_set():     # the unbounded cohort wait
        batch.done.wait()
    return batch.result
