"""Seeded GL15 violation: a non-daemon thread that is never joined,
so a forgotten worker keeps the interpreter alive after main()
returns (the process hangs on exit instead of stopping)."""

import threading


def start_forever_worker(fn):
    t = threading.Thread(target=fn, name="immortal")  # greptlint: disable=GL06
    t.start()
    return t
