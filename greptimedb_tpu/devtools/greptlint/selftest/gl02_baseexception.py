"""Seeded GL02 violation: BaseException caught and not re-raised, so a
SimulatedCrash (which must behave like SIGKILL) would survive."""

import logging

logger = logging.getLogger(__name__)


def run_job(job):
    try:
        job()
    except BaseException:
        logger.exception("job failed")  # logged (GL01-clean) but swallowed
