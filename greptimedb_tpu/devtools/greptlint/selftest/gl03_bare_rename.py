"""Seeded GL03 violation: write-then-rename bypassing utils.atomic_write
(no fsync, no crash-safe temp cleanup)."""

import os


def save_snapshot(path, data):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(data)
    os.replace(tmp, path)
