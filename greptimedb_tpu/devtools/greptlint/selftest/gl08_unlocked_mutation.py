"""Seeded GL08 violation: module declares a lock for its shared state
but one path mutates the module-level dict without holding it."""

import threading

_lock = threading.Lock()
_registry = {}


def register_safe(name, value):
    with _lock:
        _registry[name] = value


def register_racy(name, value):
    _registry[name] = value
