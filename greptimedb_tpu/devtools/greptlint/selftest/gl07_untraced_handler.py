"""Seeded GL07 violation: a Flight handler that never touches
remote_context/traceparent, dropping the caller's trace on the wire."""


class RogueFlightServer:
    def do_get(self, context, ticket):
        return self._scan(ticket)

    def _scan(self, ticket):
        return []
