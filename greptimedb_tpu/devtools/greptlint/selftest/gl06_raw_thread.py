"""Seeded GL06 violation: a bespoke thread that bypasses
common.runtime, so the worker detaches from the caller's trace and
ExecStats context."""

import threading


def start_background_flush(fn):
    t = threading.Thread(target=fn, daemon=True, name="rogue-flush")
    t.start()
    return t
