"""Seeded GL05 violation: bare RuntimeError in a retry-classified layer
(selftest/ is in the rule's scope precisely so this fixture can live
here instead of inside storage/)."""


def commit(version):
    if version < 0:
        raise RuntimeError(f"bad version {version}")
