"""greptlint rules GL01-GL14: the project's load-bearing conventions.

GL01-GL09 are per-file; GL10-GL12 are *interprocedural* — they consume
the repo-wide call graph core.build_context assembles (exception-flow,
cancellation reachability, failpoint reachability).

Each rule is grounded in a real past bug class (see README "Static
analysis & invariants"); together they turn six PRs of reviewer folklore
into a build gate. Rules are small classes over the shared
:class:`~..core.ModuleInfo` index; to add one, subclass :class:`Rule`,
give it an ``id``/``title``, implement ``check``, append it to
:data:`ALL_RULES`, and drop a seeded-violation fixture into
``selftest/`` (tests/test_greptlint.py picks it up automatically).

Path scoping note: scoped rules (GL05 storage/client/meta, GL07
servers/) also match ``selftest/`` so each rule's fixture can live with
the analyzer instead of being planted into production packages.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import (Finding, ModuleInfo, ProjectContext, _call_leaf,
                   _str_arg0)


def _segments(rel: str) -> List[str]:
    return rel.replace("\\", "/").split("/")


def _in_dirs(rel: str, dirs: Sequence[str]) -> bool:
    segs = _segments(rel)[:-1]
    return any(d in segs for d in dirs)


def _is_module(rel: str, names: Sequence[str]) -> bool:
    norm = rel.replace("\\", "/")
    return any(norm.endswith(n) for n in names)


def _dotted(node: ast.AST) -> str:
    """'os.path.join' for Attribute/Name chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _walk_shallow(stmts: Sequence[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function/class
    bodies (their control flow doesn't handle THIS except block)."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class Rule:
    id: str = "GL00"
    title: str = ""

    def check(self, mod: ModuleInfo,
              ctx: ProjectContext) -> Iterator[Finding]:
        raise NotImplementedError


def _catches(handler: ast.ExceptHandler, names: Set[str]) -> bool:
    t = handler.type
    types = t.elts if isinstance(t, ast.Tuple) else [t] if t else []
    for e in types:
        d = _dotted(e)
        if d.split(".")[-1] in names:
            return True
    return False


#: attribute names whose call inside a handler counts as "dealt with it":
#: logging, metric counters, error recording / waiter hand-off
_HANDLED_CALL_ATTRS = frozenset({
    "exception", "error", "warning", "warn", "critical", "info", "debug",
    "log", "inc", "observe", "observe_latency", "increment_counter",
    "record", "_finish", "put_nowait", "submit_later", "add_error",
    "set_exception",
})
_HANDLED_CALL_NAMES = frozenset({
    "increment_counter", "observe_latency", "logged", "record_error",
    "print",                                # CLI/REPL error reporting
})


def _handler_deals_with_it(handler: ast.ExceptHandler) -> bool:
    for node in _walk_shallow(handler.body):
        if isinstance(node, (ast.Raise, ast.Return)):
            return True
        if isinstance(node, ast.AugAssign):
            return True                      # counter bump (x += 1)
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _HANDLED_CALL_ATTRS:
                return True
            if isinstance(node.func, ast.Name) and \
                    node.func.id in _HANDLED_CALL_NAMES:
                return True
    return False


class SwallowedException(Rule):
    id = "GL01"
    title = ("`except Exception`/bare `except` must log, re-raise, count, "
             "or return a degraded value — silent swallows hide real bugs")

    def check(self, mod, ctx):
        for h in mod.nodes(ast.ExceptHandler):
            bare = h.type is None
            if not bare and not _catches(h, {"Exception"}):
                continue
            if _handler_deals_with_it(h):
                continue
            what = "bare `except:`" if bare else "`except Exception`"
            yield mod.finding(
                self.id, h,
                f"{what} swallows the error: the handler neither logs, "
                f"re-raises, counts, nor returns a degraded value")


class BaseExceptionCaught(Rule):
    id = "GL02"
    title = ("catching BaseException/SimulatedCrash without re-raising "
             "defeats crash-injection (SimulatedCrash must behave like "
             "SIGKILL outside tests/torture.py)")

    EXEMPT = ("tests/torture.py",)

    def check(self, mod, ctx):
        if _is_module(mod.rel, self.EXEMPT):
            return
        for h in mod.nodes(ast.ExceptHandler):
            bare = h.type is None
            broad = _catches(h, {"BaseException", "SimulatedCrash"})
            if not (bare or broad):
                continue
            if any(isinstance(n, ast.Raise)
                   for n in _walk_shallow(h.body)):
                continue
            what = ("bare `except:`" if bare else
                    "`except BaseException`/`except SimulatedCrash`")
            yield mod.finding(
                self.id, h,
                f"{what} without re-raise can swallow SimulatedCrash — "
                f"crash-injection recovery paths must not survive a "
                f"simulated kill; re-raise or narrow the catch")


class BareRename(Rule):
    id = "GL03"
    title = ("os.rename/os.replace outside utils.atomic_write: durable "
             "renames must go through the one fsync-then-rename helper")

    EXEMPT = ("utils/__init__.py",)

    def check(self, mod, ctx):
        if _is_module(mod.rel, self.EXEMPT):
            return
        for call in mod.nodes(ast.Call):
            d = _dotted(call.func)
            if d in ("os.rename", "os.replace"):
                yield mod.finding(
                    self.id, call,
                    f"direct {d}() — route durable write-then-rename "
                    f"through utils.atomic_write (temp file, fsync, "
                    f"rename, crash-safe cleanup)")


class UnknownFailpoint(Rule):
    id = "GL04"
    title = ("failpoint.fail_point/fires(name) literals must name a "
             "registered point — typos otherwise only WARN at runtime")

    def check(self, mod, ctx):
        for call in mod.nodes(ast.Call):
            fn = call.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else "")
            if name not in ("fail_point", "fires"):
                continue
            if not call.args:
                continue
            arg = call.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue
            if arg.value not in ctx.failpoint_names:
                yield mod.finding(
                    self.id, call,
                    f"failpoint {arg.value!r} is not registered anywhere "
                    f"(known: {len(ctx.failpoint_names)} names) — typo'd "
                    f"sites never fire")


class UntypedRaise(Rule):
    id = "GL05"
    title = ("raising bare Exception/RuntimeError in storage/client/meta "
             "bypasses the errors.* taxonomy the retry layer classifies")

    SCOPE = ("storage", "client", "meta", "selftest")
    BAD = {"Exception", "RuntimeError"}

    def check(self, mod, ctx):
        if not _in_dirs(mod.rel, self.SCOPE):
            return
        for node in mod.nodes(ast.Raise):
            exc = node.exc
            target = exc.func if isinstance(exc, ast.Call) else exc
            d = _dotted(target) if target is not None else ""
            if d in self.BAD:
                yield mod.finding(
                    self.id, node,
                    f"raise {d} in a retry-classified layer — raise a "
                    f"GreptimeError subclass (errors.py) so "
                    f"is_transient()/status codes stay meaningful")


class RawThreadConstruction(Rule):
    id = "GL06"
    title = ("ThreadPoolExecutor/threading.Thread construction outside "
             "common/runtime.py: bespoke pools bypass telemetry."
             "propagate() and detach spans/ExecStats from their query")

    EXEMPT = ("common/runtime.py", "common/telemetry.py",
              "storage/scheduler.py")

    def check(self, mod, ctx):
        if _is_module(mod.rel, self.EXEMPT):
            return
        for call in mod.nodes(ast.Call):
            d = _dotted(call.func)
            leaf = d.split(".")[-1]
            if leaf not in ("Thread", "ThreadPoolExecutor", "Timer"):
                continue
            if d not in ("Thread", "threading.Thread", "threading.Timer",
                         "Timer", "ThreadPoolExecutor",
                         "concurrent.futures.ThreadPoolExecutor",
                         "futures.ThreadPoolExecutor"):
                continue
            yield mod.finding(
                self.id, call,
                f"direct {d}() — use common.runtime (new_thread / "
                f"transient_executor / the shared runtimes) so workers "
                f"inherit the caller's trace + ExecStats context")


class UntracedHandler(Rule):
    id = "GL07"
    title = ("servers/ RPC handlers must join the caller's trace: Flight "
             "do_get/do_put/do_action need remote_context, HTTP handlers "
             "moving work off-thread need _traced_call")

    SCOPE = ("servers", "selftest")
    FLIGHT_METHODS = ("do_get", "do_put", "do_action", "do_exchange")
    TRACE_NAMES = frozenset({"remote_context", "current_traceparent",
                             "parse_traceparent"})

    def _refs(self, fn: ast.AST, names: Set[str],
              attrs: Set[str]) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id in names:
                return True
            if isinstance(node, ast.Attribute) and node.attr in (names
                                                                 | attrs):
                return True
        return False

    def check(self, mod, ctx):
        if not _in_dirs(mod.rel, self.SCOPE):
            return
        for cls in mod.nodes(ast.ClassDef):
            for stmt in cls.body:
                if not isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if stmt.name in self.FLIGHT_METHODS:
                    if not self._refs(stmt, set(self.TRACE_NAMES), set()):
                        yield mod.finding(
                            self.id, stmt,
                            f"Flight handler {cls.name}.{stmt.name} never "
                            f"touches remote_context/traceparent — wire "
                            f"RPCs would drop the caller's trace")
                elif stmt.name.startswith("handle_"):
                    uses_executor = any(
                        isinstance(n, ast.Attribute)
                        and n.attr == "run_in_executor"
                        for n in ast.walk(stmt))
                    if uses_executor and not self._refs(
                            stmt, set(self.TRACE_NAMES),
                            {"_traced_call", "_traced"}):
                        yield mod.finding(
                            self.id, stmt,
                            f"HTTP handler {cls.name}.{stmt.name} ships "
                            f"work to an executor without _traced_call — "
                            f"the worker detaches from the request trace")


class UnlockedModuleMutation(Rule):
    id = "GL08"
    title = ("in modules that declare a module-level lock, module-level "
             "dict/list state must only be mutated under `with <lock>:`")

    MUTATORS = frozenset({
        "append", "extend", "insert", "pop", "popitem", "clear", "update",
        "setdefault", "remove", "discard", "add", "move_to_end",
    })
    _CONTAINER_CALLS = frozenset({
        "dict", "list", "set", "OrderedDict", "defaultdict", "deque",
        "Counter",
    })

    def _module_locks(self, mod: ModuleInfo) -> Set[str]:
        locks: Set[str] = set()
        for stmt in mod.tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            v = stmt.value
            if not isinstance(v, ast.Call):
                continue
            d = _dotted(v.func).split(".")[-1]
            if d in ("Lock", "RLock", "TrackedLock", "TrackedRLock"):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        locks.add(t.id)
        return locks

    def _module_containers(self, mod: ModuleInfo) -> Set[str]:
        names: Set[str] = set()
        for stmt in mod.tree.body:
            targets: List[ast.expr] = []
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            else:
                continue
            is_container = isinstance(value, (ast.Dict, ast.List, ast.Set,
                                              ast.DictComp, ast.ListComp,
                                              ast.SetComp))
            if isinstance(value, ast.Call) and \
                    _dotted(value.func).split(".")[-1] in \
                    self._CONTAINER_CALLS:
                is_container = True
            if not is_container:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        return names

    def _under_lock(self, mod: ModuleInfo, node: ast.AST,
                    locks: Set[str]) -> bool:
        for anc in mod.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    e = item.context_expr
                    if isinstance(e, ast.Name) and e.id in locks:
                        return True
                    # lock attribute/call forms: `with _lock:` only —
                    # other shapes don't guard MODULE state by convention
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # keep walking: an enclosing function may hold the lock
                # around a nested helper? No — a nested def runs later.
                return False
        return False

    def check(self, mod, ctx):
        locks = self._module_locks(mod)
        if not locks:
            return
        containers = self._module_containers(mod)
        if not containers:
            return

        def container_of(node: ast.expr) -> Optional[str]:
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id in containers:
                return node.value.id
            return None

        candidates: List[Tuple[ast.AST, str, str]] = []
        for node in mod.nodes(ast.Assign):
            for t in node.targets:
                name = container_of(t)
                if name:
                    candidates.append((node, name, "item assignment"))
        for node in mod.nodes(ast.AugAssign):
            name = container_of(node.target)
            if name:
                candidates.append((node, name, "augmented assignment"))
        for node in mod.nodes(ast.Delete):
            for t in node.targets:
                name = container_of(t)
                if name:
                    candidates.append((node, name, "deletion"))
        for node in mod.nodes(ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in self.MUTATORS and \
                    isinstance(fn.value, ast.Name) and \
                    fn.value.id in containers:
                candidates.append((node, fn.value.id,
                                   f".{fn.attr}() call"))
        for node, name, how in candidates:
            # module-level statements run at import, single-threaded
            if not any(isinstance(a, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                       for a in mod.ancestors(node)):
                continue
            if self._under_lock(mod, node, locks):
                continue
            yield mod.finding(
                self.id, node,
                f"module-level container {name!r} mutated ({how}) outside "
                f"`with {'/'.join(sorted(locks))}:` although this module "
                f"declares a module lock for its shared state")


class AdhocMetricObject(Rule):
    id = "GL09"
    title = ("prometheus metric objects constructed outside "
             "common/telemetry helpers: the self-monitoring scraper and "
             "runtime_metrics only see the shared registry walk — a "
             "bespoke Counter/Gauge/Histogram also dodges the "
             "suppress_metrics recursion guard and the name-collision "
             "sanitizer")

    EXEMPT = ("common/telemetry.py",)
    METRIC_TYPES = frozenset({"Counter", "Gauge", "Histogram", "Summary",
                              "Info", "Enum"})

    def _prometheus_bindings(self, mod: ModuleInfo
                             ) -> Tuple[Set[str], Set[str]]:
        """(metric names, module aliases) bound from prometheus_client
        in this module (module level or inside functions — telemetry
        itself imports lazily), so a bare `Counter(...)` from
        collections never false-positives and `import prometheus_client
        as pc; pc.Counter(...)` doesn't dodge the rule (the GL04
        aliased-import lesson)."""
        names: Set[str] = set()
        modules: Set[str] = {"prometheus_client"}
        for imp in mod.nodes(ast.ImportFrom):
            if imp.module and imp.module.split(".")[0] == \
                    "prometheus_client":
                for alias in imp.names:
                    if alias.name in self.METRIC_TYPES:
                        names.add(alias.asname or alias.name)
        for imp in mod.nodes(ast.Import):
            for alias in imp.names:
                if alias.name.split(".")[0] == "prometheus_client":
                    modules.add(alias.asname or alias.name.split(".")[0])
        return names, modules

    def check(self, mod, ctx):
        if _is_module(mod.rel, self.EXEMPT):
            return
        bound, modules = self._prometheus_bindings(mod)
        for call in mod.nodes(ast.Call):
            d = _dotted(call.func)
            if not d:
                continue
            parts = d.split(".")
            is_metric = (len(parts) == 2 and parts[0] in modules
                         and parts[1] in self.METRIC_TYPES) \
                or d in bound
            if not is_metric:
                continue
            yield mod.finding(
                self.id, call,
                f"ad-hoc metric object {d}() — use common.telemetry "
                f"helpers (increment_counter / timer / observe_latency) "
                f"so the metric lands in the shared registry the "
                f"scraper, /metrics and runtime_metrics all read")


# ---------------------------------------------------------------------
# interprocedural rules (GL10-GL12): these consume the repo-wide call
# graph core.build_context assembles. Resolution is name-based with a
# hub cutoff (see core.CallGraph) — biased toward precision, so a
# finding is always actionable and the budget stays at zero.
# ---------------------------------------------------------------------

def _shallow_nodes(fn_node: ast.AST) -> Iterator[ast.AST]:
    """Walk one function's body without descending into nested defs
    (those are separate call-graph nodes with their own reachability)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class UntypedHandlerException(Rule):
    id = "GL10"
    title = ("exception-flow: any `raise` reachable from a protocol "
             "handler (Flight do_get/do_put/do_action, HTTP/mysql/"
             "postgres handle_*, datanode mailbox steps) must be an "
             "errors.* taxonomy type or wire-mapped — untyped raises "
             "cross the RPC boundary as status UNKNOWN")

    FLIGHT_METHODS = ("do_get", "do_put", "do_action", "do_exchange")
    MAILBOX_METHODS = ("_handle_mailbox", "_handle_balancer_msg",
                       "_balancer_step")
    #: raise targets that cross the boundary deliberately:
    #: - SimulatedCrash is crash injection (GL02 guards its catching);
    #: - NotImplementedError is an abstract-surface contract — 500 is
    #:   the honest status for "this build cannot do that";
    #: - stop/system/keyboard are control flow, not errors;
    #: - ValueError/TypeError/KeyError are the validated-input contract
    #:   the protocol surfaces translate at the boundary (http/flight
    #:   handlers and the SET machinery catch them into 400s);
    #: - OSError/FileNotFoundError are the object-store read contract
    #:   (callers branch on not-found; the retry layer classifies the
    #:   rest);
    #: - LockOrderError/IoUnderLockError are the test-only lock
    #:   detector, which must fail LOUDLY wherever it trips.
    WIRE_MAPPED = frozenset({
        "SimulatedCrash", "NotImplementedError", "StopIteration",
        "StopAsyncIteration", "KeyboardInterrupt", "SystemExit",
        "TimeoutError", "BrokenPipeError", "ConnectionError",
        "ConnectionResetError", "ValueError", "TypeError", "KeyError",
        "OSError", "FileNotFoundError", "PermissionError",
        "UnicodeDecodeError", "LockOrderError", "IoUnderLockError",
    })

    def _roots(self, ctx: ProjectContext) -> Iterator:
        for fn in ctx.callgraph.functions:
            in_servers = _in_dirs(fn.rel, ("servers", "selftest"))
            if in_servers and fn.cls and fn.name in self.FLIGHT_METHODS:
                yield fn
            elif in_servers and fn.cls and fn.name.startswith("handle_"):
                yield fn
            elif fn.rel.replace("\\", "/").endswith(
                    "datanode/instance.py") and \
                    fn.name in self.MAILBOX_METHODS:
                yield fn

    def _reach(self, ctx: ProjectContext):
        reach = ctx.cache.get(self.id)
        if reach is None:
            reach = ctx.callgraph.reachable(self._roots(ctx))
            ctx.cache[self.id] = reach
        return reach

    def check(self, mod, ctx):
        reach = self._reach(ctx)
        for fn in ctx.callgraph.functions:
            if fn.mod is not mod or fn not in reach:
                continue
            for node in _shallow_nodes(fn.node):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                exc = node.exc
                if isinstance(exc, ast.Name) and not exc.id[:1].isupper():
                    continue              # propagating a bound object
                    # (an UPPERCASE bare Name is a class raise — `raise
                    # RuntimeError` without parens raises an instance
                    # all the same and falls through to the check)
                target = exc.func if isinstance(exc, ast.Call) else exc
                d = _dotted(target) if target is not None else ""
                leaf = d.split(".")[-1]
                if not leaf or leaf in ctx.taxonomy or \
                        leaf in self.WIRE_MAPPED:
                    continue
                if not leaf[:1].isupper():
                    # `raise _to_greptime_error(e)`: a converter factory,
                    # not a class — its return type is beyond static
                    # reach, and the converters exist to produce typed
                    # errors (under-approximate rather than false-flag)
                    continue
                path = reach[fn]
                via = " -> ".join(path[-3:]) if len(path) > 1 else path[0]
                yield mod.finding(
                    self.id, node,
                    f"raise {leaf} reachable from a protocol handler "
                    f"(via {via}) — raise a GreptimeError subclass "
                    f"(errors.py) so the wire carries a real status "
                    f"code instead of UNKNOWN/500")


class UncancellableLoop(Rule):
    id = "GL11"
    title = ("cancellation reachability: every loop over SST files / "
             "regions / RPC futures / streamed slices reachable from "
             "statement execution must pass through check_cancelled(), "
             "and every cohort-wait loop (WAL group commit, ingest "
             "coalescer, scan fusion) must bound its waits or reach "
             "check_cancelled() — KILL <id> otherwise cannot interrupt "
             "it, and a dead leader wedges the cohort")

    #: loops are only *scanned* in the read/execution layers — write-side
    #: and background loops (flush, compaction, purge) must NOT be
    #: cancellable mid-flight, their atomicity is the crash-safety story.
    #: wal.py and coalesce.py join the scope for their group-commit /
    #: coalescer cohort-wait loops (requests park there mid-statement)
    SCAN_DIRS = ("query", "promql", "selftest")
    SCAN_MODULES = ("storage/region.py", "frontend/distributed.py",
                    "storage/wal.py", "servers/coalesce.py")
    #: RPC leaf calls that make a loop iteration remote-heavy
    RPC_CALLS = frozenset({"_dist_rpc"})
    #: leaf calls that PARK the thread (Event.wait / Condition.wait):
    #: inside a loop they must carry a timeout or the loop must reach a
    #: cancellation point — an unbounded park can neither be KILLed nor
    #: outlive a dead group-commit/coalesce leader
    WAIT_CALLS = frozenset({"wait"})

    def _roots(self, ctx: ProjectContext) -> Iterator:
        for fn in ctx.callgraph.functions:
            if fn.name == "do_query":
                yield fn
            elif fn.name == "execute" and _in_dirs(fn.rel, ("query",
                                                            "selftest")):
                yield fn

    def _closures(self, ctx: ProjectContext):
        cached = ctx.cache.get(self.id)
        if cached is not None:
            return cached
        from ...common.locks import IO_FAILPOINT_SITES
        cg = ctx.callgraph
        reach = cg.reachable(self._roots(ctx))

        def fixpoint(base_pred):
            members = {fn for fn in cg.functions if base_pred(fn)}
            changed = True
            while changed:
                changed = False
                for fn in cg.functions:
                    if fn in members:
                        continue
                    for callee in fn.calls:
                        if any(t in members for t in cg.targets(callee)):
                            members.add(fn)
                            changed = True
                            break
            return members

        io_reach = fixpoint(
            lambda fn: bool(fn.failpoint_sites & IO_FAILPOINT_SITES)
            or fn.name in self.RPC_CALLS)
        can_reach = fixpoint(lambda fn: "check_cancelled" in fn.calls)
        cached = (reach, io_reach, can_reach)
        ctx.cache[self.id] = cached
        return cached

    def _in_scope(self, rel: str) -> bool:
        return _in_dirs(rel, self.SCAN_DIRS) or \
            _is_module(rel, self.SCAN_MODULES)

    def check(self, mod, ctx):
        if not self._in_scope(mod.rel):
            return
        reach, io_reach, can_reach = self._closures(ctx)
        cg = ctx.callgraph
        from ...common.locks import IO_FAILPOINT_SITES

        def body_nodes(loop):
            stack = list(loop.body)
            while stack:
                node = stack.pop()
                yield node
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                stack.extend(ast.iter_child_nodes(node))

        for fn in cg.functions:
            if fn.mod is not mod:
                continue
            in_reach = fn in reach
            for loop in _shallow_nodes(fn.node):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                io_heavy = False
                covered = False
                unbounded_wait = False
                for node in body_nodes(loop):
                    if not isinstance(node, ast.Call):
                        continue
                    leaf = _call_leaf(node)
                    if leaf == "check_cancelled":
                        covered = True
                        break
                    if leaf in ("fail_point", "fires") and \
                            _str_arg0(node) in IO_FAILPOINT_SITES:
                        io_heavy = True
                        continue
                    if leaf in self.WAIT_CALLS and \
                            isinstance(node.func, ast.Attribute) and \
                            not node.args and \
                            not any(kw.arg == "timeout"
                                    for kw in node.keywords):
                        # x.wait() with neither a positional nor a
                        # timeout= bound: the park can outlive its waker
                        unbounded_wait = True
                        continue
                    targets = cg.targets(leaf)
                    if any(t in can_reach for t in targets):
                        covered = True
                        break
                    if leaf in self.RPC_CALLS or \
                            any(t in io_reach for t in targets):
                        io_heavy = True
                if covered:
                    continue
                if io_heavy and in_reach:
                    yield mod.finding(
                        self.id, loop,
                        f"loop in {fn.qual} does per-iteration I/O or "
                        f"RPC work, is reachable from statement "
                        f"execution, and never passes through "
                        f"check_cancelled() — KILL cannot interrupt it "
                        f"at a batch boundary")
                elif unbounded_wait:
                    # cohort-wait loops are flagged regardless of the
                    # do_query reach set: protocol-ingest waits (the
                    # coalescer) park request threads do_query never sees
                    yield mod.finding(
                        self.id, loop,
                        f"wait loop in {fn.qual} parks without a "
                        f"timeout and never passes through "
                        f"check_cancelled() — a dead group-commit/"
                        f"coalesce leader (or a KILL on the waiting "
                        f"statement) wedges it forever; bound the wait "
                        f"(timeout=...) or add a cancellation point")


class DeadFailpoint(Rule):
    id = "GL12"
    title = ("failpoint reachability: every registered failpoint name "
             "must be evaluated by a call site reachable from at least "
             "one non-test caller — dead failpoints rot the torture "
             "matrix (experiments arm them and silently never fire)")

    def check(self, mod, ctx):
        cg = ctx.callgraph
        for name, (rel, lineno) in \
                sorted(ctx.registered_failpoints.items()):
            if rel != mod.rel:
                continue                  # report at the register() site
            site_fns = [fn for fn in cg.functions
                        if name in fn.failpoint_sites]
            module_site = any(name in sites for sites
                              in cg.module_failpoint_sites.values())
            anchor = _Line(lineno)
            if not site_fns and not module_site:
                yield mod.finding(
                    self.id, anchor,
                    f"failpoint {name!r} is registered here but no "
                    f"fail_point()/fires() site evaluates it anywhere "
                    f"in the scanned tree — arming it never fires")
            elif not module_site and not any(
                    cg.has_caller(fn) for fn in site_fns):
                owners = ", ".join(fn.qual for fn in site_fns[:3])
                yield mod.finding(
                    self.id, anchor,
                    f"failpoint {name!r} is only evaluated inside "
                    f"{owners}, which no non-test code calls — the "
                    f"site is dead and the experiment never fires")


class RootlessBackgroundJob(Rule):
    id = "GL13"
    title = ("background root spans: every callback handed to "
             "RepeatedTask(...) or a scheduler submit/submit_later "
             "must reach background_jobs.job() or telemetry."
             "root_span() — background work that roots no trace is "
             "invisible to the durable trace store and the "
             "information_schema.background_jobs view")

    #: where background loops live (and the seeded fixture)
    SCAN_DIRS = ("storage", "flow", "monitor", "meta", "datanode",
                 "cmd", "servers", "selftest")
    #: call leaves that satisfy the contract
    ROOTING_CALLS = frozenset({"job", "root_span"})

    def _covered(self, ctx: ProjectContext):
        """Functions that (transitively) reach a rooting call — the
        GL11 fixpoint shape, cached per run."""
        cached = ctx.cache.get(self.id)
        if cached is not None:
            return cached
        cg = ctx.callgraph
        members = {fn for fn in cg.functions
                   if fn.calls & self.ROOTING_CALLS}
        changed = True
        while changed:
            changed = False
            for fn in cg.functions:
                if fn in members:
                    continue
                for callee in fn.calls:
                    if any(t in members for t in cg.targets(callee)):
                        members.add(fn)
                        changed = True
                        break
        ctx.cache[self.id] = members
        return members

    @staticmethod
    def _callback_arg(node: ast.Call, leaf: str):
        """The callback expression of a background registration, or
        None when this call is not one. RepeatedTask(interval, fn, ...);
        scheduler submit/submit_later(key: str-literal/f-string, fn) —
        the string first arg keeps ThreadPoolExecutor.submit(fn, ...)
        out (precision first)."""
        if leaf == "RepeatedTask":
            if len(node.args) >= 2:
                return node.args[1]
            return next((kw.value for kw in node.keywords
                         if kw.arg == "fn"), None)
        if leaf in ("submit", "submit_later"):
            if len(node.args) >= 2 and isinstance(
                    node.args[0], (ast.Constant, ast.JoinedStr)):
                return node.args[1]
        return None

    def check(self, mod, ctx):
        if not _in_dirs(mod.rel, self.SCAN_DIRS):
            return
        cg = ctx.callgraph
        covered = None                    # computed lazily: most files
        for fn in cg.functions:           # have no registration sites
            if fn.mod is not mod:
                continue
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                leaf = _call_leaf(node)
                if leaf not in ("RepeatedTask", "submit",
                                "submit_later"):
                    continue
                cb = self._callback_arg(node, leaf)
                if cb is None:
                    continue
                if isinstance(cb, ast.Attribute):
                    cb_name = cb.attr
                elif isinstance(cb, ast.Name):
                    cb_name = cb.id
                else:
                    continue              # lambda/call: unresolvable,
                targets = cg.targets(cb_name)   # skip for precision
                if not targets:
                    continue              # hub or external name
                if covered is None:
                    covered = self._covered(ctx)
                if any(t in covered for t in targets):
                    continue
                yield mod.finding(
                    self.id, node,
                    f"background callback {cb_name!r} (registered in "
                    f"{fn.qual}) never reaches background_jobs.job() "
                    f"or telemetry.root_span() — its work rides no "
                    f"trace and never appears in "
                    f"information_schema.background_jobs")


class _Line:
    """Anchor object for findings not tied to one AST node."""

    def __init__(self, lineno: int):
        self.lineno = lineno
        self.col_offset = 0


class UnsanctionedDataAccess(Rule):
    id = "GL14"
    title = ("promql/ and flow/ must not touch storage regions, the "
             "device scan cache or raw scan_batches outside their "
             "lowering modules — front ends reach data through the "
             "plan IR (query/ir.py), never around it")

    SCOPE = ("promql", "flow", "selftest")
    #: the ONE sanctioned IR-lowering module per front end: all region /
    #: scan-cache / raw-scan access under promql/ and flow/ lives there,
    #: so fast-path coverage (scatter, pruning, fusion) cannot silently
    #: fork per front end
    EXEMPT = ("promql/lowering.py", "flow/lowering.py")

    #: attribute accesses that reach storage underneath the IR
    ATTRS = frozenset({"regions", "scan_batches"})
    #: module-level names that bypass the IR entirely
    NAMES = frozenset({"SCAN_CACHE"})

    def check(self, mod, ctx):
        if not _in_dirs(mod.rel, self.SCOPE):
            return
        if _is_module(mod.rel, self.EXEMPT):
            return

        def hit(node, what):
            return mod.finding(
                self.id, node,
                f"{what} under {_segments(mod.rel)[-2]}/ bypasses the "
                f"plan IR — move the access into the front end's "
                f"lowering module (promql/lowering.py or "
                f"flow/lowering.py) so it rides scatter/pruning/fusion "
                f"and EXPLAIN stays truthful")

        for node in mod.nodes(ast.Attribute):
            if node.attr in self.ATTRS:
                yield hit(node, f"`.{node.attr}` access")
            elif node.attr in self.NAMES:
                yield hit(node, f"`{node.attr}` access")
        for node in mod.nodes(ast.Name):
            if node.id in self.NAMES and \
                    isinstance(node.ctx, ast.Load):
                yield hit(node, f"`{node.id}` access")
        for node in mod.nodes(ast.ImportFrom):
            for alias in node.names:
                if alias.name in self.NAMES:
                    yield hit(node, f"import of `{alias.name}`")


class UndaemonedThread(Rule):
    id = "GL15"
    title = ("threading.Thread constructed without daemon=True and "
             "never .join()ed on any shutdown path: a forgotten "
             "non-daemon thread keeps the interpreter alive after "
             "main() returns (hung process on exit)")

    THREAD_NAMES = ("Thread", "threading.Thread")

    def check(self, mod, ctx):
        # every `<target>.join(...)` in the module, by dotted receiver —
        # a Thread assigned to that receiver counts as reclaimed
        joined: Set[str] = set()
        for call in mod.nodes(ast.Call):
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr == "join":
                d = _dotted(f.value)
                if d:
                    joined.add(d)
        assigned_to: Dict[int, str] = {}
        for node in mod.nodes(ast.Assign):
            if len(node.targets) == 1 and \
                    isinstance(node.value, ast.Call):
                d = _dotted(node.targets[0])
                if d:
                    assigned_to[id(node.value)] = d
        for call in mod.nodes(ast.Call):
            if _dotted(call.func) not in self.THREAD_NAMES:
                continue
            kw = next((k for k in call.keywords
                       if k.arg == "daemon"), None)
            if kw is not None and not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value is False):
                continue          # daemon=True / daemon=<flag var>
            target = assigned_to.get(id(call))
            if target is not None and target in joined:
                continue          # reclaimed on some path
            yield mod.finding(
                self.id, call,
                "threading.Thread without daemon=True and never "
                ".join()ed — a non-daemon thread left running blocks "
                "interpreter shutdown; set daemon=True or join it on "
                "a reachable shutdown path")


ALL_RULES: List[Rule] = [
    SwallowedException(), BaseExceptionCaught(), BareRename(),
    UnknownFailpoint(), UntypedRaise(), RawThreadConstruction(),
    UntracedHandler(), UnlockedModuleMutation(), AdhocMetricObject(),
    UntypedHandlerException(), UncancellableLoop(), DeadFailpoint(),
    RootlessBackgroundJob(), UnsanctionedDataAccess(),
    UndaemonedThread(),
]
