"""greptlint rules GL01-GL08: the project's load-bearing conventions.

Each rule is grounded in a real past bug class (see README "Static
analysis & invariants"); together they turn six PRs of reviewer folklore
into a build gate. Rules are small classes over the shared
:class:`~..core.ModuleInfo` index; to add one, subclass :class:`Rule`,
give it an ``id``/``title``, implement ``check``, append it to
:data:`ALL_RULES`, and drop a seeded-violation fixture into
``selftest/`` (tests/test_greptlint.py picks it up automatically).

Path scoping note: scoped rules (GL05 storage/client/meta, GL07
servers/) also match ``selftest/`` so each rule's fixture can live with
the analyzer instead of being planted into production packages.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Sequence, Set, Tuple

from .core import Finding, ModuleInfo, ProjectContext


def _segments(rel: str) -> List[str]:
    return rel.replace("\\", "/").split("/")


def _in_dirs(rel: str, dirs: Sequence[str]) -> bool:
    segs = _segments(rel)[:-1]
    return any(d in segs for d in dirs)


def _is_module(rel: str, names: Sequence[str]) -> bool:
    norm = rel.replace("\\", "/")
    return any(norm.endswith(n) for n in names)


def _dotted(node: ast.AST) -> str:
    """'os.path.join' for Attribute/Name chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _walk_shallow(stmts: Sequence[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function/class
    bodies (their control flow doesn't handle THIS except block)."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class Rule:
    id: str = "GL00"
    title: str = ""

    def check(self, mod: ModuleInfo,
              ctx: ProjectContext) -> Iterator[Finding]:
        raise NotImplementedError


def _catches(handler: ast.ExceptHandler, names: Set[str]) -> bool:
    t = handler.type
    types = t.elts if isinstance(t, ast.Tuple) else [t] if t else []
    for e in types:
        d = _dotted(e)
        if d.split(".")[-1] in names:
            return True
    return False


#: attribute names whose call inside a handler counts as "dealt with it":
#: logging, metric counters, error recording / waiter hand-off
_HANDLED_CALL_ATTRS = frozenset({
    "exception", "error", "warning", "warn", "critical", "info", "debug",
    "log", "inc", "observe", "observe_latency", "increment_counter",
    "record", "_finish", "put_nowait", "submit_later", "add_error",
    "set_exception",
})
_HANDLED_CALL_NAMES = frozenset({
    "increment_counter", "observe_latency", "logged", "record_error",
    "print",                                # CLI/REPL error reporting
})


def _handler_deals_with_it(handler: ast.ExceptHandler) -> bool:
    for node in _walk_shallow(handler.body):
        if isinstance(node, (ast.Raise, ast.Return)):
            return True
        if isinstance(node, ast.AugAssign):
            return True                      # counter bump (x += 1)
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _HANDLED_CALL_ATTRS:
                return True
            if isinstance(node.func, ast.Name) and \
                    node.func.id in _HANDLED_CALL_NAMES:
                return True
    return False


class SwallowedException(Rule):
    id = "GL01"
    title = ("`except Exception`/bare `except` must log, re-raise, count, "
             "or return a degraded value — silent swallows hide real bugs")

    def check(self, mod, ctx):
        for h in mod.nodes(ast.ExceptHandler):
            bare = h.type is None
            if not bare and not _catches(h, {"Exception"}):
                continue
            if _handler_deals_with_it(h):
                continue
            what = "bare `except:`" if bare else "`except Exception`"
            yield mod.finding(
                self.id, h,
                f"{what} swallows the error: the handler neither logs, "
                f"re-raises, counts, nor returns a degraded value")


class BaseExceptionCaught(Rule):
    id = "GL02"
    title = ("catching BaseException/SimulatedCrash without re-raising "
             "defeats crash-injection (SimulatedCrash must behave like "
             "SIGKILL outside tests/torture.py)")

    EXEMPT = ("tests/torture.py",)

    def check(self, mod, ctx):
        if _is_module(mod.rel, self.EXEMPT):
            return
        for h in mod.nodes(ast.ExceptHandler):
            bare = h.type is None
            broad = _catches(h, {"BaseException", "SimulatedCrash"})
            if not (bare or broad):
                continue
            if any(isinstance(n, ast.Raise)
                   for n in _walk_shallow(h.body)):
                continue
            what = ("bare `except:`" if bare else
                    "`except BaseException`/`except SimulatedCrash`")
            yield mod.finding(
                self.id, h,
                f"{what} without re-raise can swallow SimulatedCrash — "
                f"crash-injection recovery paths must not survive a "
                f"simulated kill; re-raise or narrow the catch")


class BareRename(Rule):
    id = "GL03"
    title = ("os.rename/os.replace outside utils.atomic_write: durable "
             "renames must go through the one fsync-then-rename helper")

    EXEMPT = ("utils/__init__.py",)

    def check(self, mod, ctx):
        if _is_module(mod.rel, self.EXEMPT):
            return
        for call in mod.nodes(ast.Call):
            d = _dotted(call.func)
            if d in ("os.rename", "os.replace"):
                yield mod.finding(
                    self.id, call,
                    f"direct {d}() — route durable write-then-rename "
                    f"through utils.atomic_write (temp file, fsync, "
                    f"rename, crash-safe cleanup)")


class UnknownFailpoint(Rule):
    id = "GL04"
    title = ("failpoint.fail_point/fires(name) literals must name a "
             "registered point — typos otherwise only WARN at runtime")

    def check(self, mod, ctx):
        for call in mod.nodes(ast.Call):
            fn = call.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else "")
            if name not in ("fail_point", "fires"):
                continue
            if not call.args:
                continue
            arg = call.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue
            if arg.value not in ctx.failpoint_names:
                yield mod.finding(
                    self.id, call,
                    f"failpoint {arg.value!r} is not registered anywhere "
                    f"(known: {len(ctx.failpoint_names)} names) — typo'd "
                    f"sites never fire")


class UntypedRaise(Rule):
    id = "GL05"
    title = ("raising bare Exception/RuntimeError in storage/client/meta "
             "bypasses the errors.* taxonomy the retry layer classifies")

    SCOPE = ("storage", "client", "meta", "selftest")
    BAD = {"Exception", "RuntimeError"}

    def check(self, mod, ctx):
        if not _in_dirs(mod.rel, self.SCOPE):
            return
        for node in mod.nodes(ast.Raise):
            exc = node.exc
            target = exc.func if isinstance(exc, ast.Call) else exc
            d = _dotted(target) if target is not None else ""
            if d in self.BAD:
                yield mod.finding(
                    self.id, node,
                    f"raise {d} in a retry-classified layer — raise a "
                    f"GreptimeError subclass (errors.py) so "
                    f"is_transient()/status codes stay meaningful")


class RawThreadConstruction(Rule):
    id = "GL06"
    title = ("ThreadPoolExecutor/threading.Thread construction outside "
             "common/runtime.py: bespoke pools bypass telemetry."
             "propagate() and detach spans/ExecStats from their query")

    EXEMPT = ("common/runtime.py", "common/telemetry.py",
              "storage/scheduler.py")

    def check(self, mod, ctx):
        if _is_module(mod.rel, self.EXEMPT):
            return
        for call in mod.nodes(ast.Call):
            d = _dotted(call.func)
            leaf = d.split(".")[-1]
            if leaf not in ("Thread", "ThreadPoolExecutor", "Timer"):
                continue
            if d not in ("Thread", "threading.Thread", "threading.Timer",
                         "Timer", "ThreadPoolExecutor",
                         "concurrent.futures.ThreadPoolExecutor",
                         "futures.ThreadPoolExecutor"):
                continue
            yield mod.finding(
                self.id, call,
                f"direct {d}() — use common.runtime (new_thread / "
                f"transient_executor / the shared runtimes) so workers "
                f"inherit the caller's trace + ExecStats context")


class UntracedHandler(Rule):
    id = "GL07"
    title = ("servers/ RPC handlers must join the caller's trace: Flight "
             "do_get/do_put/do_action need remote_context, HTTP handlers "
             "moving work off-thread need _traced_call")

    SCOPE = ("servers", "selftest")
    FLIGHT_METHODS = ("do_get", "do_put", "do_action", "do_exchange")
    TRACE_NAMES = frozenset({"remote_context", "current_traceparent",
                             "parse_traceparent"})

    def _refs(self, fn: ast.AST, names: Set[str],
              attrs: Set[str]) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id in names:
                return True
            if isinstance(node, ast.Attribute) and node.attr in (names
                                                                 | attrs):
                return True
        return False

    def check(self, mod, ctx):
        if not _in_dirs(mod.rel, self.SCOPE):
            return
        for cls in mod.nodes(ast.ClassDef):
            for stmt in cls.body:
                if not isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if stmt.name in self.FLIGHT_METHODS:
                    if not self._refs(stmt, set(self.TRACE_NAMES), set()):
                        yield mod.finding(
                            self.id, stmt,
                            f"Flight handler {cls.name}.{stmt.name} never "
                            f"touches remote_context/traceparent — wire "
                            f"RPCs would drop the caller's trace")
                elif stmt.name.startswith("handle_"):
                    uses_executor = any(
                        isinstance(n, ast.Attribute)
                        and n.attr == "run_in_executor"
                        for n in ast.walk(stmt))
                    if uses_executor and not self._refs(
                            stmt, set(self.TRACE_NAMES),
                            {"_traced_call", "_traced"}):
                        yield mod.finding(
                            self.id, stmt,
                            f"HTTP handler {cls.name}.{stmt.name} ships "
                            f"work to an executor without _traced_call — "
                            f"the worker detaches from the request trace")


class UnlockedModuleMutation(Rule):
    id = "GL08"
    title = ("in modules that declare a module-level lock, module-level "
             "dict/list state must only be mutated under `with <lock>:`")

    MUTATORS = frozenset({
        "append", "extend", "insert", "pop", "popitem", "clear", "update",
        "setdefault", "remove", "discard", "add", "move_to_end",
    })
    _CONTAINER_CALLS = frozenset({
        "dict", "list", "set", "OrderedDict", "defaultdict", "deque",
        "Counter",
    })

    def _module_locks(self, mod: ModuleInfo) -> Set[str]:
        locks: Set[str] = set()
        for stmt in mod.tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            v = stmt.value
            if not isinstance(v, ast.Call):
                continue
            d = _dotted(v.func).split(".")[-1]
            if d in ("Lock", "RLock", "TrackedLock", "TrackedRLock"):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        locks.add(t.id)
        return locks

    def _module_containers(self, mod: ModuleInfo) -> Set[str]:
        names: Set[str] = set()
        for stmt in mod.tree.body:
            targets: List[ast.expr] = []
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            else:
                continue
            is_container = isinstance(value, (ast.Dict, ast.List, ast.Set,
                                              ast.DictComp, ast.ListComp,
                                              ast.SetComp))
            if isinstance(value, ast.Call) and \
                    _dotted(value.func).split(".")[-1] in \
                    self._CONTAINER_CALLS:
                is_container = True
            if not is_container:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        return names

    def _under_lock(self, mod: ModuleInfo, node: ast.AST,
                    locks: Set[str]) -> bool:
        for anc in mod.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    e = item.context_expr
                    if isinstance(e, ast.Name) and e.id in locks:
                        return True
                    # lock attribute/call forms: `with _lock:` only —
                    # other shapes don't guard MODULE state by convention
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # keep walking: an enclosing function may hold the lock
                # around a nested helper? No — a nested def runs later.
                return False
        return False

    def check(self, mod, ctx):
        locks = self._module_locks(mod)
        if not locks:
            return
        containers = self._module_containers(mod)
        if not containers:
            return

        def container_of(node: ast.expr) -> Optional[str]:
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id in containers:
                return node.value.id
            return None

        candidates: List[Tuple[ast.AST, str, str]] = []
        for node in mod.nodes(ast.Assign):
            for t in node.targets:
                name = container_of(t)
                if name:
                    candidates.append((node, name, "item assignment"))
        for node in mod.nodes(ast.AugAssign):
            name = container_of(node.target)
            if name:
                candidates.append((node, name, "augmented assignment"))
        for node in mod.nodes(ast.Delete):
            for t in node.targets:
                name = container_of(t)
                if name:
                    candidates.append((node, name, "deletion"))
        for node in mod.nodes(ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in self.MUTATORS and \
                    isinstance(fn.value, ast.Name) and \
                    fn.value.id in containers:
                candidates.append((node, fn.value.id,
                                   f".{fn.attr}() call"))
        for node, name, how in candidates:
            # module-level statements run at import, single-threaded
            if not any(isinstance(a, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                       for a in mod.ancestors(node)):
                continue
            if self._under_lock(mod, node, locks):
                continue
            yield mod.finding(
                self.id, node,
                f"module-level container {name!r} mutated ({how}) outside "
                f"`with {'/'.join(sorted(locks))}:` although this module "
                f"declares a module lock for its shared state")


class AdhocMetricObject(Rule):
    id = "GL09"
    title = ("prometheus metric objects constructed outside "
             "common/telemetry helpers: the self-monitoring scraper and "
             "runtime_metrics only see the shared registry walk — a "
             "bespoke Counter/Gauge/Histogram also dodges the "
             "suppress_metrics recursion guard and the name-collision "
             "sanitizer")

    EXEMPT = ("common/telemetry.py",)
    METRIC_TYPES = frozenset({"Counter", "Gauge", "Histogram", "Summary",
                              "Info", "Enum"})

    def _prometheus_bindings(self, mod: ModuleInfo
                             ) -> Tuple[Set[str], Set[str]]:
        """(metric names, module aliases) bound from prometheus_client
        in this module (module level or inside functions — telemetry
        itself imports lazily), so a bare `Counter(...)` from
        collections never false-positives and `import prometheus_client
        as pc; pc.Counter(...)` doesn't dodge the rule (the GL04
        aliased-import lesson)."""
        names: Set[str] = set()
        modules: Set[str] = {"prometheus_client"}
        for imp in mod.nodes(ast.ImportFrom):
            if imp.module and imp.module.split(".")[0] == \
                    "prometheus_client":
                for alias in imp.names:
                    if alias.name in self.METRIC_TYPES:
                        names.add(alias.asname or alias.name)
        for imp in mod.nodes(ast.Import):
            for alias in imp.names:
                if alias.name.split(".")[0] == "prometheus_client":
                    modules.add(alias.asname or alias.name.split(".")[0])
        return names, modules

    def check(self, mod, ctx):
        if _is_module(mod.rel, self.EXEMPT):
            return
        bound, modules = self._prometheus_bindings(mod)
        for call in mod.nodes(ast.Call):
            d = _dotted(call.func)
            if not d:
                continue
            parts = d.split(".")
            is_metric = (len(parts) == 2 and parts[0] in modules
                         and parts[1] in self.METRIC_TYPES) \
                or d in bound
            if not is_metric:
                continue
            yield mod.finding(
                self.id, call,
                f"ad-hoc metric object {d}() — use common.telemetry "
                f"helpers (increment_counter / timer / observe_latency) "
                f"so the metric lands in the shared registry the "
                f"scraper, /metrics and runtime_metrics all read")


ALL_RULES: List[Rule] = [
    SwallowedException(), BaseExceptionCaught(), BareRename(),
    UnknownFailpoint(), UntypedRaise(), RawThreadConstruction(),
    UntracedHandler(), UnlockedModuleMutation(), AdhocMetricObject(),
]
