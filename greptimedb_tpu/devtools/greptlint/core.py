"""greptlint driver: file collection, AST indexing, suppressions, baseline.

The analyzer is rule-based over the stdlib ``ast`` module (no external
dependencies). Each scanned file is parsed **once** into a
:class:`ModuleInfo` carrying the tree, a parent map, and a by-node-type
index; rules (see ``rules.py``) query the index instead of re-walking,
so adding a rule costs one dict lookup per node type, not a fresh pass.

Suppressions are comment-driven and reviewable in diffs:

- ``# greptlint: disable=GL01`` (trailing or own-line) silences the
  named rule(s) on that line;
- ``# greptlint: disable-file=GL03`` anywhere in the file silences the
  rule(s) for the whole file. ``all`` matches every rule.

The baseline file grandfathers pre-existing findings: keys are
``RULE:relpath:crc32(stripped source line)`` (line-number independent,
so unrelated edits don't churn it) with an occurrence count. Findings
beyond the baselined count fail the run; fixing findings never does.
"""

from __future__ import annotations

import ast
import json
import logging
import os
import re
import zlib
from collections import Counter, defaultdict
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

logger = logging.getLogger(__name__)

SUPPRESS_RE = re.compile(
    r"#\s*greptlint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")

#: directories never collected when walking (explicit file args still scan)
SKIP_DIRS = frozenset({"__pycache__", "selftest", ".git"})


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # absolute path
    rel: str           # path relative to the scan root (stable key part)
    line: int
    col: int
    msg: str
    source_line: str = ""

    def baseline_key(self) -> str:
        crc = zlib.crc32(self.source_line.strip().encode()) & 0xFFFFFFFF
        return f"{self.rule}:{self.rel}:{crc:08x}"

    def render(self) -> str:
        return f"{self.rel}:{self.line}:{self.col}: {self.rule} {self.msg}"


class ModuleInfo:
    """One parsed file: tree + parent map + node index + suppressions."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.by_type: Dict[type, List[ast.AST]] = defaultdict(list)
        for node in ast.walk(self.tree):
            self.by_type[type(node)].append(node)
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.line_suppressed: Dict[int, Set[str]] = {}
        self.file_suppressed: Set[str] = set()
        for i, text in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip().upper() for r in m.group(2).split(",")}
            if m.group(1) == "disable-file":
                self.file_suppressed |= rules
            else:
                self.line_suppressed.setdefault(i, set()).update(rules)

    def nodes(self, *types: type) -> Iterator[ast.AST]:
        for t in types:
            yield from self.by_type.get(t, ())

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, rule: str, lineno: int) -> bool:
        if "ALL" in self.file_suppressed or rule in self.file_suppressed:
            return True
        on_line = self.line_suppressed.get(lineno, ())
        return rule in on_line or "ALL" in on_line

    def finding(self, rule: str, node: ast.AST, msg: str) -> Finding:
        lineno = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule, path=self.path, rel=self.rel,
                       line=lineno, col=col, msg=msg,
                       source_line=self.line_text(lineno))


@dataclass
class FunctionInfo:
    """One function/method in the repo-wide call graph."""
    name: str                      # bare def name ("do_query")
    qual: str                      # "rel:Class.name" / "rel:name"
    rel: str
    cls: Optional[str]             # enclosing class name, if a method
    node: ast.AST                  # the FunctionDef/AsyncFunctionDef
    mod: "ModuleInfo"
    calls: Set[str] = field(default_factory=set)       # callee leaf names
    #: failpoint names this function evaluates via fail_point/fires
    failpoint_sites: Set[str] = field(default_factory=set)

    def __hash__(self) -> int:
        return hash(self.qual)


class CallGraph:
    """Repo-wide, name-resolved call graph (the interprocedural tier).

    Resolution is intentionally approximate — Python has no static
    receiver types — and biased toward *precision*: a call edge links a
    callee name to every same-named def, EXCEPT when the name is so
    common (> ``hub_limit`` defs: ``get``, ``run``, ...) that following
    it would connect everything to everything. Over-approximate hubs
    would drown GL10/GL11 in unfixable findings; dropping them only
    shrinks reach, which for a zero-budget gate is the right failure
    mode (greptlint stays a no-false-positive tool first)."""

    def __init__(self, hub_limit: int = 8):
        self.hub_limit = hub_limit
        self.functions: List[FunctionInfo] = []
        self.defs: Dict[str, List[FunctionInfo]] = defaultdict(list)
        #: callee leaf names invoked from module top level, per rel
        self.module_calls: Dict[str, Set[str]] = defaultdict(set)
        #: failpoint names evaluated at module top level, per rel
        #: (registration-time probes — trivially reachable for GL12)
        self.module_failpoint_sites: Dict[str, Set[str]] = \
            defaultdict(set)

    def add_module(self, mod: "ModuleInfo") -> None:
        for fn in _index_functions(mod):
            self.functions.append(fn)
            self.defs[fn.name].append(fn)
        self.module_calls[mod.rel] |= _module_level_calls(mod)
        for node in _walk_outside_functions(mod.tree):
            if isinstance(node, ast.Call) and \
                    _call_leaf(node) in ("fail_point", "fires"):
                name = _str_arg0(node)
                if name:
                    self.module_failpoint_sites[mod.rel].add(name)

    def targets(self, callee: str) -> List[FunctionInfo]:
        cands = self.defs.get(callee, [])
        if len(cands) > self.hub_limit:
            return []                      # hub: following it links all
        return cands

    def reachable(self, roots: Iterable[FunctionInfo]
                  ) -> Dict[FunctionInfo, Tuple[str, ...]]:
        """BFS closure: {function: call path from its nearest root}."""
        out: Dict[FunctionInfo, Tuple[str, ...]] = {}
        queue: List[FunctionInfo] = []
        for r in roots:
            if r not in out:
                out[r] = (r.qual,)
                queue.append(r)
        while queue:
            fn = queue.pop(0)
            path = out[fn]
            for callee in sorted(fn.calls):
                for tgt in self.targets(callee):
                    if tgt not in out:
                        out[tgt] = path + (tgt.qual,)
                        queue.append(tgt)
        return out

    def has_caller(self, fn: FunctionInfo) -> bool:
        """Anything (another function, or module top level) calls this
        name — the GL12 'reachable from at least one non-test caller'
        floor. By-name: a same-named sibling's caller counts, which only
        makes the check more permissive (never a false positive)."""
        for other in self.functions:
            if other is not fn and fn.name in other.calls:
                return True
        return any(fn.name in calls
                   for calls in self.module_calls.values())


def _module_level_calls(mod: "ModuleInfo") -> Set[str]:
    out: Set[str] = set()
    for node in _walk_outside_functions(mod.tree):
        if isinstance(node, ast.Call):
            leaf = _call_leaf(node)
            if leaf:
                out.add(leaf)
    return out


def _walk_outside_functions(tree: ast.AST) -> Iterator[ast.AST]:
    stack: List[ast.AST] = list(ast.iter_child_nodes(tree))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _call_leaf(call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _str_arg0(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def _index_functions(mod: "ModuleInfo") -> Iterator[FunctionInfo]:
    for node in mod.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
        cls = None
        for anc in mod.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls = None
                break                     # nested def: attribute to the
            if isinstance(anc, ast.ClassDef):   # innermost def only
                cls = anc.name
                break
        qual = f"{mod.rel}:{cls + '.' if cls else ''}{node.name}"
        fi = FunctionInfo(name=node.name, qual=qual, rel=mod.rel,
                          cls=cls, node=node, mod=mod)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                leaf = _call_leaf(sub)
                if leaf:
                    fi.calls.add(leaf)
                    if leaf in ("fail_point", "fires"):
                        name = _str_arg0(sub)
                        if name:
                            fi.failpoint_sites.add(name)
        yield fi


@dataclass
class ProjectContext:
    """Cross-file facts collected in a pre-pass before rules run."""
    root: str
    #: failpoint names registered anywhere (static `register("x")` calls
    #: across the scanned tree, unioned with the live registry when the
    #: package is importable) — GL04 checks call sites against this
    failpoint_names: Set[str] = field(default_factory=set)
    errors: List[str] = field(default_factory=list)
    #: abs path -> source read by build_context's pre-pass, consumed by
    #: run_files so each file hits the disk once, not twice
    sources: Dict[str, str] = field(default_factory=dict)
    #: abs path -> parsed ModuleInfo (one parse per file; run_files and
    #: the call-graph pre-pass share it)
    modules: Dict[str, "ModuleInfo"] = field(default_factory=dict)
    #: abs path -> parse error string (reported by run_files)
    parse_errors: Dict[str, str] = field(default_factory=dict)
    #: the repo-wide call graph (interprocedural rules GL10-GL12)
    callgraph: CallGraph = field(default_factory=CallGraph)
    #: failpoint name -> (rel, lineno) of its STATIC register("x") call
    #: within the scanned files (unlike failpoint_names this never
    #: unions the live registry: GL12 reasons about the scanned tree)
    registered_failpoints: Dict[str, Tuple[str, int]] = \
        field(default_factory=dict)
    #: exception class names participating in the errors.* taxonomy
    #: (GreptimeError + every transitive subclass defined anywhere)
    taxonomy: Set[str] = field(default_factory=set)
    #: per-run scratch for rules that compute expensive whole-graph
    #: closures once (reachability sets) — keyed by rule id
    cache: Dict[str, object] = field(default_factory=dict)


def _package_rel(path: str) -> str:
    """rel for an explicitly-passed file, matching what a directory scan
    of its containing package would produce: climb while ``__init__.py``
    marks a package, then relativize from the package root's parent.
    Path-scoped rules (GL05/GL07) and baseline keys would otherwise see
    a bare basename on single-file scans and silently not apply."""
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return os.path.relpath(path, d)


def collect_files(paths: Iterable[str]) -> List[Tuple[str, str]]:
    """Expand files/directories into (abs_path, rel_path) pairs.

    Directory walks skip SKIP_DIRS (fixtures with seeded violations live
    under ``selftest/``); a path given explicitly is always scanned."""
    out: List[Tuple[str, str]] = []
    seen: Set[str] = set()
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            rel = _package_rel(p)
            if p not in seen:
                seen.add(p)
                out.append((p, rel))
            continue
        base = os.path.dirname(p.rstrip(os.sep))
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIRS)
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                ap = os.path.join(dirpath, fn)
                if ap in seen:
                    continue
                seen.add(ap)
                out.append((ap, os.path.relpath(ap, base)))
    return out


# matches plain `register("x")` and aliased imports like
# `from ..common.failpoint import register as _fp_register` — any
# identifier ENDING in `register` counts (over-matching only shrinks
# GL04's reach, never produces a false positive)
_REGISTER_RE = re.compile(r"""\b\w*register\(\s*["']([a-z][a-z0-9_]*)["']""")


def build_context(files: List[Tuple[str, str]], root: str) -> ProjectContext:
    """Pre-pass: read + parse every file ONCE, build the repo-wide call
    graph and the cross-file fact tables the interprocedural rules
    (GL10-GL12) consume. run_files reuses the parsed ModuleInfos."""
    ctx = ProjectContext(root=root)
    for path, rel in files:
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            ctx.errors.append(f"{path}: unreadable: {e}")
            continue
        ctx.sources[path] = src
        ctx.failpoint_names.update(_REGISTER_RE.findall(src))
        try:
            mod = ModuleInfo(path, rel, src)
        except (SyntaxError, ValueError) as e:
            ctx.parse_errors[path] = f"{rel}: cannot parse: {e}"
            continue
        ctx.modules[path] = mod
        ctx.callgraph.add_module(mod)
        _collect_registered_failpoints(mod, ctx)
    _collect_taxonomy(ctx)
    # union the live registry: names registered by modules outside the
    # scanned set (the analyzer may be pointed at one subpackage)
    try:
        from ...common import failpoint
        ctx.failpoint_names.update(p["name"] for p in failpoint.list_points())
    except Exception as e:  # noqa: BLE001 — linting must not require a
        # fully importable package (e.g. scanning a broken tree); the
        # static register() sweep above already covers the common case,
        # so degrade to it with a note rather than failing the run
        logger.warning("greptlint: live failpoint registry unavailable "
                       "(%s); GL04 uses the static register() sweep only",
                       e)
    return ctx


def _collect_registered_failpoints(mod: ModuleInfo,
                                   ctx: ProjectContext) -> None:
    for call in mod.nodes(ast.Call):
        if _call_leaf(call).endswith("register"):
            name = _str_arg0(call)
            if name:
                ctx.registered_failpoints.setdefault(
                    name, (mod.rel, getattr(call, "lineno", 1)))


def _collect_taxonomy(ctx: ProjectContext) -> None:
    """Fixpoint over class defs: GreptimeError + every transitive
    subclass, wherever it is defined (errors.py, failpoint.py, meta
    modules...) — the set of raise targets GL10 accepts as wire-typed."""
    bases_of: Dict[str, Set[str]] = {}
    for mod in ctx.modules.values():
        for cls in mod.nodes(ast.ClassDef):
            names = set()
            for b in cls.bases:
                leaf = b.attr if isinstance(b, ast.Attribute) else \
                    b.id if isinstance(b, ast.Name) else ""
                if leaf:
                    names.add(leaf)
            bases_of.setdefault(cls.name, set()).update(names)
    taxonomy = {"GreptimeError"}
    changed = True
    while changed:
        changed = False
        for cls, bases in bases_of.items():
            if cls not in taxonomy and bases & taxonomy:
                taxonomy.add(cls)
                changed = True
    ctx.taxonomy = taxonomy


def run_files(files: List[Tuple[str, str]], rules: "Iterable",
              ctx: ProjectContext) -> Tuple[List[Finding], List[str]]:
    """Run every rule over the pre-parsed modules; returns (findings,
    errors). Suppression comments are honored here so every rule gets
    them free. Files absent from ctx (a ctx built by a different caller)
    parse on demand."""
    findings: List[Finding] = []
    errors: List[str] = list(ctx.errors)
    for path, rel in files:
        if path in ctx.parse_errors:
            errors.append(ctx.parse_errors[path])
            continue
        mod = ctx.modules.get(path)
        if mod is None:
            try:
                source = ctx.sources.pop(path, None)
                if source is None:       # ctx built by a different caller
                    with open(path, encoding="utf-8") as f:
                        source = f.read()
                mod = ModuleInfo(path, rel, source)
            except (OSError, SyntaxError, ValueError) as e:
                errors.append(f"{rel}: cannot parse: {e}")
                continue
        for rule in rules:
            for fnd in rule.check(mod, ctx):
                if not mod.suppressed(fnd.rule, fnd.line):
                    findings.append(fnd)
    findings.sort(key=lambda f: (f.rel, f.line, f.rule))
    return findings, errors


# ---- baseline ------------------------------------------------------

def load_baseline(path: str) -> Dict[str, int]:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("version") != 1:
        raise ValueError(f"unsupported baseline format in {path}")
    return {str(k): int(v) for k, v in doc.get("findings", {}).items()}


def save_baseline(path: str, findings: List[Finding]) -> int:
    counts = Counter(f.baseline_key() for f in findings)
    doc = {"version": 1, "findings": dict(sorted(counts.items()))}
    from ...utils import atomic_write
    atomic_write(path, json.dumps(doc, indent=1) + "\n", fsync=False)
    return sum(counts.values())


def apply_baseline(findings: List[Finding],
                   baseline: Dict[str, int]) -> List[Finding]:
    """Drop findings covered by the baseline; the overflow (more
    occurrences of a key than grandfathered) stays reported."""
    budget = Counter(baseline)
    fresh: List[Finding] = []
    for f in findings:
        key = f.baseline_key()
        if budget.get(key, 0) > 0:
            budget[key] -= 1
        else:
            fresh.append(f)
    return fresh
