"""greptlint driver: file collection, AST indexing, suppressions, baseline.

The analyzer is rule-based over the stdlib ``ast`` module (no external
dependencies). Each scanned file is parsed **once** into a
:class:`ModuleInfo` carrying the tree, a parent map, and a by-node-type
index; rules (see ``rules.py``) query the index instead of re-walking,
so adding a rule costs one dict lookup per node type, not a fresh pass.

Suppressions are comment-driven and reviewable in diffs:

- ``# greptlint: disable=GL01`` (trailing or own-line) silences the
  named rule(s) on that line;
- ``# greptlint: disable-file=GL03`` anywhere in the file silences the
  rule(s) for the whole file. ``all`` matches every rule.

The baseline file grandfathers pre-existing findings: keys are
``RULE:relpath:crc32(stripped source line)`` (line-number independent,
so unrelated edits don't churn it) with an occurrence count. Findings
beyond the baselined count fail the run; fixing findings never does.
"""

from __future__ import annotations

import ast
import json
import logging
import os
import re
import zlib
from collections import Counter, defaultdict
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

logger = logging.getLogger(__name__)

SUPPRESS_RE = re.compile(
    r"#\s*greptlint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")

#: directories never collected when walking (explicit file args still scan)
SKIP_DIRS = frozenset({"__pycache__", "selftest", ".git"})


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # absolute path
    rel: str           # path relative to the scan root (stable key part)
    line: int
    col: int
    msg: str
    source_line: str = ""

    def baseline_key(self) -> str:
        crc = zlib.crc32(self.source_line.strip().encode()) & 0xFFFFFFFF
        return f"{self.rule}:{self.rel}:{crc:08x}"

    def render(self) -> str:
        return f"{self.rel}:{self.line}:{self.col}: {self.rule} {self.msg}"


class ModuleInfo:
    """One parsed file: tree + parent map + node index + suppressions."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.by_type: Dict[type, List[ast.AST]] = defaultdict(list)
        for node in ast.walk(self.tree):
            self.by_type[type(node)].append(node)
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.line_suppressed: Dict[int, Set[str]] = {}
        self.file_suppressed: Set[str] = set()
        for i, text in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip().upper() for r in m.group(2).split(",")}
            if m.group(1) == "disable-file":
                self.file_suppressed |= rules
            else:
                self.line_suppressed.setdefault(i, set()).update(rules)

    def nodes(self, *types: type) -> Iterator[ast.AST]:
        for t in types:
            yield from self.by_type.get(t, ())

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, rule: str, lineno: int) -> bool:
        if "ALL" in self.file_suppressed or rule in self.file_suppressed:
            return True
        on_line = self.line_suppressed.get(lineno, ())
        return rule in on_line or "ALL" in on_line

    def finding(self, rule: str, node: ast.AST, msg: str) -> Finding:
        lineno = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule, path=self.path, rel=self.rel,
                       line=lineno, col=col, msg=msg,
                       source_line=self.line_text(lineno))


@dataclass
class ProjectContext:
    """Cross-file facts collected in a pre-pass before rules run."""
    root: str
    #: failpoint names registered anywhere (static `register("x")` calls
    #: across the scanned tree, unioned with the live registry when the
    #: package is importable) — GL04 checks call sites against this
    failpoint_names: Set[str] = field(default_factory=set)
    errors: List[str] = field(default_factory=list)
    #: abs path -> source read by build_context's pre-pass, consumed by
    #: run_files so each file hits the disk once, not twice
    sources: Dict[str, str] = field(default_factory=dict)


def _package_rel(path: str) -> str:
    """rel for an explicitly-passed file, matching what a directory scan
    of its containing package would produce: climb while ``__init__.py``
    marks a package, then relativize from the package root's parent.
    Path-scoped rules (GL05/GL07) and baseline keys would otherwise see
    a bare basename on single-file scans and silently not apply."""
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return os.path.relpath(path, d)


def collect_files(paths: Iterable[str]) -> List[Tuple[str, str]]:
    """Expand files/directories into (abs_path, rel_path) pairs.

    Directory walks skip SKIP_DIRS (fixtures with seeded violations live
    under ``selftest/``); a path given explicitly is always scanned."""
    out: List[Tuple[str, str]] = []
    seen: Set[str] = set()
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            rel = _package_rel(p)
            if p not in seen:
                seen.add(p)
                out.append((p, rel))
            continue
        base = os.path.dirname(p.rstrip(os.sep))
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIRS)
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                ap = os.path.join(dirpath, fn)
                if ap in seen:
                    continue
                seen.add(ap)
                out.append((ap, os.path.relpath(ap, base)))
    return out


# matches plain `register("x")` and aliased imports like
# `from ..common.failpoint import register as _fp_register` — any
# identifier ENDING in `register` counts (over-matching only shrinks
# GL04's reach, never produces a false positive)
_REGISTER_RE = re.compile(r"""\b\w*register\(\s*["']([a-z][a-z0-9_]*)["']""")


def build_context(files: List[Tuple[str, str]], root: str) -> ProjectContext:
    ctx = ProjectContext(root=root)
    for path, _rel in files:
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            ctx.errors.append(f"{path}: unreadable: {e}")
            continue
        ctx.sources[path] = src
        ctx.failpoint_names.update(_REGISTER_RE.findall(src))
    # union the live registry: names registered by modules outside the
    # scanned set (the analyzer may be pointed at one subpackage)
    try:
        from ...common import failpoint
        ctx.failpoint_names.update(p["name"] for p in failpoint.list_points())
    except Exception as e:  # noqa: BLE001 — linting must not require a
        # fully importable package (e.g. scanning a broken tree); the
        # static register() sweep above already covers the common case,
        # so degrade to it with a note rather than failing the run
        logger.warning("greptlint: live failpoint registry unavailable "
                       "(%s); GL04 uses the static register() sweep only",
                       e)
    return ctx


def run_files(files: List[Tuple[str, str]], rules: "Iterable",
              ctx: ProjectContext) -> Tuple[List[Finding], List[str]]:
    """Parse each file once and run every rule; returns (findings, errors).
    Suppression comments are honored here so every rule gets them free."""
    findings: List[Finding] = []
    errors: List[str] = list(ctx.errors)
    for path, rel in files:
        try:
            source = ctx.sources.pop(path, None)
            if source is None:           # ctx built by a different caller
                with open(path, encoding="utf-8") as f:
                    source = f.read()
            mod = ModuleInfo(path, rel, source)
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(f"{rel}: cannot parse: {e}")
            continue
        for rule in rules:
            for fnd in rule.check(mod, ctx):
                if not mod.suppressed(fnd.rule, fnd.line):
                    findings.append(fnd)
    findings.sort(key=lambda f: (f.rel, f.line, f.rule))
    return findings, errors


# ---- baseline ------------------------------------------------------

def load_baseline(path: str) -> Dict[str, int]:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("version") != 1:
        raise ValueError(f"unsupported baseline format in {path}")
    return {str(k): int(v) for k, v in doc.get("findings", {}).items()}


def save_baseline(path: str, findings: List[Finding]) -> int:
    counts = Counter(f.baseline_key() for f in findings)
    doc = {"version": 1, "findings": dict(sorted(counts.items()))}
    from ...utils import atomic_write
    atomic_write(path, json.dumps(doc, indent=1) + "\n", fsync=False)
    return sum(counts.values())


def apply_baseline(findings: List[Finding],
                   baseline: Dict[str, int]) -> List[Finding]:
    """Drop findings covered by the baseline; the overflow (more
    occurrences of a key than grandfathered) stays reported."""
    budget = Counter(baseline)
    fresh: List[Finding] = []
    for f in findings:
        key = f.baseline_key()
        if budget.get(key, 0) > 0:
            budget[key] -= 1
        else:
            fresh.append(f)
    return fresh
