"""CLI driver. ``python -m greptimedb_tpu.devtools.greptlint --help``."""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from . import (ALL_RULES, apply_baseline, build_context, collect_files,
               load_baseline, run_files, save_baseline)

DEFAULT_BASELINE = ".greptlint-baseline.json"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="greptlint",
        description="project-invariant static analyzer (rules GL01-GL12; "
                    "GL10-GL12 are interprocedural over the repo-wide "
                    "call graph)")
    ap.add_argument("paths", nargs="*", default=["greptimedb_tpu"],
                    help="files or directories to scan")
    ap.add_argument("--baseline", metavar="PATH", default=None,
                    help=f"baseline file of grandfathered findings "
                         f"(default: ./{DEFAULT_BASELINE} when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline file")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record current findings as the new baseline "
                         "and exit 0")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="GLxx", help="run only the named rule(s)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.id}  {r.title}")
        return 0

    rules = ALL_RULES
    if args.rule:
        wanted = {r.upper() for r in args.rule}
        rules = [r for r in ALL_RULES if r.id in wanted]
        unknown = wanted - {r.id for r in rules}
        if unknown:
            print(f"greptlint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline and \
            os.path.isfile(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE
    if args.no_baseline:
        baseline_path = None

    files = collect_files(args.paths)
    if not files:
        print("greptlint: no .py files found under given paths",
              file=sys.stderr)
        return 2
    root = os.path.commonpath([p for p, _ in files])
    ctx = build_context(files, root)
    findings, errors = run_files(files, rules, ctx)

    if args.write_baseline:
        path = args.baseline or DEFAULT_BASELINE
        n = save_baseline(path, findings)
        print(f"greptlint: wrote {n} grandfathered finding(s) to {path}")
        return 0

    fresh = findings
    if baseline_path is not None:
        try:
            fresh = apply_baseline(findings, load_baseline(baseline_path))
        except (OSError, ValueError) as e:
            print(f"greptlint: cannot load baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2

    for err in errors:
        print(f"greptlint: error: {err}", file=sys.stderr)
    for f in fresh:
        print(f.render())
    grandfathered = len(findings) - len(fresh)
    tail = f" ({grandfathered} grandfathered)" if grandfathered else ""
    print(f"greptlint: scanned {len(files)} files, "
          f"{len(fresh)} finding(s){tail}")
    if errors:
        return 2
    return 1 if fresh else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:     # `greptlint ... | head` closed the pipe
        sys.exit(0)
